"""Loop guards: turn exceptions/Ctrl-C inside host-driven decode loops into
clean shutdown with partial results.

≡ reference `src/sub/utils/context_managers.py:16-57` (`catch_loop_errors`
clears the `running` Event and sets/clears the queue Events so socket
threads exit).  Here there are no threads to unwind — the analog is: stop
issuing device work, let in-flight XLA dispatches drain, and hand back what
was generated so far.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

log = logging.getLogger("mdi_llm_tpu")


class LoopInterrupted(Exception):
    """Raised internally when a guarded loop should stop early."""


class catch_loop_errors:
    """Context manager guarding a host-driven generation/training loop.

    with catch_loop_errors(on_stop=engine_cleanup) as guard:
        while ...:
            step()
    # guard.interrupted is True if the loop ended on Ctrl-C

    KeyboardInterrupt is swallowed (the loop body is expected to exit via
    the exception propagating out of the `with` body) so callers can return
    partial output; other exceptions run `on_stop` then re-raise.
    """

    def __init__(self, on_stop: Optional[Callable[[], None]] = None):
        self.on_stop = on_stop
        self.interrupted = False

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            return False
        if self.on_stop is not None:
            try:
                self.on_stop()
            except Exception:  # cleanup must not mask the original error
                log.exception("loop cleanup failed")
        if exc_type in (KeyboardInterrupt, LoopInterrupted):
            self.interrupted = True
            log.warning("generation interrupted — returning partial results")
            return True
        return False
