"""Tokens-vs-time observability: CSV capture and plots.

Parity with the reference benchmark capture (`/root/reference/src/starter.py:70-105`,
`src/sub/utils/plots.py:12-52`, `src/plot_tok_time.py`): identical CSV file
naming (`tokens_time_samples_<k>nodes_<model>_<n>samples.csv`) so the
reference's comparison workflow carries over, plus a run-stats CSV
(`timestamp,n_samples,n_layers,context_size,gen_time`).
"""

from __future__ import annotations

import csv
import time
from pathlib import Path
from typing import List, Sequence, Tuple, Union

PathLike = Union[str, Path]


def tok_time_csv_path(
    logs_dir: PathLike, n_nodes: int, model_name: str, n_samples: int
) -> Path:
    safe = model_name.replace("/", "_")
    return Path(logs_dir) / f"tokens_time_samples_{n_nodes}nodes_{safe}_{n_samples}samples.csv"


def write_tok_time_csv(path: PathLike, tok_time: Sequence[Tuple[int, float]]) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with path.open("w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["tokens", "time"])
        for n, t in tok_time:
            w.writerow([n, f"{t:.6f}"])
    return path


def append_run_stats(
    path: PathLike, n_samples: int, n_layers: int, context_size: int, gen_time: float
) -> Path:
    """≡ reference stats CSV (starter.py:19-21,89-105)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    new = not path.exists()
    with path.open("a", newline="") as f:
        w = csv.writer(f)
        if new:
            w.writerow(["timestamp", "n_samples", "n_layers", "context_size", "gen_time"])
        w.writerow(
            [time.strftime("%Y-%m-%d %H:%M:%S"), n_samples, n_layers, context_size, f"{gen_time:.4f}"]
        )
    return path


def plot_tokens_per_time(
    tok_time: Sequence[Tuple[int, float]], out_png: PathLike, label: str = ""
) -> Path:
    """≡ reference `plot_tokens_per_time` (plots.py:12-52)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    out_png = Path(out_png)
    out_png.parent.mkdir(parents=True, exist_ok=True)
    fig, ax = plt.subplots(figsize=(8, 5))
    times = [t for _, t in tok_time]
    toks = [n for n, _ in tok_time]
    ax.plot(times, toks, marker=".", markersize=2, label=label or None)
    ax.set_xlabel("time (s)")
    ax.set_ylabel("tokens generated")
    ax.grid(True, alpha=0.3)
    if label:
        ax.legend()
    fig.tight_layout()
    fig.savefig(out_png, dpi=120)
    plt.close(fig)
    return out_png


def plot_overlay(csv_paths: Sequence[PathLike], out_png: PathLike) -> Path:
    """Overlay several tokens-vs-time CSVs (≡ plot_tok_time.py:28-66)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    out_png = Path(out_png)
    fig, ax = plt.subplots(figsize=(8, 5))
    for p in csv_paths:
        p = Path(p)
        xs: List[float] = []
        ys: List[int] = []
        with p.open() as f:
            r = csv.reader(f)
            next(r)
            for row in r:
                ys.append(int(row[0]))
                xs.append(float(row[1]))
        ax.plot(xs, ys, label=p.stem)
    ax.set_xlabel("time (s)")
    ax.set_ylabel("tokens generated")
    ax.grid(True, alpha=0.3)
    ax.legend(fontsize=7)
    fig.tight_layout()
    fig.savefig(out_png, dpi=120)
    plt.close(fig)
    return out_png
