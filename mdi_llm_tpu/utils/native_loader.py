"""ctypes binding for the native C++ data loader (native/mdi_data.cpp).

Drop-in accelerated counterpart of `utils.data_loader.get_batch`: mmap'd
token bins with window gathering done in C++.  Builds the shared library on
demand with the repo Makefile; falls back cleanly when no compiler is
available (`is_available()` gates usage).
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_SO_PATH = _NATIVE_DIR / "libmdi_data.so"
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    if not _SO_PATH.exists():
        try:
            subprocess.run(
                ["make", "-C", str(_NATIVE_DIR)],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except (subprocess.SubprocessError, FileNotFoundError):
            _build_failed = True
            return None
    try:
        lib = ctypes.CDLL(str(_SO_PATH))
    except OSError:
        _build_failed = True
        return None
    lib.mdi_open_bin.restype = ctypes.c_void_p
    lib.mdi_open_bin.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.mdi_num_tokens.restype = ctypes.c_int64
    lib.mdi_num_tokens.argtypes = [ctypes.c_void_p]
    lib.mdi_sample_batch.restype = ctypes.c_int
    lib.mdi_sample_batch.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.mdi_read_tokens.restype = ctypes.c_int
    lib.mdi_read_tokens.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.mdi_close_bin.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def is_available() -> bool:
    return _load() is not None


class NativeBinDataset:
    """Random-window batch sampler over a token .bin file, C++-backed."""

    def __init__(self, path, dtype=np.uint16, seed: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native loader unavailable (no compiler / build failed)")
        self._lib = lib
        dtype = np.dtype(dtype)
        if dtype == np.uint16:
            ds = 2
        elif dtype == np.uint32:
            ds = 4
        else:
            raise ValueError("token dtype must be uint16 or uint32")
        self._handle = lib.mdi_open_bin(str(path).encode(), ds)
        if not self._handle:
            raise FileNotFoundError(f"cannot open token bin {path}")
        self._counter = np.uint64(seed or 1)

    def __len__(self) -> int:
        return int(self._lib.mdi_num_tokens(self._handle))

    def get_batch(self, batch_size: int, block_size: int) -> Tuple[np.ndarray, np.ndarray]:
        x = np.empty((batch_size, block_size), np.int32)
        y = np.empty((batch_size, block_size), np.int32)
        rc = self._lib.mdi_sample_batch(
            self._handle,
            batch_size,
            block_size,
            int(self._counter),
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        if rc != 0:
            raise RuntimeError(f"mdi_sample_batch failed (rc={rc})")
        nxt = (int(self._counter) + 0x9E3779B97F4A7C15) % (1 << 64)
        self._counter = np.uint64(nxt or 1)
        return x, y

    def read(self, start: int, count: int) -> np.ndarray:
        out = np.empty((count,), np.int32)
        rc = self._lib.mdi_read_tokens(
            self._handle, start, count, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        )
        if rc != 0:
            raise RuntimeError(f"mdi_read_tokens failed (rc={rc})")
        return out

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.mdi_close_bin(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
