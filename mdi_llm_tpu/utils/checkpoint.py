"""Checkpoint pipeline: HF ↔ JAX pytree conversion, orbax persistence.

TPU-native equivalent of the reference checkpoint tooling
(`/root/reference/src/sub/utils/convert_hf_checkpoint.py`,
`convert_lit_checkpoint.py`, `utils.py:441-611`, and the lazy loader
`litgpt_utils.py`):

- HF shards (`*.safetensors` or `*.bin`, optionally index-sharded) are read
  one tensor at a time and written into the layer-stacked pytree layout used
  by `models.transformer` — the QKV fusion uses the same interleaved
  per-group `[q…, k, v]` layout as litGPT (reference
  `convert_hf_checkpoint.py:110-198`) so numerics match the reference
  exactly.
- Persistence is orbax (`params/` directory) + `model_config.yaml`
  (≡ `utils.save_config`) — the reference's `lit_model.pth` equivalent.
- The reverse map (`convert_to_hf_state_dict`) mirrors
  `convert_lit_checkpoint.py` for the llama family.

Streaming note: tensors are converted shard-by-shard with at most one f32
copy in flight, then stacked per layer — the reference needs a custom lazy
unpickler (`litgpt_utils.py`) for the same reason.
"""

from __future__ import annotations

import gc
import json
import shutil
from pathlib import Path
from typing import Any, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

from mdi_llm_tpu.config import Config

PathLike = Union[str, Path]

TOKENIZER_FILES = (
    "tokenizer.json",
    "tokenizer.model",
    "tokenizer_config.json",
    "generation_config.json",
    "prompt_style.yaml",
)


# ---------------------------------------------------------------------------
# Low-level shard reading
# ---------------------------------------------------------------------------


def _np_from_torch(t) -> np.ndarray:
    import torch

    if t.dtype == torch.bfloat16:
        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


def iter_hf_tensors(ckpt_dir: Path):
    """Yield (name, np.ndarray) across all weight shards in a HF snapshot."""
    safes = sorted(ckpt_dir.glob("*.safetensors"))
    bins = sorted(
        p
        for p in ckpt_dir.glob("*.bin")
        if "training_args" not in p.name and "optimizer" not in p.name
    )
    if safes:
        from safetensors import safe_open

        for f in safes:
            with safe_open(str(f), framework="np") as sf:
                for name in sf.keys():
                    try:
                        yield name, sf.get_tensor(name)
                    except (TypeError, ValueError):
                        # numpy framework can't express bf16 in some versions;
                        # re-read through torch
                        from safetensors import torch as st_torch

                        with safe_open(str(f), framework="pt") as sf_pt:
                            yield name, _np_from_torch(sf_pt.get_tensor(name))
    elif bins:
        import torch

        for f in bins:
            sd = torch.load(str(f), map_location="cpu", weights_only=True)
            for name, t in sd.items():
                yield name, _np_from_torch(t)
            del sd
            gc.collect()
    else:
        raise FileNotFoundError(f"no *.safetensors or *.bin weights in {ckpt_dir}")


# ---------------------------------------------------------------------------
# QKV interleave (litGPT layout)
# ---------------------------------------------------------------------------


def fuse_qkv(cfg: Config, q: np.ndarray, k: np.ndarray, v: np.ndarray) -> np.ndarray:
    """Fuse separate q/k/v projection matrices into the interleaved litGPT
    layout: per KV group g, rows [q_g (q_per_kv*hs), k_g (hs), v_g (hs)]
    (reference `copy_weights_hf_llama` qkv reassembly,
    convert_hf_checkpoint.py:183-198)."""
    G, hs = cfg.n_query_groups, cfg.head_size
    q_per_kv = cfg.n_head // G
    qs = q.reshape(G, q_per_kv * hs, -1)
    ks = k.reshape(G, hs, -1)
    vs = v.reshape(G, hs, -1)
    fused = np.concatenate([qs, ks, vs], axis=1)  # (G, (q_per_kv+2)*hs, in)
    return fused.reshape(cfg.qkv_size, -1)


def split_qkv(cfg: Config, qkv: np.ndarray):
    """Inverse of `fuse_qkv` (≡ convert_lit_checkpoint's qkv_split)."""
    G, hs = cfg.n_query_groups, cfg.head_size
    q_per_kv = cfg.n_head // G
    fused = qkv.reshape(G, (q_per_kv + 2) * hs, -1)
    q = fused[:, : q_per_kv * hs, :].reshape(G * q_per_kv * hs, -1)
    k = fused[:, q_per_kv * hs : q_per_kv * hs + hs, :].reshape(G * hs, -1)
    v = fused[:, q_per_kv * hs + hs :, :].reshape(G * hs, -1)
    return q, k, v


def _pad_vocab(arr: np.ndarray, padded: int) -> np.ndarray:
    if arr.shape[0] == padded:
        return arr
    out = np.zeros((padded,) + arr.shape[1:], dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


# ---------------------------------------------------------------------------
# HF → pytree conversion
# ---------------------------------------------------------------------------


def convert_hf_checkpoint(
    ckpt_dir: PathLike,
    model_name: Optional[str] = None,
    dtype: Any = jnp.bfloat16,
    out_dir: Optional[PathLike] = None,
) -> Path:
    """Convert a HF snapshot directory into this framework's checkpoint
    (orbax `params/` + `model_config.yaml`).  Returns the output dir.

    ≡ reference `convert_hf_checkpoint` driver
    (convert_hf_checkpoint.py:305-389) with family dispatch by model_type.
    """
    ckpt_dir = Path(ckpt_dir)
    out_dir = Path(out_dir) if out_dir else ckpt_dir
    cfg_json = ckpt_dir / "config.json"
    if model_name:
        cfg = Config.from_name(model_name)
        mt = _model_type_for(cfg)
    elif cfg_json.exists():
        hf_cfg = json.loads(cfg_json.read_text())
        cfg = Config.from_hf_config(hf_cfg)
        mt = hf_cfg.get("model_type", "llama")
    else:
        cfg = Config.from_name(ckpt_dir.name)
        mt = _model_type_for(cfg)

    raw: Dict[str, np.ndarray] = dict(iter_hf_tensors(ckpt_dir))
    if mt in ("llama", "mistral", "mixtral", "gemma"):
        params = _map_llama(cfg, raw)
    elif mt == "gpt2":
        params = _map_gpt2(cfg, raw)
    elif mt == "gpt_neox":
        params = _map_neox(cfg, raw)
    elif mt == "falcon":
        params = _map_falcon(cfg, raw)
    elif mt == "phi":
        params = _map_phi(cfg, raw)
    else:
        raise ValueError(f"unsupported model_type {mt!r} for conversion")
    del raw
    gc.collect()

    np_dtype = _np_dtype(dtype)
    params = jax.tree_util.tree_map(lambda a: np.asarray(a, dtype=np_dtype), params)
    save_checkpoint(params, cfg, out_dir)
    for f in TOKENIZER_FILES:
        src = ckpt_dir / f
        if src.exists() and not (out_dir / f).exists():
            shutil.copy(src, out_dir / f)
    return out_dir


def _model_type_for(cfg: Config) -> str:
    """Classify a config into its HF naming family purely structurally —
    name sniffing misroutes e.g. llama finetunes with "phi" in the repo
    name.  Among the parallel-residual GptNeoxMLP families: phi has a
    biased LM head, falcon has bias-free linears, neox has biased linears
    (invariants of the reference config registry)."""
    if cfg.pos_embedding == "learned":
        return "gpt2"
    if cfg.mlp_class_name == "GptNeoxMLP" and cfg.parallel_residual:
        if cfg.lm_head_bias:
            return "phi"
        if not cfg.bias:
            return "falcon"
        return "gpt_neox"
    return "llama"


def _np_dtype(dtype):
    if dtype in (jnp.bfloat16, ml_dtypes.bfloat16, "bfloat16"):
        return ml_dtypes.bfloat16
    return np.dtype(dtype)


def _stack(layers: List[Dict[str, Any]]) -> Dict[str, Any]:
    """List of per-layer nested dicts → one nested dict of stacked leaves."""
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs, axis=0), *layers)


def _map_llama(cfg: Config, raw: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """HF llama/mistral naming → stacked pytree (≡ `copy_weights_hf_llama`,
    convert_hf_checkpoint.py:110-198)."""
    L = cfg.n_layer
    layers = []
    for i in range(L):
        pre = f"model.layers.{i}."
        lp: Dict[str, Any] = {
            "norm_1": {"weight": raw[pre + "input_layernorm.weight"]},
            "norm_2": {"weight": raw[pre + "post_attention_layernorm.weight"]},
            "attn": {
                "qkv": {
                    "weight": fuse_qkv(
                        cfg,
                        raw[pre + "self_attn.q_proj.weight"],
                        raw[pre + "self_attn.k_proj.weight"],
                        raw[pre + "self_attn.v_proj.weight"],
                    )
                },
                "proj": {"weight": raw[pre + "self_attn.o_proj.weight"]},
            },
        }
        if cfg.mlp_class_name == "LLaMAMoE":
            E = cfg.n_expert
            lp["mlp"] = {
                "gate": {"weight": raw[pre + "block_sparse_moe.gate.weight"]},
                "experts": {
                    "fc_1": {"weight": np.stack([raw[f"{pre}block_sparse_moe.experts.{e}.w1.weight"] for e in range(E)])},
                    "fc_2": {"weight": np.stack([raw[f"{pre}block_sparse_moe.experts.{e}.w3.weight"] for e in range(E)])},
                    "proj": {"weight": np.stack([raw[f"{pre}block_sparse_moe.experts.{e}.w2.weight"] for e in range(E)])},
                },
            }
        else:
            lp["mlp"] = {
                "fc_1": {"weight": raw[pre + "mlp.gate_proj.weight"]},
                "fc_2": {"weight": raw[pre + "mlp.up_proj.weight"]},
                "proj": {"weight": raw[pre + "mlp.down_proj.weight"]},
            }
        layers.append(lp)

    params: Dict[str, Any] = {
        "wte": {"weight": _pad_vocab(raw["model.embed_tokens.weight"], cfg.padded_vocab_size)},
        "blocks": _stack(layers),
        "ln_f": {"weight": raw["model.norm.weight"]},
    }
    if not cfg.tie_embeddings:
        head = raw.get("lm_head.weight", raw["model.embed_tokens.weight"])
        params["lm_head"] = {"weight": _pad_vocab(head, cfg.padded_vocab_size)}
    return params


def _map_gpt2(cfg: Config, raw: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """HF gpt2 naming → pytree.  HF stores Conv1D weights transposed
    (in, out); we store (out, in).  c_attn's fused [q;k;v] blocks are
    re-interleaved per head to the litGPT group layout."""

    def g(name):
        return raw[name] if name in raw else raw["transformer." + name]

    L = cfg.n_layer
    layers = []
    for i in range(L):
        pre = f"h.{i}."
        c_attn_w = g(pre + "attn.c_attn.weight").T  # (3D, D)
        c_attn_b = g(pre + "attn.c_attn.bias")
        D = cfg.n_embd
        qkv_w = fuse_qkv(cfg, c_attn_w[:D], c_attn_w[D : 2 * D], c_attn_w[2 * D :])
        qkv_b = _fuse_qkv_bias(cfg, c_attn_b[:D], c_attn_b[D : 2 * D], c_attn_b[2 * D :])
        layers.append(
            {
                "norm_1": {"weight": g(pre + "ln_1.weight"), "bias": g(pre + "ln_1.bias")},
                "norm_2": {"weight": g(pre + "ln_2.weight"), "bias": g(pre + "ln_2.bias")},
                "attn": {
                    "qkv": {"weight": qkv_w, "bias": qkv_b},
                    "proj": {
                        "weight": g(pre + "attn.c_proj.weight").T,
                        "bias": g(pre + "attn.c_proj.bias"),
                    },
                },
                "mlp": {
                    "fc": {
                        "weight": g(pre + "mlp.c_fc.weight").T,
                        "bias": g(pre + "mlp.c_fc.bias"),
                    },
                    "proj": {
                        "weight": g(pre + "mlp.c_proj.weight").T,
                        "bias": g(pre + "mlp.c_proj.bias"),
                    },
                },
            }
        )
    return {
        "wte": {"weight": _pad_vocab(g("wte.weight"), cfg.padded_vocab_size)},
        "wpe": {"weight": g("wpe.weight")},
        "blocks": _stack(layers),
        "ln_f": {"weight": g("ln_f.weight"), "bias": g("ln_f.bias")},
    }


def _fuse_qkv_bias(cfg: Config, q: np.ndarray, k: np.ndarray, v: np.ndarray) -> np.ndarray:
    return fuse_qkv(cfg, q[:, None], k[:, None], v[:, None])[:, 0]


def _map_neox(cfg: Config, raw: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """HF gpt_neox naming → pytree.  NeoX's query_key_value is already
    per-head interleaved [q,k,v] — identical to the litGPT fused layout for
    MHA (reference `copy_weights_gpt_neox`, convert_hf_checkpoint.py:18-58)."""
    L = cfg.n_layer
    layers = []
    for i in range(L):
        pre = f"gpt_neox.layers.{i}."
        layers.append(
            {
                "norm_1": {
                    "weight": raw[pre + "input_layernorm.weight"],
                    "bias": raw[pre + "input_layernorm.bias"],
                },
                "norm_2": {
                    "weight": raw[pre + "post_attention_layernorm.weight"],
                    "bias": raw[pre + "post_attention_layernorm.bias"],
                },
                "attn": {
                    "qkv": {
                        "weight": raw[pre + "attention.query_key_value.weight"],
                        "bias": raw[pre + "attention.query_key_value.bias"],
                    },
                    "proj": {
                        "weight": raw[pre + "attention.dense.weight"],
                        "bias": raw[pre + "attention.dense.bias"],
                    },
                },
                "mlp": {
                    "fc": {
                        "weight": raw[pre + "mlp.dense_h_to_4h.weight"],
                        "bias": raw[pre + "mlp.dense_h_to_4h.bias"],
                    },
                    "proj": {
                        "weight": raw[pre + "mlp.dense_4h_to_h.weight"],
                        "bias": raw[pre + "mlp.dense_4h_to_h.bias"],
                    },
                },
            }
        )
    return {
        "wte": {
            "weight": _pad_vocab(
                raw["gpt_neox.embed_in.weight"], cfg.padded_vocab_size
            )
        },
        "blocks": _stack(layers),
        "ln_f": {
            "weight": raw["gpt_neox.final_layer_norm.weight"],
            "bias": raw["gpt_neox.final_layer_norm.bias"],
        },
        "lm_head": {"weight": _pad_vocab(raw["embed_out.weight"], cfg.padded_vocab_size)},
    }


def _map_falcon(cfg: Config, raw: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """HF falcon naming → pytree (≡ `copy_weights_falcon`,
    convert_hf_checkpoint.py:61-107).  Falcon's fused query_key_value is
    already the per-group [q…, k, v] interleave.  Covers both layouts: the
    7b one (parallel attention, shared input_layernorm) and the 40b/180B
    `new_decoder_architecture` (two norms: ln_attn + ln_mlp)."""
    L = cfg.n_layer
    layers = []
    for i in range(L):
        pre = f"transformer.h.{i}."
        if cfg.shared_attention_norm:  # 7b layout
            norms = {
                "norm_1": {
                    "weight": raw[pre + "input_layernorm.weight"],
                    "bias": raw[pre + "input_layernorm.bias"],
                },
            }
        else:  # 40b/180B new_decoder_architecture
            norms = {
                "norm_1": {
                    "weight": raw[pre + "ln_attn.weight"],
                    "bias": raw[pre + "ln_attn.bias"],
                },
                "norm_2": {
                    "weight": raw[pre + "ln_mlp.weight"],
                    "bias": raw[pre + "ln_mlp.bias"],
                },
            }
        layers.append(
            {
                **norms,
                "attn": {
                    "qkv": {"weight": raw[pre + "self_attention.query_key_value.weight"]},
                    "proj": {"weight": raw[pre + "self_attention.dense.weight"]},
                },
                "mlp": {
                    "fc": {"weight": raw[pre + "mlp.dense_h_to_4h.weight"]},
                    "proj": {"weight": raw[pre + "mlp.dense_4h_to_h.weight"]},
                },
            }
        )
    return {
        "wte": {
            "weight": _pad_vocab(
                raw["transformer.word_embeddings.weight"], cfg.padded_vocab_size
            )
        },
        "blocks": _stack(layers),
        "ln_f": {
            "weight": raw["transformer.ln_f.weight"],
            "bias": raw["transformer.ln_f.bias"],
        },
        "lm_head": {"weight": _pad_vocab(raw["lm_head.weight"], cfg.padded_vocab_size)},
    }


def _map_phi(cfg: Config, raw: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """HF phi naming → pytree (≡ `copy_weights_phi`,
    convert_hf_checkpoint.py:201-272): separate q/k/v with biases fused into
    the interleaved layout, shared input_layernorm, biased LM head."""
    L = cfg.n_layer
    layers = []
    for i in range(L):
        pre = f"model.layers.{i}."
        qkv_w = fuse_qkv(
            cfg,
            raw[pre + "self_attn.q_proj.weight"],
            raw[pre + "self_attn.k_proj.weight"],
            raw[pre + "self_attn.v_proj.weight"],
        )
        qkv_b = _fuse_qkv_bias(
            cfg,
            raw[pre + "self_attn.q_proj.bias"],
            raw[pre + "self_attn.k_proj.bias"],
            raw[pre + "self_attn.v_proj.bias"],
        )
        layers.append(
            {
                "norm_1": {
                    "weight": raw[pre + "input_layernorm.weight"],
                    "bias": raw[pre + "input_layernorm.bias"],
                },
                "attn": {
                    "qkv": {"weight": qkv_w, "bias": qkv_b},
                    "proj": {
                        "weight": raw[pre + "self_attn.dense.weight"],
                        "bias": raw[pre + "self_attn.dense.bias"],
                    },
                },
                "mlp": {
                    "fc": {
                        "weight": raw[pre + "mlp.fc1.weight"],
                        "bias": raw[pre + "mlp.fc1.bias"],
                    },
                    "proj": {
                        "weight": raw[pre + "mlp.fc2.weight"],
                        "bias": raw[pre + "mlp.fc2.bias"],
                    },
                },
            }
        )
    return {
        "wte": {
            "weight": _pad_vocab(raw["model.embed_tokens.weight"], cfg.padded_vocab_size)
        },
        "blocks": _stack(layers),
        "ln_f": {
            "weight": raw["model.final_layernorm.weight"],
            "bias": raw["model.final_layernorm.bias"],
        },
        "lm_head": {
            "weight": _pad_vocab(raw["lm_head.weight"], cfg.padded_vocab_size),
            "bias": _pad_vocab(raw["lm_head.bias"], cfg.padded_vocab_size),
        },
    }


# ---------------------------------------------------------------------------
# Reverse conversion (≡ convert_lit_checkpoint.py: llama/neox/falcon/phi,
# plus gpt2 beyond parity)
# ---------------------------------------------------------------------------


def _split_qkv_bias(cfg: Config, qkv_b: np.ndarray):
    q, k, v = split_qkv(cfg, qkv_b[:, None])
    return q[:, 0], k[:, 0], v[:, 0]


def convert_to_hf_state_dict(cfg: Config, params: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Native pytree → HF state-dict naming, dispatched by model family
    (≡ `convert_lit_checkpoint.py:15-220` copy_weights_falcon /
    copy_weights_gpt_neox / copy_weights_llama / copy_weights_phi)."""
    mt = _model_type_for(cfg)
    if mt == "falcon":
        return _rev_falcon(cfg, params)
    if mt == "phi":
        return _rev_phi(cfg, params)
    if mt == "gpt_neox":
        return _rev_neox(cfg, params)
    if mt == "gpt2":
        return _rev_gpt2(cfg, params)
    return _rev_llama(cfg, params)


def _rev_llama(cfg: Config, params: Dict[str, Any]) -> Dict[str, np.ndarray]:
    if cfg.mlp_class_name not in ("LLaMAMLP", "GemmaMLP", "LLaMAMoE"):
        # e.g. RedPajama/StableLM: GptNeoxMLP without parallel residual has
        # no HF llama naming to map onto
        raise NotImplementedError(
            f"reverse conversion not implemented for mlp_class_name="
            f"{cfg.mlp_class_name!r} with parallel_residual={cfg.parallel_residual}"
        )
    out: Dict[str, np.ndarray] = {}
    out["model.embed_tokens.weight"] = np.asarray(params["wte"]["weight"])[: cfg.vocab_size]
    out["model.norm.weight"] = np.asarray(params["ln_f"]["weight"])
    if not cfg.tie_embeddings:
        out["lm_head.weight"] = np.asarray(params["lm_head"]["weight"])[: cfg.vocab_size]
    b = params["blocks"]
    for i in range(cfg.n_layer):
        pre = f"model.layers.{i}."
        q, k, v = split_qkv(cfg, np.asarray(b["attn"]["qkv"]["weight"][i]))
        out[pre + "self_attn.q_proj.weight"] = q
        out[pre + "self_attn.k_proj.weight"] = k
        out[pre + "self_attn.v_proj.weight"] = v
        out[pre + "self_attn.o_proj.weight"] = np.asarray(b["attn"]["proj"]["weight"][i])
        out[pre + "input_layernorm.weight"] = np.asarray(b["norm_1"]["weight"][i])
        out[pre + "post_attention_layernorm.weight"] = np.asarray(b["norm_2"]["weight"][i])
        if cfg.mlp_class_name == "LLaMAMoE":
            out[pre + "block_sparse_moe.gate.weight"] = np.asarray(
                b["mlp"]["gate"]["weight"][i]
            )
            for e in range(cfg.n_expert):
                ex = b["mlp"]["experts"]
                out[f"{pre}block_sparse_moe.experts.{e}.w1.weight"] = np.asarray(
                    ex["fc_1"]["weight"][i, e]
                )
                out[f"{pre}block_sparse_moe.experts.{e}.w3.weight"] = np.asarray(
                    ex["fc_2"]["weight"][i, e]
                )
                out[f"{pre}block_sparse_moe.experts.{e}.w2.weight"] = np.asarray(
                    ex["proj"]["weight"][i, e]
                )
        else:  # LLaMAMLP / GemmaMLP
            out[pre + "mlp.gate_proj.weight"] = np.asarray(b["mlp"]["fc_1"]["weight"][i])
            out[pre + "mlp.up_proj.weight"] = np.asarray(b["mlp"]["fc_2"]["weight"][i])
            out[pre + "mlp.down_proj.weight"] = np.asarray(b["mlp"]["proj"]["weight"][i])
    return out


def _rev_neox(cfg: Config, params: Dict[str, Any]) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    # pythia-family HF checkpoints size their embeddings at the PADDED vocab
    # (GPTNeoXConfig.vocab_size == 50304): emit all rows, no truncation
    out["gpt_neox.embed_in.weight"] = np.asarray(params["wte"]["weight"])
    out["gpt_neox.final_layer_norm.weight"] = np.asarray(params["ln_f"]["weight"])
    out["gpt_neox.final_layer_norm.bias"] = np.asarray(params["ln_f"]["bias"])
    out["embed_out.weight"] = np.asarray(params["lm_head"]["weight"])
    b = params["blocks"]
    for i in range(cfg.n_layer):
        pre = f"gpt_neox.layers.{i}."
        out[pre + "input_layernorm.weight"] = np.asarray(b["norm_1"]["weight"][i])
        out[pre + "input_layernorm.bias"] = np.asarray(b["norm_1"]["bias"][i])
        out[pre + "post_attention_layernorm.weight"] = np.asarray(b["norm_2"]["weight"][i])
        out[pre + "post_attention_layernorm.bias"] = np.asarray(b["norm_2"]["bias"][i])
        out[pre + "attention.query_key_value.weight"] = np.asarray(
            b["attn"]["qkv"]["weight"][i]
        )
        out[pre + "attention.query_key_value.bias"] = np.asarray(b["attn"]["qkv"]["bias"][i])
        out[pre + "attention.dense.weight"] = np.asarray(b["attn"]["proj"]["weight"][i])
        out[pre + "attention.dense.bias"] = np.asarray(b["attn"]["proj"]["bias"][i])
        out[pre + "mlp.dense_h_to_4h.weight"] = np.asarray(b["mlp"]["fc"]["weight"][i])
        out[pre + "mlp.dense_h_to_4h.bias"] = np.asarray(b["mlp"]["fc"]["bias"][i])
        out[pre + "mlp.dense_4h_to_h.weight"] = np.asarray(b["mlp"]["proj"]["weight"][i])
        out[pre + "mlp.dense_4h_to_h.bias"] = np.asarray(b["mlp"]["proj"]["bias"][i])
    return out


def _rev_falcon(cfg: Config, params: Dict[str, Any]) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    out["transformer.word_embeddings.weight"] = np.asarray(params["wte"]["weight"])[
        : cfg.vocab_size
    ]
    out["transformer.ln_f.weight"] = np.asarray(params["ln_f"]["weight"])
    out["transformer.ln_f.bias"] = np.asarray(params["ln_f"]["bias"])
    out["lm_head.weight"] = np.asarray(params["lm_head"]["weight"])[: cfg.vocab_size]
    b = params["blocks"]
    for i in range(cfg.n_layer):
        pre = f"transformer.h.{i}."
        if cfg.shared_attention_norm:  # 7b layout
            out[pre + "input_layernorm.weight"] = np.asarray(b["norm_1"]["weight"][i])
            out[pre + "input_layernorm.bias"] = np.asarray(b["norm_1"]["bias"][i])
        else:  # 40b/180B new_decoder_architecture
            out[pre + "ln_attn.weight"] = np.asarray(b["norm_1"]["weight"][i])
            out[pre + "ln_attn.bias"] = np.asarray(b["norm_1"]["bias"][i])
            out[pre + "ln_mlp.weight"] = np.asarray(b["norm_2"]["weight"][i])
            out[pre + "ln_mlp.bias"] = np.asarray(b["norm_2"]["bias"][i])
        out[pre + "self_attention.query_key_value.weight"] = np.asarray(
            b["attn"]["qkv"]["weight"][i]
        )
        out[pre + "self_attention.dense.weight"] = np.asarray(b["attn"]["proj"]["weight"][i])
        out[pre + "mlp.dense_h_to_4h.weight"] = np.asarray(b["mlp"]["fc"]["weight"][i])
        out[pre + "mlp.dense_4h_to_h.weight"] = np.asarray(b["mlp"]["proj"]["weight"][i])
    return out


def _rev_phi(cfg: Config, params: Dict[str, Any]) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    out["model.embed_tokens.weight"] = np.asarray(params["wte"]["weight"])[: cfg.vocab_size]
    out["model.final_layernorm.weight"] = np.asarray(params["ln_f"]["weight"])
    out["model.final_layernorm.bias"] = np.asarray(params["ln_f"]["bias"])
    out["lm_head.weight"] = np.asarray(params["lm_head"]["weight"])[: cfg.vocab_size]
    out["lm_head.bias"] = np.asarray(params["lm_head"]["bias"])[: cfg.vocab_size]
    b = params["blocks"]
    for i in range(cfg.n_layer):
        pre = f"model.layers.{i}."
        out[pre + "input_layernorm.weight"] = np.asarray(b["norm_1"]["weight"][i])
        out[pre + "input_layernorm.bias"] = np.asarray(b["norm_1"]["bias"][i])
        q, k, v = split_qkv(cfg, np.asarray(b["attn"]["qkv"]["weight"][i]))
        qb, kb, vb = _split_qkv_bias(cfg, np.asarray(b["attn"]["qkv"]["bias"][i]))
        out[pre + "self_attn.q_proj.weight"], out[pre + "self_attn.q_proj.bias"] = q, qb
        out[pre + "self_attn.k_proj.weight"], out[pre + "self_attn.k_proj.bias"] = k, kb
        out[pre + "self_attn.v_proj.weight"], out[pre + "self_attn.v_proj.bias"] = v, vb
        out[pre + "self_attn.dense.weight"] = np.asarray(b["attn"]["proj"]["weight"][i])
        out[pre + "self_attn.dense.bias"] = np.asarray(b["attn"]["proj"]["bias"][i])
        out[pre + "mlp.fc1.weight"] = np.asarray(b["mlp"]["fc"]["weight"][i])
        out[pre + "mlp.fc1.bias"] = np.asarray(b["mlp"]["fc"]["bias"][i])
        out[pre + "mlp.fc2.weight"] = np.asarray(b["mlp"]["proj"]["weight"][i])
        out[pre + "mlp.fc2.bias"] = np.asarray(b["mlp"]["proj"]["bias"][i])
    return out


def _rev_gpt2(cfg: Config, params: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Inverse of `_map_gpt2`: de-interleave QKV back to HF's fused [q;k;v]
    and restore the Conv1D (in, out) transposition.  lm_head is tied."""
    out: Dict[str, np.ndarray] = {}
    out["transformer.wte.weight"] = np.asarray(params["wte"]["weight"])[: cfg.vocab_size]
    out["transformer.wpe.weight"] = np.asarray(params["wpe"]["weight"])
    out["transformer.ln_f.weight"] = np.asarray(params["ln_f"]["weight"])
    out["transformer.ln_f.bias"] = np.asarray(params["ln_f"]["bias"])
    b = params["blocks"]
    for i in range(cfg.n_layer):
        pre = f"transformer.h.{i}."
        q, k, v = split_qkv(cfg, np.asarray(b["attn"]["qkv"]["weight"][i]))
        qb, kb, vb = _split_qkv_bias(cfg, np.asarray(b["attn"]["qkv"]["bias"][i]))
        out[pre + "attn.c_attn.weight"] = np.concatenate([q, k, v], axis=0).T
        out[pre + "attn.c_attn.bias"] = np.concatenate([qb, kb, vb], axis=0)
        out[pre + "attn.c_proj.weight"] = np.asarray(b["attn"]["proj"]["weight"][i]).T
        out[pre + "attn.c_proj.bias"] = np.asarray(b["attn"]["proj"]["bias"][i])
        out[pre + "ln_1.weight"] = np.asarray(b["norm_1"]["weight"][i])
        out[pre + "ln_1.bias"] = np.asarray(b["norm_1"]["bias"][i])
        out[pre + "ln_2.weight"] = np.asarray(b["norm_2"]["weight"][i])
        out[pre + "ln_2.bias"] = np.asarray(b["norm_2"]["bias"][i])
        out[pre + "mlp.c_fc.weight"] = np.asarray(b["mlp"]["fc"]["weight"][i]).T
        out[pre + "mlp.c_fc.bias"] = np.asarray(b["mlp"]["fc"]["bias"][i])
        out[pre + "mlp.c_proj.weight"] = np.asarray(b["mlp"]["proj"]["weight"][i]).T
        out[pre + "mlp.c_proj.bias"] = np.asarray(b["mlp"]["proj"]["bias"][i])
    return out


# ---------------------------------------------------------------------------
# Persistence (orbax)
# ---------------------------------------------------------------------------


def save_checkpoint(params: Dict[str, Any], cfg: Config, out_dir: PathLike) -> Path:
    """Write `params/` (orbax) + `model_config.yaml` into `out_dir`."""
    import orbax.checkpoint as ocp

    out_dir = Path(out_dir).resolve()
    out_dir.mkdir(parents=True, exist_ok=True)
    pdir = out_dir / "params"
    if pdir.exists():
        shutil.rmtree(pdir)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(pdir, params)
    cfg.save(out_dir)
    return out_dir


def load_checkpoint(
    ckpt_dir: PathLike, dtype: Any = None, cfg: Optional[Config] = None
):
    """Load (cfg, params) from a checkpoint dir; optionally cast params."""
    import orbax.checkpoint as ocp

    ckpt_dir = Path(ckpt_dir).resolve()
    if cfg is None:
        cfg = Config.from_checkpoint(ckpt_dir)
    with ocp.PyTreeCheckpointer() as ckptr:
        params = ckptr.restore(ckpt_dir / "params")
    if dtype is not None:
        # quantization-aware cast: integer leaves (int8 weights / packed int4
        # nibbles) must never be floated — the quantized einsums dispatch on
        # them — and f32 "scale" vectors keep their precision
        def _cast(path, a):
            a = jnp.asarray(a)
            if not jnp.issubdtype(a.dtype, jnp.floating):
                return a
            if getattr(path[-1], "key", None) == "scale":
                return a
            return a.astype(dtype)

        params = jax.tree_util.tree_map_with_path(_cast, params)
    return cfg, params


def has_checkpoint(ckpt_dir: PathLike) -> bool:
    return (Path(ckpt_dir) / "params").exists()
