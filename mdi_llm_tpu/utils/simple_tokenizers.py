"""Self-contained trainable tokenizers for scratch experiments.

Capability parity with the reference's legacy generation
(`/root/reference/old/GPT2/sub/bpe_tokenizer.py` — from-scratch trainable
BPE with `tokenize(out_vocab_size)` — and `char_tokenizer.py`;
`old/nanoGPT` uses the same pair for Shakespeare/Divina Commedia toys).
Both expose the same encode/decode surface as `utils.tokenizer.Tokenizer`
plus `train(text)` and JSON persistence.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

PathLike = Union[str, Path]


class CharTokenizer:
    """Character-level tokenizer (one char = one token)."""

    def __init__(self, vocab: Optional[Dict[str, int]] = None):
        self.stoi: Dict[str, int] = dict(vocab or {})
        self.itos: Dict[int, str] = {i: c for c, i in self.stoi.items()}

    @property
    def vocab_size(self) -> int:
        return len(self.stoi)

    def train(self, text: str) -> "CharTokenizer":
        chars = sorted(set(text))
        self.stoi = {c: i for i, c in enumerate(chars)}
        self.itos = {i: c for c, i in self.stoi.items()}
        return self

    def encode(self, text: str, bos: bool = False, eos: bool = False, max_length: int = -1) -> np.ndarray:
        ids = [self.stoi[c] for c in text if c in self.stoi]
        if max_length > 0:
            ids = ids[:max_length]
        return np.asarray(ids, np.int32)

    def decode(self, ids) -> str:
        return "".join(self.itos.get(int(i), "") for i in np.asarray(ids).reshape(-1))

    def save(self, path: PathLike) -> None:
        Path(path).write_text(json.dumps({"type": "char", "vocab": self.stoi}))

    @classmethod
    def load(cls, path: PathLike) -> "CharTokenizer":
        data = json.loads(Path(path).read_text())
        return cls(data["vocab"])


class BPETokenizer:
    """Minimal trainable byte-pair-encoding tokenizer.

    `train(text, vocab_size)` learns merges greedily over byte pairs
    (≡ reference `BPETokenizer.tokenize(out_vocab_size)`,
    old/GPT2/sub/bpe_tokenizer.py:134); encode applies merges in learned
    order; decode concatenates byte sequences.
    """

    def __init__(self):
        self.merges: List[Tuple[int, int]] = []  # pair -> new id = 256 + idx
        self._ranks: Dict[Tuple[int, int], int] = {}

    @property
    def vocab_size(self) -> int:
        return 256 + len(self.merges)

    def train(self, text: str, vocab_size: int) -> "BPETokenizer":
        if vocab_size < 256:
            raise ValueError("vocab_size must be >= 256 (byte alphabet)")
        ids = list(text.encode("utf-8"))
        self.merges = []
        while 256 + len(self.merges) < vocab_size:
            counts = Counter(zip(ids, ids[1:]))
            if not counts:
                break
            pair, freq = counts.most_common(1)[0]
            if freq < 2:
                break
            new_id = 256 + len(self.merges)
            self.merges.append(pair)
            ids = self._merge(ids, pair, new_id)
        self._ranks = {p: i for i, p in enumerate(self.merges)}
        return self

    @staticmethod
    def _merge(ids: List[int], pair: Tuple[int, int], new_id: int) -> List[int]:
        out = []
        i = 0
        while i < len(ids):
            if i + 1 < len(ids) and (ids[i], ids[i + 1]) == pair:
                out.append(new_id)
                i += 2
            else:
                out.append(ids[i])
                i += 1
        return out

    def encode(self, text: str, bos: bool = False, eos: bool = False, max_length: int = -1) -> np.ndarray:
        ids = list(text.encode("utf-8"))
        while len(ids) >= 2:
            pairs = set(zip(ids, ids[1:]))
            ranked = [p for p in pairs if p in self._ranks]
            if not ranked:
                break
            best = min(ranked, key=lambda p: self._ranks[p])
            ids = self._merge(ids, best, 256 + self._ranks[best])
        if max_length > 0:
            ids = ids[:max_length]
        return np.asarray(ids, np.int32)

    def decode(self, ids) -> str:
        table: Dict[int, bytes] = {i: bytes([i]) for i in range(256)}
        for idx, (a, b) in enumerate(self.merges):
            table[256 + idx] = table[a] + table[b]
        data = b"".join(table.get(int(i), b"") for i in np.asarray(ids).reshape(-1))
        return data.decode("utf-8", errors="replace")

    def save(self, path: PathLike) -> None:
        Path(path).write_text(
            json.dumps({"type": "bpe", "merges": [list(m) for m in self.merges]})
        )

    @classmethod
    def load(cls, path: PathLike) -> "BPETokenizer":
        data = json.loads(Path(path).read_text())
        tok = cls()
        tok.merges = [tuple(m) for m in data["merges"]]
        tok._ranks = {p: i for i, p in enumerate(tok.merges)}
        return tok
