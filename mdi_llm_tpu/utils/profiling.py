"""Profiling helpers: XLA device traces, host cProfile, and CompileGuard.

The reference's only profiler is cProfile behind `--debug`
(`/root/reference/src/sample.py:34-37,272-276`); here the same flag also
captures a `jax.profiler` device trace (viewable in TensorBoard /
Perfetto) — the TPU-native upgrade called out in SURVEY.md §7.
`StepWindowProfiler` bounds that capture to N mid-run serving steps
(`mdi-serve --xprof-steps`), so production-length replays yield
fixed-size xplane artifacts.

`CompileGuard` is the runtime companion to the `mdi-lint` static rules
(docs/analysis.md): it counts jit traces and XLA backend compiles via
`jax.monitoring`, so a bench run can PROVE the steady state — after
warmup, a hot decode loop must never compile again.  bench.py fails its
decode rows on any post-warmup recompile and records the counts in every
row's `detail.compiles` (docs/perf.md "Compile stability").  The same
event stream also feeds the serving observability layer's compile
counters via `add_compile_listener` (`obs/`, docs/observability.md).
"""

from __future__ import annotations

import cProfile
import contextlib
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Union

PathLike = Union[str, Path]

# jax.monitoring event keys (jax/_src/dispatch.py): one JAXPR_TRACE per new
# jit cache entry, one BACKEND_COMPILE per XLA compilation.  Tracking BOTH
# matters: with a persistent compilation cache a recompile can be a cheap
# cache hit (trace fires, backend compile doesn't) — but it still means the
# jit cache missed, which on a hot path is the bug.
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_active_guards: List["CompileGuard"] = []
_compile_listeners: List = []  # obs-layer hooks: fn(event_key) per event
_listener_installed = False


def _dispatch_event(event: str, duration: float, **kwargs) -> None:
    for guard in _active_guards:
        guard._observe(event)
    for fn in _compile_listeners:
        fn(event)


def _install_listener() -> None:
    """Register ONE process-wide listener lazily (jax.monitoring has no
    unregister; the dispatcher is a no-op while no guard is active)."""
    global _listener_installed
    if _listener_installed:
        return
    from jax import monitoring

    monitoring.register_event_duration_secs_listener(_dispatch_event)
    _listener_installed = True


def add_compile_listener(fn) -> None:
    """Subscribe `fn(event_key)` to the same jax.monitoring compile-event
    stream CompileGuard counts (`_TRACE_EVENT` per jit cache miss,
    `_BACKEND_COMPILE_EVENT` per XLA compile).  The obs layer uses this to
    feed compile counters into a `MetricsRegistry` without owning a guard;
    pair with `remove_compile_listener` (try/finally) — the listener list
    is process-global."""
    _install_listener()
    if fn not in _compile_listeners:
        _compile_listeners.append(fn)


def remove_compile_listener(fn) -> None:
    try:
        _compile_listeners.remove(fn)
    except ValueError:
        pass


class RecompileError(RuntimeError):
    """A jitted function compiled again after the warmup boundary."""


class CompileGuard:
    """Count jit traces / XLA compiles within a region, with a warmup mark.

    Usage::

        guard = CompileGuard(label="decode")
        with guard:
            engine.generate(prompts, n, temperature=0.0)   # warmup compiles
            guard.mark_warm()
            engine.generate(prompts, n, temperature=0.0)   # steady state
        guard.expect_clean()   # raises RecompileError if anything compiled

    Counters are process-wide (jax.monitoring does not attribute events to
    functions), which is exactly the invariant a bench wants: NOTHING in
    the steady-state region may build a new executable.  Guards nest
    safely; each keeps independent counts.
    """

    def __init__(self, label: str = "", max_recompiles_after_warmup: int = 0):
        self.label = label
        self.max_recompiles_after_warmup = int(max_recompiles_after_warmup)
        self.traces = 0
        self.backend_compiles = 0
        self._warm_traces: Optional[int] = None
        self._warm_backend: Optional[int] = None

    # -- context manager -----------------------------------------------------

    def __enter__(self) -> "CompileGuard":
        _install_listener()
        _active_guards.append(self)
        return self

    def __exit__(self, *exc) -> None:
        _active_guards.remove(self)

    # -- event sink ----------------------------------------------------------

    def _observe(self, event: str) -> None:
        if event == _TRACE_EVENT:
            self.traces += 1
        elif event == _BACKEND_COMPILE_EVENT:
            self.backend_compiles += 1

    # -- warmup boundary -----------------------------------------------------

    def mark_warm(self) -> None:
        """Everything compiled so far is warmup; later compiles are suspect."""
        self._warm_traces = self.traces
        self._warm_backend = self.backend_compiles

    @property
    def traces_after_warmup(self) -> Optional[int]:
        if self._warm_traces is None:
            return None
        return self.traces - self._warm_traces

    @property
    def backend_compiles_after_warmup(self) -> Optional[int]:
        if self._warm_backend is None:
            return None
        return self.backend_compiles - self._warm_backend

    def summary(self) -> Dict[str, Optional[int]]:
        """JSON-ready counters (recorded per bench row in BENCH_*.json)."""
        return {
            "traces": self.traces,
            "backend_compiles": self.backend_compiles,
            "traces_after_warmup": self.traces_after_warmup,
            "backend_compiles_after_warmup": self.backend_compiles_after_warmup,
        }

    def expect_clean(self) -> None:
        """Raise RecompileError if the post-warmup region compiled anything
        beyond the allowance (default 0).  No-op if mark_warm was never
        called (there is no steady-state region to judge)."""
        after = self.traces_after_warmup
        if after is None:
            return
        if after > self.max_recompiles_after_warmup:
            name = f" [{self.label}]" if self.label else ""
            raise RecompileError(
                f"CompileGuard{name}: {after} jit trace(s) "
                f"({self.backend_compiles_after_warmup} backend compile(s)) "
                "after warmup — the steady state is recompiling; check for "
                "float static args, shape drift, or jit-in-loop "
                "(run `mdi-lint` / see docs/analysis.md)"
            )


class StepWindowProfiler:
    """Bounded `jax.profiler` capture of N mid-run engine steps.

    A production-length serving replay cannot wrap the whole run in a
    trace — xplane captures grow with wall time and a multi-minute replay
    produces an unloadable artifact.  This window starts the trace after
    `skip` engine steps (past warmup compiles, into steady state) and
    stops it `n_steps` later, so `mdi-serve --xprof-steps N` yields a
    bounded deep profile of representative dispatches whatever the run
    length.  Drive it from `ServingEngine.run(step_hook=prof.on_step)`;
    `close()` (call it in a finally) stops a window left open by an early
    exit — a dangling trace wedges later jax.profiler sessions.
    """

    def __init__(self, logdir: PathLike, n_steps: int, skip: int = 8):
        if n_steps < 1:
            raise ValueError("n_steps must be >= 1")
        self.logdir = str(logdir)
        self.n_steps = int(n_steps)
        self.skip = max(0, int(skip))
        self.active = False
        self.done = False
        self.window: Optional[tuple] = None  # (first_step, last_step)

    def on_step(self, i: int) -> None:
        """Hook for the engine loop: `i` is the 1-based count of COMPLETED
        steps.  The trace spans steps skip+1 .. skip+n_steps inclusive."""
        if self.done:
            return
        self._last = i
        if not self.active and i >= self.skip:
            import jax

            jax.profiler.start_trace(self.logdir)
            self.active = True
            self._first = i + 1
            return
        if self.active and i >= self.skip + self.n_steps:
            self._stop()

    def _stop(self) -> None:
        import jax

        jax.profiler.stop_trace()
        self.active = False
        self.done = True
        self.window = (self._first, self._last)

    def close(self) -> None:
        """Stop a still-open window (short runs, exceptions)."""
        if self.active:
            self._stop()


@contextlib.contextmanager
def profile(
    logdir: Optional[PathLike] = None,
    host_profile_path: Optional[PathLike] = None,
) -> Iterator[None]:
    """Capture a jax.profiler trace to `logdir` and/or a cProfile dump."""
    import jax

    prof = None
    if host_profile_path is not None:
        prof = cProfile.Profile()
        prof.enable()
    trace_cm = (
        jax.profiler.trace(str(logdir)) if logdir is not None else contextlib.nullcontext()
    )
    try:
        with trace_cm:
            yield
    finally:
        if prof is not None:
            prof.disable()
            p = Path(host_profile_path)
            p.parent.mkdir(parents=True, exist_ok=True)
            prof.dump_stats(str(p))
