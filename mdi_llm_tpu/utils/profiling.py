"""Profiling helpers: XLA device traces + host cProfile.

The reference's only profiler is cProfile behind `--debug`
(`/root/reference/src/sample.py:34-37,272-276`); here the same flag also
captures a `jax.profiler` device trace (viewable in TensorBoard /
Perfetto) — the TPU-native upgrade called out in SURVEY.md §7.
"""

from __future__ import annotations

import cProfile
import contextlib
from pathlib import Path
from typing import Iterator, Optional, Union

PathLike = Union[str, Path]


@contextlib.contextmanager
def profile(
    logdir: Optional[PathLike] = None,
    host_profile_path: Optional[PathLike] = None,
) -> Iterator[None]:
    """Capture a jax.profiler trace to `logdir` and/or a cProfile dump."""
    import jax

    prof = None
    if host_profile_path is not None:
        prof = cProfile.Profile()
        prof.enable()
    trace_cm = (
        jax.profiler.trace(str(logdir)) if logdir is not None else contextlib.nullcontext()
    )
    try:
        with trace_cm:
            yield
    finally:
        if prof is not None:
            prof.disable()
            p = Path(host_profile_path)
            p.parent.mkdir(parents=True, exist_ok=True)
            prof.dump_stats(str(p))
