"""HuggingFace Hub checkpoint download.

Parity with the reference downloader
(`/root/reference/src/sub/utils/download.py:15-182`): pattern-filtered
snapshot download (tokenizer + weights), safetensors preferred, friendly
errors for gated/nonexistent repos, then conversion to the framework's
checkpoint layout.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Union

PathLike = Union[str, Path]

_WEIGHT_PATTERNS = ["*.safetensors*", "*.bin*", "*.json", "tokenizer.model"]


def download_from_hub(
    repo_id: str,
    checkpoints_dir: PathLike = "checkpoints",
    access_token: Optional[str] = None,
    tokenizer_only: bool = False,
    convert: bool = True,
    dtype=None,
) -> Path:
    """Download `org/name` into checkpoints/<org>/<name> and convert.

    ≡ reference `download_from_hub` (download.py:15-123); conversion goes
    straight to the orbax pytree layout (no intermediate lit_model.pth).
    """
    from huggingface_hub import snapshot_download
    from huggingface_hub.utils import GatedRepoError, RepositoryNotFoundError

    out = Path(checkpoints_dir) / repo_id
    patterns = (
        ["tokenizer*", "*.json", "*.model"] if tokenizer_only else _WEIGHT_PATTERNS
    )
    try:
        snapshot_download(
            repo_id,
            local_dir=out,
            allow_patterns=patterns,
            token=access_token,
        )
    except GatedRepoError as e:  # pragma: no cover - needs network
        raise RuntimeError(
            f"{repo_id} is a gated repo: accept the license on huggingface.co and "
            "pass --access-token (≡ reference gated_repo_catcher)"
        ) from e
    except RepositoryNotFoundError as e:  # pragma: no cover - needs network
        raise RuntimeError(f"repository {repo_id!r} not found on the HF hub") from e

    if convert and not tokenizer_only:
        import jax.numpy as jnp

        from mdi_llm_tpu.utils.checkpoint import convert_hf_checkpoint

        convert_hf_checkpoint(out, dtype=dtype or jnp.bfloat16)
    return out
