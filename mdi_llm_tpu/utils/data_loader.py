"""Training data pipeline: tokenized .bin memmaps and batch sampling.

Capability parity with the reference data loader
(`/root/reference/src/sub/utils/data_loader.py:14-126` and
`src/prepare_data.py`): tokenize a text corpus to uint16 `train.bin` /
`val.bin`, then sample random block_size windows as (x, y) next-token pairs.
Host-side NumPy; device placement/sharding happens in the trainer.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Tuple, Union

import numpy as np

PathLike = Union[str, Path]


def load_dataset(path: PathLike, tokenizer) -> np.ndarray:
    """Tokenize a raw text file into one long uint16/uint32 id array
    (≡ reference `load_dataset`)."""
    text = Path(path).read_text()
    ids = tokenizer.encode(text, bos=False)
    dtype = np.uint16 if int(ids.max()) < 2**16 else np.uint32
    return ids.astype(dtype)


def split_dataset(data: np.ndarray, frac_train: float = 0.9) -> Tuple[np.ndarray, np.ndarray]:
    """90/10 train/val split (≡ reference `split_dataset`)."""
    n = int(len(data) * frac_train)
    return data[:n], data[n:]


def prepare_bin(
    text_path: PathLike, out_dir: PathLike, tokenizer, frac_train: float = 0.9
) -> Tuple[Path, Path]:
    """Tokenize `text_path` and write train.bin/val.bin (≡ prepare_data.py)."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    data = load_dataset(text_path, tokenizer)
    train, val = split_dataset(data, frac_train)
    train_p, val_p = out_dir / "train.bin", out_dir / "val.bin"
    train.tofile(train_p)
    val.tofile(val_p)
    return train_p, val_p


def open_bin(path: PathLike, dtype=np.uint16) -> np.ndarray:
    """Memory-map a token bin file (≡ reference np.memmap usage,
    train.py:138-139)."""
    return np.memmap(path, dtype=dtype, mode="r")


def get_batch(
    data: np.ndarray,
    batch_size: int,
    block_size: int,
    rng: Optional[np.random.Generator] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Sample `batch_size` random windows: x = tokens[i:i+T],
    y = tokens[i+1:i+T+1] (≡ reference `get_batch`, data_loader.py:70-126)."""
    rng = rng or np.random.default_rng()
    ix = rng.integers(0, len(data) - block_size - 1, size=batch_size)
    x = np.stack([np.asarray(data[i : i + block_size], dtype=np.int32) for i in ix])
    y = np.stack(
        [np.asarray(data[i + 1 : i + 1 + block_size], dtype=np.int32) for i in ix]
    )
    return x, y
