"""Dual-backend tokenizer wrapper.

Capability parity with the reference tokenizer
(`/root/reference/src/sub/tokenizer.py:34-149`): auto-detect a HuggingFace
`tokenizer.json` (via the `tokenizers` library) or a SentencePiece
`tokenizer.model` in a checkpoint directory, resolve bos/eos ids from
`tokenizer_config.json` / `generation_config.json`, and expose
encode/decode.  Returns NumPy int32 arrays (host-side; device placement is
the caller's concern).  SentencePiece is optional in this image — the
backend is gated behind an import check.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List, Optional, Union

import numpy as np


class Tokenizer:
    def __init__(self, checkpoint_dir: Union[str, Path], force_backend: Optional[str] = None):
        checkpoint_dir = Path(checkpoint_dir)
        if not checkpoint_dir.exists():
            raise NotADirectoryError(f"checkpoint dir {checkpoint_dir} not found")

        self.model_name = checkpoint_dir.stem
        self.use_bos = self._check_use_bos(checkpoint_dir)
        self.bos_id: Optional[int] = None
        self.eos_id: Optional[int] = None
        self.backend: str

        hf_file = checkpoint_dir / "tokenizer.json"
        sp_file = checkpoint_dir / "tokenizer.model"

        want = force_backend
        if want not in (None, "huggingface", "sentencepiece"):
            raise ValueError(f"unknown tokenizer backend {want!r}")

        if (want == "sentencepiece" or (want is None and sp_file.is_file())) and sp_file.is_file():
            try:
                from sentencepiece import SentencePieceProcessor  # type: ignore
            except ImportError as e:
                if want == "sentencepiece":
                    raise RuntimeError(
                        "sentencepiece backend requested but the library is not installed"
                    ) from e
                SentencePieceProcessor = None  # fall through to HF
            else:
                self.processor = SentencePieceProcessor(model_file=str(sp_file))
                self.backend = "sentencepiece"
                self.bos_id = self.processor.bos_id()
                self.eos_id = self.processor.eos_id()
                self._load_special_ids(checkpoint_dir)
                return

        if hf_file.is_file():
            from tokenizers import Tokenizer as HFTokenizer

            self.processor = HFTokenizer.from_file(str(hf_file))
            self.backend = "huggingface"
            self._load_special_ids(checkpoint_dir)
            if self.bos_id is None:
                self.bos_id = self.token_to_id("<s>", missing_ok=True)
            if self.eos_id is None:
                self.eos_id = self.token_to_id("</s>", missing_ok=True)
            return

        raise NotImplementedError(
            f"no tokenizer.json or usable tokenizer.model in {checkpoint_dir}"
        )

    # -- special ids ---------------------------------------------------------

    def _load_special_ids(self, checkpoint_dir: Path) -> None:
        """bos/eos resolution order mirrors the reference
        (tokenizer.py:58-79): tokenizer_config.json tokens, then
        generation_config.json ids."""
        cfg_path = checkpoint_dir / "tokenizer_config.json"
        if cfg_path.is_file():
            cfg = json.loads(cfg_path.read_text())

            def tok_str(entry):
                if entry is None:
                    return None
                return entry["content"] if isinstance(entry, dict) else entry

            bos = tok_str(cfg.get("bos_token"))
            eos = tok_str(cfg.get("eos_token"))
            if bos is not None and self.bos_id is None:
                self.bos_id = self.token_to_id(bos, missing_ok=True)
            if eos is not None and self.eos_id is None:
                self.eos_id = self.token_to_id(eos, missing_ok=True)
        gen_path = checkpoint_dir / "generation_config.json"
        if gen_path.is_file():
            gen = json.loads(gen_path.read_text())
            if self.bos_id is None:
                b = gen.get("bos_token_id")
                self.bos_id = b[0] if isinstance(b, list) else b
            if self.eos_id is None:
                e = gen.get("eos_token_id")
                self.eos_id = e[0] if isinstance(e, list) else e

    @staticmethod
    def _check_use_bos(checkpoint_dir: Path) -> bool:
        cfg_path = checkpoint_dir / "tokenizer_config.json"
        if cfg_path.is_file():
            cfg = json.loads(cfg_path.read_text())
            if "add_bos_token" in cfg:
                return bool(cfg["add_bos_token"])
            # LlamaTokenizer adds bos by default
            return cfg.get("tokenizer_class") == "LlamaTokenizer"
        return False

    # -- API -----------------------------------------------------------------

    @property
    def vocab_size(self) -> int:
        if self.backend == "huggingface":
            return self.processor.get_vocab_size(with_added_tokens=False)
        return self.processor.vocab_size()

    def token_to_id(self, token: str, missing_ok: bool = False) -> Optional[int]:
        if self.backend == "huggingface":
            tid = self.processor.token_to_id(token)
        else:
            tid = self.processor.piece_to_id(token)
        if tid is None and not missing_ok:
            raise ValueError(f"token {token!r} not found in the vocabulary")
        return tid

    def encode(
        self,
        text: str,
        bos: Optional[bool] = None,
        eos: bool = False,
        max_length: int = -1,
    ) -> np.ndarray:
        if self.backend == "huggingface":
            ids: List[int] = self.processor.encode(text).ids
        else:
            ids = self.processor.encode(text)

        use_bos = self.use_bos if bos is None else bos
        if use_bos:
            if self.bos_id is None:
                raise NotImplementedError("tokenizer has no bos token")
            if not ids or ids[0] != self.bos_id:
                ids = [self.bos_id] + ids
        if eos and (not ids or ids[-1] != self.eos_id):
            ids = ids + [self.eos_id]
        if max_length > 0:
            ids = ids[:max_length]
        return np.asarray(ids, dtype=np.int32)

    def decode(self, ids) -> str:
        ids = [int(t) for t in np.asarray(ids).reshape(-1)]
        if self.backend == "huggingface":
            return self.processor.decode(ids)
        return self.processor.decode(ids)
