"""Host-side utilities: tokenization, prompts, checkpoints, data, plots."""
