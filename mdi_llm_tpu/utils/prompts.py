"""Prompt styles, stop-token sequences, and multi-prompt loading.

Capability parity with the reference prompt subsystem
(`/root/reference/src/sub/prompts.py`): ~25 chat/instruct formats with
per-style stop-token sequences, regex dispatch from model name, YAML
persistence next to checkpoints, and the `FILE:`-prefixed multi-prompt
loader (`prompts.py:392-447`).

Design: instead of a class per style, a style is a small dataclass holding a
`template` callable and a `stop` callable — the registry is data.  The
template strings are the public litGPT/vendor chat formats (interop facts,
needed so instruct checkpoints behave).
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from mdi_llm_tpu.utils.tokenizer import Tokenizer

StopFn = Callable[[Tokenizer], Tuple[List[int], ...]]


def _eos_only(tok: Tokenizer) -> Tuple[List[int], ...]:
    return ([tok.eos_id],)


def _ids(tok: Tokenizer, *names, missing_ok=True) -> List[int]:
    out = []
    for n in names:
        i = tok.token_to_id(n, missing_ok=missing_ok) if isinstance(n, str) else n
        if i is None:
            return []
        out.append(i)
    return out


@dataclass
class PromptStyle:
    name: str
    template: Callable[[str], str]
    stop: StopFn = _eos_only

    def apply(self, prompt: str, **kwargs: str) -> str:
        return self.template(prompt)

    def stop_tokens(self, tokenizer: Tokenizer) -> Tuple[List[int], ...]:
        return tuple(s for s in self.stop(tokenizer) if s and s[0] is not None)

    @classmethod
    def from_name(cls, name: str) -> "PromptStyle":
        return styles[name]

    @classmethod
    def from_config(cls, config) -> "PromptStyle":
        return style_for_model(config.name)


def _alpaca(p: str) -> str:
    return (
        "Below is an instruction that describes a task. "
        "Write a response that appropriately completes the request.\n\n"
        f"### Instruction:\n{p}\n\n### Response:\n"
    )


def _llama2(p: str) -> str:
    sys_prompt = (
        "You are a helpful, respectful and honest assistant. Always answer as helpfully as"
        " possible, while being safe.  Your answers should not include any harmful, unethical, racist, sexist,"
        " toxic, dangerous, or illegal content. Please ensure that your responses are socially unbiased and"
        " positive in nature.\n\nIf a question does not make any sense, or is not factually coherent, explain why"
        " instead of answering something not correct. If you don't know the answer to a question, please don't"
        " share false information."
    )
    return f"[INST] <<SYS>>\n{sys_prompt}\n<</SYS>>\n\n {p} [/INST] "


def _llama3(p: str) -> str:
    # Meta's llama3 chat format (public spec)
    return (
        "<|begin_of_text|><|start_header_id|>system<|end_header_id|>\n\n"
        "You are a helpful assistant.<|eot_id|>\n"
        "<|start_header_id|>user<|end_header_id|>\n\n"
        f"{p}<|eot_id|>\n"
        "<|start_header_id|>assistant<|end_header_id|>\n\n"
    )


def _stablelm_alpha(p: str) -> str:
    return (
        "<|SYSTEM|># StableLM Tuned (Alpha version)\n- StableLM is a helpful and harmless open-source AI language"
        " model developed by StabilityAI.\n- StableLM is excited to be able to help the user, but will refuse to do"
        " anything that could be considered harmful to the user.\n- StableLM is more than just an information"
        " source, StableLM is also able to write poetry, short stories, and make jokes.\n- StableLM will refuse to"
        f" participate in anything that could harm a human.<|USER|>{p}<|ASSISTANT|>"
    )


def _tinyllama(p: str) -> str:
    return (
        "<|system|>\n"
        "You are a friendly chatbot who always gives helpful, detailed, and polite answers.</s>\n"
        "<|user|>\n"
        f"{p}</s>\n"
        "<|assistant|>\n"
    )


def _llama2_fc(p: str) -> str:
    # Trelis function-calling v2 format: functions block + INST/SYS wrapper
    function_metadata = {
        "function": "search_bing",
        "description": (
            "Search the web for content on Bing. This allows users to search online/the internet/the web for"
            " content."
        ),
        "arguments": [
            {"name": "query", "type": "string", "description": "The search query string"}
        ],
    }
    system_prompt = (
        "You are a helpful, respectful and honest assistant. Always answer as helpfully as"
        "possible. Your only response should be JSON formatted functions"
    )
    fn_list = json.dumps(function_metadata).replace("{", "{{").replace("}", "}}")
    return (
        f"<FUNCTIONS>{fn_list.strip()}</FUNCTIONS>\n\n"
        f"[INST]<<SYS>>\n{system_prompt.strip()}\n<</SYS>>\n\n{p}[/INST]\n\n"
    )


styles: Dict[str, PromptStyle] = {}


def _register(name: str, template: Callable[[str], str], stop: StopFn = _eos_only):
    styles[name] = PromptStyle(name, template, stop)


_register("default", lambda p: p)
_register("alpaca", _alpaca)
_register("flan", _alpaca)
_register("longform", _alpaca)
_register(
    "stablelm-alpha",
    _stablelm_alpha,
    lambda t: (
        [t.eos_id],
        _ids(t, "<|SYSTEM|>"),
        _ids(t, "<|ASSISTANT|>"),
        _ids(t, "<|USER|>"),
    ),
)
_register("stablelm-zephyr", lambda p: f"<|user|>\n{p}<|endoftext|>\n<|assistant|>\n")
_register(
    "togethercomputer-chat",
    lambda p: f"<human>: {p}\n<bot>:",
    lambda t: (
        [t.eos_id],
        _ids(t, "<", "human", ">:"),
        _ids(t, "<", "bot", ">:"),
    ),
)
_register(
    "togethercomputer-instruct",
    lambda p: f"Q: {p}\nA:",
    lambda t: (
        [t.eos_id],
        _ids(t, "Q", ":"),
        _ids(t, "Question"),
        _ids(t, "A", ":"),
        _ids(t, "Label", ":"),
        [187, 187],
        [535],
        [2756],
    ),
)
_register(
    "falcon",
    lambda p: f"Do not prefix your replies with 'Bot: '\nUser: {p}\n",
    lambda t: ([t.eos_id], _ids(t, "User", ":"), _ids(t, 193, "User")),
)
_register(
    "vicuna",
    lambda p: (
        "A chat between a curious user and an artificial intelligence assistant. The assistant gives helpful, "
        f"detailed, and polite answers to the user's questions. USER: {p} ASSISTANT:"
    ),
)
_register("llama2-function-calling", _llama2_fc)
_register("llama2", _llama2)
_register(
    "llama3",
    _llama3,
    lambda t: ([t.eos_id], _ids(t, "<|eot_id|>")),
)
_register(
    "freewilly2",
    lambda p: (
        "### System:\nThis is a system prompt, please behave and help the user.\n\n"
        f"### User:\n{p}\n\n### Assistant:\n"
    ),
)
_register("platypus", lambda p: f"### Instruction:\n\n{p}\n\n### Response:\n")
_register("nous-research", lambda p: f"### Instruction:\n{p}\n\n### Response:\n")
_register("stablecode", lambda p: f"###Instruction\n{p}###Response\n")
_register("codellama", lambda p: f"<s>[INST] {p} [/INST]")
_register(
    "phi-1",
    lambda p: f"{p}\n\nAnswer:",
    lambda t: ([t.eos_id], _ids(t, "Answer", ":"), _ids(t, 198, "Answer", ":")),
)
_register("phi-2", lambda p: f"Instruct: {p}\nOutput:")
_register("tinyllama", _tinyllama)
_register("gemma", lambda p: f"<start_of_turn>user\n{p}<end_of_turn>\n<start_of_turn>model\n")
_register("h2oai", lambda p: f"<|prompt|>{p}</s><|answer|>")
# generation starts from a bare newline (reference `NoPrompt`)
_register("no-prompt", lambda p: "\n")


# (pattern, style) dispatch — mirrors reference
# `model_name_to_prompt_style` (prompts.py:325-366)
_MODEL_STYLE_RULES: Sequence[Tuple[str, str]] = (
    (r"stablelm-tuned-alpha", "stablelm-alpha"),
    (r"stablelm-zephyr-3b", "stablelm-zephyr"),
    (r"stablecode-instruct", "stablecode"),
    (r"RedPajama-INCITE.*-Chat", "togethercomputer-chat"),
    (r"RedPajama-INCITE.*-Instruct", "togethercomputer-instruct"),
    (r"falcon.*-instruct", "falcon"),
    (r"vicuna|longchat", "vicuna"),
    (r"Llama-2-7b-chat-hf-function-calling-v2", "llama2-function-calling"),
    (r"Llama-2.*-chat", "llama2"),
    (r"Llama-3.*-Instruct", "llama3"),
    (r"FreeWilly2", "freewilly2"),
    (r"Platypus", "platypus"),
    (r"Nous-Hermes", "nous-research"),
    (r"CodeLlama|Mistral.*Instruct", "codellama"),
    (r"phi-1", "phi-1"),
    (r"phi-2", "phi-2"),
    (r"tiny-llama.*chat|TinyLlama.*Chat", "tinyllama"),
    (r"(Code)?Gemma.*-it", "gemma"),
    (r"Danube2.*-chat", "h2oai"),
    (r"(?i)nanollama", "no-prompt"),
)


def style_for_model(model_name: str) -> PromptStyle:
    for pattern, style in _MODEL_STYLE_RULES:
        if re.search(pattern, model_name):
            return styles[style]
    return styles["default"]


# -- persistence (≡ reference save/load/has_prompt_style, prompts.py:369-389)


def save_prompt_style(style: Union[str, PromptStyle], checkpoint_dir: Union[str, Path]) -> None:
    name = style if isinstance(style, str) else style.name
    if name not in styles:
        raise ValueError(f"unknown prompt style {name!r}")
    p = Path(checkpoint_dir)
    p.mkdir(parents=True, exist_ok=True)
    (p / "prompt_style.yaml").write_text(f"style: {json.dumps(name)}\n")


def load_prompt_style(checkpoint_dir: Union[str, Path]) -> PromptStyle:
    text = (Path(checkpoint_dir) / "prompt_style.yaml").read_text()
    m = re.search(r"style:\s*\"?([\w.-]+)\"?", text)
    if not m:
        raise ValueError(f"malformed prompt_style.yaml in {checkpoint_dir}")
    return styles[m.group(1)]


def has_prompt_style(checkpoint_dir: Union[str, Path]) -> bool:
    return (Path(checkpoint_dir) / "prompt_style.yaml").is_file()


# -- multi-prompt loading (≡ reference get_user_prompt, prompts.py:392-447) --


def get_user_prompt(prompt: str, n_samples: int, custom_style: Optional[PromptStyle] = None) -> List[str]:
    """Resolve `prompt` into exactly `n_samples` prompt strings.

    `FILE:<path>` loads a text file with one prompt per blank-line-separated
    paragraph; fewer paragraphs than samples → cycle; more → truncate
    (reference semantics, prompts.py:392-447).
    """
    if prompt.startswith("FILE:"):
        path = Path(prompt[len("FILE:") :])
        text = path.read_text()
        paragraphs = [p.strip() for p in re.split(r"\n\s*\n", text) if p.strip()]
        if not paragraphs:
            raise ValueError(f"prompt file {path} is empty")
    else:
        paragraphs = [prompt]
    out = [paragraphs[i % len(paragraphs)] for i in range(n_samples)]
    if custom_style is not None:
        out = [custom_style.apply(p) for p in out]
    return out
