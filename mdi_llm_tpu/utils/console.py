"""Console affordances: progress bar, wait spinner, object sizing.

≡ reference `src/sub/utils/utils.py:28-57` (`get_obj_size`),
`:133-172` (`loading_bar`, `waiting_animation`).
"""

from __future__ import annotations

import gc
import sys
import threading
import time
from typing import Iterable, Optional


def loading_bar(current: int, total: int, width: int = 20, fill: str = "=") -> str:
    """Render a textual progress bar like `[=====>    ]` (≡ utils.py:133-150)."""
    if total <= 0:
        return "[" + " " * width + "]"
    done = int(width * min(current, total) / total)
    head = ">" if 0 < done < width else ""
    return "[" + fill * max(done - len(head), 0) + head + " " * (width - done) + "]"


class waiting_animation:
    """Context manager printing a spinner on a daemon thread while a slow
    host-side step runs (≡ utils.py:153-172's thread + Event protocol).

    with waiting_animation("converting"):
        convert(...)
    """

    FRAMES = "|/-\\"

    def __init__(self, message: str = "working", stream=None, interval: float = 0.2):
        self.message = message
        self.stream = stream or sys.stderr
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _spin(self):
        i = 0
        while not self._stop.is_set():
            self.stream.write(f"\r{self.message} {self.FRAMES[i % len(self.FRAMES)]}")
            self.stream.flush()
            i += 1
            self._stop.wait(self.interval)
        self.stream.write("\r" + " " * (len(self.message) + 2) + "\r")
        self.stream.flush()

    def __enter__(self):
        if self.stream.isatty():  # no spinner pollution in logs/pipes
            self._thread = threading.Thread(target=self._spin, daemon=True)
            self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        return False


def get_obj_size(obj) -> int:
    """Deep in-memory size of a Python object graph in bytes
    (≡ utils.py:28-57: BFS over gc referents, skipping types/modules)."""
    import types

    seen = set()
    size = 0
    frontier = [obj]
    while frontier:
        nxt = []
        for o in frontier:
            if id(o) in seen or isinstance(
                o, (type, types.ModuleType, types.FunctionType)
            ):
                continue
            seen.add(id(o))
            size += sys.getsizeof(o)
            nxt.append(o)
        frontier = [
            r for r in gc.get_referents(*nxt) if id(r) not in seen
        ] if nxt else []
    return size
