"""Training: data-parallel (+ optional tensor-parallel) pre-training loop.

Capability parity with the reference trainer (`/root/reference/src/train.py`):
init from scratch (GPT-NeoX init) / resume / converted-HF weights, AdamW
with weight decay groups, cosine LR with linear warmup (≡ `get_lr`,
utils.py:110-130), gradient accumulation and clipping, periodic eval with
`estimate_loss` (utils.py:61-107), checkpoint-on-best with patience early
stop (train.py:280-318), and MFU logging (model.py:348-368).

TPU-native differences:
- DDP/NCCL (train.py:88-103) → a declarative `dp`(/`tp`) mesh: batches are
  sharded on `dp`, params laid out by `parallel.sharding.param_specs`; XLA
  inserts the psum for gradient averaging.  Multi-host uses
  `jax.distributed.initialize` with the same program.
- AMP autocast + GradScaler (train.py:119-133) → straight bf16 params or
  bf16 compute with f32 master params; no scaler needed on TPU.
- `torch.compile` flag → everything is jitted always.
- Gradient accumulation runs as a `lax.scan` of micro-steps inside one jit
  (≡ the reference's `require_backward_grad_sync` trick at the last
  micro-step — here the psum happens once, after accumulation, for free).
- Block-level rematerialization (`jax.checkpoint`) trades FLOPs for HBM.
"""

from __future__ import annotations

import json
import shutil
import time
from dataclasses import asdict, dataclass, field
from functools import partial
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from mdi_llm_tpu.config import Config
from mdi_llm_tpu.models import transformer
from mdi_llm_tpu.parallel.partition import pad_stage_blocks, unpad_stage_blocks
from mdi_llm_tpu.parallel.sharding import param_specs, validate_tp_divisibility
from mdi_llm_tpu.utils import data_loader


@dataclass
class TrainingConfig:
    """Hyper-parameters (≡ reference `TrainingConfig` + argparse flags,
    config.py:21-163)."""

    batch_size: int = 8
    block_size: Optional[int] = None  # None → cfg.block_size
    grad_acc_steps: int = 1
    learning_rate: float = 3e-4
    weight_decay: float = 1e-1
    beta1: float = 0.9
    beta2: float = 0.95
    grad_clip: float = 1.0
    decay_lr: bool = True
    warmup_iters: int = 2000
    lr_decay_iters: int = 600000
    min_lr: float = 6e-5
    max_iters: int = 600000
    eval_iters: int = 20
    ckpt_interval: int = 1000
    log_interval: int = 10
    patience: int = 5
    seed: int = 10137
    dtype: str = "bfloat16"  # params/compute dtype
    remat: bool = True
    # run attention through the Pallas flash kernel (fwd + FA-2 backward,
    # ops/flash.py) instead of the XLA einsum path.  None → auto (TPU
    # backend only).  ≡ the reference training through fused SDPA
    # (model.py:738-751).  sp training keeps the ring-attention path (its
    # blockwise online softmax already avoids the (T, T) materialization).
    use_flash: Optional[bool] = None
    # MoE training (LLaMAMoE configs only): weight on the Switch/GShard
    # load-balancing auxiliary loss (transformer.moe_forward docstring);
    # 0 disables (pure CE, the reference's behavior, model.py:823-853).
    # The capacity factor bounds the dispatch buffers for expert-parallel
    # (`ep` mesh) training; None → exact capacity (no drops, grads match
    # the dense formulation bit-for-bit).
    moe_aux_weight: float = 0.01
    moe_capacity_factor: Optional[float] = None


def get_lr(it: int, tc: TrainingConfig) -> float:
    """Cosine schedule with linear warmup (≡ reference `get_lr`,
    utils.py:110-130)."""
    if not tc.decay_lr:
        return tc.learning_rate
    if it < tc.warmup_iters:
        return tc.learning_rate * it / tc.warmup_iters
    if it > tc.lr_decay_iters:
        return tc.min_lr
    ratio = (it - tc.warmup_iters) / (tc.lr_decay_iters - tc.warmup_iters)
    coeff = 0.5 * (1.0 + np.cos(np.pi * ratio))
    return tc.min_lr + coeff * (tc.learning_rate - tc.min_lr)


def cross_entropy_loss(
    cfg: Config, params, tokens, targets, remat=True, use_flash=False,
    moe_impl=None, moe_aux_weight=0.0,
):
    """Mean next-token CE in f32 (vocab padding columns get -inf'd out by
    the softmax normalizer naturally since their logits are finite but the
    targets never point at them).

    `moe_impl` routes MoE layers through an alternative implementation
    (`parallel.expert.ep_moe_forward` for token-dispatch expert-parallel
    training); `moe_aux_weight` > 0 adds the load-balancing auxiliary loss
    (layer-mean) for LLaMAMoE configs."""
    collect = moe_aux_weight > 0 and cfg.mlp_class_name == "LLaMAMoE"
    out = transformer.forward(
        cfg,
        params,
        tokens,
        jnp.zeros((tokens.shape[0],), jnp.int32),
        remat=remat,
        use_flash=use_flash,
        moe_impl=moe_impl,
        collect_moe_aux=collect,
    )
    logits = out[0].astype(jnp.float32)
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    loss = losses.mean()
    if collect:
        loss = loss + moe_aux_weight * out[2] / cfg.n_layer
    return loss


def lr_schedule(tc: TrainingConfig):
    """Traced twin of `get_lr` usable as an optax schedule."""
    if not tc.decay_lr:
        return tc.learning_rate

    def sched(count):
        it = jnp.asarray(count, jnp.float32)
        warm = tc.learning_rate * it / max(tc.warmup_iters, 1)
        ratio = (it - tc.warmup_iters) / max(tc.lr_decay_iters - tc.warmup_iters, 1)
        ratio = jnp.clip(ratio, 0.0, 1.0)
        coeff = 0.5 * (1.0 + jnp.cos(jnp.pi * ratio))
        cos_lr = tc.min_lr + coeff * (tc.learning_rate - tc.min_lr)
        return jnp.where(it < tc.warmup_iters, warm, cos_lr)

    return sched


def make_optimizer(tc: TrainingConfig) -> optax.GradientTransformation:
    """AdamW with decay masked off norms/biases (≡ reference fused AdamW
    param groups, train.py:254-261: decay only on ≥2-D params) and the
    cosine-with-warmup schedule baked in."""

    def decay_mask(params):
        # the reference decays params with dim >= 2 in the UNSTACKED torch
        # layout (train.py:254-261): true weight matrices only.  Our stacked
        # layout makes per-layer norm weights (L, D) and biases (L, out)
        # 2-D, so the rule is by path: weights outside norm subtrees.
        def leaf_mask(path, p):
            keys = {getattr(k, "key", None) for k in path}
            if keys & {"norm_1", "norm_2", "ln_f"}:
                return False
            return getattr(path[-1], "key", None) == "weight" and p.ndim >= 2

        return jax.tree_util.tree_map_with_path(leaf_mask, params)

    return optax.chain(
        optax.clip_by_global_norm(tc.grad_clip),
        optax.adamw(
            learning_rate=lr_schedule(tc),
            b1=tc.beta1,
            b2=tc.beta2,
            weight_decay=tc.weight_decay,
            mask=decay_mask,
        ),
    )


def estimate_flops_per_token(cfg: Config, T: int) -> float:
    """PaLM-style estimate: 6N + 12·L·H·hs·T (≡ reference `estimate_mfu`
    inputs, model.py:348-368)."""
    N = cfg.estimate_params()
    return 6.0 * N + 12.0 * cfg.n_layer * cfg.n_head * cfg.head_size * T


class Trainer:
    """Single-program trainer; the mesh decides the parallelism (dp, dp×tp)."""

    def __init__(
        self,
        cfg: Config,
        tc: TrainingConfig,
        mesh: Optional[Mesh] = None,
        params: Optional[Any] = None,
        out_dir: Optional[Path] = None,
    ):
        self.cfg = cfg
        self.tc = tc
        self.block_size = int(tc.block_size or cfg.block_size)
        self.mesh = mesh
        self.out_dir = Path(out_dir) if out_dir else None
        self.iter_num = 0
        self.best_val_loss = float("inf")
        # flash kernel needs a real TPU unless explicitly forced (tests
        # trace with use_flash=True to pin the kernel into the jaxpr).
        # Auto only engages on an UNMESHED trainer: under jit-with-shardings
        # GSPMD has no partitioning rule for the pallas custom call.  The sp
        # loss runs inside shard_map (manual mode) where the kernel is legal
        # per-device, but that path is explicit opt-in (use_flash=True) —
        # not auto — so the default sp config keeps every safety check and a
        # checker/lowering gap in the opt-in path fails loudly at trace
        # time rather than changing defaults.
        sp_mesh = mesh is not None and "sp" in mesh.axis_names
        self.use_flash = (
            jax.default_backend() == "tpu"
            and mesh is None
            # v5e measurement (generation.py): XLA's fused attention wins
            # below ~2k, so short-context training stays on the XLA path
            # unless explicitly forced
            and self.block_size >= 2048
            if tc.use_flash is None
            else tc.use_flash
        )
        if self.use_flash and mesh is not None and not sp_mesh:
            raise ValueError(
                "use_flash=True cannot combine with a dp/tp/pp training "
                "mesh: GSPMD cannot partition the Pallas flash call; drop "
                "the mesh, use an sp mesh, or set use_flash=False/None"
            )
        if self.use_flash and sp_mesh and "tp" in mesh.axis_names:
            raise ValueError(
                "use_flash sp training does not compose with a tp axis: "
                "the Pallas call would sit on the auto tp axis, which "
                "GSPMD cannot partition; drop tp or use_flash"
            )
        dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[tc.dtype]

        key = jax.random.PRNGKey(tc.seed)
        if params is None:
            params = transformer.init_params(cfg, key, dtype=dtype)
        else:
            params = transformer.cast_params(params, dtype)

        self.optimizer = make_optimizer(tc)

        self.sp = mesh is not None and "sp" in mesh.axis_names
        self.pp = mesh is not None and "pp" in mesh.axis_names
        # expert parallelism: tokens dispatched to ep-sharded experts via
        # all_to_all inside the loss (parallel/expert.ep_moe_forward) —
        # the TPU-first redesign of what the reference cannot do at all
        # (its MoE always runs whole on one device, model.py:823-853)
        self.ep = mesh is not None and "ep" in mesh.axis_names
        self._moe_impl = None
        if self.ep:
            if cfg.mlp_class_name != "LLaMAMoE":
                raise ValueError(
                    f"'ep' mesh axis needs an MoE config; {cfg.name} has "
                    f"mlp_class_name={cfg.mlp_class_name!r}"
                )
            if self.sp or self.pp:
                raise ValueError("ep composes with dp only (ep×sp/pp: future work)")
            if cfg.n_expert % int(mesh.shape["ep"]):
                raise ValueError(
                    f"n_expert={cfg.n_expert} not divisible by "
                    f"ep={int(mesh.shape['ep'])}"
                )
            from mdi_llm_tpu.parallel.expert import ep_moe_forward

            self._moe_impl = partial(
                ep_moe_forward,
                mesh=mesh,
                axis="ep",
                capacity_factor=tc.moe_capacity_factor,
                # split tokens over dp×ep so MoE cost scales with BOTH axes
                dp_axis="dp" if "dp" in mesh.axis_names else None,
            )
        self._moe_aux_w = (
            tc.moe_aux_weight if cfg.mlp_class_name == "LLaMAMoE" else 0.0
        )
        if self._moe_aux_w and self.pp:
            # the pp ring scans stage-sharded blocks and does not thread the
            # per-layer aux accumulator; training proceeds as pure CE there
            # (the reference's behavior) — say so rather than silently
            # dropping the term the config promises.  (sp DOES apply it:
            # _sp_loss_fn psums the router stats across the mesh.)
            import sys

            print(
                "warning: moe_aux_weight is not applied on pp training "
                "meshes (MoE trains dense, pure CE there); set "
                "moe_aux_weight=0 to silence",
                file=sys.stderr,
            )
            self._moe_aux_w = 0.0
        self.dp_axis: Optional[str] = "dp"
        if self.pp:
            # GPipe-style pipeline-parallel training over a ("dp", "pp")
            # (optionally ×"tp") mesh: stage-sharded blocks, microbatched
            # ring forward.  tp mirrors the inference pipe×tp trick
            # (parallel/pipeline.py): the ring shard_map is manual over
            # dp/pp only, the stage matmuls additionally carry Megatron
            # shardings on the auto tp axis and GSPMD inserts the
            # within-stage all-reduces over ICI — 3D (dp, pp, tp) training
            if self.sp:
                raise ValueError("pp composes with dp/tp only (pp×sp: future work)")
            S = int(mesh.shape["pp"])
            self.pp_stages = S
            self.pp_tp = int(mesh.shape.get("tp", 1))
            if self.pp_tp > 1:
                validate_tp_divisibility(cfg, self.pp_tp)
            # balanced split (NOT the inference table): the training ring
            # runs embed+head on every stage anyway, and every stage scans
            # l_max layers per micro-step — padded layers cost full FLOPs,
            # so minimizing l_max = ceil(L/S) is what matters here
            base, rem = divmod(cfg.n_layer, S)
            self.pp_counts = [base + (1 if s >= S - rem else 0) for s in range(S)]
            self.pp_lmax = max(self.pp_counts)
            dp_size = int(mesh.shape.get("dp", 1))
            if tc.batch_size % (dp_size * S):
                raise ValueError(
                    f"pp training microbatches each dp shard over the stages: "
                    f"batch_size {tc.batch_size} must divide by dp×pp="
                    f"{dp_size * S}"
                )
            stages = self._split_balanced(params)
            pp_params: Dict[str, Any] = {
                "stage_blocks": pad_stage_blocks(stages, self.pp_lmax)
            }
            for k in ("wte", "wpe", "ln_f", "lm_head"):
                if k in params:
                    pp_params[k] = params[k]
            params = jax.tree_util.tree_map(jnp.asarray, pp_params)
            pspecs = jax.tree_util.tree_map(lambda _: P(), params)
            if self.pp_tp > 1:
                # stage axis + Megatron layout within each stage (leaf
                # shapes: (S, L_stage, ...) → P("pp", *block_spec))
                bspecs = param_specs(cfg, "tp")["blocks"]
                pspecs["stage_blocks"] = jax.tree_util.tree_map(
                    lambda _, s: P("pp", *s), params["stage_blocks"], bspecs
                )
            else:
                pspecs["stage_blocks"] = jax.tree_util.tree_map(
                    lambda _: P("pp"), params["stage_blocks"]
                )
            self.param_shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), pspecs
            )
            params = jax.tree_util.tree_map(
                jax.device_put, params, self.param_shardings
            )
            self.batch_sharding = NamedSharding(mesh, P("dp"))
        elif mesh is not None:
            # sequence parallelism uses explicit shard_map collectives over
            # (dp, sp); a tp axis composes the same way as pp×tp — the ring
            # stays manual, params carry Megatron shardings on the auto tp
            # axis and GSPMD all-reduces within each sequence chunk
            tp = "tp" if "tp" in mesh.axis_names else None
            if tp:
                validate_tp_divisibility(cfg, int(mesh.shape["tp"]))
            pspecs = param_specs(cfg, tp, ep_axis="ep" if self.ep else None)
            self.param_shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), pspecs
            )
            params = jax.tree_util.tree_map(
                jax.device_put, params, self.param_shardings
            )
            seq_axis = "sp" if self.sp else None
            dp_axis = "dp" if "dp" in mesh.axis_names else None
            self.dp_axis = dp_axis
            self.batch_sharding = NamedSharding(mesh, P(dp_axis, seq_axis))
        else:
            self.param_shardings = None
            self.batch_sharding = None

        self.params = params
        self.opt_state = self.optimizer.init(params)
        self._step = self._build_step()
        self._eval = self._build_eval()

    # ------------------------------------------------------------------

    def _sp_loss_fn(self, aux_w: Optional[float] = None):
        """Sequence-parallel loss: shard_map over (dp, sp); each device holds
        a sequence chunk, attention rides the ring (ops.ring_attention), the
        scalar loss is psum-reduced.  jax.grad differentiates through the
        shard_map (psum transposes handled by JAX).

        MoE configs additionally apply the load-balancing aux loss: each
        device routes only its chunk, so the raw router stats psum across
        (dp, sp) BEFORE the aux is formed (`moe_forward(stats_reduce=...)`)
        — the exact global formula, not a mean of per-chunk auxes."""
        cfg, tc, mesh = self.cfg, self.tc, self.mesh

        use_flash = self.use_flash
        aux_w = self._moe_aux_w if aux_w is None else aux_w
        # with a tp axis the ring is manual over (dp, sp) only and vma
        # checking is unavailable (same partial-auto construction as pp×tp)
        manual_vma = int(mesh.shape.get("tp", 1)) == 1

        def psum_vary(t):
            # cast-to-varying whatever doesn't already vary (the static
            # token count), then reduce — same pattern as the pp psums
            def cast(v):
                if not manual_vma:
                    return v
                have = getattr(jax.typeof(v), "vma", frozenset())
                need = tuple(a for a in ("dp", "sp") if a not in have)
                return jax.lax.pcast(v, need, to="varying") if need else v

            return jax.lax.psum(jax.tree_util.tree_map(cast, t), ("dp", "sp"))

        collect = aux_w > 0
        moe_impl = (
            partial(transformer.moe_forward, stats_reduce=psum_vary)
            if collect
            else None
        )

        def local_loss(params, x, y):
            start = jax.lax.axis_index("sp") * x.shape[1]
            input_pos = jnp.full((x.shape[0],), start, jnp.int32)
            out = transformer.forward(
                cfg, params, x, input_pos, remat=tc.remat, sp_axis="sp",
                use_flash=use_flash, moe_impl=moe_impl,
                collect_moe_aux=collect,
            )
            logits = out[0]
            losses = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y
            )
            total = jax.lax.psum(losses.sum(), ("dp", "sp"))
            count = jax.lax.psum(
                jnp.asarray(losses.size, jnp.float32), ("dp", "sp")
            )
            loss = total / count
            if collect:
                loss = loss + aux_w * out[2] / cfg.n_layer
            return loss

        repl = jax.tree_util.tree_map(lambda _: P(), self.params)
        kwargs = {}
        if not manual_vma:
            kwargs = {"axis_names": {"dp", "sp"}, "check_vma": False}
        return jax.shard_map(
            local_loss,
            mesh=mesh,
            in_specs=(repl, P("dp", "sp"), P("dp", "sp")),
            out_specs=P(),
            **kwargs,
        )

    def _pp_loss_fn(self):
        """GPipe-style pipeline-parallel loss: shard_map over ("dp", "pp").

        The batch splits into S microbatches; the ring runs S + S - 1
        lockstep micro-steps where stage s processes microbatch t - s and
        `ppermute`s its activation downstream (the training analog of the
        inference ring in parallel/pipeline.py).  The last stage's emitted
        activations feed final-norm/head/CE once; `jax.grad` differentiates
        through the scan and ppermute (transpose = reverse permute), giving
        the 1F1B-equivalent backward for free.  Zero-padded stage layers are
        exact identities and receive zero gradients, and AdamW keeps them at
        zero (masked decay, zero moments).

        With a "tp" mesh axis the ring is manual over (dp, pp) only: the
        stage blocks carry Megatron shardings on the auto tp axis, GSPMD
        inserts the within-stage all-reduces (same construction as the
        inference pipe×tp ring, parallel/pipeline.py) — vma checking is
        unavailable in partial-auto mode, so the pcast bookkeeping below
        only runs in the fully-manual case."""
        cfg, tc, mesh = self.cfg, self.tc, self.mesh
        S = self.pp_stages
        n_micro = S
        manual_vma = self.pp_tp == 1

        def local_loss(params, x, y):
            blocks = jax.tree_util.tree_map(
                lambda a: a[0], params["stage_blocks"]
            )  # strip the local stage axis
            d = jax.lax.axis_index("pp")
            B, T = x.shape
            mu = B // n_micro
            xm = x.reshape(n_micro, mu, T)
            pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (mu, T))
            rope = transformer.get_rope_cache(cfg)
            cos = jnp.take(jnp.asarray(rope[0]), pos, axis=0)
            sin = jnp.take(jnp.asarray(rope[1]), pos, axis=0)
            perm = [(i, (i + 1) % S) for i in range(S)]
            n_steps = n_micro + S - 1
            emb_dtype = transformer.param_dtype(params)

            def step(x_act, t):
                mb = t - d
                active = (mb >= 0) & (mb < n_micro)
                mb_c = jnp.clip(mb, 0, n_micro - 1)
                x0 = transformer.embed(cfg, params, xm[mb_c], pos)
                xin = jnp.where(d == 0, x0.astype(x_act.dtype), x_act)
                y_out, _ = transformer.run_blocks(
                    cfg, blocks, xin, pos, cos, sin, remat=tc.remat
                )
                y_out = jnp.where(active, y_out, jnp.zeros_like(y_out))
                return jax.lax.ppermute(y_out, "pp", perm), y_out

            # the carry becomes device-varying after the first ppermute; a
            # fresh-zeros carry would type as unvarying and fail the scan
            x0c = jnp.zeros((mu, T, cfg.n_embd), emb_dtype)
            if manual_vma:
                x0c = jax.lax.pcast(x0c, ("dp", "pp"), to="varying")
            _, emitted = jax.lax.scan(
                step, x0c, jnp.arange(n_steps, dtype=jnp.int32)
            )
            # stage S-1 processed microbatch m at micro-step m + S - 1
            outs = emitted[S - 1 : S - 1 + n_micro].reshape(B, T, cfg.n_embd)
            logits = transformer.head(cfg, params, outs).astype(jnp.float32)
            losses = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            def psum_all(v):
                # cast-to-varying exactly the axes the value does not already
                # vary on (e.g. losses.size is a constant, invarying on both)
                if manual_vma:
                    have = getattr(jax.typeof(v), "vma", frozenset())
                    need = tuple(a for a in ("dp", "pp") if a not in have)
                    if need:
                        v = jax.lax.pcast(v, need, to="varying")
                return jax.lax.psum(v, ("dp", "pp"))

            is_last = (d == S - 1).astype(jnp.float32)
            total = psum_all(losses.sum() * is_last)
            count = psum_all(jnp.asarray(losses.size, jnp.float32) * is_last)
            return total / count

        pspec = jax.tree_util.tree_map(lambda _: P(), self.params)
        pspec["stage_blocks"] = jax.tree_util.tree_map(
            lambda _: P("pp"), self.params["stage_blocks"]
        )
        kwargs = {}
        if not manual_vma:
            # manual over the dp/pp ring only; "tp" stays an auto GSPMD axis
            kwargs = {"axis_names": {"dp", "pp"}, "check_vma": False}
        return jax.shard_map(
            local_loss,
            mesh=mesh,
            in_specs=(pspec, P("dp"), P("dp")),
            out_specs=P(),
            **kwargs,
        )

    def _build_step(self):
        cfg, tc = self.cfg, self.tc

        if self.pp:
            pp_loss = self._pp_loss_fn()

            def loss_fn(params, x, y):
                return pp_loss(params, x, y)

        elif self.sp:
            sp_loss = self._sp_loss_fn()

            def loss_fn(params, x, y):
                return sp_loss(params, x, y)

        else:

            def loss_fn(params, x, y):
                return cross_entropy_loss(
                    cfg, params, x, y, remat=tc.remat, use_flash=self.use_flash,
                    moe_impl=self._moe_impl, moe_aux_weight=self._moe_aux_w,
                )

        def step(params, opt_state, xs, ys):
            # gradient accumulation: scan micro-batches, mean the grads
            def micro(carry, xy):
                acc = carry
                loss, g = jax.value_and_grad(loss_fn)(params, xy[0], xy[1])
                acc = jax.tree_util.tree_map(jnp.add, acc, g)
                return acc, loss

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )
            acc, losses = jax.lax.scan(micro, zeros, (xs, ys))
            grads = jax.tree_util.tree_map(lambda g: g / xs.shape[0], acc)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, losses.mean()

        donate = (0, 1)
        if self.mesh is None:
            return jax.jit(step, donate_argnums=donate)
        seq_axis = "sp" if self.sp else None
        micro_batch_sh = NamedSharding(self.mesh, P(None, self.dp_axis, seq_axis))
        return jax.jit(
            step,
            donate_argnums=donate,
            in_shardings=(self.param_shardings, None, micro_batch_sh, micro_batch_sh),
            out_shardings=(self.param_shardings, None, None),
        )

    def _build_eval(self):
        cfg = self.cfg

        if self.pp:
            ev = self._pp_loss_fn()
        elif self.sp:
            # eval stays pure CE (same reasoning as the default branch)
            ev = self._sp_loss_fn(aux_w=0.0)
        else:

            def ev(params, x, y):
                # eval stays pure CE (comparable across aux-weight settings;
                # early stopping tracks modeling quality, not router balance)
                return cross_entropy_loss(
                    cfg, params, x, y, remat=False, use_flash=self.use_flash,
                    moe_impl=self._moe_impl,
                )

        if self.mesh is None:
            return jax.jit(ev)
        return jax.jit(
            ev,
            in_shardings=(self.param_shardings, self.batch_sharding, self.batch_sharding),
        )

    # ------------------------------------------------------------------

    def train_step(self, xs: np.ndarray, ys: np.ndarray):
        """One optimizer step over (grad_acc_steps, batch, T) token arrays."""
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, jnp.asarray(xs), jnp.asarray(ys)
        )
        self.iter_num += 1
        return float(loss)

    @staticmethod
    def _sample_batch(data, batch_size, block_size, rng):
        """Accepts a token array (NumPy path) or any object exposing
        `.get_batch(batch, block)` (e.g. the native C++ loader)."""
        if hasattr(data, "get_batch"):
            return data.get_batch(batch_size, block_size)
        return data_loader.get_batch(data, batch_size, block_size, rng)

    def estimate_loss(self, data, rng) -> float:
        """Mean loss over eval_iters random batches (≡ reference
        `estimate_loss`)."""
        losses = []
        for _ in range(self.tc.eval_iters):
            x, y = self._sample_batch(data, self.tc.batch_size, self.block_size, rng)
            losses.append(float(self._eval(self.params, jnp.asarray(x), jnp.asarray(y))))
        return float(np.mean(losses))

    def fit(
        self,
        train_data: np.ndarray,
        val_data: Optional[np.ndarray] = None,
        max_iters: Optional[int] = None,
        log_cb=None,
    ) -> Dict[str, Any]:
        """Run the training loop (≡ reference train.py:272-370)."""
        tc = self.tc
        max_iters = max_iters if max_iters is not None else tc.max_iters
        rng = np.random.default_rng(tc.seed + 1)
        flops_tok = estimate_flops_per_token(self.cfg, self.block_size)
        toks_per_iter = tc.grad_acc_steps * tc.batch_size * self.block_size
        patience_left = tc.patience
        history = []
        t0 = time.perf_counter()

        while self.iter_num < max_iters:
            if (
                self.iter_num % tc.ckpt_interval == 0
                and val_data is not None
                and self.iter_num > 0
            ):
                val_loss = self.estimate_loss(val_data, rng)
                if val_loss < self.best_val_loss:
                    self.best_val_loss = val_loss
                    patience_left = tc.patience
                    if self.out_dir:
                        self.save(self.out_dir)
                else:
                    patience_left -= 1
                    if patience_left <= 0:
                        break
                history.append({"iter": self.iter_num, "val_loss": val_loss})

            xs = np.empty((tc.grad_acc_steps, tc.batch_size, self.block_size), np.int32)
            ys = np.empty_like(xs)
            for m in range(tc.grad_acc_steps):
                xs[m], ys[m] = self._sample_batch(
                    train_data, tc.batch_size, self.block_size, rng
                )
            loss = self.train_step(xs, ys)
            if self.iter_num % tc.log_interval == 0:
                dt = time.perf_counter() - t0
                tflops = flops_tok * toks_per_iter * self.iter_num / dt / 1e12
                history.append({"iter": self.iter_num, "loss": loss, "tflops": tflops})
                if log_cb:
                    log_cb(history[-1])
        return {
            "iter_num": self.iter_num,
            "best_val_loss": self.best_val_loss,
            "history": history,
        }

    # ------------------------------------------------------------------
    # checkpoint / resume (≡ reference train_ckpt.pkl + lit_model.pth,
    # train.py:166-186,290-311)
    # ------------------------------------------------------------------

    def _split_balanced(self, params_like):
        """Slice a standard params-shaped tree into balanced pp stages
        (same mechanics as partition.split_params, balanced pp_counts)."""
        stages = []
        lo = 0
        for s, c in enumerate(self.pp_counts):
            stage = {
                "blocks": jax.tree_util.tree_map(
                    lambda x: x[lo : lo + c], params_like["blocks"]
                )
            }
            if s == 0:
                for k in ("wte", "wpe", "ln_f", "lm_head"):
                    if k in params_like:
                        stage[k] = params_like[k]
            stages.append(stage)
            lo += c
        return stages

    def _pp_tree_to_standard(self, tree):
        std = {k: v for k, v in tree.items() if k != "stage_blocks"}
        std["blocks"] = unpad_stage_blocks(
            # mdi-lint: disable-next-line=host-sync -- checkpoint path: params must land on host anyway, one batched pull per save
            jax.device_get(tree["stage_blocks"]), self.pp_counts
        )
        return std

    def _pp_tree_from_standard(self, tree):
        pp = {k: v for k, v in tree.items() if k != "blocks"}
        pp["stage_blocks"] = pad_stage_blocks(
            self._split_balanced(tree), self.pp_lmax
        )
        return jax.tree_util.tree_map(jax.device_put, pp, self.param_shardings)

    def _map_param_subtrees(self, state, fn, marker):
        """Apply `fn` to every params-shaped subtree (a dict containing
        `marker`) inside an optax state (tuples / NamedTuples / dicts)."""

        def walk(node):
            if isinstance(node, dict):
                if marker in node:
                    return fn(node)
                return {k: walk(v) for k, v in node.items()}
            if isinstance(node, tuple):
                cls = type(node)
                if hasattr(node, "_fields"):  # NamedTuple (optax states)
                    return cls(*(walk(c) for c in node))
                return cls(walk(c) for c in node)
            if isinstance(node, list):
                return [walk(c) for c in node]
            return node

        return walk(state)

    def _standard_params(self):
        """Params in the standard stacked-(L, ...) layout, regardless of the
        training-time partitioning (pp stage layout is unsplit for
        checkpoints so they interop with every other component)."""
        if not self.pp:
            return self.params
        return self._pp_tree_to_standard(self.params)

    def save(self, out_dir) -> Path:
        import orbax.checkpoint as ocp
        from flax import serialization

        out_dir = Path(out_dir).resolve()
        out_dir.mkdir(parents=True, exist_ok=True)
        p = out_dir / "params"
        if p.exists():
            shutil.rmtree(p)
        with ocp.PyTreeCheckpointer() as ck:
            ck.save(p, self._standard_params())
        # optimizer state holds NamedTuples — msgpack with a structure
        # template on restore keeps it exact; pp moments are unsplit to the
        # standard layout (same interop rule as the params)
        opt_state = self.opt_state
        if self.pp:
            opt_state = self._map_param_subtrees(
                opt_state, self._pp_tree_to_standard, "stage_blocks"
            )
        (out_dir / "opt_state.msgpack").write_bytes(
            serialization.to_bytes(opt_state)
        )
        self.cfg.save(out_dir)
        (out_dir / "train_state.json").write_text(
            json.dumps(
                {
                    "iter_num": self.iter_num,
                    "best_val_loss": self.best_val_loss,
                    "training_config": asdict(self.tc),
                }
            )
        )
        return out_dir

    @classmethod
    def resume(cls, out_dir, mesh: Optional[Mesh] = None) -> "Trainer":
        import orbax.checkpoint as ocp
        from flax import serialization

        out_dir = Path(out_dir).resolve()
        state = json.loads((out_dir / "train_state.json").read_text())
        cfg = Config.from_checkpoint(out_dir)
        tc = TrainingConfig(**state["training_config"])
        with ocp.PyTreeCheckpointer() as ck:
            import warnings

            with warnings.catch_warnings():
                # orbax warns that sharding info comes from the file; the
                # Trainer re-places every leaf onto its own mesh right after
                # (device_put in __init__), so the notice is moot here
                warnings.filterwarnings(
                    "ignore", message=".*Sharding info not provided.*"
                )
                params = ck.restore(out_dir / "params")
        tr = cls(cfg, tc, mesh=mesh, params=params, out_dir=out_dir)
        raw = (out_dir / "opt_state.msgpack").read_bytes()
        if tr.pp:
            # on-disk moments use the standard layout; repartition on load
            template = tr._map_param_subtrees(
                tr.opt_state, tr._pp_tree_to_standard, "stage_blocks"
            )
            tr.opt_state = tr._map_param_subtrees(
                serialization.from_bytes(template, raw),
                tr._pp_tree_from_standard,
                "blocks",
            )
        else:
            tr.opt_state = serialization.from_bytes(tr.opt_state, raw)
        tr.iter_num = state["iter_num"]
        tr.best_val_loss = state["best_val_loss"]
        return tr
