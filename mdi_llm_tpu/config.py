"""Model configuration and the named-model registry.

TPU-native re-design of the reference's model configuration layer
(`/root/reference/src/sub/config.py:175-1669` and the `Config` dataclass at
`/root/reference/src/sub/model.py:93-273`).  Field names follow the public
litGPT schema so that `model_config.yaml` files written by the reference (and
by litGPT itself) load unchanged, and so HF checkpoint conversion can share
weight layouts.  The implementation is new: plain dataclass + dict registry,
no torch dependency, plus TPU-specific additions (`pos_embedding` for the
legacy GPT-2 generation, dtype policy helpers).

Registry notes: entries are generated programmatically per model family from
public architecture specs.  `Config.from_hf_config` exists as the ground-truth
path — an HF `config.json` always wins over the registry.
"""

from __future__ import annotations

import copy
import dataclasses
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional

__all__ = [
    "Config",
    "ServingConfig",
    "name_to_config",
    "configs",
    "find_multiple",
    "dtype_bytes",
    # generation defaults (parity with reference src/sub/config.py:47-52)
    "TOP_K",
    "TEMPERATURE",
]

# Generation defaults — parity with reference `src/sub/config.py:47-52`.
TOP_K = 200
TEMPERATURE = 0.8

# Default RNG seed used across all reference entry points
# (`starter.py:195`, `sample.py:354`, `train.py:471`).
DEFAULT_SEED = 10137


def find_multiple(n: int, k: int) -> int:
    """Smallest multiple of `k` that is >= `n`."""
    if n % k == 0:
        return n
    return n + k - (n % k)


# Itemsize table for the dtypes this stack actually stores.  Kept as a plain
# dict (no numpy/jax import) so memory estimation (`estimate_kv_bytes`,
# `ServingConfig.pool_bytes`, analysis/plan.py) stays backend-free.
_DTYPE_BYTES = {
    "float64": 8, "int64": 8,
    "float32": 4, "int32": 4, "f32": 4,
    "float16": 2, "bfloat16": 2, "f16": 2, "bf16": 2,
    "float8": 1, "float8_e4m3fn": 1, "float8_e5m2": 1, "int8": 1, "uint8": 1,
}


def dtype_bytes(dtype) -> int:
    """Bytes per element for a dtype given as a string name, a numpy dtype,
    or a jax/numpy scalar type — resolved without importing numpy or jax."""
    if not isinstance(dtype, str):
        itemsize = getattr(dtype, "itemsize", None)
        if isinstance(itemsize, int) and itemsize > 0:
            return itemsize  # np.dtype instances
        dtype = getattr(dtype, "__name__", None) or getattr(
            dtype, "name", str(dtype)
        )  # scalar types (np.float32, jnp.bfloat16=ml_dtypes.bfloat16)
    key = str(dtype).lower()
    if key not in _DTYPE_BYTES:
        raise ValueError(f"unknown dtype {dtype!r} (known: {sorted(_DTYPE_BYTES)})")
    return _DTYPE_BYTES[key]


@dataclass
class Config:
    """Architecture hyper-parameters for one decoder-only transformer.

    Field names intentionally match the public litGPT schema (the reference's
    `Config` at `model.py:93-183` is litGPT-derived) so YAML checkpoints
    interoperate.  Extra TPU-framework fields are listed at the bottom.
    """

    name: str = ""
    hf_config: dict = field(default_factory=dict)
    scale_embeddings: bool = False
    block_size: int = 4096
    vocab_size: int = 50254
    padding_multiple: int = 512
    padded_vocab_size: Optional[int] = None
    n_layer: int = 16
    n_head: int = 32
    head_size: Optional[int] = None
    n_embd: int = 4096
    rotary_percentage: float = 0.25
    parallel_residual: bool = True
    bias: bool = True
    lm_head_bias: bool = False
    # n_query_groups: n_head => MHA, 1 => MQA, in between => GQA
    n_query_groups: Optional[int] = None
    shared_attention_norm: bool = False
    norm_class_name: str = "LayerNorm"  # "LayerNorm" | "RMSNorm"
    norm_eps: float = 1e-5
    mlp_class_name: str = "GptNeoxMLP"  # GptNeoxMLP | LLaMAMLP | GemmaMLP | LLaMAMoE
    gelu_approximate: str = "none"
    intermediate_size: Optional[int] = None
    rope_condense_ratio: int = 1
    rope_base: int = 10000
    n_expert: int = 0
    n_expert_per_token: int = 0

    # ---- TPU-framework extensions (not in litGPT) --------------------------
    # "rope" for all modern families; "learned" resurrects the legacy GPT-2
    # generation (reference `old/GPT2/sub/model.py`) with learned absolute
    # position embeddings.
    pos_embedding: str = "rope"
    # Tie lm_head to wte (Gemma, GPT-2, and scratch-trained models).
    tie_embeddings: bool = False
    # Gemma-style RMSNorm: weight enters as (1 + w) (reference RMSNorm
    # unit-offset variant, model.py:950-981).
    rmsnorm_add_unit_offset: bool = False

    def __post_init__(self):
        if not self.name:
            self.name = self.hf_config.get("name", self.name)

        if self.head_size is None:
            assert self.n_embd % self.n_head == 0, (self.n_embd, self.n_head)
            self.head_size = self.n_embd // self.n_head

        if self.padded_vocab_size is None:
            self.padded_vocab_size = find_multiple(
                self.vocab_size, self.padding_multiple
            )
        else:
            self.vocab_size = min(self.vocab_size, self.padded_vocab_size)

        if self.n_query_groups is not None:
            assert self.n_head % self.n_query_groups == 0
        else:
            self.n_query_groups = self.n_head

        if self.intermediate_size is None:
            if self.mlp_class_name == "LLaMAMLP":
                raise ValueError(
                    f"config {self.name!r} needs `intermediate_size` for LLaMAMLP"
                )
            self.intermediate_size = 4 * self.n_embd

        self.rope_n_elem = int(self.rotary_percentage * self.head_size)

    # ---- derived sizes -----------------------------------------------------

    @property
    def qkv_size(self) -> int:
        """Rows of the fused QKV projection (litGPT layout: interleaved
        per-group [q*q_per_kv, k, v])."""
        q_per_kv = self.n_head // self.n_query_groups
        return (q_per_kv + 2) * self.head_size * self.n_query_groups

    @property
    def attn_out_size(self) -> int:
        return self.head_size * self.n_head

    def estimate_params(self) -> int:
        """Rough parameter count (embeddings counted once if tied)."""
        V, D, L = self.padded_vocab_size, self.n_embd, self.n_layer
        emb = V * D
        head = 0 if self.tie_embeddings else V * D
        attn = D * self.qkv_size + self.attn_out_size * D
        if self.bias:
            attn += self.qkv_size + D
        I = self.intermediate_size
        if self.mlp_class_name in ("LLaMAMLP", "GemmaMLP"):
            mlp = 3 * D * I
        elif self.mlp_class_name == "LLaMAMoE":
            mlp = self.n_expert * 3 * D * I + D * self.n_expert
        else:
            mlp = 2 * D * I + (I + D if self.bias else 0)
        norms = 2 * D * (2 if self.bias and self.norm_class_name == "LayerNorm" else 1)
        return emb + head + L * (attn + mlp + norms) + D

    def estimate_param_bytes(self, dtype="bfloat16") -> int:
        """HBM bytes of the parameter tree stored at `dtype` — the
        backend-free analytic twin of `obs.roofline.param_bytes` (which
        measures a LIVE tree, quantized storage included).  Used by the
        roofline/docs tables when no weights exist yet."""
        return self.estimate_params() * dtype_bytes(dtype)

    def estimate_kv_bytes(
        self, batch: int, seq: int, dtype="bfloat16", n_layer: Optional[int] = None
    ) -> int:
        """HBM bytes of a dense KV cache for `batch` sequences of length
        `seq`: k + v, each (L, B, G, S, hs) — `transformer.init_kv_cache`.
        Pass `n_layer` for a pipeline stage's slice."""
        L = self.n_layer if n_layer is None else n_layer
        per = L * batch * self.n_query_groups * seq * self.head_size
        return 2 * per * dtype_bytes(dtype)

    # ---- constructors ------------------------------------------------------

    @classmethod
    def from_name(cls, name: str, **overrides: Any) -> "Config":
        """Look up a named config; accepts exact registry names."""
        if name in name_to_config:
            conf_dict = name_to_config[name]
        else:
            # allow e.g. "Llama-3-8B-Instruct" to match template "Llama-3-8B{}"
            matches = [
                d
                for d in configs
                if "{}" in d["name"]
                and name.startswith(d["name"].split("{}")[0])
                and name.endswith(d["name"].split("{}")[1])
            ]
            if not matches:
                raise ValueError(f"unknown model name {name!r}")
            conf_dict = matches[0]
        conf_dict = dict(conf_dict)
        conf_dict["name"] = name
        conf_dict.update(overrides)
        conf_dict.pop("_template", None)
        return cls(**conf_dict)

    @classmethod
    def from_file(cls, path: "str | Path", **overrides: Any) -> "Config":
        """Load from a `model_config.yaml` (reference `model.py:203-214`) or
        a JSON config file."""
        path = Path(path)
        text = path.read_text()
        if path.suffix in (".yaml", ".yml"):
            data = _parse_simple_yaml(text)
        else:
            data = json.loads(text)
        data.update(overrides)
        known = {f.name for f in dataclasses.fields(cls)}
        data = {k: v for k, v in data.items() if k in known}
        return cls(**data)

    @classmethod
    def from_checkpoint(cls, ckpt_dir: "str | Path", **overrides: Any) -> "Config":
        """Load config given a checkpoint directory: `model_config.yaml` if
        present, else fall back to the registry by directory name
        (reference `model.py:216-236`)."""
        ckpt_dir = Path(ckpt_dir)
        for fname in ("model_config.yaml", "model_config.json", "config.json"):
            p = ckpt_dir / fname
            if p.exists():
                if fname == "config.json":
                    return cls.from_hf_config(json.loads(p.read_text()), **overrides)
                return cls.from_file(p, **overrides)
        return cls.from_name(ckpt_dir.name, **overrides)

    @classmethod
    def from_hf_config(cls, hf: Dict[str, Any], **overrides: Any) -> "Config":
        """Ground-truth path: map a HuggingFace `config.json` dict to Config.

        Supports the llama/mistral/mixtral families (the reference's final
        generation targets litGPT Llama, `convert_hf_checkpoint.py:110-198`),
        plus gpt2 and gpt_neox for the legacy generations.
        """
        mt = hf.get("model_type", "llama")
        if mt in ("llama", "mistral", "mixtral"):
            data = dict(
                name=hf.get("_name_or_path", mt),
                block_size=hf.get("max_position_embeddings", 4096),
                vocab_size=hf["vocab_size"],
                padded_vocab_size=hf["vocab_size"],
                n_layer=hf["num_hidden_layers"],
                n_head=hf["num_attention_heads"],
                n_embd=hf["hidden_size"],
                n_query_groups=hf.get(
                    "num_key_value_heads", hf["num_attention_heads"]
                ),
                head_size=hf.get("head_dim"),  # Mistral-Nemo etc.: != D // H
                rotary_percentage=1.0,
                parallel_residual=False,
                bias=False,
                norm_class_name="RMSNorm",
                norm_eps=hf.get("rms_norm_eps", 1e-5),
                mlp_class_name="LLaMAMoE" if mt == "mixtral" else "LLaMAMLP",
                intermediate_size=hf["intermediate_size"],
                rope_base=int(hf.get("rope_theta", 10000)),
                tie_embeddings=hf.get("tie_word_embeddings", False),
            )
            if mt == "mixtral":
                data["n_expert"] = hf.get("num_local_experts", 8)
                data["n_expert_per_token"] = hf.get("num_experts_per_tok", 2)
        elif mt == "gpt2":
            data = dict(
                name=hf.get("_name_or_path", "gpt2"),
                block_size=hf.get("n_positions", 1024),
                vocab_size=hf["vocab_size"],
                padding_multiple=64,
                n_layer=hf["n_layer"],
                n_head=hf["n_head"],
                n_embd=hf["n_embd"],
                rotary_percentage=0.0,
                pos_embedding="learned",
                parallel_residual=False,
                bias=True,
                norm_class_name="LayerNorm",
                norm_eps=hf.get("layer_norm_epsilon", 1e-5),
                mlp_class_name="GptNeoxMLP",
                gelu_approximate=(
                    "tanh"
                    if hf.get("activation_function", "gelu_new") == "gelu_new"
                    else "none"
                ),
                tie_embeddings=True,
            )
        elif mt == "falcon":
            multi_query = hf.get("multi_query", True)
            new_arch = hf.get("new_decoder_architecture", False)
            if new_arch:
                groups = hf.get("num_kv_heads", hf["num_attention_heads"])
            elif multi_query:
                groups = 1
            else:
                groups = hf["num_attention_heads"]
            data = dict(
                name=hf.get("_name_or_path", "falcon"),
                block_size=hf.get("max_position_embeddings", 2048),
                vocab_size=hf["vocab_size"],
                padded_vocab_size=hf["vocab_size"],
                n_layer=hf["num_hidden_layers"],
                n_head=hf["num_attention_heads"],
                n_embd=hf["hidden_size"],
                n_query_groups=groups,
                rotary_percentage=1.0,
                parallel_residual=hf.get("parallel_attn", True),
                bias=hf.get("bias", False),
                shared_attention_norm=not new_arch,
                norm_class_name="LayerNorm",
                norm_eps=hf.get("layer_norm_epsilon", 1e-5),
                mlp_class_name="GptNeoxMLP",
                rope_base=int(hf.get("rope_theta", 10000)),
                tie_embeddings=hf.get("tie_word_embeddings", False),
            )
        elif mt == "phi":
            data = dict(
                name=hf.get("_name_or_path", "phi"),
                block_size=hf.get("max_position_embeddings", 2048),
                vocab_size=hf["vocab_size"],
                padded_vocab_size=hf["vocab_size"],
                n_layer=hf["num_hidden_layers"],
                n_head=hf["num_attention_heads"],
                n_embd=hf["hidden_size"],
                rotary_percentage=hf.get("partial_rotary_factor", 0.5),
                parallel_residual=True,
                shared_attention_norm=True,
                bias=True,
                lm_head_bias=True,
                norm_class_name="LayerNorm",
                norm_eps=hf.get("layer_norm_eps", 1e-5),
                mlp_class_name="GptNeoxMLP",
                gelu_approximate="tanh",
                intermediate_size=hf.get("intermediate_size"),
                rope_base=int(hf.get("rope_theta", 10000)),
            )
        elif mt == "gemma":
            data = dict(
                name=hf.get("_name_or_path", "gemma"),
                block_size=hf.get("max_position_embeddings", 8192),
                vocab_size=hf["vocab_size"],
                padded_vocab_size=hf["vocab_size"],
                n_layer=hf["num_hidden_layers"],
                n_head=hf["num_attention_heads"],
                n_embd=hf["hidden_size"],
                n_query_groups=hf.get("num_key_value_heads", 1),
                head_size=hf.get("head_dim"),
                rotary_percentage=1.0,
                parallel_residual=False,
                bias=False,
                norm_class_name="RMSNorm",
                norm_eps=hf.get("rms_norm_eps", 1e-6),
                mlp_class_name="GemmaMLP",
                gelu_approximate="tanh",
                intermediate_size=hf["intermediate_size"],
                rope_base=int(hf.get("rope_theta", 10000)),
                scale_embeddings=True,
                tie_embeddings=True,
                rmsnorm_add_unit_offset=True,
            )
        elif mt == "gpt_neox":
            data = dict(
                name=hf.get("_name_or_path", "gpt_neox"),
                block_size=hf.get("max_position_embeddings", 2048),
                vocab_size=hf["vocab_size"],
                padded_vocab_size=hf["vocab_size"],
                n_layer=hf["num_hidden_layers"],
                n_head=hf["num_attention_heads"],
                n_embd=hf["hidden_size"],
                rotary_percentage=hf.get("rotary_pct", 0.25),
                parallel_residual=hf.get("use_parallel_residual", True),
                bias=True,
                norm_class_name="LayerNorm",
                norm_eps=hf.get("layer_norm_eps", 1e-5),
                mlp_class_name="GptNeoxMLP",
                intermediate_size=hf.get("intermediate_size"),
                rope_base=int(hf.get("rotary_emb_base", 10000)),
                tie_embeddings=hf.get("tie_word_embeddings", False),
            )
        else:
            raise ValueError(f"unsupported HF model_type {mt!r}")
        data.update(overrides)
        return cls(**data)

    # ---- serialization -----------------------------------------------------

    def asdict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d.pop("rope_n_elem", None)
        return d

    def save(self, ckpt_dir: "str | Path") -> None:
        """Write `model_config.yaml` (reference `utils.py:608-611`)."""
        ckpt_dir = Path(ckpt_dir)
        ckpt_dir.mkdir(parents=True, exist_ok=True)
        lines = []
        for k, v in self.asdict().items():
            if isinstance(v, dict):
                lines.append(f"{k}:")
                for kk, vv in v.items():
                    lines.append(f"  {kk}: {_yaml_scalar(vv)}")
            else:
                lines.append(f"{k}: {_yaml_scalar(v)}")
        (ckpt_dir / "model_config.yaml").write_text("\n".join(lines) + "\n")

    def replace(self, **kw: Any) -> "Config":
        d = self.asdict()
        d.update(kw)
        return Config(**d)


@dataclass
class ServingConfig:
    """Knobs for the paged-KV continuous-batching engine
    (`serving.engine.ServingEngine`, built via `Generator.serve`).

    Not to be confused with `Config.block_size` (the model's context
    window): `block_size` HERE is the width of one KV pool block in tokens.
    """

    # KV pool geometry -------------------------------------------------------
    block_size: int = 16  # tokens per KV block (pool page width)
    max_blocks: Optional[int] = None  # pool size; None → full coverage
    # (1 trash block + max_batch × ceil(max_seq_length / block_size))
    # scheduling --------------------------------------------------------------
    max_batch: int = 8  # concurrent decode slots (jit batch shape)
    prefill_chunk: int = 128  # max prompt tokens one sequence feeds per step
    prefix_caching: bool = True  # hash-chain block reuse for shared prompts
    token_budget: Optional[int] = None  # unified-step token budget: the
    # mixed ragged batch packs every decode lane's pending token FIRST,
    # then prefill chunk tokens into the remainder, all in ONE forward of
    # static width `token_budget` (Sarathi-style composition; prompts
    # longer than the leftover split across steps).  None → max_batch +
    # prefill_chunk (every lane plus one full chunk).  Must exceed
    # max_batch or prefill could never progress (mdi-audit:
    # bad-token-budget)
    # decode dispatch ---------------------------------------------------------
    decode_chunk: int = 8  # device decode steps per host sync (lax.scan):
    # the host reads tokens once per K steps instead of per token, so the
    # dispatch RTT amortizes as RTT/K (docs/perf.md "Serving host-sync").
    # 1 = the per-step engine (one sync per token)
    double_buffer: bool = True  # dispatch chunk N+1 (chained on device
    # arrays) before reading chunk N, so the host read overlaps compute;
    # engaged only while no prefill/admission/preemption work is pending
    spec_k: int = 0  # speculative draft length for serving decode: per-slot
    # drafts (prompt n-gram lookup, or the optional draft model below)
    # verified in ONE ragged forward over the paged cache, emitting up to
    # K+1 tokens per sync.  At temperature 0 the verify is exact-match
    # (token-identical to plain decode); at temperature>0 it is the
    # rejection-sampled verify (accept draft token w.p.
    # min(1, p_verify/p_draft), else resample the residual) — emitted
    # tokens are distributed exactly as the per-step sampler's.
    # `spec_sampled` gates the verify rule.  None → auto: exact-match at
    # temperature 0, rejection-sampled at temperature>0.  False pins the
    # OLD greedy-only exact-match path (temperature>0 with spec_k then
    # refuses, naming this flag); True forces the sampled rule even at
    # temperature 0 (same tokens as exact-match there, by construction).
    spec_sampled: Optional[bool] = None
    # optional small draft model (a `Config.from_name` registry name) that
    # drafts spec_k tokens in one jitted greedy scan for slots where
    # `ngram_draft` misses.  It shares the paged-pool budget: a second
    # KVPool holds its blocks, carved out of `max_blocks` by `draft_share`
    # when the pool is bounded (full coverage when max_blocks is None).
    draft_model: Optional[str] = None
    # fraction of a bounded `max_blocks` budget handed to the draft pool
    # (block-count partition of the shared range; the draft model's
    # smaller per-block bytes make its slice cheap).  mdi-audit refuses a
    # share that leaves the TARGET pool below one slot's
    # chunk-reservation headroom (bad-serving-config).
    draft_share: float = 0.25
    # sampling (engine-wide: the decode step is one jitted batch) ------------
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    # attention backend: None → auto (Pallas kernel on TPU decode steps,
    # exact lax gather fallback elsewhere — tier-1 CPU tests use the latter)
    use_kernel: Optional[bool] = None
    # paged-pool storage dtype.  None → follow the engine's cache dtype
    # (the fp path, byte- and bit-identical to before this knob existed).
    # "int8" stores blocks as int8 with per-block-per-KV-group f32 scales
    # (quantize-on-scatter, dequantize inside the kernels' block loop —
    # ops/paged_attention.py), roughly doubling the blocks a fixed HBM
    # budget holds; other float names cast on write like the dense cache's
    # --kv-dtype.  Unknown names are refused via `dtype_bytes`.
    kv_dtype: Optional[str] = None
    # open-system front-end (server/frontend.py): bound on requests the
    # server has ACCEPTED but not yet seated in a decode slot (the
    # submission channel plus the scheduler's waiting queue).  Arrivals
    # past the bound are rejected with backpressure (HTTP 429) instead of
    # growing an unbounded queue whose tail latency no SLO survives.
    # None → the replay engine's behavior (no bound; mdi-serve queues the
    # whole trace) and the server default of 4 × max_batch.
    admission_queue: Optional[int] = None
    # host-RAM KV tier (serving/host_tier.py, docs/perf.md "Tiered KV"):
    # a pinned host-side block store sized in MiB.  0 = no tier — today's
    # recompute-on-preemption behavior, bit-for-bit.  When > 0, preempted
    # victims SWAP their (possibly int8) blocks to host instead of
    # recomputing (cost model permitting) and resume with zero re-prefill,
    # and cold prefix-cache chains spill to host instead of being dropped.
    host_pool_mib: int = 0
    # estimated host↔device link bandwidth in GB/s for the swap-vs-
    # recompute cost model.  None → the per-device-generation table
    # (host_tier.HOST_LINK_GBPS) keyed on device_kind; 0 disables swapping
    # entirely (and mdi-audit flags the dead tier: bad-host-tier).
    host_link_gbps: Optional[float] = None
    # blocks per jitted transfer quantum: swap-out gathers and restore
    # scatters run in fixed-width batches of this many blocks (padded with
    # the trash block), so the tier adds exactly TWO executables per
    # engine regardless of sequence length — zero post-warmup recompiles.
    swap_chunk_blocks: int = 8
    # spill evicted prefix-cache chains to the host tier (needs
    # prefix_caching; hits on spilled chains restore blocks and count as
    # prefix_hits_host).  False = the tier serves preemption swaps only.
    host_prefix_spill: bool = True

    def resolved_admission_queue(self) -> int:
        """The open-system admission-queue bound: `admission_queue` when
        set, else 4 × max_batch — deep enough to keep every slot fed
        through retirement churn, shallow enough that queue-wait cannot
        silently dominate TTFT.  Shared by `server.ServingFrontend` and
        the mdi-audit `bad-server-config` checker."""
        if self.admission_queue is not None:
            return int(self.admission_queue)
        return 4 * self.max_batch

    def resolved_token_budget(self) -> int:
        """The unified serving step's per-dispatch token-axis width: every
        decode lane's pending token plus the prefill tokens that fit.
        `token_budget` when set, else max_batch + prefill_chunk — so the
        default always serves a full decode batch alongside one full
        prefill chunk.  Shared by the engine (the `_mixed_fn` compile
        shape) and the mdi-audit `bad-token-budget` checker."""
        if self.token_budget is not None:
            return int(self.token_budget)
        return self.max_batch + max(1, self.prefill_chunk)

    def reserve_headroom_blocks(self) -> int:
        """Worst-case blocks one live slot holds AHEAD of its written tokens
        under K-step chunk reservation (`decode_chunk`, doubled while a
        speculative second chunk is in flight under `double_buffer`) or
        speculative verify (`spec_k` + 1 writes), plus one block of
        partial-block slack.  The default full-coverage pool already bounds
        every slot at the window, so this only matters for hand-sized
        `max_blocks` pools — the mdi-audit serving checker uses it to refuse
        pools too small to hold even one slot's reservation."""
        ahead = max(1, self.decode_chunk, self.spec_k + 1)
        if self.double_buffer and self.spec_k == 0:
            ahead += max(1, self.decode_chunk)
        return -(-ahead // self.block_size) + 1

    def spec_verify_sampled(self) -> bool:
        """True iff the speculative verify uses the rejection-sampling
        rule (accept w.p. min(1, p_verify/p_draft), else resample the
        residual) instead of exact greedy match.  Auto (`spec_sampled` is
        None): sampled iff temperature > 0 — so greedy serving keeps the
        bit-identical exact-match path and sampling serving preserves the
        per-step distribution.  `spec_sampled=False` pins exact-match
        (the engine refuses temperature>0 with spec_k on that pin);
        `spec_sampled=True` forces the sampled rule everywhere."""
        if self.spec_sampled is not None:
            return bool(self.spec_sampled)
        return self.temperature != 0.0

    def num_draft_blocks(self, max_seq_length: int) -> int:
        """Draft-pool size in blocks (0 when no `draft_model`): the draft
        model's slice of the shared paged-pool budget.  Bounded pools
        (`max_blocks` set) partition the block range — the draft pool
        takes `draft_share` of `max_blocks` (at least 2: trash + one
        usable block) and `num_pool_blocks` hands the target the rest.
        Unbounded pools give the draft full coverage, same formula as the
        target's (the draft model's smaller per-block bytes keep that
        cheap)."""
        if not self.draft_model:
            return 0
        if self.max_blocks is not None:
            return max(2, int(int(self.max_blocks) * self.draft_share))
        per_seq = -(-int(max_seq_length) // self.block_size)
        return 1 + self.max_batch * per_seq

    def num_pool_blocks(self, max_seq_length: int) -> int:
        """TARGET pool size in blocks: `max_blocks` when set (minus the
        draft pool's `num_draft_blocks` slice when a draft model shares
        the bounded budget), else full coverage (1 trash block +
        max_batch × ceil(max_seq_length / block_size)) — the same default
        `serving.engine.ServingEngine` computes."""
        if self.max_blocks is not None:
            return int(self.max_blocks) - self.num_draft_blocks(max_seq_length)
        per_seq = -(-int(max_seq_length) // self.block_size)
        return 1 + self.max_batch * per_seq

    def draft_config(self) -> Optional["Config"]:
        """The draft model's `Config` (registry lookup on `draft_model`),
        or None — shared by the engine, mdi-audit's byte accounting and
        `trace_serving`'s abstract construction so all three price the
        same architecture."""
        if not self.draft_model:
            return None
        return Config.from_name(self.draft_model)

    def draft_pool_bytes(
        self,
        cfg: "Config",
        tp: int = 1,
        max_seq_length: Optional[int] = None,
        dtype="bfloat16",
    ) -> int:
        """Per-device HBM bytes of the DRAFT paged pool for draft model
        `cfg` (pass `draft_config()`): `num_draft_blocks` × the draft
        architecture's itemized `block_bytes` — byte-exact against the
        live engine's second pool, the contract `pool_bytes` keeps for
        the target.  0 when no draft model."""
        if not self.draft_model:
            return 0
        max_seq = int(min(max_seq_length or cfg.block_size, cfg.block_size))
        n_blocks = self.num_draft_blocks(max_seq)
        return n_blocks * self.block_bytes(cfg, dtype, tp=tp)["total_bytes"]

    def resolved_kv_dtype(self, default="bfloat16") -> str:
        """The pool's storage dtype NAME: `kv_dtype` when set, else the
        caller's `default` (the engine passes its cache dtype; audit passes
        the plan's).  Normalized to a string so byte accounting and the
        int8 branch key on one spelling."""
        dt = self.kv_dtype if self.kv_dtype is not None else default
        if isinstance(dt, str):
            return dt
        name = getattr(dt, "__name__", None) or getattr(dt, "name", None)
        return name or str(dt)

    def block_bytes(
        self, cfg: "Config", dtype="bfloat16", tp: int = 1
    ) -> Dict[str, Any]:
        """Itemized HBM bytes of ONE pool block, k + v across all layers —
        THE per-block cost model shared by `pool_bytes`, the mdi-audit
        breakdown and the `--hbm-gb` blocks-that-fit computation, so the
        three can never disagree (the pre-refactor `pool_bytes` pushed a
        bare dtype through `estimate_kv_bytes` with no room for the int8
        scale side arrays).

        Returns {"kv_dtype", "kv_bytes", "scale_bytes", "total_bytes"};
        int8 pools add the per-block-per-KV-group f32 scales
        (`ops/paged_attention.py` layout), every other dtype has
        scale_bytes 0.  `tp > 1` gives the PER-DEVICE slice (the KV-group
        axis shards when divisible — `paged_kv_spec` — scales included).
        Unknown dtype names raise via `dtype_bytes` (the refusal contract
        for `kv_dtype` values the byte table doesn't know)."""
        name = self.resolved_kv_dtype(dtype)
        item = dtype_bytes(name)  # raises on unknown names
        G = cfg.n_query_groups
        if tp > 1 and G % tp == 0:
            G //= int(tp)
        kv = 2 * cfg.n_layer * self.block_size * G * cfg.head_size * item
        scale = 2 * cfg.n_layer * G * 4 if name == "int8" else 0
        return {
            "kv_dtype": name,
            "kv_bytes": int(kv),
            "scale_bytes": int(scale),
            "total_bytes": int(kv + scale),
        }

    def pool_bytes(
        self, cfg: "Config", max_seq_length: Optional[int] = None, dtype="bfloat16"
    ) -> int:
        """HBM bytes of the paged KV pool for model `cfg`: num_pool_blocks ×
        the itemized `block_bytes` (k + v payload at the pool dtype, plus
        the int8 scale arrays) — byte-exact against
        `transformer.init_paged_kv_cache`'s live arrays at either dtype.
        `self.kv_dtype` wins over the `dtype` argument when set.
        Used by the mdi-audit memory checker and the bench/serve logs."""
        max_seq = int(min(max_seq_length or cfg.block_size, cfg.block_size))
        n_blocks = self.num_pool_blocks(max_seq)
        return n_blocks * self.block_bytes(cfg, dtype)["total_bytes"]

    def pool_bytes_per_device(
        self,
        cfg: "Config",
        tp: int = 1,
        max_seq_length: Optional[int] = None,
        dtype="bfloat16",
    ) -> int:
        """Per-device HBM bytes of the pool under a tp serving mesh: the
        KV-group axis shards over tp (`parallel.sharding.paged_kv_spec`,
        int8 scale arrays included), so each chip holds exactly 1/tp of
        every block's bytes.  Byte-exact against the live sharded engine
        because G % tp == 0 is a serving precondition
        (`validate_tp_divisibility`; mdi-audit errors with
        `bad-serving-mesh` otherwise and this falls back to the whole pool,
        mirroring the runtime's drop-indivisible-sharding rule)."""
        max_seq = int(min(max_seq_length or cfg.block_size, cfg.block_size))
        n_blocks = self.num_pool_blocks(max_seq)
        return n_blocks * self.block_bytes(cfg, dtype, tp=tp)["total_bytes"]

    def num_host_blocks(self, cfg: "Config", dtype="bfloat16") -> int:
        """Blocks the host tier holds: the `host_pool_mib` budget divided
        by the FULL (unsharded, tp=1) `block_bytes` — the host store keeps
        whole blocks even when the HBM pool shards over tp, so a block
        restored on a differently-sized mesh is still complete.  0 when
        the tier is off."""
        if self.host_pool_mib <= 0:
            return 0
        per_block = self.block_bytes(cfg, dtype, tp=1)["total_bytes"]
        if per_block <= 0:
            return 0
        return int(self.host_pool_mib * 2**20) // per_block

    def host_pool_bytes(self, cfg: "Config", dtype="bfloat16") -> int:
        """Host-RAM bytes the tier's block store actually allocates:
        whole blocks only (the MiB budget rounds DOWN to block granularity)
        — byte-exact against the live `host_tier.HostBlockStore` slabs,
        the same contract `pool_bytes` keeps with the HBM pool.  The
        mdi-audit `kv_pool` breakdown and `--host-gb` check read this."""
        n = self.num_host_blocks(cfg, dtype)
        return n * self.block_bytes(cfg, dtype, tp=1)["total_bytes"]

    def resolved_host_link_gbps(self, device_kind: Optional[str] = None) -> float:
        """Host↔device link bandwidth (GB/s) the swap cost model uses:
        `host_link_gbps` when set, else the per-device-generation table in
        `serving.host_tier.HOST_LINK_GBPS` keyed on `device_kind` (its
        conservative default covers CPU/unknown).  0 means swapping can
        never win — mdi-audit flags a tier configured that way."""
        if self.host_link_gbps is not None:
            return float(self.host_link_gbps)
        from mdi_llm_tpu.serving.host_tier import lookup_host_link_gbps

        return lookup_host_link_gbps(device_kind)


def _yaml_scalar(v: Any) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        return json.dumps(v)
    if isinstance(v, float):
        s = repr(v)
        # YAML 1.1 floats need a dot in the mantissa ("1e-05" parses as str)
        if "e" in s and "." not in s.split("e")[0]:
            s = s.replace("e", ".0e")
        return s
    return str(v)


def _parse_simple_yaml(text: str) -> Dict[str, Any]:
    """Minimal YAML subset parser for flat `model_config.yaml` files (scalars
    plus one level of nested dict, which is all litGPT/the reference emit).
    Avoids a hard pyyaml dependency; uses it when available."""
    try:
        import yaml  # type: ignore

        return yaml.safe_load(text)
    except ImportError:
        pass
    out: Dict[str, Any] = {}
    current: Optional[str] = None
    for raw in text.splitlines():
        if not raw.strip() or raw.lstrip().startswith("#"):
            continue
        indented = raw.startswith(("  ", "\t"))
        line = raw.strip()
        if ":" not in line:
            continue
        key, _, val = line.partition(":")
        key, val = key.strip(), val.strip()
        if indented and current is not None:
            out[current][key] = _yaml_value(val)
        elif val == "":
            current = key
            out[key] = {}
        else:
            current = None
            out[key] = _yaml_value(val)
    return out


def _yaml_value(v: str) -> Any:
    if v in ("null", "~", "None"):
        return None
    if v in ("true", "True"):
        return True
    if v in ("false", "False"):
        return False
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        pass
    if len(v) >= 2 and v[0] in "'\"" and v[-1] == v[0]:
        return v[1:-1]
    return v


# ===========================================================================
# Named-model registry.
#
# Capability parity with the reference registry (`src/sub/config.py:175-1669`,
# ~85 entries across ~20 families).  Entries are dicts (converted lazily by
# Config.from_name).  Specs come from the public model cards / litGPT.
# ===========================================================================

configs: List[Dict[str, Any]] = []


def _add(entry: Dict[str, Any], variants: Optional[List[str]] = None) -> None:
    if variants is None:
        configs.append(entry)
        return
    for v in variants:
        e = copy.deepcopy(entry)
        e["name"] = entry["name"].format(v)
        if "hf_config" in e:
            e["hf_config"] = dict(
                org=entry["hf_config"]["org"],
                name=entry["hf_config"]["name"].format(v),
            )
        configs.append(e)
    # keep the template too, so from_name can match novel suffixes
    t = copy.deepcopy(entry)
    t["_template"] = True
    configs.append(t)


_llama = dict(
    rotary_percentage=1.0,
    parallel_residual=False,
    bias=False,
    norm_class_name="RMSNorm",
    mlp_class_name="LLaMAMLP",
)

# ---- custom / scratch-trainable (reference NanoLlama, README.md:325-330) --
_add(
    dict(
        name="NanoLlama",
        hf_config=dict(org="custom", name="NanoLlama"),
        block_size=2048,
        vocab_size=32000,
        padding_multiple=64,
        n_layer=12,
        n_head=16,
        n_embd=1024,
        n_query_groups=16,
        norm_eps=1e-5,
        intermediate_size=5632,
        **_llama,
    )
)

# ---- TinyLlama (reference config.py:1606-1645) ----------------------------
_add(
    dict(
        name="tiny-llama-1.1b{}",
        hf_config=dict(org="TinyLlama", name="TinyLlama-1.1B{}"),
        block_size=2048,
        vocab_size=32000,
        padding_multiple=64,
        n_layer=22,
        n_head=32,
        n_embd=2048,
        n_query_groups=4,
        norm_eps=1e-5,
        intermediate_size=5632,
        **_llama,
    ),
    variants=["", "-intermediate-step-1431k-3T", "-chat", "-Chat-v1.0"],
)

# ---- Llama 2 (reference config.py:820-878) --------------------------------
for size, (L, D, H, G, I) in {
    "7b": (32, 4096, 32, 32, 11008),
    "13b": (40, 5120, 40, 40, 13824),
    "70b": (80, 8192, 64, 8, 28672),
}.items():
    _add(
        dict(
            name=f"Llama-2-{size}{{}}-hf",
            hf_config=dict(org="meta-llama", name=f"Llama-2-{size}{{}}-hf"),
            block_size=4096,
            vocab_size=32000,
            padding_multiple=64,
            n_layer=L,
            n_head=H,
            n_embd=D,
            n_query_groups=G,
            norm_eps=1e-5,
            intermediate_size=I,
            **_llama,
        ),
        variants=["", "-chat"],
    )

# ---- Llama 3 (reference config.py:880-924) --------------------------------
for size, (L, D, H, G, I) in {
    "8B": (32, 4096, 32, 8, 14336),
    "70B": (80, 8192, 64, 8, 28672),
}.items():
    _add(
        dict(
            name=f"Llama-3-{size}{{}}",
            hf_config=dict(org="meta-llama", name=f"Meta-Llama-3-{size}{{}}"),
            block_size=8192,
            vocab_size=128000,
            padded_vocab_size=128256,
            n_layer=L,
            n_head=H,
            n_embd=D,
            n_query_groups=G,
            norm_eps=1e-5,
            intermediate_size=I,
            rope_base=500000,
            **_llama,
        ),
        variants=["", "-Instruct"],
    )

# ---- CodeLlama (reference config.py:1060-1294) ----------------------------
for size, (L, D, H, G, I) in {
    "7b": (32, 4096, 32, 32, 11008),
    "13b": (40, 5120, 40, 40, 13824),
    "34b": (48, 8192, 64, 8, 22016),
    "70b": (80, 8192, 64, 8, 28672),
}.items():
    for flavor in ("", "-Python", "-Instruct"):
        _add(
            dict(
                name=f"CodeLlama-{size}{flavor}-hf",
                hf_config=dict(org="codellama", name=f"CodeLlama-{size}{flavor}-hf"),
                block_size=16384,
                vocab_size=32016,
                padding_multiple=16,
                n_layer=L,
                n_head=H,
                n_embd=D,
                n_query_groups=G,
                norm_eps=1e-5,
                intermediate_size=I,
                rope_base=1000000,
                **_llama,
            )
        )

# ---- Mistral / Mixtral (reference config.py:1487-1604) --------------------
_add(
    dict(
        name="Mistral-7B-{}v0.1",
        hf_config=dict(org="mistralai", name="Mistral-7B-{}v0.1"),
        block_size=4096,  # 32k with sliding window; litGPT caps at 4096
        vocab_size=32000,
        padding_multiple=512,
        n_layer=32,
        n_head=32,
        n_embd=4096,
        n_query_groups=8,
        norm_eps=1e-5,
        intermediate_size=14336,
        **_llama,
    ),
    variants=["", "Instruct-"],
)
_add(
    dict(
        name="Mixtral-8x7B-{}v0.1",
        hf_config=dict(org="mistralai", name="Mixtral-8x7B-{}v0.1"),
        block_size=32768,
        vocab_size=32000,
        padding_multiple=512,
        n_layer=32,
        n_head=32,
        n_embd=4096,
        n_query_groups=8,
        norm_eps=1e-5,
        intermediate_size=14336,
        rope_base=1000000,
        n_expert=8,
        n_expert_per_token=2,
        rotary_percentage=1.0,
        parallel_residual=False,
        bias=False,
        norm_class_name="RMSNorm",
        mlp_class_name="LLaMAMoE",
    ),
    variants=["", "Instruct-"],
)
for ver, vocab in (("v0.2", 32000), ("v0.3", 32768)):
    _add(
        dict(
            name=f"Mistral-7B-{{}}{ver}",
            hf_config=dict(org="mistralai", name=f"Mistral-7B-{{}}{ver}"),
            block_size=32768,
            vocab_size=vocab,
            padding_multiple=512,
            n_layer=32,
            n_head=32,
            n_embd=4096,
            n_query_groups=8,
            norm_eps=1e-5,
            intermediate_size=14336,
            rope_base=1000000,
            **_llama,
        ),
        variants=["", "Instruct-"],
    )

# ---- Pythia (reference config.py:283-397) ---------------------------------
for size, (L, D, H) in {
    "14m": (6, 128, 4),
    "31m": (6, 256, 8),
    "70m": (6, 512, 8),
    "160m": (12, 768, 12),
    "410m": (24, 1024, 16),
    "1b": (16, 2048, 8),
    "1.4b": (24, 2048, 16),
    "2.8b": (32, 2560, 32),
    "6.9b": (32, 4096, 32),
    "12b": (36, 5120, 40),
}.items():
    _add(
        dict(
            name=f"pythia-{size}{{}}",
            hf_config=dict(org="EleutherAI", name=f"pythia-{size}{{}}"),
            # 14m/31m were trained at shorter context (HF config.json)
            block_size={"14m": 512, "31m": 1024}.get(size, 2048),
            vocab_size=50254,
            padding_multiple=128,
            n_layer=L,
            n_head=H,
            n_embd=D,
            rotary_percentage=0.25,
            parallel_residual=True,
            bias=True,
            norm_class_name="LayerNorm",
            mlp_class_name="GptNeoxMLP",
        ),
        variants=["", "-deduped"],
    )

# ---- Dolly v2 (pythia-based, reference config.py:399-428) -----------------
for size, (L, D, H) in {"3b": (32, 2560, 32), "7b": (32, 4096, 32), "12b": (36, 5120, 40)}.items():
    _add(
        dict(
            name=f"dolly-v2-{size}",
            hf_config=dict(org="databricks", name=f"dolly-v2-{size}"),
            block_size=2048,
            vocab_size=50254,
            padded_vocab_size=50280,
            n_layer=L,
            n_head=H,
            n_embd=D,
            rotary_percentage=0.25,
            parallel_residual=True,
            bias=True,
            norm_class_name="LayerNorm",
            mlp_class_name="GptNeoxMLP",
        )
    )

# ---- RedPajama-INCITE (gpt-neox arch, reference config.py:430-470) --------
for nm, (L, D, H) in {
    "RedPajama-INCITE-{}-3B-v1": (32, 2560, 32),
    "RedPajama-INCITE-7B-{}": (32, 4096, 32),
    # early v0.1 naming of the 7B release (reference config.py:454-463)
    "RedPajama-INCITE-{}-7B-v0.1": (32, 4096, 32),
}.items():
    _add(
        dict(
            name=nm,
            hf_config=dict(org="togethercomputer", name=nm),
            block_size=2048,
            vocab_size=50254,
            padding_multiple=256,
            n_layer=L,
            n_head=H,
            n_embd=D,
            rotary_percentage=1.0,
            parallel_residual=False,
            bias=True,
            norm_class_name="LayerNorm",
            mlp_class_name="GptNeoxMLP",
        ),
        variants=["Base", "Chat", "Instruct"],
    )

# ---- Falcon (reference config.py:472-538) ---------------------------------
_add(
    dict(
        name="falcon-7b{}",
        hf_config=dict(org="tiiuae", name="falcon-7b{}"),
        block_size=2048,
        vocab_size=65024,
        padded_vocab_size=65024,
        n_layer=32,
        n_head=71,
        n_embd=4544,
        n_query_groups=1,
        rotary_percentage=1.0,
        parallel_residual=True,
        bias=False,
        shared_attention_norm=True,
        norm_class_name="LayerNorm",
        mlp_class_name="GptNeoxMLP",
    ),
    variants=["", "-instruct"],
)
_add(
    dict(
        name="falcon-40b{}",
        hf_config=dict(org="tiiuae", name="falcon-40b{}"),
        block_size=2048,
        vocab_size=65024,
        padded_vocab_size=65024,
        n_layer=60,
        n_head=128,
        n_embd=8192,
        n_query_groups=8,
        rotary_percentage=1.0,
        parallel_residual=True,
        bias=False,
        norm_class_name="LayerNorm",
        mlp_class_name="GptNeoxMLP",
    ),
    variants=["", "-instruct"],
)
_add(
    dict(
        name="falcon-180B{}",
        hf_config=dict(org="tiiuae", name="falcon-180B{}"),
        block_size=2048,
        vocab_size=65024,
        padded_vocab_size=65024,
        n_layer=80,
        n_head=232,
        n_embd=14848,
        n_query_groups=8,
        rotary_percentage=1.0,
        parallel_residual=True,
        bias=False,
        norm_class_name="LayerNorm",
        mlp_class_name="GptNeoxMLP",
    ),
    variants=["", "-chat"],
)

# ---- StableLM (reference config.py:180-280) -------------------------------
for nm, (L, D, H, bs) in {
    "stablelm-base-alpha-3b": (16, 4096, 32, 4096),
    "stablelm-base-alpha-7b": (16, 6144, 48, 4096),
    "stablelm-tuned-alpha-3b": (16, 4096, 32, 4096),
    "stablelm-tuned-alpha-7b": (16, 6144, 48, 4096),
}.items():
    _add(
        dict(
            name=nm,
            hf_config=dict(org="stabilityai", name=nm),
            block_size=bs,
            vocab_size=50254,
            padded_vocab_size=50432,
            n_layer=L,
            n_head=H,
            n_embd=D,
            rotary_percentage=0.25,
            parallel_residual=True,
            bias=True,
            norm_class_name="LayerNorm",
            mlp_class_name="GptNeoxMLP",
        )
    )
for nm in ("stablelm-3b-4e1t", "stablelm-zephyr-3b"):
    _add(
        dict(
            name=nm,
            hf_config=dict(org="stabilityai", name=nm),
            block_size=4096,
            vocab_size=50254,
            padding_multiple=512,
            n_layer=32,
            n_head=32,
            n_embd=2560,
            parallel_residual=False,
            bias=False,
            rotary_percentage=0.25,
            norm_class_name="LayerNorm",
            mlp_class_name="LLaMAMLP",
            intermediate_size=6912,
        )
    )

# ---- StableCode (gpt-neox arch; reference config.py:240-280) --------------
for nm, bs in {
    "stablecode-completion-alpha-3b": 16384,
    "stablecode-completion-alpha-3b-4k": 4096,
    "stablecode-instruct-alpha-3b": 4096,
}.items():
    _add(
        dict(
            name=nm,
            hf_config=dict(org="stabilityai", name=nm),
            block_size=bs,
            vocab_size=49152,
            n_layer=32,
            n_head=32,
            n_embd=2560,
            rotary_percentage=0.25,
            parallel_residual=True,
            bias=True,
            norm_class_name="LayerNorm",
            mlp_class_name="GptNeoxMLP",
        )
    )
_add(
    dict(
        name="stable-code-3b",
        hf_config=dict(org="stabilityai", name="stable-code-3b"),
        block_size=16384,
        vocab_size=50254,
        padded_vocab_size=50304,
        n_layer=32,
        n_head=32,
        n_embd=2560,
        rotary_percentage=0.25,
        parallel_residual=False,
        bias=False,
        norm_class_name="LayerNorm",
        mlp_class_name="LLaMAMLP",
        intermediate_size=6912,
    )
)

# ---- OpenLLaMA / Vicuna / LongChat / Nous-Hermes / Platypus ---------------
for nm, (org, (L, D, H, I, bs)) in {
    "open_llama_3b": ("openlm-research", (26, 3200, 32, 8640, 2048)),
    "open_llama_7b": ("openlm-research", (32, 4096, 32, 11008, 2048)),
    "open_llama_13b": ("openlm-research", (40, 5120, 40, 13824, 2048)),
    "vicuna-7b-v1.3": ("lmsys", (32, 4096, 32, 11008, 2048)),
    "vicuna-13b-v1.3": ("lmsys", (40, 5120, 40, 13824, 2048)),
    "vicuna-33b-v1.3": ("lmsys", (60, 6656, 52, 17920, 2048)),
    "vicuna-7b-v1.5": ("lmsys", (32, 4096, 32, 11008, 4096)),
    "vicuna-7b-v1.5-16k": ("lmsys", (32, 4096, 32, 11008, 16384)),
    "vicuna-13b-v1.5": ("lmsys", (40, 5120, 40, 13824, 4096)),
    "vicuna-13b-v1.5-16k": ("lmsys", (40, 5120, 40, 13824, 16384)),
    "longchat-7b-16k": ("lmsys", (32, 4096, 32, 11008, 16384)),
    "longchat-13b-16k": ("lmsys", (40, 5120, 40, 13824, 16384)),
    "Nous-Hermes-llama-2-7b": ("NousResearch", (32, 4096, 32, 11008, 4096)),
    "Nous-Hermes-13b": ("NousResearch", (40, 5120, 40, 13824, 2048)),
    "Nous-Hermes-Llama2-13b": ("NousResearch", (40, 5120, 40, 13824, 4096)),
    "Platypus-30B": ("garage-bAInd", (60, 6656, 52, 17920, 2048)),
    "Platypus2-7B": ("garage-bAInd", (32, 4096, 32, 11008, 4096)),
    "Platypus2-13B": ("garage-bAInd", (40, 5120, 40, 13824, 4096)),
    "Platypus2-70B": ("garage-bAInd", (80, 8192, 64, 28672, 4096)),
    "Platypus2-70B-instruct": ("garage-bAInd", (80, 8192, 64, 28672, 4096)),
    "Camel-Platypus2-13B": ("garage-bAInd", (40, 5120, 40, 13824, 4096)),
    "Camel-Platypus2-70B": ("garage-bAInd", (80, 8192, 64, 28672, 4096)),
    "Stable-Platypus2-13B": ("garage-bAInd", (40, 5120, 40, 13824, 4096)),
    "FreeWilly2": ("stabilityai", (80, 8192, 64, 28672, 4096)),
    "LLaMA-2-7B-32K": ("togethercomputer", (32, 4096, 32, 11008, 32768)),
}.items():
    groups = 8 if (L, D) in ((80, 8192),) else H
    _add(
        dict(
            name=nm,
            hf_config=dict(org=org, name=nm),
            block_size=bs,
            vocab_size=32000,
            padding_multiple=64,
            n_layer=L,
            n_head=H,
            n_embd=D,
            n_query_groups=groups,
            # longchat also uses 1e-6 (reference config.py:736,758)
            norm_eps=1e-6 if ("open_llama" in nm or "longchat" in nm) else 1e-5,
            intermediate_size=I,
            # long-context variants extend their base context via positional
            # interpolation: longchat 2k->16k and LLaMA-2-7B-32K 4k->32k
            # condense by 8, vicuna-v1.5-16k 4k->16k by 4 (reference
            # config.py:666,700,735,757,1445)
            **(
                dict(rope_condense_ratio=8)
                if nm == "LLaMA-2-7B-32K" or "longchat" in nm
                else dict(rope_condense_ratio=4)
                if nm.endswith("-16k")
                else {}
            ),
            **_llama,
        )
    )

# ---- Phi (reference config.py:1451-1485) ----------------------------------
_add(
    dict(
        name="phi-1_5",
        hf_config=dict(org="microsoft", name="phi-1_5"),
        block_size=2048,
        vocab_size=50257,
        padded_vocab_size=51200,
        n_layer=24,
        n_head=32,
        n_embd=2048,
        rotary_percentage=0.5,
        shared_attention_norm=True,
        parallel_residual=True,
        bias=True,
        lm_head_bias=True,
        norm_class_name="LayerNorm",
        mlp_class_name="GptNeoxMLP",
        gelu_approximate="tanh",
    )
)
_add(
    dict(
        name="phi-2",
        hf_config=dict(org="microsoft", name="phi-2"),
        block_size=2048,
        vocab_size=50257,
        padded_vocab_size=51200,
        n_layer=32,
        n_head=32,
        n_embd=2560,
        rotary_percentage=0.4,
        shared_attention_norm=True,
        parallel_residual=True,
        bias=True,
        lm_head_bias=True,
        norm_class_name="LayerNorm",
        mlp_class_name="GptNeoxMLP",
        gelu_approximate="tanh",
    )
)

# ---- Gemma / CodeGemma (reference config.py:930-1007) ---------------------
for nm, (L, D, H, G, hs, I) in {
    "Gemma-2b": (18, 2048, 8, 1, 256, 16384),
    "Gemma-2b-it": (18, 2048, 8, 1, 256, 16384),
    "Gemma-7b": (28, 3072, 16, 16, 256, 24576),
    "Gemma-7b-it": (28, 3072, 16, 16, 256, 24576),
    "CodeGemma-7b-it": (28, 3072, 16, 16, 256, 24576),
}.items():
    _add(
        dict(
            name=nm,
            hf_config=dict(org="google", name=nm.lower()),
            block_size=8192,
            vocab_size=256000,
            padded_vocab_size=256000,
            n_layer=L,
            n_head=H,
            n_embd=D,
            n_query_groups=G,
            head_size=hs,
            rotary_percentage=1.0,
            parallel_residual=False,
            bias=False,
            norm_class_name="RMSNorm",
            norm_eps=1e-6,
            mlp_class_name="GemmaMLP",
            gelu_approximate="tanh",
            intermediate_size=I,
            scale_embeddings=True,
            tie_embeddings=True,
            rmsnorm_add_unit_offset=True,
        )
    )

# ---- Danube2 (reference config.py:1009-1034) ------------------------------
_add(
    dict(
        name="Danube2-1.8b-chat",
        hf_config=dict(org="h2oai", name="h2o-danube2-1.8b-chat"),
        block_size=4096,
        vocab_size=32000,
        padding_multiple=64,
        n_layer=24,
        n_head=32,
        n_embd=2560,
        n_query_groups=8,
        norm_eps=1e-5,
        intermediate_size=6912,
        rope_base=10000,
        **_llama,
    )
)

# ---- Function-calling Llama 2 (reference config.py:1643-1662) -------------
_add(
    dict(
        name="Llama-2-7b-chat-hf-function-calling-v2",
        hf_config=dict(org="Trelis", name="Llama-2-7b-chat-hf-function-calling-v2"),
        block_size=4096,
        vocab_size=32000,
        padding_multiple=64,
        n_layer=32,
        n_head=32,
        n_embd=4096,
        norm_eps=1e-5,
        intermediate_size=11008,
        **_llama,
    )
)

# ---- GPT-2 family (legacy generation parity, old/GPT2/sub/model.py) -------
for nm, (L, D, H) in {
    "gpt2": (12, 768, 12),
    "gpt2-medium": (24, 1024, 16),
    "gpt2-large": (36, 1280, 20),
    "gpt2-xl": (48, 1600, 25),
}.items():
    _add(
        dict(
            name=nm,
            hf_config=dict(org="openai-community", name=nm),
            block_size=1024,
            vocab_size=50257,
            padding_multiple=64,
            n_layer=L,
            n_head=H,
            n_embd=D,
            rotary_percentage=0.0,
            pos_embedding="learned",
            parallel_residual=False,
            bias=True,
            norm_class_name="LayerNorm",
            mlp_class_name="GptNeoxMLP",
            gelu_approximate="tanh",  # HF gpt2 uses gelu_new
            tie_embeddings=True,
        )
    )

name_to_config: Dict[str, Dict[str, Any]] = {
    d["name"]: d for d in configs if "_template" not in d
}
