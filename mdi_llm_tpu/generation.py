"""Autoregressive generation engine: jitted prefill + decode, host loop.

TPU-native equivalent of the reference generation paths —
`GPT.generate`/`generate_chat` (`/root/reference/src/sub/model.py:460-573`)
and the single-device driver (`/root/reference/src/sample.py:131-214`):

- **Two jitted phases** (SURVEY.md §7 "shape polymorphism"): prefill pads the
  prompt to a power-of-two bucket (one compile per bucket) and gathers the
  last-valid-position logit per sample; decode is a fixed (B, 1) step.
  Sampling runs inside jit so only the token ids cross the host boundary.
- **Donated KV cache**: the cache argument is donated to the decode step, so
  XLA updates it in place in HBM (≡ `KVCache.index_copy_`).
- **Batched samples**: the reference round-robins ≥N samples over N pipeline
  nodes to keep them busy ("recurrent pipeline parallelism"); on one chip the
  analog is a batch axis over samples with per-sample positions — same
  per-sample KV-cache semantics (gptserver.py:751-784) without Python-object
  swapping.
- **Stop tokens** are detected host-side per emitted token against the
  style's stop sequences (≡ `detect_stop_tokens`, utils.py:185-225), and
  `find_eot` truncation happens at decode end.
- **Per-token timing** (`tok_time`) matches the reference's benchmark capture
  (`gptserver.py:904-956`): list of (token_index, elapsed_seconds).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from mdi_llm_tpu.config import TEMPERATURE, TOP_K, Config
from mdi_llm_tpu.models import transformer
from mdi_llm_tpu.utils.context_managers import catch_loop_errors
from mdi_llm_tpu.ops.quant import FLAG_TO_MODE
from mdi_llm_tpu.ops.sampling import (
    sample,
    sample_mode,
    sample_traced,
    sampling_operands,
)


# ---------------------------------------------------------------------------
# Stop-token utilities (host-side)
# ---------------------------------------------------------------------------


def detect_stop_tokens(tokens: Sequence[int], stop_sequences: Sequence[Sequence[int]]) -> bool:
    """True if `tokens` ends with any of the stop sequences."""
    for seq in stop_sequences:
        n = len(seq)
        if n and len(tokens) >= n and list(tokens[-n:]) == list(seq):
            return True
    return False


def find_eot(tokens: Sequence[int], stop_sequences: Sequence[Sequence[int]]) -> int:
    """Index of the first stop-sequence start in `tokens` (len(tokens) if
    none) — truncation point for decoding (≡ reference `find_eot`)."""
    tokens = list(tokens)
    best = len(tokens)
    for seq in stop_sequences:
        n = len(seq)
        if not n:
            continue
        for i in range(len(tokens) - n + 1):
            if tokens[i : i + n] == list(seq):
                best = min(best, i)
                break
    return best


class StopPrefixFilter:
    """Streaming stop-sequence suppression, shared by every streaming
    surface (generate_chat, pipeline chat): tokens are pushed as sampled;
    all but the trailing max_stop-1 (a potential stop-sequence prefix) are
    released to `emit`, and once a full stop sequence appears the stream
    ends without ever emitting any part of the marker."""

    def __init__(self, stop_sequences: Sequence[Sequence[int]], emit):
        self.stop_sequences = stop_sequences
        self.emit = emit
        self.hold = max(0, max((len(s) for s in stop_sequences), default=0) - 1)
        self.seen: List[int] = []
        self.emitted = 0
        self.stopped = False

    def push(self, tok: int) -> None:
        if self.stopped:
            return
        self.seen.append(tok)
        if detect_stop_tokens(self.seen, self.stop_sequences):
            # a shorter stop sequence may fire while longer-prefix tokens
            # are still held back; release everything before the stop start
            # so the stream matches the find_eot-trimmed result exactly
            cut = find_eot(self.seen, self.stop_sequences)
            while self.emitted < cut:
                self.emit(self.seen[self.emitted])
                self.emitted += 1
            self.stopped = True
            return
        while self.emitted < len(self.seen) - self.hold:
            self.emit(self.seen[self.emitted])
            self.emitted += 1

    def flush(self) -> None:
        """End of stream without a stop: release the held-back tail."""
        if self.stopped:
            return
        while self.emitted < len(self.seen):
            self.emit(self.seen[self.emitted])
            self.emitted += 1


def stop_filtered_stream(raw_stream, stop_sequences):
    """Wrap a raw sampled-token iterator with StopPrefixFilter semantics:
    yield tokens as they clear the hold-back window, end at a stop without
    ever emitting any part of the marker, flush the tail at exhaustion.
    The single implementation of the streaming stop contract, shared by
    every generate_chat backend (single-device/tp, sp)."""
    ready: List[int] = []
    filt = StopPrefixFilter(stop_sequences, ready.append)
    for t in raw_stream:
        filt.push(t)
        yield from ready
        ready.clear()
        if filt.stopped:
            return
    filt.flush()
    yield from ready


class StreamPrinter:
    """Incremental console printer for a token stream, shared by the chat
    and starter CLIs: stop-prefix hold-back (StopPrefixFilter) plus
    incremental re-decode so multi-byte/merged tokens print correctly
    (≡ reference chat.py:174-200).

    `push(tok)` feeds the filtered live stream; `emit(tok)` bypasses the
    filter (for sources that already filtered, e.g. generate_chat);
    `finish(final_tokens)` reconciles with the authoritative trimmed
    output — emitting any held-back or missed tail — and returns the
    printed token list."""

    def __init__(self, tokenizer, stop_sequences: Sequence[Sequence[int]], out=None):
        import sys

        self.tokenizer = tokenizer
        self.out = out or sys.stdout
        self.reply: List[int] = []
        self.printed = ""
        self.filter = StopPrefixFilter(stop_sequences, self.emit)

    def emit(self, tok: int) -> None:
        self.reply.append(tok)
        text = self.tokenizer.decode(np.asarray(self.reply))
        if text.startswith(self.printed):
            self.out.write(text[len(self.printed) :])
            self.out.flush()
            self.printed = text

    def push(self, tok: int) -> None:
        self.filter.push(tok)

    def finish(self, final_tokens: Sequence[int]) -> List[int]:
        for tok in list(final_tokens)[len(self.reply) :]:
            self.emit(tok)
        return self.reply


def ngram_draft(tokens: Sequence[int], k: int, ngram: int = 3) -> List[int]:
    """Prompt-lookup drafting for speculative decoding: find the most recent
    earlier occurrence of the trailing `ngram` tokens and propose the k
    tokens that followed it.  Cheap, model-free, and effective whenever the
    continuation echoes earlier context (code, structured text, chat)."""
    tokens = list(tokens)
    if len(tokens) <= ngram:
        return []
    tail = tokens[-ngram:]
    for start in range(len(tokens) - ngram - 1, -1, -1):
        if tokens[start : start + ngram] == tail:
            return tokens[start + ngram : start + ngram + k]
    return []


def _place_ep_quantized(params, mesh: Mesh, n_expert: int):
    """Place a (possibly quantized) MoE tree on an ep(+dp) mesh: every >=2-D
    leaf under an "experts" subtree shards axis 1 (the expert axis, after
    the stacked-layer axis) over "ep"; every other leaf replicates.  Works
    by position rather than leaf name, so weight_q/scale/weight_q4 layouts
    need no dedicated spec table.  Positional placement is guarded by shape:
    a future storage layout whose axis 1 is NOT the expert axis must fail
    loudly here, not mis-shard silently."""

    def walk(node, in_experts):
        if isinstance(node, dict):
            return {
                k: walk(v, in_experts or k == "experts") for k, v in node.items()
            }
        nd = np.ndim(node)
        if in_experts and nd >= 2:
            if node.shape[1] != n_expert:
                raise ValueError(
                    f"expert-subtree leaf has axis-1 size {node.shape[1]}, "
                    f"expected n_expert={n_expert}; this storage layout "
                    "needs its own ep placement rule"
                )
            spec = P(None, "ep", *([None] * (nd - 2)))
        else:
            spec = P(*([None] * nd))
        return jax.device_put(node, NamedSharding(mesh, spec))

    return walk(params, False)


def _bucket(n: int, minimum: int = 16) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def _cache_bucket(n: int, granularity: int = 256) -> int:
    """KV-cache length bucket: finer-grained than the pow2 prompt buckets.
    Decode attention reads the WHOLE cache buffer every step, so sizing it
    to the run (prompt+max_new rounded up) instead of max_seq_length
    directly cuts cache HBM traffic for short runs."""
    return max(granularity, -(-n // granularity) * granularity)


def _run_cache_len(max_seq_length: int, total_max: int, Tb: int) -> int:
    """Cache length for one run: covers the generation horizon AND the
    padded prompt bucket (prefill writes the whole Tb-wide chunk), capped at
    the engine maximum.  Callers must clamp Tb <= max_seq_length."""
    return min(max_seq_length, _cache_bucket(max(total_max, Tb)))


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


@dataclass
class GenerationStats:
    tok_time: List[Tuple[int, float]] = field(default_factory=list)
    prefill_s: float = 0.0
    decode_s: float = 0.0
    tokens_generated: int = 0
    # pipeline engine: full ring rotations executed (scheduling efficiency)
    rotations: int = 0
    # pipeline engine: lanes refilled token-by-token (partial-slot refills)
    token_fills: int = 0
    # Generator: batch compactions performed (early-stop lane reclaim)
    compactions: int = 0
    # True when the decode loop ended on Ctrl-C (partial output)
    interrupted: bool = False

    @property
    def tokens_per_s(self) -> float:
        return self.tokens_generated / self.decode_s if self.decode_s else 0.0


class Generator:
    """Compile-once, call-many generation driver for a single device (or a
    data-parallel sharded batch; pipeline generation lives in
    `mdi_llm_tpu.parallel.pipeline`)."""

    def __init__(
        self,
        cfg: Config,
        params: Any,
        max_seq_length: Optional[int] = None,
        cache_dtype=None,  # None → params dtype
        rng_seed: int = 1337,
        use_flash: Optional[bool] = None,  # None → auto (TPU backend)
        flash_min_len: int = 2048,  # engage flash at prompt buckets >= this
        quantize: Optional[str] = None,  # None | "int8" (weight-only) |
        # "w8a8" (dynamic activation quant, full int8 MXU matmuls)
        mesh: Optional[Mesh] = None,  # GSPMD dp/tp mesh: params laid out
        # under parallel/sharding.py's Megatron rules, XLA inserts the
        # collectives (beyond reference parity — the reference has no
        # tensor-parallel inference at all, SURVEY.md §2.4).  A mesh with an
        # "ep" axis on a MoE config switches the experts to token-dispatch
        # expert parallelism (parallel/expert.py, all_to_all over ICI)
        moe_capacity_factor: Optional[float] = None,  # None → exact (no
        # dropped assignments); a finite factor bounds the EP dispatch
        # buffers at the cost of Switch-style token drops
        scan_unroll: int = 1,  # layer-scan unroll factor: decode steps are
        # small, so XLA while-loop bookkeeping per layer is measurable;
        # unrolling trades compile time for loop overhead (bench
        # --scan-unroll to measure before changing the default)
        abstract: bool = False,  # trace-only construction (analysis/ir.py):
        # params stay a host-side stub tree (plan.abstract_params), nothing
        # is placed on a device, and the PRNG key is a ShapeDtypeStruct.
        # The resulting Generator/engine can build and abstractly trace
        # every executable but must never be dispatched
    ):
        self.cfg = cfg
        self.mesh = mesh
        self.abstract = bool(abstract)
        self._kv_sharding = None
        self._paged_kv_sharding = None
        self._paged_kv_scale_sharding = None
        self._dp = 1
        self._moe_impl = None
        if quantize not in (None, "none") and quantize not in FLAG_TO_MODE:
            raise ValueError(f"unknown quantize mode {quantize!r}")
        quantized = quantize in FLAG_TO_MODE
        # mesh-derived axis sizes, shared by the guard and sharding blocks
        tp_n = int(mesh.shape.get("tp", 1)) if mesh is not None else 1
        dp_n = int(mesh.shape.get("dp", 1)) if mesh is not None else 1
        ep_n = int(mesh.shape.get("ep", 1)) if mesh is not None else 1
        ep_moe = ep_n > 1 and cfg.mlp_class_name == "LLaMAMoE"
        if mesh is not None:
            from mdi_llm_tpu.ops.quant import tree_has_quantized

            # Structural check, not just the flag: a pre-quantized
            # checkpoint (prepare_model --quantize) loads with
            # quantize='none' but its tree still has weight_q/scale leaves.
            # Quantized trees shard fine on tp/dp meshes — the standard
            # Megatron specs adapt to the storage layouts
            # (sharding.adapt_specs_to_tree); ep-MoE meshes use the
            # positional expert placement below.
            quantized = quantized or tree_has_quantized(params)
        if quantize in FLAG_TO_MODE:
            from mdi_llm_tpu.ops.quant import quantize_params

            # quantization happens host-side (numpy); pin the tree on device
            # or every jit call re-uploads the whole model (under a mesh the
            # sharded placement below does the pinning)
            params = quantize_params(params, mode=FLAG_TO_MODE[quantize])
            if mesh is None and not abstract:
                params = jax.device_put(params)
        if mesh is not None:
            from mdi_llm_tpu.parallel.sharding import (
                shard_params,
                validate_tp_divisibility,
            )
            # vocab counts here: the Generator tp-shards embeddings/head
            validate_tp_divisibility(cfg, tp_n, check_vocab=True)
            ep_axis = None
            if ep_moe:
                if cfg.n_expert % ep_n:
                    raise ValueError(
                        f"ep={ep_n} does not divide n_expert={cfg.n_expert}"
                    )
                from mdi_llm_tpu.parallel.expert import ep_moe_forward

                ep_axis = "ep"
                self._moe_impl = partial(
                    ep_moe_forward,
                    mesh=mesh,
                    axis="ep",
                    capacity_factor=moe_capacity_factor,
                )
            if abstract:
                # trace-only: the divisibility validation above still ran,
                # but the stub tree stays host-side (shardings reach the
                # traces through the kv pool/operand ShapeDtypeStructs)
                pass
            elif quantized and ep_moe:
                # name-agnostic placement: leaves under an "experts" subtree
                # shard their (layer, expert, ...) expert axis over ep (this
                # covers weight_q/scale layouts too); all else replicates
                params = _place_ep_quantized(params, mesh, cfg.n_expert)
            else:
                # standard Megatron layout; quantized storage layouts map
                # onto it name-agnostically (adapt_specs_to_tree)
                params = shard_params(
                    params, cfg, mesh, "tp" if tp_n > 1 else None, ep_axis
                )
            self._dp = dp_n
            # KV cache (L, B, G, S, hs): batch on dp, KV groups on tp
            self._kv_sharding = NamedSharding(
                mesh,
                P(
                    None,
                    "dp" if dp_n > 1 else None,
                    "tp" if tp_n > 1 else None,
                ),
            )
            # serving engine's paged pool (L, NB, BS, G, hs): KV groups on
            # tp, every block resident on every device's head-slice.  The
            # int8 pool's (L, NB, G) scale arrays shard the same group axis
            from mdi_llm_tpu.parallel.sharding import (
                paged_kv_scale_spec,
                paged_kv_spec,
            )

            self._paged_kv_sharding = NamedSharding(
                mesh, paged_kv_spec("tp" if tp_n > 1 else None)
            )
            self._paged_kv_scale_sharding = NamedSharding(
                mesh, paged_kv_scale_spec("tp" if tp_n > 1 else None)
            )
        self.params = params
        if cache_dtype is None:
            cache_dtype = transformer.param_dtype(params)
        if use_flash is None:
            use_flash = jax.default_backend() == "tpu"
        self.use_flash = use_flash
        # v5e r3 measurements (TinyLlama bf16): XLA's fused attention wins
        # below ~2k (135 vs 145 ms at T=1024); flash wins 1.13x at T=2040
        # and its edge grows with the T^2 term.  Short buckets stay on XLA.
        self.flash_min_len = int(flash_min_len)
        self.max_seq_length = int(min(max_seq_length or cfg.block_size, cfg.block_size))
        self.cache_dtype = cache_dtype
        self.scan_unroll = int(scan_unroll)
        self.rope = transformer.get_rope_cache(cfg)
        if abstract:
            # shape/dtype of jax.random.PRNGKey(seed) without compiling the
            # threefry seed program (mdi-ir's zero-backend contract)
            self.key = jax.ShapeDtypeStruct((2,), np.uint32)
        else:
            self.key = jax.random.PRNGKey(rng_seed)
        self._prefill_fns: Dict[Tuple[int, int], Any] = {}
        self._decode_fns: Dict[int, Any] = {}
        self._decode_chunk_fns: Dict[Tuple[int, int], Any] = {}
        # serving-engine compiled fns, shared across ServingEngine instances
        # bound to this Generator (keyed by the serving knobs that shape the
        # trace): a bench warmup engine and its timed twin must reuse ONE
        # jit cache or the timed run re-traces every shape it warmed
        self._serve_fns: Dict[Any, Dict[Any, Any]] = {}
        # XLA ExecutableReports (obs/device.py), keyed (label, shape-key,
        # pool dtype) and shared across engines for the same reason the jit
        # cache is: AOT introspection happens once per executable per
        # Generator — during warmup — so a device-obs timed run never
        # lowers anything post-warm (the CompileGuard contract)
        self._exec_reports: Dict[Any, Any] = {}
        # sequential-path device introspection: attach_device_obs() sets a
        # DeviceReportRegistry and generate()'s prefill/decode-chunk
        # dispatches capture their cost sheets into it
        self.device_obs = None

    def attach_device_obs(self, registry) -> None:
        """Attach an `obs.device.DeviceReportRegistry`: subsequent
        `generate()` calls capture each compiled phase's XLA cost sheet
        (`ExecutableReport`, one AOT lower+compile per (path, shape) —
        side-band, zero device work, the jit cache untouched).  Pass None
        to detach.  The serving engine has its own hook via
        `ServingObserver(device=True)`; this one serves the sequential
        paths (docs/observability.md "Device-side observability")."""
        self.device_obs = registry

    def _dev_capture(self, label, key, fn, args, static_kwargs=None) -> None:
        """Capture-once hook on the sequential dispatch sites: a dict
        lookup when the report exists, one AOT introspection when not."""
        if self.device_obs is not None and self.device_obs.capture_enabled:
            self.device_obs.capture(label, key, fn, args, static_kwargs)

    def _place_kv(self, kv):
        """Lay a fresh KV cache over the inference mesh (no-op without one)."""
        if self._kv_sharding is None:
            return kv
        return jax.device_put(kv, self._kv_sharding)

    def _place_paged_kv(self, kv):
        """Lay the serving engine's pooled block cache over the mesh: KV
        groups sharded on tp (`parallel.sharding.paged_kv_spec`), block and
        token axes resident everywhere.  The int8 pool's 3-D scale leaves
        take the matching group-sharded `paged_kv_scale_spec` layout.
        No-op without a mesh."""
        if self._paged_kv_sharding is None:
            return kv
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(
                x,
                self._paged_kv_sharding if x.ndim == 5
                else self._paged_kv_scale_sharding,
            ),
            kv,
        )

    # -- compiled phases -----------------------------------------------------

    def _prefill_fn(self, B: int, T: int):
        if (B, T) not in self._prefill_fns:

            @partial(jax.jit, donate_argnums=(2,))
            def prefill(params, tokens, kv, true_len):
                logits, kv = transformer.forward(
                    self.cfg,
                    params,
                    tokens,
                    jnp.zeros((tokens.shape[0],), jnp.int32),
                    kv=kv,
                    rope=self.rope,
                    fresh_prefill=True,
                    # flash pays off on big tiles; small buckets stay on XLA
                    use_flash=self.use_flash and T >= self.flash_min_len,
                    # no unroll here: prefill tiles are large enough that
                    # loop bookkeeping is noise, and unrolled bodies
                    # multiply compile time per prompt bucket
                    moe_impl=self._moe_impl,
                )
                last = jnp.take_along_axis(
                    logits, (true_len - 1)[:, None, None], axis=1
                )[:, 0]
                return last, kv

            self._prefill_fns[(B, T)] = prefill
        return self._prefill_fns[(B, T)]

    def _broadcast_lanes_fn(self, B: int):
        """Replicate a 1-lane prefill result across B decode lanes (shared-
        prompt fast path): logits along axis 0, KV along the cache's batch
        axis 1 — (L, B, G, S, hs), transformer.init_kv_cache."""
        key_ = ("bcast", B)
        if key_ not in self._decode_chunk_fns:

            # no donation: the B-lane output cannot reuse the 1-lane buffer
            @jax.jit
            def bcast(last1, kv1):
                last = jnp.repeat(last1, B, axis=0)
                kv = jax.tree_util.tree_map(
                    lambda x: jnp.repeat(x, B, axis=1), kv1
                )
                return last, kv

            self._decode_chunk_fns[key_] = bcast
        return self._decode_chunk_fns[key_]

    def _decode_fn(self, B: int):
        if B not in self._decode_fns:

            # temperature/top_p are traced f32 operands — only the tiny
            # `mode` string and the int top_k key the jit cache, so sweeping
            # temperature never recompiles (mdi-lint: static-float-arg)
            @partial(jax.jit, donate_argnums=(2,), static_argnames=("mode", "top_k"))
            def decode(params, tokens, kv, input_pos, key, temperature, top_p,
                       mode, top_k):
                logits, kv = transformer.forward(
                    self.cfg, params, tokens, input_pos, kv=kv, rope=self.rope,
                    moe_impl=self._moe_impl, unroll=self.scan_unroll,
                )
                key, sub = jax.random.split(key)
                tok = sample_traced(
                    logits[:, -1], sub, temperature, top_p, mode=mode, top_k=top_k
                )
                return tok.astype(jnp.int32), kv, key

            self._decode_fns[B] = decode
        return self._decode_fns[B]

    def _decode_chunk_fn(self, B: int, n_steps: int):
        """K decode steps scanned inside one jit call — amortizes dispatch
        latency (critical when the chip sits behind an RPC tunnel).  Returns
        the K sampled tokens; stop detection happens between chunks."""
        key_ = (B, n_steps)
        if key_ not in self._decode_chunk_fns:

            # see _decode_fn: float knobs are traced so the cache keys only
            # on (mode, top_k), never on a float value
            @partial(
                jax.jit,
                donate_argnums=(2,),
                static_argnames=("mode", "top_k"),
            )
            def decode_chunk(params, tok0, kv, input_pos, key, temperature,
                             top_p, mode, top_k):
                def body(carry, _):
                    tok, kv, pos, key = carry
                    logits, kv = transformer.forward(
                        self.cfg, params, tok[:, None], pos, kv=kv, rope=self.rope,
                        moe_impl=self._moe_impl, unroll=self.scan_unroll,
                    )
                    key, sub = jax.random.split(key)
                    nxt = sample_traced(
                        logits[:, -1], sub, temperature, top_p,
                        mode=mode, top_k=top_k,
                    ).astype(jnp.int32)
                    return (nxt, kv, pos + 1, key), nxt

                (tok, kv, pos, key), toks = jax.lax.scan(
                    body, (tok0, kv, input_pos, key), None, length=n_steps
                )
                return toks, kv, key  # toks: (n_steps, B)

            self._decode_chunk_fns[key_] = decode_chunk
        return self._decode_chunk_fns[key_]

    def _verify_fn(self, T: int):
        """Greedy verification forward for speculative decoding: score T
        tokens (last accepted + T-1 drafted) in one pass, return the greedy
        successor at every position.  Exactness relies on attention masking
        strictly by absolute position (ops/attention.py), so stale cache
        entries past a rejected draft are invisible until overwritten."""
        key_ = ("verify", T)
        if key_ not in self._decode_chunk_fns:

            @partial(jax.jit, donate_argnums=(2,))
            def verify(params, tokens, kv, input_pos):
                logits, kv = transformer.forward(
                    self.cfg, params, tokens, input_pos, kv=kv, rope=self.rope,
                    moe_impl=self._moe_impl,
                )
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), kv

            self._decode_chunk_fns[key_] = verify
        return self._decode_chunk_fns[key_]

    # -- static enumeration (analysis/ir.py) ---------------------------------

    def enumerate_executables(
        self,
        batch_size: int = 1,
        prompt_len: int = 32,
        max_new_tokens: int = 32,
        chunk_size: int = 16,
        temperature: float = TEMPERATURE,
        top_k: Optional[int] = TOP_K,
        top_p: Optional[float] = None,
        speculative: Optional[int] = None,
        compact: bool = True,
    ) -> List[Any]:
        """The sequential `generate()` compile set for ONE workload shape,
        as abstract `ExecutableSpec`s: prefill at the prompt's pow2 bucket,
        the decode-chunk ladder (the full chunk width, the tail chunk, and
        every lane count batch compaction can gather down to), and the
        speculative verify forward when `speculative=K`.

        Unlike the serving engine's set (closed by construction — the
        zero-recompile contract), `generate()` retraces per workload shape
        BY DESIGN (prompt buckets, 256-granular cache lengths), so this is
        the nominal set for one (B, prompt_len, max_new_tokens) workload,
        for mdi-ir jaxpr inspection rather than closure proofs.  The
        shared-prefill broadcast variant (prompt-content dependent) and
        cache-pressure-clamped tail widths share these traced structures
        at other shapes and are not enumerated."""
        from mdi_llm_tpu.obs.device import ExecutableSpec, abstractify

        B = int(batch_size)
        if B < 1 or prompt_len < 1 or max_new_tokens < 1:
            raise ValueError("batch_size, prompt_len and max_new_tokens must be >= 1")
        total_max = prompt_len + max_new_tokens
        if total_max > self.max_seq_length:
            raise ValueError(
                f"prompt+generation length {total_max} exceeds max_seq_length "
                f"{self.max_seq_length}"
            )
        Tb = min(_bucket(prompt_len), self.max_seq_length)
        cache_len = _run_cache_len(self.max_seq_length, total_max, Tb)
        i32 = jnp.int32
        sds = jax.ShapeDtypeStruct
        params = abstractify(self.params)
        key = abstractify(self.key)

        def kv_abs(nb):
            t = jax.eval_shape(
                partial(
                    transformer.init_kv_cache,
                    self.cfg,
                    nb,
                    cache_len,
                    dtype=self.cache_dtype,
                )
            )
            if self._kv_sharding is not None:
                t = jax.tree_util.tree_map(
                    lambda l: sds(l.shape, l.dtype, sharding=self._kv_sharding),
                    t,
                )
            return t

        # every generate() dispatch takes params at argnum 0 and the dense
        # kv cache at argnum 2 (same role map as the serving engine's set)
        roles = {0: "params", 2: "kv"}
        specs = [
            ExecutableSpec(
                "prefill",
                (B, Tb),
                self._prefill_fn(B, Tb),
                (params, sds((B, Tb), i32), kv_abs(B), sds((B,), i32)),
                None,
                (2,),
                dict(roles),
            )
        ]
        statics = {"mode": sample_mode(temperature, top_k, top_p), "top_k": top_k}
        t_op = sds((), jnp.float32)
        p_op = sds((), jnp.float32)
        # decode-chunk widths the host loop dispatches: n starts at 1 (the
        # prefill-sampled token), so the full width is min(chunk_size,
        # max_new_tokens - 1) and the remainder rides in one tail chunk
        k_full = min(int(chunk_size), max_new_tokens - 1)
        widths = []
        if k_full >= 1:
            widths.append(k_full)
            tail = (max_new_tokens - 1) % k_full
            if tail and tail != k_full:
                widths.append(tail)
        # batch-compaction lane ladder: compaction gathers survivors into the
        # next pow2 bucket >= the live count, floored at min(4, B) and only
        # when the bucket is <= half the current lane count — so the
        # reachable lane counts are B plus every pow2 in [min(4, B), B // 2]
        lane_counts = {B}
        if compact and self.mesh is None:
            v = 1
            while v <= B // 2:
                if v >= min(4, B):
                    lane_counts.add(v)
                v *= 2
        for nb in sorted(lane_counts, reverse=True):
            kvn = kv_abs(nb)
            for w in widths:
                specs.append(
                    ExecutableSpec(
                        "decode_chunk",
                        (nb, w),
                        self._decode_chunk_fn(nb, w),
                        (params, sds((nb,), i32), kvn, sds((nb,), i32), key, t_op, p_op),
                        dict(statics),
                        (2,),
                        dict(roles),
                    )
                )
        if speculative:
            K = int(speculative)
            specs.append(
                ExecutableSpec(
                    "verify",
                    (K + 1,),
                    self._verify_fn(K + 1),
                    (params, sds((1, K + 1), i32), kv_abs(1), sds((1,), i32)),
                    None,
                    (2,),
                    dict(roles),
                )
            )
        return specs

    # -- public API ----------------------------------------------------------

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: int,
        temperature: float = TEMPERATURE,
        top_k: Optional[int] = TOP_K,
        top_p: Optional[float] = None,
        stop_sequences: Sequence[Sequence[int]] = (),
        stream_cb=None,
        chunk_size: int = 16,
        speculative: Optional[int] = None,
        compact: bool = True,
        shared_prefill: Optional[bool] = None,
    ) -> Tuple[List[List[int]], GenerationStats]:
        """Generate continuations for a batch of token-id prompts.

        Returns (full token lists incl. prompt, truncated at stop sequences)
        and timing stats.  `stream_cb(sample_idx, token)` is invoked per
        generated token when given (chat streaming).

        `chunk_size` decode steps run inside one jit call (`lax.scan`) to
        amortize host-dispatch latency; stop sequences are checked between
        chunks, so up to chunk_size-1 extra tokens are computed then
        discarded — the token stream itself is unchanged.

        `compact` (unmeshed runs only) reclaims lanes of early-stopped
        samples by gathering the survivors into a smaller batch, so decode
        HBM traffic tracks the LIVE sample count.  Greedy token streams
        are unchanged (pure gather); with temperature > 0 the surviving
        samples keep their distribution but not their exact RNG draws
        (the batch shape feeds the sampler) — pass compact=False for
        draw-level reproducibility across different stop configurations.

        `speculative=K` enables greedy speculative decoding with
        prompt-lookup (n-gram) drafting: K tokens are drafted from earlier
        context and verified in one forward pass, emitting up to K+1 tokens
        per dispatch.  Exact (token-identical to plain greedy); requires
        temperature == 0 and a single sample.

        `shared_prefill` (unmeshed runs only): when every prompt is
        identical (the reference's n-samples workload), prefill runs once
        at B=1 and the cache/logits broadcast across lanes.  Greedy
        streams are unchanged; with temperature > 0 the B=1 prefill may
        differ from the B-lane one in the last ULP (XLA accumulation
        order), shifting exact RNG draws.  The rule: `None` (default)
        auto-enables the fast path only for greedy decoding
        (temperature == 0), so identical-prompt SAMPLING workloads keep
        draw-level reproducibility with distinct-prompt batching out of
        the box; pass `True` to opt the broadcast path in regardless
        (cheaper, distribution unchanged), `False` to force per-lane
        prefill always.
        """
        if speculative:
            if temperature != 0.0 or len(prompts) != 1:
                raise ValueError(
                    "speculative decoding requires temperature=0 and exactly "
                    "one prompt (it is a latency optimization for B=1 greedy)"
                )
        B = len(prompts)
        lens = [len(p) for p in prompts]
        if min(lens) < 1:
            raise ValueError("empty prompt")
        max_len = max(lens)
        total_max = max_len + max_new_tokens
        if total_max > self.max_seq_length:
            raise ValueError(
                f"prompt+generation length {total_max} exceeds max_seq_length "
                f"{self.max_seq_length}; pass --sequence-length or shorten"
            )

        # clamp the pow2 bucket at the engine max so a non-pow2
        # max_seq_length can never leave the cache narrower than the chunk
        Tb = min(_bucket(max_len), self.max_seq_length)
        batch = np.zeros((B, Tb), np.int32)
        for i, p in enumerate(prompts):
            batch[i, : lens[i]] = np.asarray(p, np.int32)

        if B % self._dp:
            raise ValueError(
                f"batch of {B} samples must be divisible by the mesh's "
                f"dp={self._dp}"
            )
        # cache sized to this run, not the engine maximum (jit retraces per
        # cache shape; the 256-granularity keeps the shape set small)
        cache_len = _run_cache_len(self.max_seq_length, total_max, Tb)

        stats = GenerationStats()
        t0 = time.perf_counter()
        # N identical prompts (the reference's headline workload: n-samples
        # of one prompt, starter.py --n-samples) need only ONE lane of
        # prefill compute: run it at B=1 and broadcast the cache/logits
        # across lanes on device.  Unmeshed only — under dp/tp the lanes
        # and cache are sharded and the plain prefill is already parallel.
        p0 = list(prompts[0])
        if shared_prefill is None:  # auto: greedy only (see docstring rule)
            shared_prefill = temperature == 0.0
        shared = (
            shared_prefill and B > 1 and self.mesh is None
            and all(list(p) == p0 for p in prompts[1:])
        )
        if shared:
            kv1 = transformer.init_kv_cache(
                self.cfg, 1, cache_len, dtype=self.cache_dtype
            )
            last1, kv1 = self._prefill_fn(1, Tb)(
                self.params, jnp.asarray(batch[:1]), kv1,
                jnp.asarray(lens[:1], jnp.int32),
            )
            last_logits, kv = self._broadcast_lanes_fn(B)(last1, kv1)
        else:
            kv = self._place_kv(
                transformer.init_kv_cache(self.cfg, B, cache_len, dtype=self.cache_dtype)
            )
            pf = self._prefill_fn(B, Tb)
            self._dev_capture(
                "prefill", (B, Tb), pf,
                (self.params, batch, kv, np.asarray(lens, np.int32)),
            )
            last_logits, kv = pf(
                self.params, jnp.asarray(batch), kv, jnp.asarray(lens, jnp.int32)
            )
        # first sampled token (from prefill logits)
        self.key, sub = jax.random.split(self.key)
        tok = sample(last_logits, sub, temperature=temperature, top_k=top_k, top_p=top_p)
        tok = np.asarray(tok.astype(jnp.int32))
        stats.prefill_s = time.perf_counter() - t0

        out = [list(p) for p in prompts]
        done = [False] * B
        positions = np.asarray(lens, np.int32)
        t_dec = time.perf_counter()
        # decode lane -> original sample index (None = padding after a batch
        # compaction); every per-lane structure below is indexed through it
        lanes: List[Optional[int]] = list(range(B))

        def emit(toks_bvec, n_emitted):
            for b, j in enumerate(lanes):
                if j is not None and not done[j]:
                    out[j].append(int(toks_bvec[b]))
                    if stream_cb is not None:
                        stream_cb(j, int(toks_bvec[b]))
                    if detect_stop_tokens(out[j][lens[j] :], stop_sequences):
                        done[j] = True
            stats.tok_time.append((n_emitted, time.perf_counter() - t0))

        n = 1
        emit(tok, n)

        # ---- speculative fast path (B=1 greedy): draft K via n-gram lookup,
        # verify in one forward, emit the matching prefix + bonus token ----
        if speculative:
            K = int(speculative)
            # loop-invariant device operands hoisted: two tiny host->device
            # uploads per token would be pure RTT tax on a remote chip
            t_greedy, p_greedy = sampling_operands(0.0, top_p)
            with catch_loop_errors() as g_spec:
                while (
                    n < max_new_tokens
                    and not done[0]
                    and cache_len - int(positions[0]) - 1 >= K + 1
                ):
                    draft = ngram_draft(out[0], K)
                    if not draft:
                        # no lookup match: a (K+1)-wide verify would burn
                        # (K+1)x the step cost to emit one token — run a
                        # plain chunked burst instead and retry drafting
                        c = min(
                            chunk_size,
                            max_new_tokens - n,
                            cache_len - int(positions[0]) - 1,
                        )
                        toks_j, kv, self.key = self._decode_chunk_fn(1, c)(
                            self.params,
                            jnp.asarray(tok, jnp.int32),
                            kv,
                            jnp.asarray(positions),
                            self.key,
                            t_greedy,
                            p_greedy,
                            mode="greedy",
                            top_k=top_k,
                        )
                        toks_np = np.asarray(toks_j)  # mdi-lint: disable=host-sync -- chunk-boundary read: one sync per c steps
                        fed = 0
                        for i in range(c):
                            n += 1
                            fed = i + 1
                            emit(toks_np[i], n)
                            if done[0]:
                                break
                        # advance by tokens actually emitted: a stop sequence
                        # mid-chunk must not leave positions pointing past the
                        # last real token (poisons continuation/cache reuse)
                        tok = toks_np[fed - 1]
                        positions = positions + fed
                        continue
                    emitted, kv = _verify_accept(
                        self, kv, tok, draft, K, positions
                    )
                    allowed = min(len(emitted), max_new_tokens - n)
                    fed = 0
                    for t in emitted[:allowed]:
                        n += 1
                        fed += 1
                        emit(np.asarray([t]), n)
                        if done[0]:
                            break
                    tok = np.asarray([emitted[fed - 1]], np.int32)
                    positions = positions + fed
            stats.interrupted = g_spec.interrupted
            # the plain loop below finishes any tail the cache window allows

        # mesh runs keep their lane count: the KV sharding is laid out for
        # the original dp-divisible batch
        compact_enabled = compact and self.mesh is None

        def compact_lanes():
            """Batch compaction: once enough samples have finished that the
            live set fits a power-of-two bucket <= half the current lane
            count, gather the surviving lanes (KV cache, last tokens,
            positions) into the smaller batch — decode bytes/step are
            proportional to the lane count, so early-stopping workloads
            stop paying full-batch HBM traffic for dead lanes (the
            single-chip analog of the pipeline engine's slot refill).
            Greedy streams are unchanged (pure gather); sampled streams
            keep their distribution but not their exact draws."""
            nonlocal kv, tok, positions, lanes
            active = [b for b, j in enumerate(lanes) if j is not None and not done[j]]
            if not active or len(lanes) <= 1:
                return
            nB = 1
            while nB < len(active):
                nB *= 2
            # floor at 4 lanes: each new lane count compiles a fresh decode
            # chunk per chunk width, and below 4 lanes the reclaimed HBM
            # traffic can no longer repay a multi-second XLA compile
            nB = max(nB, min(4, len(lanes)))
            if nB > len(lanes) // 2:
                return
            sel = active + [active[0]] * (nB - len(active))
            sel_j = jnp.asarray(sel, jnp.int32)
            kv = {kk: vv[:, sel_j] for kk, vv in kv.items()}
            tok = tok[np.asarray(sel)]
            positions = positions[np.asarray(sel)]
            lanes = [lanes[b] for b in active] + [None] * (nB - len(active))
            stats.compactions += 1

        # loop-invariant sampling operands/mode hoisted out of the chunk loop
        t_op, p_op = sampling_operands(temperature, top_p)
        mode = sample_mode(temperature, top_k, top_p)
        # Ctrl-C mid-loop returns what was generated so far
        # (≡ catch_loop_errors clean shutdown, context_managers.py:16-57)
        with catch_loop_errors() as guard:
            while n < max_new_tokens and not all(done) and not stats.interrupted:
                if compact_enabled:
                    compact_lanes()
                room = cache_len - int(positions.max()) - 1
                k = min(chunk_size, max_new_tokens - n, room)
                if k < 1:
                    break
                dc = self._decode_chunk_fn(len(lanes), k)
                # tok/positions are host ndarrays here: the capture reads
                # shapes only, no device value is touched
                self._dev_capture(
                    "decode_chunk", (len(lanes), k), dc,
                    (self.params, tok.astype(np.int32), kv,
                     positions, self.key, t_op, p_op),
                    {"mode": mode, "top_k": top_k},
                )
                toks_j, kv, self.key = dc(
                    self.params,
                    jnp.asarray(tok, jnp.int32),
                    kv,
                    jnp.asarray(positions),
                    self.key,
                    t_op,
                    p_op,
                    mode=mode,
                    top_k=top_k,
                )
                toks_np = np.asarray(toks_j)  # (k, len(lanes))  # mdi-lint: disable=host-sync -- chunk-boundary read: one sync per k steps
                for i in range(k):
                    n += 1
                    emit(toks_np[i], n)
                tok = toks_np[-1]
                positions = positions + k

        stats.interrupted = stats.interrupted or guard.interrupted
        stats.decode_s = time.perf_counter() - t_dec
        stats.tokens_generated = sum(len(o) - l for o, l in zip(out, lens))

        # final truncation at the earliest stop sequence (≡ find_eot)
        trimmed = []
        for o, l in zip(out, lens):
            gen = o[l:]
            cut = find_eot(gen, stop_sequences)
            trimmed.append(o[: l + cut])
        return trimmed, stats

    def generate_chat(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        temperature: float = TEMPERATURE,
        top_k: Optional[int] = TOP_K,
        top_p: Optional[float] = None,
        stop_sequences: Sequence[Sequence[int]] = (),
    ) -> Iterator[int]:
        """Streaming single-sample generation (≡ `GPT.generate_chat`,
        model.py:526-573): yields tokens as they are sampled, buffering
        potential stop-sequence prefixes so a partial stop marker is never
        emitted."""
        # validate at call time: this method returns an inner generator, so
        # putting a raise in a generator body would defer it to the first
        # next(), after the caller may already be streaming
        if self._dp > 1:
            raise ValueError("streaming generates one sample; use a tp-only mesh")
        return stop_filtered_stream(
            self._generate_stream(
                prompt, max_new_tokens, temperature, top_k, top_p, stop_sequences
            ),
            stop_sequences,
        )

    def _generate_stream(self, prompt, max_new_tokens, temperature, top_k, top_p, stop_sequences):
        lens = len(prompt)
        total_max = lens + max_new_tokens
        if total_max > self.max_seq_length:
            raise ValueError("prompt too long for max_seq_length")
        Tb = min(_bucket(lens), self.max_seq_length)
        batch = np.zeros((1, Tb), np.int32)
        batch[0, :lens] = np.asarray(prompt, np.int32)
        cache_len = _run_cache_len(self.max_seq_length, total_max, Tb)
        kv = self._place_kv(
            transformer.init_kv_cache(self.cfg, 1, cache_len, dtype=self.cache_dtype)
        )
        last_logits, kv = self._prefill_fn(1, Tb)(
            self.params, jnp.asarray(batch), kv, jnp.asarray([lens], jnp.int32)
        )
        self.key, sub = jax.random.split(self.key)
        tok = sample(last_logits, sub, temperature=temperature, top_k=top_k, top_p=top_p)
        tok = np.asarray(tok.astype(jnp.int32))
        yield from _decode_token_stream(
            self, [kv], tok, lens, cache_len, max_new_tokens,
            temperature, top_k, top_p, stop_sequences,
        )

    def _grow_kv_fn(self, new_len: int):
        """Jitted cache growth for `ChatSession`: allocate the longer cache
        INSIDE jit and donate the old buffer, so XLA fuses zeros+copy into
        one materialization and releases the old KV HBM immediately —
        without donation both caches were live across the copy, a transient
        ~2x KV spike at every growth boundary (ADVICE.md round 5)."""
        key_ = ("grow", new_len)
        if key_ not in self._decode_chunk_fns:

            def grow(old):
                fresh = transformer.init_kv_cache(
                    self.cfg, 1, new_len, dtype=self.cache_dtype
                )
                return jax.tree_util.tree_map(
                    lambda big, small: jax.lax.dynamic_update_slice(
                        big, small.astype(big.dtype), (0,) * big.ndim
                    ),
                    fresh, old,
                )

            jit_kw: Dict[str, Any] = dict(donate_argnums=(0,))
            if self._kv_sharding is not None:
                jit_kw["out_shardings"] = self._kv_sharding
            self._decode_chunk_fns[key_] = jax.jit(grow, **jit_kw)
        return self._decode_chunk_fns[key_]

    def _prefill_at_fn(self, T: int):
        """Chunk prefill at a running cache offset (used by `ChatSession`):
        forward T tokens whose absolute start is `pos`, write their KV into
        the session cache, return the logits at the last real token.  Unlike
        `_prefill_fn` this attends THROUGH the cache buffer
        (fresh_prefill=False) so earlier turns' entries participate; masking
        is strictly by absolute position — the same contract the speculative
        `_verify_fn` relies on — so slots at or beyond the query position
        are invisible regardless of their contents."""
        key_ = ("chat_prefill", T)
        if key_ not in self._decode_chunk_fns:

            @partial(jax.jit, donate_argnums=(2,))
            def prefill_at(params, tokens, kv, pos, true_len):
                logits, kv = transformer.forward(
                    self.cfg, params, tokens, pos, kv=kv, rope=self.rope,
                    moe_impl=self._moe_impl,
                )
                last = jnp.take_along_axis(
                    logits, (true_len - 1)[:, None, None], axis=1
                )[:, 0]
                return last, kv

            self._decode_chunk_fns[key_] = prefill_at
        return self._decode_chunk_fns[key_]

    def chat_session(self) -> "ChatSession":
        """A stateful conversation handle with cross-turn KV reuse."""
        return ChatSession(self)

    def serve(self, serving=None, obs=None, policy=None, draft_gen=None,
              **knobs):
        """A paged-KV continuous-batching engine bound to this model
        (serving/engine.py): request queue, unified token-budget steps
        (decode lanes + prefill chunks in ONE ragged forward per
        dispatch), mid-batch retirement, prefix-cached blocks.

        Works on a single device or a parallel mesh: under
        `mesh={"tp": N}` the paged pool shards its KV-group axis across
        the chips (each holds its head-slice of every block) and every
        serving dispatch runs the same per-shard math as the dense tp
        forward — one all-reduce per layer.  Under `mesh={"pp": N}`
        (alone or composed with tp) the layers split over a recurrent
        pipeline ring and each stage owns its own shard of the paged
        pool (`serving.pipeline.PipelinedServingEngine`).  Unsupported
        meshes (dp > 1, ep/sp axes) are rejected HERE, before any pool
        is allocated.

        Pass a `ServingConfig`, or its fields as keywords::

            engine = gen.serve(block_size=16, max_batch=8)
            engine.add_request("r0", prompt_tokens, max_new_tokens=128)
            results, stats = engine.run()

        `obs` takes an `obs.ServingObserver` for request-lifecycle
        tracing and TTFT/TPOT percentile metrics — fed only at the
        engine's existing host-sync boundaries, so enabling it changes
        no dispatch, sync or compile behaviour (docs/observability.md).

        `policy` takes a `serving.policy.SchedulingPolicy` (or None for
        FCFS): admission order and prefill packing order become
        pluggable — priority classes, per-tenant fair share,
        TTFT-deadline EDF — while dispatch shapes and the sync cadence
        stay structurally identical (docs/serving.md "Scheduling
        policies").

        `draft_gen` takes a Generator for `ServingConfig.draft_model`'s
        checkpoint (same vocabulary as this model); None lets the engine
        random-init the named config — fine for benchmarks and tests,
        useless acceptance rates on real text.
        """
        from mdi_llm_tpu.config import ServingConfig
        from mdi_llm_tpu.serving.engine import (
            ServingEngine,
            validate_serving_mesh,
        )

        # fail at serve() time with the offending axis named — not deep
        # inside engine init after the pool/scheduler are half-built
        validate_serving_mesh(self.mesh)
        if serving is None:
            serving = ServingConfig(**knobs)
        elif knobs:
            raise ValueError("pass a ServingConfig or keywords, not both")
        if self.mesh is not None and int(
            dict(self.mesh.shape).get("pp", 1)
        ) > 1:
            # pp axis present: stage the layers over the recurrent ring
            # (serving/pipeline.py), each stage owning its own shard of
            # the paged pool — the request/stats surface is identical
            from mdi_llm_tpu.serving.pipeline import PipelinedServingEngine

            return PipelinedServingEngine(self, serving, obs=obs, policy=policy)
        return ServingEngine(self, serving, obs=obs, policy=policy,
                             draft_gen=draft_gen)




def pad_draft(draft, K: int) -> List[int]:
    """Pad/trim an n-gram draft to exactly K tokens (0-padding; padded
    positions can only be rejected)."""
    return (list(draft) + [0] * K)[:K]


def accept_draft(draft, g, K: int) -> List[int]:
    """Longest-accepted-prefix rule shared by every speculative backend:
    `g[i]` is the greedy successor of ([tok]+draft)[i]; accept while the
    draft agrees, return the accepted tokens plus the bonus successor."""
    a = 0
    while a < K and draft[a] == int(g[a]):
        a += 1
    return [int(x) for x in g[: a + 1]]


def _verify_accept(gen: Generator, kv, tok, draft, K: int, positions):
    """Speculative verify-and-accept core, shared by `generate()`'s fast
    path and `ChatSession`: pad the draft to K, score [tok]+draft in one
    forward (`_verify_fn`), and return (burst, kv)."""
    draft = pad_draft(draft, K)
    toks_in = np.asarray([[int(tok[0])] + draft], np.int32)
    g, kv = gen._verify_fn(K + 1)(
        gen.params, jnp.asarray(toks_in), kv, jnp.asarray(positions)
    )
    return accept_draft(draft, np.asarray(g)[0], K), kv


def _decode_token_stream(
    gen: Generator,
    kvbox: List[Any],
    first_tok: np.ndarray,
    start_pos: int,
    cache_len: int,
    max_new: int,
    temperature, top_k, top_p, stop_sequences,
    fed: Optional[List[int]] = None,
):
    """Shared single-sample decode loop: yield raw sampled tokens one at a
    time (stop filtering is the caller's job).  `kvbox[0]` holds the live KV
    cache through the donation cycle so callers that persist the cache
    (ChatSession) see the latest buffer even if the stream is abandoned;
    `fed`, when given, counts tokens actually forwarded through the model
    (all but the final sampled one)."""
    decode = gen._decode_fn(1)
    tok = first_tok
    pos = np.asarray([start_pos], np.int32)
    # loop-invariant sampling operands: uploaded once, not per token
    t_op, p_op = sampling_operands(temperature, top_p)
    mode = sample_mode(temperature, top_k, top_p)
    emitted: List[int] = []
    for i in range(max_new):
        t = int(tok[0])
        emitted.append(t)
        yield t
        if detect_stop_tokens(emitted, stop_sequences):
            return
        if i == max_new - 1 or int(pos[0]) + 1 >= cache_len:
            return
        kv_in, kvbox[0] = kvbox[0], None  # donated
        tok_j, kv_out, gen.key = decode(
            gen.params, jnp.asarray(tok)[:, None], kv_in, jnp.asarray(pos),
            gen.key, t_op, p_op, mode=mode, top_k=top_k,
        )
        kvbox[0] = kv_out
        tok = np.asarray(tok_j)  # mdi-lint: disable=host-sync -- per-token stream: yielding each token IS the product
        if fed is not None:
            fed[0] += 1
        pos = pos + 1


class ChatSession:
    """Cross-turn KV reuse for interactive chat — a TPU-first upgrade over
    the reference REPL (chat.py:36-54,174-200), which re-runs prefill over
    the ENTIRE conversation every turn.  The session keeps one KV cache and
    a running position; each `send` prefills only the new tokens at that
    offset, so turn latency scales with the turn length, not the
    conversation length.

    Works on any Generator backend (single-device, tp, ep, quantized).
    Compile shapes stay bounded: turn prefills use power-of-two buckets
    only (the session slides the window early rather than compile an
    arbitrary residual width), and the cache grows geometrically from the
    first turn's run-sized length toward `max_seq_length` (decode HBM
    traffic tracks the conversation, and growth recompiles are O(log)).

    State invariant between sends: `history` is the logical conversation;
    the cache holds real entries for all of it except the trailing
    `_pending` tokens (at most the final sampled reply token, which was
    never fed through the model).  Rolled-back slots (stop-marker tokens
    trimmed from a reply) are dead by the absolute-position masking
    contract and are overwritten by the next turn's prefill.
    """

    def __init__(self, gen: Generator):
        if gen._dp > 1:
            raise ValueError("chat session streams one sample; use a tp-only mesh")
        self.gen = gen
        self.reset()

    def reset(self) -> None:
        self.history: List[int] = []
        self._kvbox: List[Any] = [None]
        self._cache_len = 0
        self._pos = 0  # cache slots holding real (attendable) entries
        self._pending: List[int] = []  # history tail not yet in the cache

    def rollback(self, history: Sequence[int]) -> None:
        """Restore a logical conversation (e.g. after interrupting a reply
        mid-stream): the cache is rebuilt by one full prefill on the next
        send, the same cost the stateless REPL pays every turn."""
        self.reset()
        self.history = list(history)
        self._pending = list(history)

    @property
    def capacity(self) -> int:
        return self.gen.max_seq_length

    @property
    def used(self) -> int:
        return len(self.history)

    def send(
        self,
        turn: Sequence[int],
        max_new_tokens: int,
        temperature: float = TEMPERATURE,
        top_k: Optional[int] = TOP_K,
        top_p: Optional[float] = None,
        stop_sequences: Sequence[Sequence[int]] = (),
        speculative: Optional[int] = None,
    ) -> Iterator[int]:
        """Stream the reply to `turn` (stop-filtered, like generate_chat).
        Session state updates as the iterator is consumed; exhaust it before
        the next send.

        `speculative=K` (greedy only): draft K tokens by prompt-lookup over
        the WHOLE conversation — chat replies echo earlier turns, which is
        exactly the regime where n-gram drafting hits — and verify them in
        one forward pass, emitting up to K+1 tokens per dispatch.  Exact
        (token-identical to the plain stream)."""
        turn = list(turn)
        max_new = int(max_new_tokens)
        if not turn:
            raise ValueError("empty turn")
        if max_new + 1 >= self.gen.max_seq_length:
            raise ValueError("max_new_tokens too large for max_seq_length")
        if speculative and temperature != 0.0:
            raise ValueError("speculative chat requires temperature=0")
        return self._send(
            turn, max_new, temperature, top_k, top_p, stop_sequences,
            speculative=int(speculative) if speculative else None,
        )

    def _grow_cache(self, needed: int) -> None:
        """Ensure the cache covers `needed` slots: grow geometrically (at
        least doubling, 256-slot granularity) and copy existing entries into
        the leading corner — dynamic_update_slice at the origin is layout-
        agnostic in which axis is the sequence.  The grow/copy runs as one
        jitted call with the OLD buffer donated (`Generator._grow_kv_fn`),
        so growth no longer holds two live KV caches."""
        gen = self.gen
        if self._cache_len >= needed:
            return
        new_len = min(
            gen.max_seq_length,
            max(_cache_bucket(needed), 2 * self._cache_len),
        )
        old = self._kvbox[0]
        if old is None or self._pos == 0:
            self._kvbox[0] = gen._place_kv(
                transformer.init_kv_cache(gen.cfg, 1, new_len, dtype=gen.cache_dtype)
            )
        else:
            self._kvbox[0] = None  # donated to the grow fn
            self._kvbox[0] = gen._grow_kv_fn(new_len)(old)
        self._cache_len = new_len

    def _spec_raw_stream(
        self, tok0, prompt_end, cache_len, max_new, K, top_k, top_p,
        stop_sequences, posbox,
    ):
        """Greedy speculative raw stream for a session turn: draft K tokens
        by n-gram lookup over conversation+reply, verify in one forward
        (`_verify_fn`), emit the matching prefix + bonus token.  Falls back
        to single plain decode steps when no draft is found or the cache is
        nearly full.  `posbox[0]` tracks the absolute position of the
        current (unfed) token so the caller can reconcile cache state."""
        gen = self.gen
        tok = tok0
        pos = prompt_end  # absolute slot of the current unfed token
        t_greedy, p_greedy = sampling_operands(0.0, top_p)  # loop-invariant
        emitted: List[int] = [int(tok[0])]
        posbox[0] = pos
        yield emitted[0]
        miss_skip = 0  # after a lookup miss, decode a few plain steps
        # before rescanning: the O(history) host-side n-gram scan per token
        # would otherwise rival the device step cost on non-echoing replies
        while len(emitted) < max_new:
            if detect_stop_tokens(emitted, stop_sequences):
                return
            room = cache_len - pos - 1
            if room < 1:
                return
            draft = []
            if room >= K + 1 and miss_skip == 0:
                draft = ngram_draft(self.history + emitted, K)
                if not draft:
                    miss_skip = 4
            if draft:
                kv_in, self._kvbox[0] = self._kvbox[0], None  # donated
                burst, kv_out = _verify_accept(
                    gen, kv_in, tok, draft, K, [pos]
                )
                self._kvbox[0] = kv_out
                fed = 0
                stopped = False
                for t in burst[: max_new - len(emitted)]:
                    emitted.append(t)
                    fed += 1
                    yield t
                    if detect_stop_tokens(emitted, stop_sequences):
                        stopped = True
                        break
                tok = np.asarray([emitted[-1]], np.int32)
                pos += fed
                posbox[0] = pos
                if stopped:
                    return
            else:
                miss_skip = max(0, miss_skip - 1)
                kv_in, self._kvbox[0] = self._kvbox[0], None  # donated
                tok_j, kv_out, gen.key = gen._decode_fn(1)(
                    gen.params, jnp.asarray(tok)[:, None], kv_in,
                    jnp.asarray([pos], jnp.int32), gen.key,
                    t_greedy, p_greedy, mode="greedy", top_k=top_k,
                )
                self._kvbox[0] = kv_out
                tok = np.asarray(tok_j)  # mdi-lint: disable=host-sync -- per-token stream fallback between drafts
                pos += 1
                posbox[0] = pos
                emitted.append(int(tok[0]))
                yield emitted[-1]

    def _send(self, turn, max_new, temperature, top_k, top_p, stop_sequences,
              speculative=None):
        gen = self.gen
        cap = gen.max_seq_length
        self.history.extend(turn)
        feed = self._pending + turn
        lens = len(feed)
        # Slide the window when the conversation outgrows capacity
        # (reference behavior) — and also, at a nonzero offset, when the
        # pow2 prefill bucket no longer fits: compiling an arbitrary
        # residual width would add a one-off jit shape per boundary turn,
        # so pay one full re-prefill instead and keep the shape set small.
        fits_exact = self._pos + lens + max_new + 1 <= cap
        fits_bucket = self._pos + _bucket(lens) + max_new + 1 <= cap
        if not fits_exact or (self._pos > 0 and not fits_bucket):
            window = self.history[-(cap - max_new - 1):]
            self._kvbox, self._cache_len = [None], 0
            self._pos, self._pending = 0, []
            self.history = list(window)
            feed = window
            lens = len(feed)
        fresh_start = self._pos == 0
        Tb = min(_bucket(lens), cap) if fresh_start else _bucket(lens)
        self._grow_cache(min(cap, self._pos + max(Tb, lens + max_new)))
        cache_len = self._cache_len
        batch = np.zeros((1, Tb), np.int32)
        batch[0, :lens] = np.asarray(feed, np.int32)
        kv, self._kvbox[0] = self._kvbox[0], None  # donated to prefill
        if fresh_start:
            # empty cache at offset 0: the fresh-prefill path applies (and
            # engages the Pallas flash kernel on long pasted prompts)
            last, kv = gen._prefill_fn(1, Tb)(
                gen.params, jnp.asarray(batch), kv, jnp.asarray([lens], jnp.int32)
            )
        else:
            last, kv = gen._prefill_at_fn(Tb)(
                gen.params, jnp.asarray(batch), kv,
                jnp.asarray([self._pos], jnp.int32),
                jnp.asarray([lens], jnp.int32),
            )
        self._kvbox[0] = kv
        prompt_end = self._pos + lens
        self._pos = prompt_end
        self._pending = []

        gen.key, sub = jax.random.split(gen.key)
        tok = sample(last, sub, temperature=temperature, top_k=top_k, top_p=top_p)
        tok = np.asarray(tok.astype(jnp.int32))
        if speculative:
            posbox = [prompt_end]
            raw = self._spec_raw_stream(
                tok, prompt_end, cache_len, max_new, speculative,
                top_k, top_p, stop_sequences, posbox,
            )
        else:
            fed = [0]
            raw = _decode_token_stream(
                gen, self._kvbox, tok, prompt_end, cache_len, max_new,
                temperature, top_k, top_p, stop_sequences, fed=fed,
            )
        reply: List[int] = []
        for t in stop_filtered_stream(raw, stop_sequences):
            reply.append(t)
            yield t
        # reconcile: the cache holds prompt + the fed reply prefix; the
        # logical reply may be shorter (stop marker trimmed -> roll back
        # those slots) or longer than what was fed (the final sampled token
        # — or a speculative bonus burst — was never fed -> carry as
        # pending for the next turn's prefill)
        self.history.extend(reply)
        advance = (posbox[0] - prompt_end) if speculative else fed[0]
        keep = min(len(reply), advance)
        self._pos = prompt_end + keep
        self._pending = reply[keep:]
