"""mdi_llm_tpu — TPU-native model-distributed LLM inference & training.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
davmacario/MDI-LLM (recurrent pipeline-parallel LLM inference across devices,
single-device generation/chat, training, checkpoint tooling) for TPU
hardware: pjit/shard_map over device meshes, ppermute activation hops over
ICI/DCN, layer-stacked scanned transformer blocks, functional KV caches.
"""

__version__ = "0.1.0"

from mdi_llm_tpu.config import Config, name_to_config

__all__ = ["Config", "name_to_config", "__version__"]
