"""Host-RAM KV tier: a pinned block store behind the paged HBM pool.

The serving scale ceiling is KV bytes resident in HBM: pool exhaustion
triggers recompute preemption (`scheduler.preempt_latest` — generated
work thrown away and re-prefilled) and the hash-chain prefix cache drops
cold chains at capacity (`kv_pool.KVPool._take`).  This module adds the
tier both paths fall back to instead:

- **Swap on preemption**: a victim's blocks copy to host slots through a
  fixed-width jitted gather (`engine._fetch_blocks_fn`), the HBM blocks
  return to the free list immediately, and on resume the payload
  restores through explicit `jax.device_put`s + a donated scatter
  overlapped behind the next dispatch — the sequence re-enters
  mid-generation with zero re-prefill.
- **Prefix spill**: chains evicted from the HBM prefix cache land in
  host slots keyed by the SAME chain hash; a later `match_prefix` hit on
  a spilled block restores it and counts as `prefix_hits_host`.

Everything here is host-side bookkeeping over numpy slabs — no jax
imports, no device placement.  The device interaction (gather/scatter
executables, explicit transfers at host-sync boundaries) stays in
`serving/engine.py`; the split mirrors `KVPool`, whose tables are
likewise device-blind (docs/perf.md "Tiered KV").

Content state walks `hbm → in-flight → host → hbm`: "in-flight" is a
gather snapshot whose device→host copy has not materialized yet (the
HBM blocks are already free — the snapshot owns the bytes); the engine
materializes pending snapshots at the next host-sync boundary.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "HOST_LINK_GBPS",
    "DEFAULT_HOST_LINK_GBPS",
    "lookup_host_link_gbps",
    "SwapCostModel",
    "HostBlockStore",
    "HostTier",
    "SwapRecord",
]

# Host↔device link bandwidth priors in GB/s per device generation: PCIe
# gen3-x16-ish for v4/v5e boards, gen4/5 for v5p/v6e, a conservative
# default for CPU and unknown kinds.  Priors only — the cost model
# EWMA-corrects toward measured transfer rates as swaps happen.
HOST_LINK_GBPS: Dict[str, float] = {
    "TPU v4": 16.0,
    "TPU v5 lite": 16.0,
    "TPU v5e": 16.0,
    "TPU v5p": 32.0,
    "TPU v5": 32.0,
    "TPU v6 lite": 32.0,
    "TPU v6e": 32.0,
    "TPU v6": 32.0,
}
DEFAULT_HOST_LINK_GBPS = 8.0


def lookup_host_link_gbps(device_kind: Optional[str]) -> float:
    """Longest-prefix match of `device_kind` against the generation
    table; unknown kinds (CPU, new TPUs) get the conservative default."""
    if device_kind:
        best = ""
        for kind in HOST_LINK_GBPS:
            if device_kind.startswith(kind) and len(kind) > len(best):
                best = kind
        if best:
            return HOST_LINK_GBPS[best]
    return DEFAULT_HOST_LINK_GBPS


@dataclasses.dataclass
class SwapCostModel:
    """Swap-vs-recompute decision for one preemption victim.

    Swapping a victim costs a round trip of its block bytes over the
    host link; recomputing costs re-prefilling every token it had fed.
    Both sides start from priors (`link_gbps` from the device-generation
    table, `prefill_tokens_per_s` from a deliberately pessimistic
    default) and EWMA-correct toward measured rates, so the decision
    tracks the actual machine rather than the table.  `clock` is
    injectable for deterministic unit tests."""

    link_gbps: float
    prefill_tokens_per_s: float = 2000.0
    ewma: float = 0.25
    clock: Callable[[], float] = time.perf_counter

    def swap_seconds(self, nbytes: int) -> float:
        """One-way transfer time for `nbytes` at the estimated link BW."""
        if self.link_gbps <= 0:
            return float("inf")
        return nbytes / (self.link_gbps * 1e9)

    def recompute_seconds(self, refill_tokens: int) -> float:
        return refill_tokens / max(self.prefill_tokens_per_s, 1e-9)

    def should_swap(self, nbytes: int, refill_tokens: int) -> bool:
        """True when the swap round trip (out + back in) beats
        re-prefilling `refill_tokens`.  A zero/negative-BW link can never
        win — mdi-audit flags a tier configured that way (bad-host-tier)."""
        if self.link_gbps <= 0:
            return False
        return 2.0 * self.swap_seconds(nbytes) < self.recompute_seconds(
            refill_tokens
        )

    def observe_transfer(self, nbytes: int, seconds: float) -> None:
        """Fold one measured host↔device transfer into the BW estimate."""
        if seconds <= 0 or nbytes <= 0:
            return
        measured = nbytes / (seconds * 1e9)
        self.link_gbps += self.ewma * (measured - self.link_gbps)

    def observe_prefill(self, tokens: int, seconds: float) -> None:
        """Fold one measured prefill burst into the recompute estimate."""
        if seconds <= 0 or tokens <= 0:
            return
        measured = tokens / seconds
        self.prefill_tokens_per_s += self.ewma * (
            measured - self.prefill_tokens_per_s
        )


class HostBlockStore:
    """Fixed-capacity pinned block store: one numpy slab per pool leaf.

    The slab layout is derived from the LIVE pool's leaf shapes with the
    block axis hoisted to the front — slot i of leaf j is
    ``slabs[j][i]``, one block's worth of that leaf (full, unsharded
    bytes: under tp the pool leaves' GLOBAL shapes feed the template, so
    a stored block is complete regardless of the mesh it left).  Total
    `nbytes` is exactly ``num_slots × ServingConfig.block_bytes(tp=1)``
    for the flat pool layout — the byte-exactness contract the mdi-audit
    `host_pool_bytes` breakdown pins.

    Allocation mirrors `KVPool`: LIFO free list, all-or-nothing
    `alloc`."""

    def __init__(
        self,
        leaf_shapes: Sequence[Tuple[Tuple[int, ...], Any]],
        block_axis: int,
        num_slots: int,
    ):
        self.block_axis = int(block_axis)
        self.num_slots = int(num_slots)
        self.slabs: List[np.ndarray] = []
        for shape, dtype in leaf_shapes:
            ba = self.block_axis
            per_block = tuple(shape[:ba]) + tuple(shape[ba + 1:])
            self.slabs.append(
                np.zeros((self.num_slots,) + per_block, dtype=np.dtype(dtype))
            )
        self._free: List[int] = list(range(self.num_slots))

    @property
    def nbytes(self) -> int:
        return sum(s.nbytes for s in self.slabs)

    @property
    def used(self) -> int:
        return self.num_slots - len(self._free)

    @property
    def available(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """All-or-nothing: n slots or None (no partial grabs to unwind)."""
        if n < 0 or n > len(self._free):
            return None
        slots = [self._free.pop() for _ in range(n)]
        return slots

    def release(self, slots: Sequence[int]) -> None:
        for s in slots:
            self._free.append(int(s))

    def write(self, slots: Sequence[int], arrays: Sequence[np.ndarray]) -> None:
        """Store per-leaf payloads (block axis LEADING: row k is block k)
        into `slots`; payload rows past len(slots) are transfer padding
        and are dropped."""
        idx = np.asarray(slots, dtype=np.int64)
        for slab, arr in zip(self.slabs, arrays):
            slab[idx] = arr[: len(slots)]

    def read(self, slots: Sequence[int]) -> List[np.ndarray]:
        """Per-leaf payloads for `slots`, block axis leading — the inverse
        of `write` (copies: the slabs stay valid while restores are in
        flight)."""
        idx = np.asarray(slots, dtype=np.int64)
        return [slab[idx] for slab in self.slabs]


@dataclasses.dataclass
class SwapRecord:
    """What a swapped-out victim needs to resume: which host slots hold
    its blocks (block-chain order) and how many tokens of KV they cover
    (`n_tokens` = the victim's fed position count; the LAST slot is a
    partial block unless n_tokens is block-aligned)."""

    slots: List[int]
    n_tokens: int
    nbytes: int


class HostTier:
    """Bookkeeping over one `HostBlockStore`: swap records vs spilled
    prefix blocks, with swaps taking priority for capacity (evicting
    spilled blocks LRU when the free list runs dry — state beats cache).

    Purely host-side; the engine owns every device interaction and calls
    down here only at host-sync boundaries."""

    def __init__(self, store: HostBlockStore, cost_model: SwapCostModel,
                 prefix_spill: bool = True):
        self.store = store
        self.cost_model = cost_model
        self.prefix_spill = bool(prefix_spill)
        # chain hash -> host slot, LRU order (oldest first) — the spilled
        # shadow of KVPool._evictable
        self.spilled: "OrderedDict[int, int]" = OrderedDict()
        # counters the engine folds into ServingStats / obs at run end
        self.swaps_out = 0
        self.swaps_in = 0
        self.swap_out_bytes = 0
        self.swap_in_bytes = 0
        self.spills = 0

    # -- capacity ----------------------------------------------------------

    def alloc_for_swap(self, n: int) -> Optional[List[int]]:
        """n slots for a preemption swap, evicting spilled prefix blocks
        (oldest first) when the free list alone cannot cover it."""
        while self.store.available < n and self.spilled:
            _, slot = self.spilled.popitem(last=False)
            self.store.release([slot])
        return self.store.alloc(n)

    def alloc_for_spill(self) -> Optional[int]:
        """One slot for a spilled prefix block: free list first, else
        recycle the oldest spilled block — spills never displace swap
        records."""
        slots = self.store.alloc(1)
        if slots is None and self.spilled:
            _, slot = self.spilled.popitem(last=False)
            return slot
        return slots[0] if slots else None

    # -- spilled-prefix map ------------------------------------------------

    def record_spill(self, chain_hash: int, slot: int) -> None:
        self.spilled[chain_hash] = slot
        self.spilled.move_to_end(chain_hash)
        self.spills += 1

    def lookup_spill(self, chain_hash: int) -> Optional[int]:
        return self.spilled.get(chain_hash)

    def take_spill(self, chain_hash: int) -> Optional[int]:
        """Claim a spilled block's slot for restore; the caller releases
        the slot once the payload is back in HBM."""
        return self.spilled.pop(chain_hash, None)

    def snapshot(self) -> Dict[str, int]:
        """Tier gauges for `KVPool.snapshot` / obs: slot occupancy plus
        the lifetime swap/spill counters."""
        return {
            "host_blocks": self.store.num_slots,
            "host_used_blocks": self.store.used,
            "host_spilled_blocks": len(self.spilled),
            "host_pool_bytes": self.store.nbytes,
            "swaps_out": self.swaps_out,
            "swaps_in": self.swaps_in,
            "swap_out_bytes": self.swap_out_bytes,
            "swap_in_bytes": self.swap_in_bytes,
        }
