"""Host-side block allocator for the pooled KV cache.

The device side is a `(L, num_blocks, block_size, G, hs)` array pair
(`transformer.init_paged_kv_cache`); this module owns the METADATA: which
blocks are free, which sequence references which blocks, and — the piece
that makes shared system prompts cheap — a hash-chain prefix cache in the
style of vLLM's automatic prefix caching:

- every FULL block of a prompt is identified by
  `hash(parent_hash, tokens_in_block)`, so equal prompt prefixes map to
  equal hash chains regardless of which request produced them;
- on allocation, cached blocks matching the prompt's chain are reused by
  refcount (copy-free: no KV bytes move);
- on release, refcounts drop; hash-registered blocks whose count hits zero
  stay warm in an LRU "evictable" set and only return to circulation when
  the free list runs dry (copy-free release — nothing is zeroed or moved).

Block 0 is reserved as the write-only TRASH block: padded lanes and
bucket-padding positions scatter their garbage K/V there
(`ops.paged_attention.paged_update`), so it is never handed out.

Dtype blindness (the quantized-pool contract): this allocator also never
learns what the blocks store.  `ServingConfig(kv_dtype="int8")` swaps the
device arrays for int8 payload + per-block-per-group scale arrays riding
the same `(L, num_blocks, ...)` layout (`ops/paged_attention.py`), but a
block id still means "block_size token slots" — free lists, refcounts and
the hash-chain prefix cache are untouched, so a prefix hit reuses an int8
block (payload AND scale) exactly as copy-free as an fp one.

Device-count blindness (the tensor-parallel serving contract): this
allocator never learns how many chips back the pool.  Under a tp mesh the
device arrays shard their KV-GROUP axis (`parallel.sharding.paged_kv_spec`
— each device holds its head-slice of EVERY block), so block ids, free
lists, refcounts and the hash chains are identical on 1 chip or N; only
the bytes behind a block id shrink per device (by exactly 1/tp —
`ServingConfig.pool_bytes_per_device`).  Sharding the BLOCK axis instead
would have forced per-device free lists and device-aware tables; sharding
heads keeps this file untouched by distribution.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["KVPool"]


class KVPool:
    """Free-list block allocator with refcounts and hash-based prefix reuse."""

    def __init__(self, num_blocks: int, block_size: int, prefix_caching: bool = True):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is the trash block)")
        if block_size < 1:
            raise ValueError("block_size must be positive")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.prefix_caching = prefix_caching
        # LIFO free list keeps recently-released blocks hot in HBM caches
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref: Dict[int, int] = {}
        # prefix cache state
        self._hash_to_block: Dict[int, int] = {}
        self._block_hash: Dict[int, int] = {}  # registered full blocks only
        self._evictable: "OrderedDict[int, None]" = OrderedDict()  # ref==0, cached
        self.prefix_hits = 0  # blocks reused copy-free
        self.prefix_queries = 0  # full blocks looked up
        # host-RAM tier seam (serving/host_tier.py): the allocator stays
        # device- AND tier-blind — the engine installs these when
        # ServingConfig.host_pool_mib > 0, and every tier decision rides
        # the two existing choke points (`_take` eviction, `match_prefix`
        # miss).  None (the default) is today's behavior, bit-for-bit.
        self.host = None  # Optional[host_tier.HostTier], for snapshot()
        # (block, chain_hash) -> None, called as a cached block is evicted:
        # the engine copies the block's bytes to a host slot (spill)
        self.spill_hook: Optional[Callable[[int, int], None]] = None
        # chain_hash -> fresh HBM block (refcount 1) with the spilled
        # payload's restore scheduled, or None when the hash isn't spilled
        # / no capacity.  Hits through this path count as prefix_hits_host.
        self.restore_hook: Optional[Callable[[int], Optional[int]]] = None
        self.prefix_hits_host = 0  # blocks restored from the host tier

    # -- capacity ------------------------------------------------------------

    @property
    def available(self) -> int:
        """Blocks allocatable right now (free + evictable cached)."""
        return len(self._free) + len(self._evictable)

    @property
    def used(self) -> int:
        """Blocks referenced by live sequences."""
        return sum(1 for c in self._ref.values() if c > 0)

    @property
    def utilization(self) -> float:
        """Fraction of allocatable blocks held by live sequences."""
        return self.used / max(1, self.num_blocks - 1)

    def blocks_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.block_size)

    def snapshot(self) -> Dict[str, float]:
        """Point-in-time allocator gauges for the observability layer
        (`obs/`): pure host-side counters the pool already maintains —
        reading them costs nothing and touches no device state."""
        snap = {
            "num_blocks": self.num_blocks,
            "used_blocks": self.used,
            "available_blocks": self.available,
            "evictable_blocks": len(self._evictable),
            "utilization": self.utilization,
            "prefix_hits": self.prefix_hits,
            "prefix_queries": self.prefix_queries,
        }
        if self.host is not None:
            snap["prefix_hits_host"] = self.prefix_hits_host
            snap.update(self.host.snapshot())
        return snap

    # -- allocation ----------------------------------------------------------

    def _take(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        if self._evictable:  # evict the least-recently-released cached block
            blk, _ = self._evictable.popitem(last=False)
            h = self._block_hash.pop(blk)
            del self._hash_to_block[h]
            if self.spill_hook is not None:
                # host tier: copy the cold chain block down instead of
                # dropping it (the gather snapshots the block's bytes
                # before the new owner's first write can land)
                self.spill_hook(blk, h)
            return blk
        return None

    def alloc(self, n: int) -> Optional[List[int]]:
        """Take `n` fresh blocks (refcount 1 each); None if short — the
        caller must not have mutated state (all-or-nothing)."""
        if n > self.available:
            return None
        out = []
        for _ in range(n):
            blk = self._take()
            assert blk is not None
            self._ref[blk] = 1
            out.append(blk)
        return out

    def release(self, blocks: Sequence[int]) -> None:
        """Drop one reference per block.  Copy-free: registered blocks whose
        refcount reaches zero stay warm for prefix reuse; unregistered ones
        go straight back to the free list."""
        for blk in blocks:
            c = self._ref.get(blk, 0) - 1
            if c > 0:
                self._ref[blk] = c
                continue
            self._ref.pop(blk, None)
            if blk in self._block_hash:
                self._evictable[blk] = None
                self._evictable.move_to_end(blk)
            else:
                self._free.append(blk)

    # -- prefix caching ------------------------------------------------------

    @staticmethod
    def chain_hashes(tokens: Sequence[int], block_size: int) -> List[int]:
        """One hash per FULL block, each chained on its parent so a block's
        identity covers the whole prefix up to and including it."""
        hashes: List[int] = []
        parent = 0
        for i in range(len(tokens) // block_size):
            parent = hash((parent, tuple(tokens[i * block_size : (i + 1) * block_size])))
            hashes.append(parent)
        return hashes

    def match_prefix(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached block-chain prefix of `tokens`, with a reference
        taken on every matched block.  Returns (blocks, n_cached_tokens).

        At most `(len(tokens) - 1) // block_size` blocks are matched: the
        prompt's last token is always recomputed, and keeping the cached
        span block-aligned means the requester's first write lands in a
        block it owns exclusively (no copy-on-write machinery needed).
        """
        if not self.prefix_caching:
            return [], 0
        max_blocks = max(0, (len(tokens) - 1)) // self.block_size
        matched: List[int] = []
        for h in self.chain_hashes(tokens, self.block_size)[:max_blocks]:
            self.prefix_queries += 1
            blk = self._hash_to_block.get(h)
            if blk is None:
                # host tier: a chain that fell out of HBM may live on in
                # the spilled store — the hook hands back a fresh block
                # (refcount already 1) with the payload restore scheduled
                if self.restore_hook is not None:
                    blk = self.restore_hook(h)
                if blk is None:
                    break
                self.prefix_hits_host += 1
                self._hash_to_block[h] = blk
                self._block_hash[blk] = h
                matched.append(blk)
                continue
            self.prefix_hits += 1
            self._ref[blk] = self._ref.get(blk, 0) + 1
            self._evictable.pop(blk, None)
            matched.append(blk)
        return matched, len(matched) * self.block_size

    def register_prefix(self, blocks: Sequence[int], tokens: Sequence[int]) -> None:
        """Record the hash chain for the full blocks of `tokens`, making
        them reusable by future requests.  Blocks already registered under
        the same hash keep the existing mapping (first writer wins)."""
        if not self.prefix_caching:
            return
        for blk, h in zip(blocks, self.chain_hashes(tokens, self.block_size)):
            if h in self._hash_to_block:
                continue
            if blk in self._block_hash:  # block already identifies another chain
                continue
            self._hash_to_block[h] = blk
            self._block_hash[blk] = h
