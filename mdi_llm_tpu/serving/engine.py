"""Continuous-batching serving engine over the paged KV pool.

Request-level scheduling on top of the existing jitted forward machinery:
where `Generator.generate` allocates one contiguous `[B, S]` cache per call
and holds the batch shape for the whole run, `ServingEngine` keeps ONE
pooled block cache (`transformer.init_paged_kv_cache`) shared by every
in-flight request, admits requests from a queue into `max_batch` decode
slots, serves prefill and decode in ONE unified ragged forward per step,
retires finished sequences mid-batch, and reuses blocks across requests
(including copy-free prefix sharing for common prompt heads — chat system
prompts, `utils/prompts.py` styles).

Unified mixed step (the single-chip analogue of the paper's keep-every-
resource-busy pipeline): whenever prefill work exists, the scheduler
composes a token-budget batch — every decode lane's pending token FIRST,
then prefill chunks split to fit `ServingConfig.token_budget` — and
`_mixed_fn(B, T_budget)` runs it as ONE forward over the paged pool
(`ops/paged_attention.paged_prefill`: tokens packed slot-major into a
static (1, T_budget) axis, per-slot ragged spans, per-token block-table
resolution).  One dispatch + one host sync serves every lane; an arriving
prompt no longer stalls the decode lanes behind a B=1 bucket-padded
prefill, and the only padding is the batch tail (`ServingStats.
padded_token_frac` measures exactly that).

Greedy parity contract (pinned by tests/test_serving.py): because the
paged attention op masks strictly by absolute position and its lax
fallback runs the exact `ops/attention.py` softmax chain, the per-request
greedy token streams are identical to sequential `Generator.generate`
calls — scheduling order, chunking, lane assignment and block placement
are all invisible to the math.

Device dispatch shapes stay bounded AND prompt-independent: the mixed
step is a fixed `(1, token_budget)` packed batch (one compile total — the
per-prompt-bucket prefill executables are gone), and pure decode is a
fixed `(max_batch, decode_chunk)` scan (dead lanes ride along as padding
writing into the pool's trash block).

Host-sync amortization (docs/perf.md "Serving host-sync & speculative"):
with `decode_chunk=K` the inner loop runs K decode steps in ONE jitted
`lax.scan` — per-slot remaining-budget and stop-token masks freeze
finished lanes on device — and the host reads tokens once per K steps
instead of per token; with `double_buffer` chunk N+1 is dispatched
(chained on device arrays) before chunk N's tokens are read, so the read
overlaps compute.  `spec_k=K` adds batched speculative decoding: per-slot
drafts verified in one ragged multi-query forward over the paged cache
(`ops/paged_attention.py`), emitting up to K+1 tokens per sync.  At
temperature 0 the verify is exact-match accept against the greedy
successors (bit-identical streams); at temperature>0 it is the
rejection-sampled accept/resample rule (`ops/sampling.speculative_verify`)
— each emitted token distributed exactly as the per-step sampler's.
Drafts come from prompt lookup (`ngram_draft`), and optionally from a
small draft model (`ServingConfig.draft_model`) running over its OWN
paged pool carved out of the block budget: it mirrors every mixed step to
keep its KV in lockstep and proposes K greedy tokens in one jitted
catch-up + scan (`_draft_scan_fn`) for lanes where the n-gram misses.

Tensor-parallel serving (docs/perf.md "Distributed serving"): built from a
Generator with a tp mesh, the SAME engine serves sharded — model weights
under `parallel/sharding.py`'s Megatron rules, the paged pool's KV-group
axis split across chips (`paged_kv_spec`: each device holds its head-slice
of EVERY block), while the allocator, block tables, hash-chain prefix
cache and the scheduler stay host-side and device-count-blind.  All three
dispatch paths (`_mixed_fn`, `_decode_chunk_fn`, `_verify_fn`) keep their
single-device traces: GSPMD partitions the lax-fallback attention and the
`paged_update` scatter along the sharded groups, the Pallas kernels run
per shard under `jax.shard_map` (`ops/paged_attention.shard_axes`), and the
only cross-chip reductions are the dense tp forward's own — one all-reduce
per layer at the row-parallel projections, one at the sampled logits.  The
per-request token streams stay bit-identical to the single-device engine:
per-head attention math never crosses a shard boundary, so tp changes the
summation layout exactly where the dense tp `generate()` path already does.
dp/ep/sp serving meshes are rejected at `Generator.serve()` time.

Observability (docs/observability.md): pass `obs=ServingObserver()` to
`Generator.serve()` and the engine/scheduler report request-lifecycle
events, per-step spans and KV/queue gauges into it — exclusively at the
host-sync boundaries this loop already performs (the one `np.asarray`
read per dispatch), so tracing adds zero extra syncs, zero device ops and
zero recompiles; per-request TTFT/TPOT/E2E/queue-wait percentiles and a
Perfetto-loadable timeline come out the other side.  With
`ServingObserver(device=True)` the engine additionally captures each
executable's XLA cost sheet (`obs/device.py` ExecutableReport — AOT
cost/memory analysis over abstract shapes, once per (path, shape, pool
dtype) per Generator at warmup; zero device work, jit cache untouched).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mdi_llm_tpu.config import ServingConfig
from mdi_llm_tpu.generation import (
    Generator,
    accept_draft,
    detect_stop_tokens,
    find_eot,
    ngram_draft,
    pad_draft,
)
from mdi_llm_tpu.models import transformer
from mdi_llm_tpu.ops.sampling import (
    sample_mode,
    sample_traced,
    sampling_operands,
    speculative_verify,
)
from mdi_llm_tpu.serving.kv_pool import KVPool
from mdi_llm_tpu.serving.scheduler import Request, Scheduler, SequenceState

__all__ = ["ServingEngine", "ServingStats", "validate_serving_mesh"]


def validate_serving_mesh(mesh) -> None:
    """Reject meshes the serving engine cannot run, naming the offending
    axis AND the supported alternative.  Called from `Generator.serve()`
    (so the error fires BEFORE any pool allocation) and defensively from
    `ServingEngine.__init__` for direct constructions.

    Supported: no mesh, a `tp` axis (the paged pool shards its KV-group
    axis), a `pp` axis (layer stages each own their shard of the pool —
    `serving/pipeline.py`'s recurrent ring), or `tp` and `pp` composed
    (tp stays a GSPMD auto axis inside each stage).  dp>1 is unsupported
    for serving — requests are scheduler-routed, not batch-split, so a dp
    axis would replicate the pool without serving anything on the
    replicas.  ep would need the MoE all_to_all threaded through every
    serving dispatch, and sp's sequence-sharded cache contradicts the
    pooled block layout."""
    if mesh is None:
        return
    for axis in mesh.axis_names:
        size = int(mesh.shape[axis])
        if size <= 1 or axis in ("tp", "pp"):
            continue
        if axis == "dp":
            raise ValueError(
                f"serving does not support dp={size}: the engine "
                "schedules requests into slots, not dp-split batches — "
                "use a tp and/or pp mesh (or run one engine per replica)"
            )
        if axis == "ep":
            raise ValueError(
                f"serving does not support ep={size}: expert parallelism "
                "would need the MoE all_to_all threaded through every "
                "serving dispatch — shard experts within a stage via tp, "
                "or split layers over pp"
            )
        if axis == "sp":
            raise ValueError(
                f"serving does not support sp={size}: a sequence-sharded "
                "cache contradicts the pooled block layout (every block "
                "holds full heads of a token span) — use tp and/or pp"
            )
        raise ValueError(
            f"serving does not support a mesh with axis {axis!r} "
            f"(size {size}): only tensor parallelism ('tp', KV-group "
            "sharding) and pipeline parallelism ('pp', per-stage pool "
            "shards), alone or composed, serve the paged pool"
        )


def _pin_kv(kv, sharding):
    """Pin the paged pool's sharding on a traced output (no-op off-mesh).
    Donation keeps the buffers where they are, but without the constraint
    GSPMD may pick a different output layout per executable — and the NEXT
    dispatch would retrace on the new input sharding, tripping the
    CompileGuard zero-post-warmup-recompile contract.  `sharding` is a
    (pool, scale) pair: the int8 pool's scale leaves pin the matching
    group-sharded layout (`paged_kv_scale_spec`); fp pools only ever see
    the payload branch.  The ndim split covers both pool layouts: the
    flat 5-D payload / 3-D scale, and the pipeline engine's stage-stacked
    6-D payload / 4-D scale."""
    if sharding is None:
        return kv
    pool_s, scale_s = sharding
    return jax.tree_util.tree_map(
        lambda x: jax.lax.with_sharding_constraint(
            x, pool_s if x.ndim >= 5 else scale_s
        ),
        kv,
    )


@dataclass
class ServingStats:
    tokens_generated: int = 0
    prefill_tokens: int = 0
    prefill_chunks: int = 0
    mixed_steps: int = 0  # unified ragged prefill+decode dispatches
    decode_steps: int = 0  # device decode steps (scan iterations + verifies)
    host_syncs: int = 0  # decode/verify host reads (one per chunk dispatch)
    # padding accounting: `tokens_dispatched` counts device token-axis
    # positions computed (mixed-step budget width, decode B×K incl. dead
    # lanes, verify B×(K+1)); `tokens_useful` counts the positions whose
    # token actually advanced a stream (prefill feeds, retained decode
    # steps — frozen post-stop scan steps are padding — and the verify's
    # pending + ACCEPTED draft rows).  padded_token_frac is their gap —
    # the MXU waste the unified ragged step exists to shrink.
    tokens_dispatched: int = 0
    tokens_useful: int = 0
    # mixed-batch occupancy: live lanes per unified step / max_batch
    _occ_sum: float = 0.0
    _occ_n: int = 0
    spec_drafted: int = 0  # draft tokens scored by speculative verify
    spec_accepted: int = 0  # draft tokens accepted (emitted without a step)
    # per-source split of the totals above: n-gram prompt lookup vs the
    # optional draft model (zero when no draft_model is configured)
    spec_drafted_ngram: int = 0
    spec_accepted_ngram: int = 0
    spec_drafted_model: int = 0
    spec_accepted_model: int = 0
    requests_finished: int = 0
    preemptions: int = 0
    # open-system fields (server/frontend.py fills them; replay runs keep
    # the zero defaults so both modes report ONE schema — a bench
    # serve-open row and an mdi-serve replay line are key-compatible)
    requests_rejected: int = 0  # admission-queue backpressure (429s)
    queue_depth_peak: int = 0  # max waiting+preempted seen at any step
    offered_qps: float = 0.0  # arrival rate offered by the open-loop
    # driver (submissions/second including rejected ones); 0 in replay
    # mode where the whole trace is queued up front
    # peak concurrently-resident sequences (live lanes holding pool blocks
    # in one dispatch) — THE capacity number a quantized pool moves at
    # fixed HBM (the serving-cb-int8 bench rung reads it off this field)
    resident_peak: int = 0
    prefix_cache_hits: int = 0  # blocks reused copy-free
    # host-RAM tier (serving/host_tier.py): folded from the tier at run
    # end.  All-zero when no tier is configured (host_pool_mib=0), so the
    # one stats schema serves tiered and untiered runs alike.
    swaps_out: int = 0  # preemptions resolved by swap instead of recompute
    swaps_in: int = 0  # resumes restored from host payloads (no re-prefill)
    swap_out_bytes: int = 0
    swap_in_bytes: int = 0
    prefix_hits_host: int = 0  # prefix blocks restored from spilled chains
    restore_issue_s: float = 0.0  # host time issuing restores (the part
    # NOT hidden behind the next dispatch — the restore-overlap residual)
    wall_s: float = 0.0
    decode_s: float = 0.0
    prefill_s: float = 0.0
    # block-pool utilization, sampled at every decode step as a running
    # aggregate (a long-lived engine must not grow per-step state)
    _kv_util_sum: float = 0.0
    _kv_util_n: int = 0
    _kv_util_peak: float = 0.0

    def observe_kv_utilization(self, util: float) -> None:
        self._kv_util_sum += util
        self._kv_util_n += 1
        self._kv_util_peak = max(self._kv_util_peak, util)

    def observe_dispatch(self, dispatched: int, useful: int) -> None:
        self.tokens_dispatched += dispatched
        self.tokens_useful += useful

    def observe_mixed_occupancy(self, live: int, max_batch: int) -> None:
        self._occ_sum += live / max(1, max_batch)
        self._occ_n += 1

    def observe_resident(self, live: int) -> None:
        self.resident_peak = max(self.resident_peak, live)

    @property
    def padded_token_frac(self) -> float:
        """Fraction of dispatched device token positions that carried no
        real token (batch-tail padding, frozen/dead decode lanes, rejected
        verify rows) — the padding win of the unified step is this number
        going DOWN vs the bucket-padded prefill engine."""
        if not self.tokens_dispatched:
            return 0.0
        return 1.0 - self.tokens_useful / self.tokens_dispatched

    @property
    def mixed_batch_occupancy(self) -> float:
        """Mean live-lane fraction of the unified mixed steps (slots with a
        token in the packed batch / max_batch)."""
        return self._occ_sum / self._occ_n if self._occ_n else 0.0

    @property
    def tokens_per_s(self) -> float:
        return self.tokens_generated / self.wall_s if self.wall_s else 0.0

    @property
    def tokens_per_sync(self) -> float:
        """Generated tokens per decode-path host read — the amortization
        the chunked/speculative loop buys (per-step serving pins this at
        ~1 plus the prefill-sampled tokens)."""
        return self.tokens_generated / self.host_syncs if self.host_syncs else 0.0

    @property
    def spec_accept_rate(self) -> float:
        return self.spec_accepted / self.spec_drafted if self.spec_drafted else 0.0

    @property
    def kv_utilization_mean(self) -> float:
        return self._kv_util_sum / self._kv_util_n if self._kv_util_n else 0.0

    @property
    def kv_utilization_peak(self) -> float:
        return self._kv_util_peak

    def to_dict(self) -> Dict[str, Any]:
        """THE canonical JSON view of a serving run — `mdi-serve`'s stats
        line and bench serve rows both embed exactly this dict (plus their
        own topology/config extras), so the derived aggregates
        (`padded_token_frac`, `tokens_per_sync`, the `_occ_*`/`_kv_util_*`
        private sums) can never desync between surfaces.  Keys are stable:
        suite JSON consumers key on them across rounds."""
        return {
            "requests": self.requests_finished,
            "tokens_generated": self.tokens_generated,
            "tokens_per_s": round(self.tokens_per_s, 2),
            "wall_s": round(self.wall_s, 2),
            "decode_s": round(self.decode_s, 3),
            "prefill_s": round(self.prefill_s, 3),
            "decode_steps": self.decode_steps,
            "mixed_steps": self.mixed_steps,
            "prefill_chunks": self.prefill_chunks,
            "prefill_tokens": self.prefill_tokens,
            "host_syncs": self.host_syncs,
            "tokens_per_sync": round(self.tokens_per_sync, 2),
            "padded_token_frac": round(self.padded_token_frac, 4),
            "mixed_batch_occupancy": round(self.mixed_batch_occupancy, 4),
            "spec_accept_rate": round(self.spec_accept_rate, 4),
            "spec_drafted": self.spec_drafted,
            "spec_accepted": self.spec_accepted,
            "spec_drafted_ngram": self.spec_drafted_ngram,
            "spec_accepted_ngram": self.spec_accepted_ngram,
            "spec_drafted_model": self.spec_drafted_model,
            "spec_accepted_model": self.spec_accepted_model,
            "kv_block_utilization_mean": round(self.kv_utilization_mean, 4),
            "kv_block_utilization_peak": round(self.kv_utilization_peak, 4),
            "prefix_cache_hits": self.prefix_cache_hits,
            "preemptions": self.preemptions,
            "swaps_out": self.swaps_out,
            "swaps_in": self.swaps_in,
            "swap_out_bytes": self.swap_out_bytes,
            "swap_in_bytes": self.swap_in_bytes,
            "prefix_hits_host": self.prefix_hits_host,
            "restore_issue_s": round(self.restore_issue_s, 4),
            "resident_peak": self.resident_peak,
            "requests_rejected": self.requests_rejected,
            "queue_depth_peak": self.queue_depth_peak,
            "offered_qps": round(self.offered_qps, 3),
        }


class ServingEngine:
    """Paged-KV continuous-batching loop bound to one `Generator`'s model.

    Build via `Generator.serve(...)`.  Typical use::

        engine = gen.serve(block_size=16, max_batch=8)
        engine.add_request("a", prompt_tokens, max_new_tokens=128)
        results, stats = engine.run()

    Tensor-parallel: build the Generator with `mesh=make_mesh({"tp": N})`
    and the SAME calls serve sharded (pool KV groups split over tp; see
    the module docstring); token streams are identical to single-device.
    """

    # which axis of a pool leaf indexes blocks — 1 for the flat
    # (L, NB, ...) payload/scale leaves, 2 for the pipeline engine's
    # stage-stacked (S, l_max, NB, ...) layout.  The host tier's
    # fetch/restore fns and the host store's slab layout both key off it.
    _kv_block_axis = 1

    def __init__(self, gen: Generator, serving: ServingConfig, obs=None,
                 policy=None, draft_gen: Optional[Generator] = None):
        validate_serving_mesh(gen.mesh)  # serve() checks too; direct
        # constructions must hit the same wall before the pool allocates
        self.gen = gen
        # the parameter bundle every dispatch passes: gen.params here; the
        # pipeline engine swaps in its stage-stacked bundle after super()
        # so the inherited _run_* host loops dispatch it unchanged
        self._params = gen.params
        self.cfg = serving
        # observability (obs.ServingObserver or None): fed exclusively at
        # the host-sync boundaries this loop already owns — enabling it
        # adds zero device ops, zero extra syncs and zero recompiles
        # (tests/test_obs.py pins all three; docs/observability.md)
        self.obs = obs
        # tensor-parallel serving: the pool shards its KV-group axis over
        # tp (Generator._paged_kv_sharding), the kernels run per shard
        self._tp = int(gen.mesh.shape.get("tp", 1)) if gen.mesh is not None else 1
        self._paged_shard = (gen.mesh, "tp") if self._tp > 1 else None
        # (pool, scale) sharding pair for _pin_kv: fp pools only use the
        # first element; the int8 pool's scale leaves pin the second
        self._kv_sharding_pair = (
            None if gen._paged_kv_sharding is None
            else (gen._paged_kv_sharding, gen._paged_kv_scale_sharding)
        )
        if (
            self._paged_shard is not None
            and serving.use_kernel
            and not hasattr(jax, "shard_map")
        ):
            raise ValueError(
                "use_kernel=True over a tp mesh needs jax.shard_map (the "
                "Pallas paged kernels cannot be GSPMD-partitioned) and "
                "this jax build lacks it; leave use_kernel unset/False "
                "for the exact lax fallback"
            )
        bs = serving.block_size
        if bs < 1:
            raise ValueError("block_size must be positive")
        if serving.decode_chunk < 1:
            raise ValueError("decode_chunk must be >= 1")
        if serving.spec_k < 0:
            raise ValueError("spec_k must be >= 0")
        if (
            serving.spec_k
            and serving.temperature != 0.0
            and not serving.spec_verify_sampled()
        ):
            # only the OLD exact-match verify is greedy-only; the default
            # (spec_sampled=None → auto) switches to the rejection-sampled
            # verify at temperature>0, which preserves the sampler's
            # distribution draw-for-draw
            raise ValueError(
                "spec_sampled=False pins the exact-match verify, which "
                "emits greedy successors and is only exact at "
                "temperature=0 — drop spec_sampled=False (auto selects "
                "the rejection-sampled verify at temperature>0) or set "
                "temperature=0"
            )
        if serving.draft_model and not serving.spec_k:
            raise ValueError(
                "draft_model is a drafter for speculative serving: set "
                "spec_k > 0 (the draft scan proposes up to spec_k tokens "
                "per slot per verify)"
            )
        # pool storage dtype: kv_dtype=None keeps the fp path untouched
        # (gen.cache_dtype, bit-identical to before the knob existed);
        # "int8" builds the quantized pool; other float names cast on
        # write.  Unknown names are refused through the same byte table
        # the audit estimator uses (config.dtype_bytes), so the engine
        # and mdi-audit can never disagree on what a kv_dtype means.
        from mdi_llm_tpu.config import dtype_bytes
        if serving.kv_dtype is None:
            self._pool_dtype = gen.cache_dtype
            self.kv_dtype_name = serving.resolved_kv_dtype(gen.cache_dtype)
        else:
            name = serving.resolved_kv_dtype()
            dtype_bytes(name)  # ValueError on names the table doesn't know
            if name == "int8":
                self._pool_dtype = "int8"
            elif name in ("float8", "float8_e4m3fn"):
                self._pool_dtype = jnp.float8_e4m3fn
            elif name == "float8_e5m2":
                self._pool_dtype = jnp.float8_e5m2
            elif name in ("bfloat16", "float16", "float32", "float64"):
                self._pool_dtype = jnp.dtype(name)
            else:
                raise ValueError(
                    f"kv_dtype {name!r} is not a paged-pool storage dtype: "
                    "use 'int8' (quantized blocks + per-block scales) or a "
                    "float dtype (cast on write)"
                )
            self.kv_dtype_name = name
        self.token_budget = serving.resolved_token_budget()
        if self.token_budget <= serving.max_batch:
            raise ValueError(
                f"token_budget {self.token_budget} must exceed max_batch "
                f"{serving.max_batch}: the unified step packs one decode "
                "token per live slot FIRST, so a budget at or below "
                "max_batch leaves no room for any prefill token and "
                "prefill could never progress (None defaults to "
                "max_batch + prefill_chunk)"
            )
        self.max_seq_length = gen.max_seq_length
        # blocks per sequence table: full coverage of the engine window
        self.max_blocks_per_seq = -(-self.max_seq_length // bs)
        # pool size: ServingConfig owns the formula (max_blocks, or every
        # slot grown to the full window plus the trash block) so the
        # mdi-audit memory checker budgets exactly what gets allocated
        num_blocks = serving.num_pool_blocks(self.max_seq_length)
        self.pool = KVPool(num_blocks, bs, prefix_caching=serving.prefix_caching)
        self.scheduler = Scheduler(
            self.pool, serving.max_batch, serving.prefill_chunk,
            self.max_seq_length, policy=policy,
        )
        self.scheduler.observer = obs  # lifecycle edges report from there
        self._kv = self._init_pool(num_blocks, bs)
        # host-RAM tier (serving/host_tier.py): host_pool_mib = 0 keeps
        # every table, hook and the compile set bit-for-bit untouched.
        # Abstract engines (mdi-ir/mdi-flow) never allocate slabs — the
        # tier's reachable fetch/restore signatures derive from the
        # ServingConfig alone.
        self.host_tier = None
        self._host_block_bytes = 0
        # gather snapshots issued but not yet copied into host slabs:
        # (host slots, on-device per-leaf arrays, live block count)
        self._pending_swaps: List[Tuple[List[int], Any, int]] = []
        if serving.host_pool_mib > 0 and not getattr(gen, "abstract", False):
            self._init_host_tier()
        # persistent host-side block table, updated incrementally as blocks
        # are appended / slots reassigned — rebuilding the full
        # (max_batch, max_blocks_per_seq) ndarray per decode dispatch was
        # O(table) of host work per token
        self._tables = np.zeros(
            (serving.max_batch, self.max_blocks_per_seq), np.int32
        )
        self._table_seq: List[Optional[SequenceState]] = (
            [None] * serving.max_batch
        )
        self._table_len = [0] * serving.max_batch
        # compiled-phase cache, shared across engines of the same Generator:
        # every other serving knob (temperature/top_p are traced operands;
        # pool geometry/batch/chunk widths key the entries via call shapes)
        # leaves the traces unchanged, so only use_kernel partitions it
        self._fns: Dict[Any, Any] = gen._serve_fns.setdefault(
            self._fn_cache_key(), {}
        )
        # sampling knobs are engine-lifetime constants: upload the traced
        # operands once, not two tiny transfers per decode step (abstract
        # engines keep the shape/dtype only — nothing may touch a device)
        if getattr(gen, "abstract", False):
            self._t_op = jax.ShapeDtypeStruct((), jnp.float32)
            self._p_op = jax.ShapeDtypeStruct((), jnp.float32)
        else:
            self._t_op, self._p_op = sampling_operands(
                serving.temperature, serving.top_p
            )
        self._sample_mode = sample_mode(
            serving.temperature, serving.top_k, serving.top_p
        )
        # optional draft model: a second, smaller transformer with its OWN
        # KVPool carved out of the block budget (ServingConfig.
        # num_draft_blocks owns the split).  All attributes stay None
        # without draft_model, so every existing path is untouched.
        self.draft_gen: Optional[Generator] = None
        self.draft_pool: Optional[KVPool] = None
        self._draft_params = None
        self._draft_kv = None
        self._draft_kv_sharding = None
        self._draft_tables: Optional[np.ndarray] = None
        if serving.draft_model:
            self._init_draft(draft_gen)
        self.stats = ServingStats()
        self._results: Dict[str, List[int]] = {}
        self._stream_cb = None

    # -- backend seams (overridden by serving/pipeline.py) -------------------

    def _fn_cache_key(self):
        """Namespace key of this engine's compiled-phase cache on
        `gen._serve_fns`.  Execution backends with different traces for
        the same (B, T) shapes (the pipeline engine's staged rings) must
        re-key so two engines of one Generator never share executables."""
        return ("serve", self.cfg.use_kernel)

    def kernel_info(self) -> Dict[str, Any]:
        """The attention route this engine's dispatches resolve to, plus
        the tuning-table provenance (`ops/tuning.py`): ``{"variant":
        "unified"|"fallback", "tuned", "table_source", "params"}``.  bench
        serve rows record it as ``detail.kernel``.  Pure host-side lookup
        — the same trace-time resolution the dispatch runs, so calling it
        never traces, compiles, or touches the pool."""
        from mdi_llm_tpu.ops.paged_attention import _kernel_auto
        from mdi_llm_tpu.ops.tuning import resolve_kernel_params

        cfg = self.gen.cfg
        use_kernel = self.cfg.use_kernel
        if use_kernel is None:
            use_kernel = _kernel_auto(self._paged_shard)
        device_kind = None
        if jax.default_backend() == "tpu":
            device_kind = jax.devices()[0].device_kind
        params, meta = resolve_kernel_params(
            n_head=cfg.n_head, n_groups=cfg.n_query_groups,
            head_size=cfg.head_size, block_size=self.cfg.block_size,
            kv_dtype="int8" if self._pool_dtype == "int8" else None,
            device_kind=device_kind,
        )
        return {
            "variant": "unified" if use_kernel else "fallback",
            "tuned": meta["tuned"],
            "table_source": meta["table_source"],
            "params": params.to_dict(),
        }

    def _init_pool(self, num_blocks: int, bs: int):
        """Allocate and place the device-side paged pool.  The base
        engine's flat (L, num_blocks, bs, G, hs) pool, tp-sharded along
        its KV-group axis; the pipeline engine overrides this with the
        per-stage stacked layout.  On an abstract Generator the pool is a
        ShapeDtypeStruct tree carrying the same shardings — zero bytes,
        zero device work (the mdi-ir contract)."""
        if getattr(self.gen, "abstract", False):
            tmpl = jax.eval_shape(
                lambda: transformer.init_paged_kv_cache(
                    self.gen.cfg, num_blocks, bs, dtype=self._pool_dtype
                )
            )
            pool_sh = self.gen._paged_kv_sharding
            scale_sh = self.gen._paged_kv_scale_sharding

            def leaf(l):
                sh = None
                if pool_sh is not None:
                    sh = pool_sh if l.ndim == 5 else scale_sh
                if sh is not None:
                    return jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=sh)
                return jax.ShapeDtypeStruct(l.shape, l.dtype)

            return jax.tree_util.tree_map(leaf, tmpl)
        return self.gen._place_paged_kv(transformer.init_paged_kv_cache(
            self.gen.cfg, num_blocks, bs, dtype=self._pool_dtype
        ))

    # -- draft model (speculative drafting over a second paged pool) ---------

    def _init_draft(self, draft_gen: Optional[Generator]) -> None:
        """Build the draft Generator and its own paged pool.  The block
        split is `ServingConfig.num_draft_blocks` / `num_pool_blocks` —
        the same formulas mdi-audit's `draft_*` breakdown budgets, so the
        engine and the estimator can never disagree on the carve-out.
        The draft pool has no prefix cache and no host tier: draft KV is
        always recomputable from the token list, so retire/preempt drop
        it wholesale (`Scheduler._release_draft`)."""
        serving = self.cfg
        tcfg = self.gen.cfg
        if draft_gen is not None:
            dcfg = draft_gen.cfg  # a caller-built draft wins over from_name
        else:
            dcfg = serving.draft_config()
        if dcfg.padded_vocab_size != tcfg.padded_vocab_size:
            raise ValueError(
                f"draft_model {serving.draft_model!r} has padded vocab "
                f"{dcfg.padded_vocab_size}, the target has "
                f"{tcfg.padded_vocab_size}: the rejection verify compares "
                "token ids, so drafter and verifier must share a vocabulary"
            )
        if dcfg.block_size < self.max_seq_length:
            raise ValueError(
                f"draft_model {serving.draft_model!r} context window "
                f"{dcfg.block_size} is smaller than the engine's "
                f"max_seq_length {self.max_seq_length}: the draft must "
                "follow every lane to the window edge"
            )
        if draft_gen is None:
            draft_gen = self._build_draft_gen(dcfg)
        self.draft_gen = draft_gen
        self._draft_params = draft_gen.params
        self._draft_kv_sharding = (
            None if draft_gen._paged_kv_sharding is None
            else (
                draft_gen._paged_kv_sharding,
                draft_gen._paged_kv_scale_sharding,
            )
        )
        n_blocks = serving.num_draft_blocks(self.max_seq_length)
        self.draft_pool = KVPool(
            n_blocks, serving.block_size, prefix_caching=False
        )
        self.scheduler.draft_pool = self.draft_pool
        self._draft_kv = self._init_draft_kv(n_blocks, serving.block_size)
        self._draft_tables = np.zeros(
            (serving.max_batch, self.max_blocks_per_seq), np.int32
        )

    def _build_draft_gen(self, dcfg) -> Generator:
        """Default draft Generator when the caller did not hand one in:
        random init at the target's (floating) parameter dtype — real
        checkpoints come through `Generator.serve(draft_gen=...)`, which
        cli/serve.py wires when `--draft-model` names a downloaded model.
        On an abstract engine (mdi-ir / mdi-flow) the draft is abstract
        too: zero bytes, zero device work."""
        gen = self.gen
        if getattr(gen, "abstract", False):
            from mdi_llm_tpu.analysis.plan import abstract_params

            return Generator(
                dcfg, abstract_params(dcfg),
                max_seq_length=self.max_seq_length, mesh=gen.mesh,
                abstract=True,
            )
        dt = jnp.bfloat16
        for leaf in jax.tree_util.tree_leaves(gen.params):
            d = jnp.dtype(leaf.dtype)
            if jnp.issubdtype(d, jnp.floating):
                dt = d
                break
        dparams = transformer.init_params(dcfg, jax.random.PRNGKey(0), dtype=dt)
        return Generator(
            dcfg, dparams, max_seq_length=self.max_seq_length,
            cache_dtype=gen.cache_dtype, mesh=gen.mesh,
            scan_unroll=gen.scan_unroll,
        )

    def _init_draft_kv(self, num_blocks: int, bs: int):
        """The draft model's paged pool, `_init_pool`'s exact shape
        discipline (tp sharding, pool dtype, abstract ShapeDtypeStructs)
        applied to the draft config."""
        dgen = self.draft_gen
        if getattr(self.gen, "abstract", False):
            tmpl = jax.eval_shape(
                lambda: transformer.init_paged_kv_cache(
                    dgen.cfg, num_blocks, bs, dtype=self._pool_dtype
                )
            )
            pool_sh = dgen._paged_kv_sharding
            scale_sh = dgen._paged_kv_scale_sharding

            def leaf(l):
                if pool_sh is not None:
                    return jax.ShapeDtypeStruct(
                        l.shape, l.dtype,
                        sharding=pool_sh if l.ndim == 5 else scale_sh,
                    )
                return jax.ShapeDtypeStruct(l.shape, l.dtype)

            return jax.tree_util.tree_map(leaf, tmpl)
        return dgen._place_paged_kv(transformer.init_paged_kv_cache(
            dgen.cfg, num_blocks, bs, dtype=self._pool_dtype
        ))

    def _ensure_draft_blocks(self, seq: SequenceState, n_tokens: int) -> bool:
        """Grow `seq`'s draft-pool table to cover `n_tokens` positions,
        WITHOUT preemption (the draft pool is a fixed carve-out; a lane it
        cannot cover simply keeps the n-gram drafter)."""
        pool = self.draft_pool
        need = pool.blocks_needed(min(n_tokens, self.max_seq_length))
        while len(seq.draft_blocks) < need:
            got = pool.alloc(1)
            if got is None:
                return False
            seq.draft_blocks.extend(got)
        return True

    def _sync_draft_tables(self, seqs: Sequence[SequenceState]) -> np.ndarray:
        """Block table into the DRAFT pool, rebuilt per dispatch (draft
        dispatches are per-round, not per-token — simple beats the
        incremental machinery here).  Zero rows redirect every absent or
        stale lane's writes to the draft pool's trash block."""
        t = self._draft_tables
        t[:] = 0
        for seq in seqs:
            n = len(seq.draft_blocks)
            t[seq.slot, :n] = seq.draft_blocks
        return t

    # -- host-RAM tier (serving/host_tier.py) --------------------------------

    def _kv_leaf_shapes(self) -> List[Tuple[Tuple[int, ...], Any]]:
        """(shape, dtype) per pool leaf in tree-flatten order — the slab
        template the host store mirrors and the payload signature the
        fetch/restore executables move."""
        return [
            (tuple(l.shape), np.dtype(l.dtype))
            for l in jax.tree_util.tree_leaves(self._kv)
        ]

    def _init_host_tier(self) -> None:
        """Build the host block store + cost model and install the tier
        hooks on the pool and scheduler.  Slab shapes come from the LIVE
        pool leaves (so int8 payload+scale, fp, tp-sharded and pp-stacked
        layouts all round-trip byte-identically); slot count divides the
        `host_pool_mib` budget by the per-block byte footprint — for the
        flat layout exactly `ServingConfig.num_host_blocks`, the byte
        contract mdi-audit's `host_pool_bytes` breakdown pins."""
        from mdi_llm_tpu.serving.host_tier import (
            HostBlockStore,
            HostTier,
            SwapCostModel,
        )

        ba = self._kv_block_axis
        leaf_shapes = self._kv_leaf_shapes()
        per_block = sum(
            np.dtype(d).itemsize
            * int(np.prod(s[:ba] + s[ba + 1:], dtype=np.int64))
            for s, d in leaf_shapes
        )
        self._host_block_bytes = per_block
        num_slots = (self.cfg.host_pool_mib * 2**20) // max(1, per_block)
        device_kind = None
        if jax.default_backend() == "tpu":
            device_kind = jax.devices()[0].device_kind
        store = HostBlockStore(leaf_shapes, ba, num_slots)
        self.host_tier = HostTier(
            store,
            SwapCostModel(
                link_gbps=self.cfg.resolved_host_link_gbps(device_kind)
            ),
            # spilling rides the hash chain: without prefix_caching there
            # is no chain to key the spilled blocks (mdi-audit's
            # bad-host-tier check flags the config asking for both)
            prefix_spill=(
                self.cfg.host_prefix_spill and self.cfg.prefix_caching
            ),
        )
        self.pool.host = self.host_tier
        if self.host_tier.prefix_spill:
            self.pool.spill_hook = self._spill_block
            self.pool.restore_hook = self._restore_spilled
        self.scheduler.swap_out_hook = self._swap_out
        self.scheduler.swap_in_hook = self._swap_in
        self.scheduler.swap_drop_hook = self._swap_drop

    def _issue_fetch(self, blocks: List[int], slots: List[int]) -> None:
        """Enqueue gather snapshots of `blocks` toward host `slots` in
        fixed-width chunks (ONE fetch executable per engine, whatever the
        victim size; short tails pad with reads of block 0).  Device
        in-order execution snapshots the payload before any later
        dispatch's writes — the blocks may return to the free list
        immediately.  The device→host copy materializes at the next
        host-sync boundary (`_drain_swaps`)."""
        W = max(1, self.cfg.swap_chunk_blocks)
        fetch = self._fetch_blocks_fn(W)
        for i in range(0, len(blocks), W):
            chunk = blocks[i : i + W]
            idx = np.zeros((W,), np.int32)
            idx[: len(chunk)] = chunk
            out = fetch(self._kv, jnp.asarray(idx))
            self._pending_swaps.append((slots[i : i + W], out, len(chunk)))

    def _drain_swaps(self) -> None:
        """Materialize every pending gather snapshot into its host slots.
        Runs at host-sync boundaries (each step, and before any host-slab
        read) so the device→host copies overlap dispatched compute; the
        measured rate feeds the cost model's link-BW estimate."""
        if not self._pending_swaps:
            return
        tier = self.host_tier
        t0 = time.perf_counter()
        nbytes = 0
        for slots, out, n in self._pending_swaps:
            arrays = [
                np.asarray(l)  # mdi-lint: disable=host-sync -- the swap tier's explicit device→host copy, drained only at host-sync boundaries
                for l in jax.tree_util.tree_leaves(out)
            ]
            tier.store.write(slots, arrays)
            nbytes += n * self._host_block_bytes
        self._pending_swaps.clear()
        tier.cost_model.observe_transfer(nbytes, time.perf_counter() - t0)

    def _swap_out(self, seq: SequenceState):
        """Scheduler hook at `preempt_latest`, called while the victim
        still owns its blocks: decide swap-vs-recompute from the cost
        model, claim host slots, and enqueue the gather.  Returns the
        SwapRecord riding the preempted entry, or None for the historical
        recompute path."""
        from mdi_llm_tpu.serving.host_tier import SwapRecord

        tier = self.host_tier
        if tier is None or seq.fed <= 0:
            return None
        n_blocks = self.pool.blocks_needed(seq.fed)
        nbytes = n_blocks * self._host_block_bytes
        # recompute would re-prefill every fed token on resume
        if not tier.cost_model.should_swap(nbytes, seq.fed):
            return None
        slots = tier.alloc_for_swap(n_blocks)
        if slots is None:
            return None
        self._issue_fetch(seq.blocks[:n_blocks], slots)
        tier.swaps_out += 1
        tier.swap_out_bytes += nbytes
        if self.obs is not None:
            self.obs.tier_swap_out(n_blocks, nbytes)
        return SwapRecord(slots=slots, n_tokens=seq.fed, nbytes=nbytes)

    def _swap_in(self, record, blocks: List[int]) -> None:
        """Scheduler hook at swapped-resume admission: restore the
        record's payload into freshly allocated HBM `blocks` through the
        fixed-width donated scatter (padding targets the write-only trash
        block 0).  The restores are ENQUEUED here and overlap behind the
        resumed sequence's next dispatch — the data dependency through
        the donated pool orders them before any later read/write."""
        assert len(blocks) == len(record.slots)
        tier = self.host_tier
        self._drain_swaps()  # the record's own gather may still be pending
        t0 = time.perf_counter()
        W = max(1, self.cfg.swap_chunk_blocks)
        restore = self._restore_blocks_fn(W)
        arrays = tier.store.read(record.slots)
        for i in range(0, len(blocks), W):
            chunk = blocks[i : i + W]
            idx = np.zeros((W,), np.int32)  # padding scatters to trash
            idx[: len(chunk)] = chunk
            payload = []
            for arr in arrays:
                rows = arr[i : i + W]
                if rows.shape[0] < W:
                    pad = np.zeros(
                        (W - rows.shape[0],) + rows.shape[1:], rows.dtype
                    )
                    rows = np.concatenate([rows, pad], axis=0)
                payload.append(jnp.asarray(rows))
            kv = self._kv
            self._kv = None  # donated
            try:
                self._kv = restore(kv, jnp.asarray(idx), payload)
            except Exception:
                self._kv = kv  # see _run_mixed: keep failures diagnosable
                raise
        tier.store.release(record.slots)
        tier.swaps_in += 1
        tier.swap_in_bytes += record.nbytes
        dt = time.perf_counter() - t0
        self.stats.restore_issue_s += dt
        if self.obs is not None:
            self.obs.tier_swap_in(len(blocks), record.nbytes)
            self.obs.restore_wait(dt)

    def _swap_drop(self, record) -> None:
        """Release a swap record's host slots without restoring (the
        frontend cancelled the preempted request)."""
        self._drain_swaps()  # its gather may still target those slots
        self.host_tier.store.release(record.slots)

    def _spill_block(self, blk: int, chain_hash: int) -> None:
        """Pool hook as a cached chain block is evicted: copy it to a
        host slot instead of dropping it.  The gather snapshots the bytes
        before the block's new owner can write (in-order execution), so
        eviction stays copy-free on the HBM side."""
        tier = self.host_tier
        slot = tier.alloc_for_spill()
        if slot is None:
            return
        self._issue_fetch([blk], [slot])
        tier.record_spill(chain_hash, slot)

    def _restore_spilled(self, chain_hash: int):
        """Pool hook on a prefix-cache miss: if the chain spilled to the
        host tier, claim a fresh HBM block (refcount 1), enqueue its
        payload restore, and hand it back to `match_prefix` — the hit
        counts as `prefix_hits_host`.  None when the hash is not spilled
        or the pool has no block to spare (the host copy is dropped:
        a chain the pool cannot re-admit is dead weight in the store)."""
        tier = self.host_tier
        slot = tier.take_spill(chain_hash)
        if slot is None:
            return None
        got = self.pool.alloc(1)
        if got is None:
            tier.store.release([slot])
            return None
        self._drain_swaps()  # the spill's gather may still be in flight
        t0 = time.perf_counter()
        W = max(1, self.cfg.swap_chunk_blocks)
        restore = self._restore_blocks_fn(W)
        arrays = tier.store.read([slot])
        idx = np.zeros((W,), np.int32)  # padding scatters to trash
        idx[0] = got[0]
        payload = []
        for arr in arrays:
            pad = np.zeros((W - 1,) + arr.shape[1:], arr.dtype)
            payload.append(jnp.asarray(np.concatenate([arr, pad], axis=0)))
        kv = self._kv
        self._kv = None  # donated
        try:
            self._kv = restore(kv, jnp.asarray(idx), payload)
        except Exception:
            self._kv = kv  # see _run_mixed: keep failures diagnosable
            raise
        tier.store.release([slot])
        tier.swaps_in += 1
        tier.swap_in_bytes += self._host_block_bytes
        dt = time.perf_counter() - t0
        self.stats.restore_issue_s += dt
        if self.obs is not None:
            self.obs.tier_swap_in(1, self._host_block_bytes)
            self.obs.restore_wait(dt)
        return got[0]

    # -- compiled phases -----------------------------------------------------

    def _mixed_fn(self, B: int, T: int):
        """ONE unified forward for the token-budget mixed batch: every
        decode lane's pending token plus up to the remaining budget of
        prefill chunk tokens, packed slot-major into a static (1, T) token
        axis that attends through the shared paged pool
        (`ops/paged_attention.paged_prefill`).  Returns, per SLOT, the
        sampled successor of the slot's LAST packed token — the decoded
        next token for a decode lane, the first output token for a prefill
        that completed its prompt this step (garbage for absent slots and
        unfinished prefills; the host uses only what it needs).  This is
        the only serving executable whose shape the prompts can never
        perturb: one compile per (max_batch, token_budget)."""
        key_ = ("mixed", B, T)
        if key_ not in self._fns:
            gen = self.gen
            use_kernel = self.cfg.use_kernel  # no self in the closure: the
            # fn cache outlives this engine (gen._serve_fns) and capturing
            # self would pin its entire paged pool for the Generator's life
            shard = self._paged_shard
            kv_sharding = self._kv_sharding_pair

            # float knobs ride as traced operands (see _decode_fn)
            @partial(
                jax.jit, donate_argnums=(2,),
                static_argnames=("mode", "top_k"),
            )
            def mixed(params, tokens, kv, tables, pos, q_slot, q_start,
                      q_len, last_idx, key, temperature, top_p, mode, top_k):
                logits, kv = transformer.forward(
                    gen.cfg, params, tokens, pos, kv=kv, rope=gen.rope,
                    moe_impl=gen._moe_impl, unroll=gen.scan_unroll,
                    paged_tables=tables, paged_kernel=use_kernel,
                    paged_ragged=(q_slot, q_start, q_len),
                    paged_shard=shard,
                )
                kv = _pin_kv(kv, kv_sharding)
                key, sub = jax.random.split(key)
                nxt = sample_traced(
                    logits[0, last_idx], sub, temperature, top_p,
                    mode=mode, top_k=top_k,
                )
                return nxt.astype(jnp.int32), kv, key

            self._fns[key_] = mixed
        return self._fns[key_]

    def _decode_fn(self, B: int):
        key_ = ("decode", B)
        if key_ not in self._fns:
            gen = self.gen
            use_kernel = self.cfg.use_kernel  # see _mixed_fn: no self
            shard = self._paged_shard
            kv_sharding = self._kv_sharding_pair

            # float knobs ride as traced operands; the cache keys only on
            # (mode, top_k) — a per-request temperature sweep would otherwise
            # compile one decode executable per distinct float
            @partial(
                jax.jit, donate_argnums=(2,),
                static_argnames=("mode", "top_k"),
            )
            def decode(params, tok, kv, tables, input_pos, key,
                       temperature, top_p, mode, top_k):
                logits, kv = transformer.forward(
                    gen.cfg, params, tok[:, None], input_pos, kv=kv,
                    rope=gen.rope, moe_impl=gen._moe_impl,
                    unroll=gen.scan_unroll, paged_tables=tables,
                    paged_kernel=use_kernel, paged_shard=shard,
                )
                kv = _pin_kv(kv, kv_sharding)
                key, sub = jax.random.split(key)
                nxt = sample_traced(
                    logits[:, -1], sub, temperature, top_p,
                    mode=mode, top_k=top_k,
                )
                return nxt.astype(jnp.int32), kv, key

            self._fns[key_] = decode
        return self._fns[key_]

    def _decode_chunk_fn(self, B: int, K: int):
        """K batched decode steps scanned INSIDE one jit call over the paged
        pool — the host syncs once per K tokens instead of per token.

        Per-slot masks keep finished lanes inert without branching the
        trace: `limit` is the number of steps a slot may advance (its
        remaining budget/window, 0 for dead lanes) and `stop_tok` its
        single-token stop id (-1 for none).  A frozen lane re-forwards its
        last (token, position) pair each remaining step, which rewrites the
        identical K/V bytes in place — combined with strictly-by-absolute-
        position masking and the zero-table → trash-block redirect for
        dead lanes, no masked step can perturb any live slot's stream, so
        the retained tokens are bit-identical to the per-step engine's."""
        key_ = ("decode_chunk", B, K)
        if key_ not in self._fns:
            gen = self.gen
            use_kernel = self.cfg.use_kernel  # see _mixed_fn: no self
            shard = self._paged_shard
            kv_sharding = self._kv_sharding_pair

            # float knobs ride as traced operands (see _decode_fn)
            @partial(
                jax.jit, donate_argnums=(2,),
                static_argnames=("mode", "top_k"),
            )
            def decode_chunk(params, tok0, kv, tables, pos0, limit, stop_tok,
                             key, temperature, top_p, mode, top_k):
                def body(carry, i):
                    tok, kv, pos, done, key = carry
                    active = jnp.logical_and(i < limit, ~done)
                    logits, kv = transformer.forward(
                        gen.cfg, params, tok[:, None], pos, kv=kv,
                        rope=gen.rope, moe_impl=gen._moe_impl,
                        unroll=gen.scan_unroll, paged_tables=tables,
                        paged_kernel=use_kernel, paged_shard=shard,
                    )
                    # pin the scan carry's pool layout every step: a GSPMD
                    # layout flip inside the loop would resharding-copy the
                    # whole pool per iteration
                    kv = _pin_kv(kv, kv_sharding)
                    key, sub = jax.random.split(key)
                    nxt = sample_traced(
                        logits[:, -1], sub, temperature, top_p,
                        mode=mode, top_k=top_k,
                    ).astype(jnp.int32)
                    nxt = jnp.where(active, nxt, tok)  # frozen lanes hold
                    done = jnp.logical_or(
                        done, jnp.logical_and(active, nxt == stop_tok)
                    )
                    pos = pos + active.astype(pos.dtype)
                    return (nxt, kv, pos, done, key), nxt

                done0 = jnp.zeros((B,), bool)
                (tok, kv, pos, done, key), toks = jax.lax.scan(
                    body, (tok0, kv, pos0, done0, key),
                    jnp.arange(K, dtype=jnp.int32),
                )
                # final carry rides back so double-buffering can chain the
                # next chunk on device arrays without a host read
                return toks, tok, pos, kv, key  # toks: (K, B)

            self._fns[key_] = decode_chunk
        return self._fns[key_]

    def _verify_fn(self, B: int, T: int):
        """Batched greedy speculative verify over the paged pool: score T
        tokens per slot ([pending] + K drafted) in ONE ragged multi-query
        forward — every slot at its own depth, per-slot q_pos masking in
        `ops/paged_attention.py` — and return the greedy successor at every
        position.  Stale K/V past a rejected draft is invisible until
        overwritten (absolute-position masking), the same contract the
        single-sequence `Generator._verify_fn` relies on."""
        key_ = ("verify", B, T)
        if key_ not in self._fns:
            gen = self.gen
            use_kernel = self.cfg.use_kernel  # see _mixed_fn: no self
            shard = self._paged_shard
            kv_sharding = self._kv_sharding_pair

            @partial(jax.jit, donate_argnums=(2,))
            def verify(params, tokens, kv, tables, pos0):
                logits, kv = transformer.forward(
                    gen.cfg, params, tokens, pos0, kv=kv, rope=gen.rope,
                    moe_impl=gen._moe_impl, unroll=gen.scan_unroll,
                    paged_tables=tables, paged_kernel=use_kernel,
                    paged_shard=shard,
                )
                kv = _pin_kv(kv, kv_sharding)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), kv

            self._fns[key_] = verify
        return self._fns[key_]

    def _verify_sample_fn(self, B: int, T: int):
        """Rejection-sampled speculative verify: the same ragged
        multi-query forward as `_verify_fn`, but the T-1 drafted tokens
        are accepted/resampled per position against the EXACT filtered
        distribution `sample_traced` draws from (`ops/sampling.
        speculative_verify`) — temperature/top_p ride as traced operands,
        so the temperature-sweep contract (zero post-warmup recompiles)
        carries over from the per-step sampler.  Returns (out, n_emit,
        kv, key): row b emits out[b, :n_emit[b]] — its accepted draft
        prefix plus one resampled/bonus token."""
        key_ = ("verify_sample", B, T)
        if key_ not in self._fns:
            gen = self.gen
            use_kernel = self.cfg.use_kernel  # see _mixed_fn: no self
            shard = self._paged_shard
            kv_sharding = self._kv_sharding_pair

            @partial(
                jax.jit, donate_argnums=(2,),
                static_argnames=("mode", "top_k"),
            )
            def verify_sample(params, tokens, kv, tables, pos0, draft_len,
                              key, temperature, top_p, mode, top_k):
                logits, kv = transformer.forward(
                    gen.cfg, params, tokens, pos0, kv=kv, rope=gen.rope,
                    moe_impl=gen._moe_impl, unroll=gen.scan_unroll,
                    paged_tables=tables, paged_kernel=use_kernel,
                    paged_shard=shard,
                )
                kv = _pin_kv(kv, kv_sharding)
                key, sub = jax.random.split(key)
                out, n_emit = speculative_verify(
                    logits, tokens[:, 1:], draft_len, sub, temperature,
                    top_p, mode=mode, top_k=top_k,
                )
                return out, n_emit, kv, key

            self._fns[key_] = verify_sample
        return self._fns[key_]

    def _draft_mixed_fn(self, B: int, T: int):
        """The draft model's mirror of `_mixed_fn`: the SAME packed ragged
        batch (tokens, positions, slot spans) forwarded through the DRAFT
        pool, so the draft's KV tracks the target's feed positions in
        lockstep through prefill and decode feeds.  No sampling head and
        nothing to sync — the dispatch rides asynchronously behind the
        target step's boundary read."""
        key_ = ("draft_mixed", self.cfg.draft_model, B, T)
        if key_ not in self._fns:
            dgen = self.draft_gen
            use_kernel = self.cfg.use_kernel  # see _mixed_fn: no self
            shard = self._paged_shard
            kv_sharding = self._draft_kv_sharding

            @partial(jax.jit, donate_argnums=(2,))
            def draft_mixed(params, tokens, kv, tables, pos, q_slot,
                            q_start, q_len):
                _, kv = transformer.forward(
                    dgen.cfg, params, tokens, pos, kv=kv, rope=dgen.rope,
                    moe_impl=dgen._moe_impl, unroll=dgen.scan_unroll,
                    paged_tables=tables, paged_kernel=use_kernel,
                    paged_ragged=(q_slot, q_start, q_len),
                    paged_shard=shard,
                )
                return _pin_kv(kv, kv_sharding)

            self._fns[key_] = draft_mixed
        return self._fns[key_]

    def _draft_scan_fn(self, B: int, F: int):
        """Draft K = F-2 tokens per lane in ONE jitted call against the
        DRAFT pool: a ragged catch-up forward over the lane's last `n_in`
        un-drafted tokens (pending token included — F covers the worst
        post-accept gap of K+1, so n_in <= F), then a K-1 step greedy
        scan feeding each proposal back.  Greedy drafting keeps `p_draft`
        one-hot — the assumption `speculative_verify`'s acceptance rule
        is derived under.  Rows with n_in=0 are dead lanes: zero table
        rows redirect their writes to the draft pool's trash block.
        Catch-up positions past n_in hold garbage KV only at positions
        the NEXT round's catch-up rewrites before trusting (all are >=
        the post-round `draft_fed`)."""
        key_ = ("draft_scan", self.cfg.draft_model, B, F)
        if key_ not in self._fns:
            K = F - 2
            dgen = self.draft_gen
            use_kernel = self.cfg.use_kernel  # see _mixed_fn: no self
            shard = self._paged_shard
            kv_sharding = self._draft_kv_sharding

            @partial(jax.jit, donate_argnums=(2,))
            def draft_scan(params, toks_in, kv, tables, pos0, n_in):
                logits, kv = transformer.forward(
                    dgen.cfg, params, toks_in, pos0, kv=kv, rope=dgen.rope,
                    moe_impl=dgen._moe_impl, unroll=dgen.scan_unroll,
                    paged_tables=tables, paged_kernel=use_kernel,
                    paged_shard=shard,
                )
                kv = _pin_kv(kv, kv_sharding)
                # first proposal: greedy successor of the pending token
                # (the catch-up row's last REAL position, n_in - 1)
                idx = jnp.maximum(n_in - 1, 0)
                first = jnp.argmax(
                    jnp.take_along_axis(logits, idx[:, None, None], axis=1)
                    [:, 0, :],
                    axis=-1,
                ).astype(jnp.int32)

                def body(carry, _):
                    tok, kv, pos = carry
                    lg, kv = transformer.forward(
                        dgen.cfg, params, tok[:, None], pos, kv=kv,
                        rope=dgen.rope, moe_impl=dgen._moe_impl,
                        unroll=dgen.scan_unroll, paged_tables=tables,
                        paged_kernel=use_kernel, paged_shard=shard,
                    )
                    kv = _pin_kv(kv, kv_sharding)  # see _decode_chunk_fn
                    nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
                    return (nxt, kv, pos + 1), nxt

                (_tok, kv, _pos), rest = jax.lax.scan(
                    body, (first, kv, pos0 + jnp.maximum(n_in, 1)),
                    jnp.arange(max(K - 1, 0), dtype=jnp.int32),
                )
                drafts = jnp.concatenate(
                    [first[:, None], jnp.swapaxes(rest, 0, 1)], axis=1
                )
                return drafts, kv

            self._fns[key_] = draft_scan
        return self._fns[key_]

    def _fetch_blocks_fn(self, W: int):
        """Gather `W` pool blocks into block-LEADING per-leaf arrays —
        the host tier's swap-out/spill snapshot (`HostBlockStore.write`'s
        exact layout).  Fixed width: every transfer quantizes to
        `swap_chunk_blocks`, so the tier adds exactly this one extra
        executable however many victims swap (the zero-post-warmup-
        recompile contract).  Non-donating — the pool stays live; short
        tails pad with reads of block 0 and are dropped host-side."""
        key_ = ("fetch", W)
        if key_ not in self._fns:
            ba = self._kv_block_axis  # see _mixed_fn: no self in closures

            @jax.jit
            def fetch(kv, idx):
                return jax.tree_util.tree_map(
                    lambda l: jnp.moveaxis(jnp.take(l, idx, axis=ba), ba, 0),
                    kv,
                )

            self._fns[key_] = fetch
        return self._fns[key_]

    def _restore_blocks_fn(self, W: int):
        """Scatter `W` block-leading payload rows back into the pool at
        `idx` — the host tier's restore half, donating the pool like
        every serving dispatch so the blocks land in place.  Padding rows
        target the write-only trash block 0.  `payload` is the pool's
        leaf list in tree-flatten order (`_kv_leaf_shapes`)."""
        key_ = ("restore", W)
        if key_ not in self._fns:
            ba = self._kv_block_axis  # see _mixed_fn: no self in closures
            kv_sharding = self._kv_sharding_pair

            @partial(jax.jit, donate_argnums=(0,))
            def restore(kv, idx, payload):
                leaves, treedef = jax.tree_util.tree_flatten(kv)
                out = [
                    l.at[(slice(None),) * ba + (idx,)].set(
                        jnp.moveaxis(p, 0, ba)
                    )
                    for l, p in zip(leaves, payload)
                ]
                kv = jax.tree_util.tree_unflatten(treedef, out)
                return _pin_kv(kv, kv_sharding)

            self._fns[key_] = restore
        return self._fns[key_]

    # -- static enumeration (analysis/ir.py) ---------------------------------

    def reachable_signatures(self) -> List[Tuple[str, Tuple[int, ...]]]:
        """Every (label, shape-key) `step()` can dispatch for THIS engine's
        ServingConfig — the compile set the warmup pass and the
        zero-post-warmup-recompile contract must cover:

        - ``mixed(max_batch, token_budget)`` always (prefill + decode pack
          into the one unified step);
        - ``verify(max_batch, spec_k + 1)`` when speculative decoding is on
          (spec_k > 0) and the verify resolves to exact-match (greedy), or
          ``verify_sample`` at the same shape when it resolves to the
          rejection-sampled rule (`ServingConfig.spec_verify_sampled`) —
          and spec decode FALLS THROUGH to the plain decode path whenever
          no slot drafts, so the decode entry below stays reachable
          alongside it;
        - ``draft_mixed(max_batch, token_budget)`` and
          ``draft_scan(max_batch, spec_k + 2)`` when a draft model is
          configured (the mixed-step mirror and the K-token draft scan);
        - ``decode_chunk(max_batch, decode_chunk)`` when decode_chunk > 1,
          else ``decode(max_batch,)``.

        mdi-ir's compile-set-closure rule re-derives this set independently
        from the ServingConfig and diffs it against
        `enumerate_executables()`, so an engine subclass that forgets a
        dispatch path here is caught statically."""
        B = self.scheduler.max_batch
        sigs: List[Tuple[str, Tuple[int, ...]]] = [
            ("mixed", (B, self.token_budget))
        ]
        if self.cfg.spec_k:
            label = (
                "verify_sample" if self.cfg.spec_verify_sampled()
                else "verify"
            )
            sigs.append((label, (B, self.cfg.spec_k + 1)))
            if self.cfg.draft_model:
                sigs.append(("draft_mixed", (B, self.token_budget)))
                sigs.append(("draft_scan", (B, self.cfg.spec_k + 2)))
        if self.cfg.decode_chunk > 1:
            sigs.append(("decode_chunk", (B, self.cfg.decode_chunk)))
        else:
            sigs.append(("decode", (B,)))
        if self.cfg.host_pool_mib > 0:
            # the host tier's fixed-width transfer pair (swap-out/spill
            # gather + restore scatter) — reachable from any preemption
            # or prefix miss once a tier is configured
            W = max(1, self.cfg.swap_chunk_blocks)
            sigs.append(("fetch", (W,)))
            sigs.append(("restore", (W,)))
        return sigs

    def enumerate_executables(self) -> List[Any]:
        """One abstract `ExecutableSpec` per reachable signature: the
        exact jitted callable each dispatch site calls, with
        ShapeDtypeStruct arguments mirroring the `_run_*` operand
        construction (shapes, dtypes AND shardings — the pool specs ride
        on the kv ShapeDtypeStructs).  Works on live engines
        (`abstractify` strips real buffers to their signatures) and on
        abstract ones (`Generator(abstract=True)`) identically; building
        the specs constructs closures but traces/compiles nothing.  The
        pipeline engine inherits this unchanged — its overridden
        `_mixed_fn`/... builders hand back the staged-ring variants under
        the same labels and keys."""
        from mdi_llm_tpu.obs.device import ExecutableSpec, abstractify

        B = self.scheduler.max_batch
        i32 = jnp.int32
        sds = jax.ShapeDtypeStruct
        params = abstractify(self._params)
        kv = abstractify(self._kv)
        tables = sds((B, self.max_blocks_per_seq), i32)
        key = abstractify(self.gen.key)
        t_op = abstractify(self._t_op)
        p_op = abstractify(self._p_op)
        statics = {"mode": self._sample_mode, "top_k": self.cfg.top_k}
        # every serving dispatch takes params at argnum 0 and the paged
        # pool at argnum 2 — named so mdi-flow's byte attribution (and any
        # other ExecutableSpec consumer) need not guess by size
        roles = {0: "params", 2: "kv"}
        specs: List[Any] = []
        for label, k in self.reachable_signatures():
            if label == "mixed":
                T = k[1]
                args = (
                    params, sds((1, T), i32), kv, tables, sds((1, T), i32),
                    sds((T,), i32), sds((B,), i32), sds((B,), i32),
                    sds((B,), i32), key, t_op, p_op,
                )
                specs.append(ExecutableSpec(
                    "mixed", k, self._mixed_fn(B, T), args, dict(statics),
                    (2,), dict(roles),
                ))
            elif label == "decode":
                args = (
                    params, sds((B,), i32), kv, tables, sds((B,), i32),
                    key, t_op, p_op,
                )
                specs.append(ExecutableSpec(
                    "decode", k, self._decode_fn(B), args, dict(statics),
                    (2,), dict(roles),
                ))
            elif label == "decode_chunk":
                K = k[1]
                args = (
                    params, sds((B,), i32), kv, tables, sds((B,), i32),
                    sds((B,), i32), sds((B,), i32), key, t_op, p_op,
                )
                specs.append(ExecutableSpec(
                    "decode_chunk", k, self._decode_chunk_fn(B, K), args,
                    dict(statics), (2,), dict(roles),
                ))
            elif label == "verify":
                T = k[1]
                args = (params, sds((B, T), i32), kv, tables, sds((B,), i32))
                specs.append(ExecutableSpec(
                    "verify", k, self._verify_fn(B, T), args, None, (2,),
                    dict(roles),
                ))
            elif label == "verify_sample":
                T = k[1]
                args = (
                    params, sds((B, T), i32), kv, tables, sds((B,), i32),
                    sds((B,), i32), key, t_op, p_op,
                )
                specs.append(ExecutableSpec(
                    "verify_sample", k, self._verify_sample_fn(B, T), args,
                    dict(statics), (2,), dict(roles),
                ))
            elif label == "draft_mixed":
                T = k[1]
                dparams = abstractify(self._draft_params)
                dkv = abstractify(self._draft_kv)
                args = (
                    dparams, sds((1, T), i32), dkv, tables, sds((1, T), i32),
                    sds((T,), i32), sds((B,), i32), sds((B,), i32),
                )
                specs.append(ExecutableSpec(
                    "draft_mixed", k, self._draft_mixed_fn(B, T), args,
                    None, (2,), dict(roles),
                ))
            elif label == "draft_scan":
                F = k[1]
                dparams = abstractify(self._draft_params)
                dkv = abstractify(self._draft_kv)
                args = (
                    dparams, sds((B, F), i32), dkv, tables, sds((B,), i32),
                    sds((B,), i32),
                )
                specs.append(ExecutableSpec(
                    "draft_scan", k, self._draft_scan_fn(B, F), args,
                    None, (2,), dict(roles),
                ))
            elif label in ("fetch", "restore"):
                # the host tier's transfer pair moves pool blocks, not
                # model activations: kv rides at argnum 0 for both
                W = k[0]
                ba = self._kv_block_axis
                payload = [
                    sds((W,) + tuple(l.shape[:ba]) + tuple(l.shape[ba + 1:]),
                        l.dtype)
                    for l in jax.tree_util.tree_leaves(kv)
                ]
                if label == "fetch":
                    specs.append(ExecutableSpec(
                        "fetch", k, self._fetch_blocks_fn(W),
                        (kv, sds((W,), i32)), None, (), {0: "kv"},
                    ))
                else:
                    specs.append(ExecutableSpec(
                        "restore", k, self._restore_blocks_fn(W),
                        (kv, sds((W,), i32), payload), None, (0,),
                        {0: "kv"},
                    ))
        return specs

    # -- device-side introspection (obs/device.py) ---------------------------

    def _introspect(self, label, key, fn, args, static_kwargs=None) -> None:
        """Capture this executable's XLA cost sheet (`ExecutableReport`:
        cost_analysis FLOPs/bytes + memory_analysis temp/arg/output
        bytes) ONCE per (path, shape, pool dtype) per Generator, via a
        side-band AOT `.lower().compile()` over abstract shapes — zero
        device work, the jit dispatch cache untouched.  Reports cache on
        `gen._exec_reports` (the same lifetime as the jit cache), so the
        capture compiles only at warmup — first dispatch of each shape —
        and the post-warmup steady state never lowers anything: device
        obs rides the CompileGuard contract (tests/test_device_obs.py).
        Only runs when the attached observer asked for capture
        (`ServingObserver(device=True)`)."""
        obs = self.obs
        if obs is None or not obs.device.capture_enabled:
            return
        cache = self.gen._exec_reports
        k = (label, key, self.kv_dtype_name)
        if k not in cache:
            from mdi_llm_tpu.obs.device import introspect

            cache[k] = introspect(
                fn, args, static_kwargs,
                label=label, key=key, variant=self.kv_dtype_name,
            )
        obs.publish_device_report(cache[k])

    # -- request surface -----------------------------------------------------

    def add_request(
        self,
        rid: str,
        prompt: Sequence[int],
        max_new_tokens: int,
        stop_sequences: Sequence[Sequence[int]] = (),
        priority: int = 0,
        tenant: str = "",
        ttft_slo_s: Optional[float] = None,
    ) -> str:
        """Queue a request; raises ValueError if it can never fit.
        `priority`/`tenant`/`ttft_slo_s` feed the scheduling policy
        (serving/policy.py) and are inert under the default FCFS."""
        self.scheduler.add(Request(
            rid=rid, prompt=[int(t) for t in prompt],
            max_new_tokens=int(max_new_tokens),
            stop_sequences=stop_sequences,
            priority=int(priority), tenant=str(tenant),
            ttft_slo_s=ttft_slo_s,
        ))
        return rid

    def _sync_tables(self, live: Sequence[SequenceState]) -> np.ndarray:
        """The persistent (max_batch, max_blocks_per_seq) block table for a
        decode dispatch, updated incrementally: appended blocks extend a
        slot's row in place, a reassigned slot rewrites its row, and every
        slot NOT in `live` is zeroed.  The zeroing is load-bearing, not
        cosmetic: dead/prefilling lanes ride the batched dispatch writing
        at position 0, and a stale row would route that garbage into a
        real block (worst case a prefix-cached block another request
        attends) — a zero row redirects it to the reserved trash block."""
        want: List[Optional[SequenceState]] = [None] * self.scheduler.max_batch
        for seq in live:
            want[seq.slot] = seq
        for slot, seq in enumerate(want):
            if seq is None:
                if self._table_seq[slot] is not None or self._table_len[slot]:
                    self._tables[slot] = 0
                    self._table_seq[slot], self._table_len[slot] = None, 0
                continue
            n = len(seq.blocks)
            if seq is not self._table_seq[slot] or n < self._table_len[slot]:
                row = self._tables[slot]
                row[:] = 0
                row[:n] = seq.blocks
                self._table_seq[slot], self._table_len[slot] = seq, n
            elif n > self._table_len[slot]:
                self._tables[slot, self._table_len[slot]: n] = \
                    seq.blocks[self._table_len[slot]:]
                self._table_len[slot] = n
        return self._tables

    # -- execution -----------------------------------------------------------

    def _run_mixed(self, entries: List[Tuple[SequenceState, int]]) -> None:
        """ONE unified ragged forward serving every lane: the scheduler's
        token-budget batch packs each decode lane's pending token and each
        prefilling lane's next chunk slot-major into a static
        (1, token_budget) axis; every packed token reads/writes the pool
        through its own slot's table row at its own absolute position
        (`paged_prefill`), the batch tail pads with trash-block writes.
        One dispatch, one host sync, no bucket-padded B=1 prefill.

        Per-sequence math is untouched by the packing: each token attends
        only its own slot's table, so decode streams and prefill logits
        are bit-identical to the dedicated dispatches they replace — the
        greedy parity contract carries over unchanged."""
        t0 = time.perf_counter()
        # block coverage for every entry's writes; growth may preempt —
        # _live_reserved keeps only entries whose sequence still owns its
        # slot afterwards (a victim resumes from the queue, fed intact)
        need = {id(s): n for s, n in entries}
        live = [
            (s, need[id(s)])
            for s in self._live_reserved(
                [s for s, _ in entries], lambda s: need[id(s)]
            )
        ]
        if not live:
            return
        # prefill tokens this step feeds — measured against the step's
        # wall time below, they EWMA-correct the swap cost model's
        # recompute-rate prior toward the actual machine
        n_prefill_toks = sum(n for s, n in live if s.needs_prefill)
        B = self.scheduler.max_batch
        T = self.token_budget
        trash_pos = self.max_blocks_per_seq * self.pool.block_size
        tokens = np.zeros((1, T), np.int32)
        # padding positions sit past every table's coverage, so their K/V
        # writes land in the reserved trash block whatever slot id they
        # carry (ops/paged_attention.paged_update's overflow redirect)
        pos = np.full((1, T), trash_pos, np.int32)
        q_slot = np.zeros((T,), np.int32)
        q_start = np.zeros((B,), np.int32)
        q_len = np.zeros((B,), np.int32)
        last_idx = np.zeros((B,), np.int32)
        off = 0
        for seq, n in live:
            feed = (seq.tokens[seq.fed : seq.fed + n]
                    if seq.needs_prefill else [seq.next_tok])
            tokens[0, off : off + n] = feed
            pos[0, off : off + n] = np.arange(seq.fed, seq.fed + n)
            q_slot[off : off + n] = seq.slot
            q_start[seq.slot] = off
            q_len[seq.slot] = n
            last_idx[seq.slot] = off + n - 1
            off += n
        tables = self._sync_tables([s for s, _ in live])
        fn = self._mixed_fn(B, T)
        self._introspect(
            "mixed", (B, T), fn,
            (self._params, tokens, self._kv, tables, pos, q_slot,
             q_start, q_len, last_idx, self.gen.key, self._t_op, self._p_op),
            {"mode": self._sample_mode, "top_k": self.cfg.top_k},
        )
        kv = self._kv
        self._kv = None  # donated
        try:
            nxt, self._kv, self.gen.key = fn(
                self._params, jnp.asarray(tokens), kv,
                jnp.asarray(tables), jnp.asarray(pos), jnp.asarray(q_slot),
                jnp.asarray(q_start), jnp.asarray(q_len),
                jnp.asarray(last_idx), self.gen.key, self._t_op, self._p_op,
                mode=self._sample_mode, top_k=self.cfg.top_k,
            )
        except Exception:
            # keep the engine debuggable after a failed dispatch: restore
            # the pool handle (if the donation consumed it, later use fails
            # with jax's clear deleted-buffer error, not a paged-cache one)
            self._kv = kv
            raise
        # draft-model lockstep: mirror the packed batch through the draft
        # pool BEFORE the boundary read below, so the two forwards overlap
        self._mirror_mixed_to_draft(live, tokens, pos, q_slot, q_start, q_len)
        nxt = np.asarray(nxt)  # mdi-lint: disable=host-sync -- THE unified step's one boundary read: a single sync serves every decode lane and prefill chunk in the batch
        self.stats.mixed_steps += 1
        self.stats.host_syncs += 1
        self.stats.observe_dispatch(T, off)
        self.stats.observe_mixed_occupancy(len(live), B)
        self.stats.observe_resident(len(self.scheduler.running()))
        self.stats.observe_kv_utilization(self.pool.utilization)
        if self.obs is not None:
            # one stamp at THIS boundary; every token/retirement below
            # shares it (the free-attribution contract)
            self.obs.step(
                "mixed", width=T, live=len(live), t_start=t0,
                kv_utilization=self.pool.utilization,
                queue_depth=self._queue_depth(), useful_tokens=off,
            )
        any_decode = False
        for seq, n in live:
            if seq.needs_prefill:
                seq.fed += n
                self.stats.prefill_tokens += n
                self.stats.prefill_chunks += 1
                if self.obs is not None:
                    self.obs.prefill_chunk(seq.req.rid, n)
                if seq.fed >= seq.prefill_target:
                    # prompt (as far as it was actually FED) is in the pool:
                    # publish its full blocks for prefix reuse.  Only now —
                    # registering before the KV is written would let a
                    # concurrent request attend garbage — and only up to
                    # `fed`: a resumed sequence's prefill stops one token
                    # short (the pending token decodes later), so a
                    # block-aligned prompt would otherwise register a block
                    # whose last slot is still unwritten.
                    self.pool.register_prefix(
                        seq.blocks, seq.req.prompt[: seq.fed]
                    )
                    if seq.resume_tok is not None:
                        # preserved across preemption
                        seq.next_tok = seq.resume_tok
                    else:
                        self._emit(seq, int(nxt[seq.slot]))
            else:
                any_decode = True
                seq.fed += 1
                self._emit(seq, int(nxt[seq.slot]))
        if any_decode:
            self.stats.decode_steps += 1
        if self.host_tier is not None and n_prefill_toks:
            self.host_tier.cost_model.observe_prefill(
                n_prefill_toks, time.perf_counter() - t0
            )
        self.stats.prefill_s += time.perf_counter() - t0

    def _mirror_mixed_to_draft(
        self, live: List[Tuple[SequenceState, int]], tokens: np.ndarray,
        pos: np.ndarray, q_slot: np.ndarray, q_start: np.ndarray,
        q_len: np.ndarray,
    ) -> None:
        """Feed the mixed step's packed batch through the draft model so
        its pool tracks the target's feed positions (`draft_fed == fed`
        lockstep).  Only lanes the draft is actually following get table
        rows: stale lanes and lanes the draft carve-out cannot cover ride
        the dispatch writing into the draft trash block and keep the
        n-gram drafter.  Called between the target dispatch and its
        boundary read, so the mirror's compute overlaps the sync."""
        if self.draft_gen is None:
            return
        fresh: List[Tuple[SequenceState, int]] = []
        for seq, n in live:
            if seq.draft_stale or seq.draft_fed != seq.fed:
                continue
            if not self._ensure_draft_blocks(seq, seq.fed + n):
                # the carve-out cannot follow this lane's prefill; spending
                # scan catch-up on it later would hit the same wall
                seq.draft_stale = True
                continue
            fresh.append((seq, n))
        if not fresh:
            return
        tables = self._sync_draft_tables([s for s, _ in fresh])
        B = self.scheduler.max_batch
        T = self.token_budget
        fn = self._draft_mixed_fn(B, T)
        self._introspect(
            "draft_mixed", (B, T), fn,
            (self._draft_params, tokens, self._draft_kv, tables, pos,
             q_slot, q_start, q_len),
        )
        kv = self._draft_kv
        self._draft_kv = None  # donated
        try:
            self._draft_kv = fn(
                self._draft_params, jnp.asarray(tokens), kv,
                jnp.asarray(tables), jnp.asarray(pos), jnp.asarray(q_slot),
                jnp.asarray(q_start), jnp.asarray(q_len),
            )
        except Exception:
            self._draft_kv = kv  # see _run_mixed: keep failures diagnosable
            raise
        for seq, n in fresh:
            seq.draft_fed += n

    def _queue_depth(self) -> int:
        return len(self.scheduler.waiting) + len(self.scheduler.preempted)

    def _emit(self, seq: SequenceState, tok: int) -> None:
        """Append one generated token, stream it, and retire on stop/limit."""
        seq.tokens.append(tok)
        seq.next_tok = tok
        self.stats.tokens_generated += 1
        if self.obs is not None:
            self.obs.tokens(seq.req.rid)  # stamped at the last sync
        if self._stream_cb is not None:
            self._stream_cb(seq.req.rid, tok)
        gen_tokens = seq.generated()
        if (
            len(gen_tokens) >= seq.req.max_new_tokens
            or detect_stop_tokens(gen_tokens, seq.req.stop_sequences)
            or len(seq.tokens) >= self.max_seq_length
        ):
            self._finish(seq)

    def pop_result(self, rid: str) -> Optional[List[int]]:  # mdi-thread: engine
        """Take one finished request's token list (prompt + generation,
        stop-trimmed) out of the engine, or None if it has not finished.
        The open-system front-end (`server/frontend.py`) collects results
        through this so a long-lived engine's result map stays bounded by
        requests in flight, not by traffic history; the replay `run()`
        return value is unaffected (it snapshots before anyone pops)."""
        return self._results.pop(rid, None)

    def _finish(self, seq: SequenceState) -> None:
        gen_tokens = seq.generated()
        cut = find_eot(gen_tokens, seq.req.stop_sequences)
        self._results[seq.req.rid] = seq.tokens[: seq.n_prompt + cut]
        self.scheduler.retire(seq)
        self.stats.requests_finished += 1

    def _live_reserved(
        self, seqs: List[SequenceState], n_writes_of,
    ) -> List[SequenceState]:
        """Filter to sequences that still own their slot AND have block
        coverage for their next writes; growth may preempt — drop any
        sequence that lost its own slot in the process."""
        live: List[SequenceState] = []
        for seq in seqs:
            if self.scheduler.slots[seq.slot] is seq and \
                    self.scheduler.ensure_blocks_for(seq, n_writes_of(seq)):
                live.append(seq)
        return [s for s in live if self.scheduler.slots[s.slot] is s]

    def _run_decode(self, seqs: List[SequenceState]) -> None:
        t0 = time.perf_counter()
        live = self._live_reserved(seqs, lambda s: 1)
        if not live:
            return
        B = self.scheduler.max_batch
        tok = np.zeros((B,), np.int32)
        pos = np.zeros((B,), np.int32)
        for seq in live:
            tok[seq.slot] = seq.next_tok
            pos[seq.slot] = seq.fed
        tables = self._sync_tables(live)
        fn = self._decode_fn(B)
        self._introspect(
            "decode", (B,), fn,
            (self._params, tok, self._kv, tables, pos, self.gen.key,
             self._t_op, self._p_op),
            {"mode": self._sample_mode, "top_k": self.cfg.top_k},
        )
        kv = self._kv
        self._kv = None  # donated
        try:
            nxt, self._kv, self.gen.key = fn(
                self._params, jnp.asarray(tok), kv, jnp.asarray(tables),
                jnp.asarray(pos), self.gen.key, self._t_op, self._p_op,
                mode=self._sample_mode, top_k=self.cfg.top_k,
            )
        except Exception:
            self._kv = kv  # see _run_mixed: keep failures diagnosable
            raise
        nxt = np.asarray(nxt)
        self.stats.decode_steps += 1
        self.stats.host_syncs += 1
        self.stats.observe_dispatch(B, len(live))
        self.stats.observe_resident(len(self.scheduler.running()))
        self.stats.observe_kv_utilization(self.pool.utilization)
        if self.obs is not None:
            self.obs.step(
                "decode", width=B, live=len(live), t_start=t0,
                kv_utilization=self.pool.utilization,
                queue_depth=self._queue_depth(),
            )
        for seq in live:
            seq.fed += 1
            self._emit(seq, int(nxt[seq.slot]))
        self.stats.decode_s += time.perf_counter() - t0

    # -- chunked decode (the multi-token serving step) ------------------------

    def _chunk_limit(self, seq: SequenceState, K: int, ahead: int = 0) -> int:
        """Steps this slot may actually advance in a K-step chunk: its
        remaining token budget and window room, minus `ahead` tokens already
        committed to an in-flight (undrained) chunk."""
        remaining = seq.req.max_new_tokens - seq.n_generated - ahead
        window = self.max_seq_length - len(seq.tokens) - ahead
        return max(0, min(K, remaining, window))

    @staticmethod
    def _stop1(seq: SequenceState) -> int:
        """The slot's single-token stop id for the device-side stop mask
        (-1 for none).  Multi-token stop sequences are detected host-side
        between chunks, exactly like `Generator.generate`'s chunked loop —
        the extra computed tokens are discarded, the stream is unchanged."""
        for s in seq.req.stop_sequences:
            if len(s) == 1:
                return int(s[0])
        return -1

    def _drain_tokens(
        self, live: List[SequenceState], limits: np.ndarray, toks: np.ndarray,
    ) -> bool:
        """Credit one drained chunk to the scheduler state: emit each live
        slot's retained tokens (up to its limit, stopping at the first
        host-detected stop/budget retirement).  Returns True when every
        slot emitted its full limit and survived — the precondition for
        chaining another speculative chunk."""
        self.stats.host_syncs += 1
        self.stats.observe_kv_utilization(self.pool.utilization)
        self.stats.observe_resident(len(self.scheduler.running()))
        if self.obs is not None:
            # span start defaults to the previous boundary stamp — under
            # double-buffering the drained chunk's compute overlapped the
            # previous read, so boundary-to-boundary IS its wall window
            self.obs.step(
                "decode_chunk",
                width=self.scheduler.max_batch * self.cfg.decode_chunk,
                live=len(live), kv_utilization=self.pool.utilization,
                queue_depth=self._queue_depth(),
            )
        clean = True
        for seq in live:
            if self.scheduler.slots[seq.slot] is not seq:
                clean = False  # lost the slot while the chunk was in flight
                continue
            lim = int(limits[seq.slot])
            emitted = 0
            for s in range(lim):
                seq.fed += 1
                emitted += 1
                self._emit(seq, int(toks[s, seq.slot]))
                if seq.done:
                    break
            self.stats.tokens_useful += emitted  # drain-time useful credit
            if seq.done or emitted < lim:
                clean = False
        return clean

    def _can_pipeline(self) -> bool:
        """Double-buffering is only sound while the scheduler has no other
        work: an admission/prefill would change the live set mid-flight,
        and a preemption would free blocks the device is still writing.
        With spec_k the chunk is only the no-draft fallback — control must
        return to the scheduler after every chunk so freshly-echoing slots
        switch back to the verify path."""
        sched = self.scheduler
        return (
            self.cfg.double_buffer
            and not self.cfg.spec_k
            and not sched.waiting
            and not sched.preempted
            and not any(s.needs_prefill for s in sched.running())
        )

    def _run_decode_chunk(self, seqs: List[SequenceState]) -> None:
        """One decode action in chunked mode: scan K steps on device per
        host sync, and — while no other scheduler work is pending —
        double-buffer the dispatch so chunk N's host read overlaps chunk
        N+1's compute (the next chunk chains on the scan's final carry,
        device-to-device; block reservation for it must succeed WITHOUT
        preemption, since a preempted victim's blocks could be reallocated
        while the in-flight chunk still writes them)."""
        t0 = time.perf_counter()
        K = self.cfg.decode_chunk
        live = self._live_reserved(seqs, lambda s: self._chunk_limit(s, K))
        if not live:
            return
        B = self.scheduler.max_batch
        fn = self._decode_chunk_fn(B, K)
        tok = np.zeros((B,), np.int32)
        pos = np.zeros((B,), np.int32)
        stop1 = np.full((B,), -1, np.int32)
        limits = np.zeros((B,), np.int32)
        for seq in live:
            tok[seq.slot] = seq.next_tok
            pos[seq.slot] = seq.fed
            stop1[seq.slot] = self._stop1(seq)
            limits[seq.slot] = self._chunk_limit(seq, K)
        tok_d, pos_d = jnp.asarray(tok), jnp.asarray(pos)
        stop_d = jnp.asarray(stop1)
        tables = self._sync_tables(live)
        self._introspect(
            "decode_chunk", (B, K), fn,
            (self._params, tok, self._kv, tables, pos, limits, stop1,
             self.gen.key, self._t_op, self._p_op),
            {"mode": self._sample_mode, "top_k": self.cfg.top_k},
        )
        pending = None  # (limits, sampled tokens still on device)
        while True:
            kv = self._kv
            self._kv = None  # donated
            try:
                toks_j, tok_d, pos_d, self._kv, self.gen.key = fn(
                    self._params, tok_d, kv, jnp.asarray(tables), pos_d,
                    jnp.asarray(limits), stop_d, self.gen.key,
                    self._t_op, self._p_op,
                    mode=self._sample_mode, top_k=self.cfg.top_k,
                )
            except Exception:
                self._kv = kv  # see _run_mixed: keep failures diagnosable
                raise
            self.stats.decode_steps += K
            # useful side credited at drain time: only tokens actually
            # retained count (a lane stop-frozen mid-chunk reports its
            # remaining steps as padding, per the padded_token_frac contract)
            self.stats.observe_dispatch(B * K, 0)
            clean = True
            if pending is not None:
                prev_limits, prev_toks = pending
                # THE chunk-boundary sync: one host read per K decode steps,
                # overlapping the chunk dispatched above
                toks_np = np.asarray(prev_toks)  # mdi-lint: disable=host-sync -- the intentional chunk-boundary read; everything else in this loop stays on device
                clean = self._drain_tokens(live, prev_limits, toks_np)
            pending = (limits, toks_j)
            if not (clean and self._can_pipeline()):
                break
            # project the next chunk's limits assuming full emission; a
            # slot that just exhausted its budget projects to 0 (frozen)
            nxt = np.zeros((B,), np.int32)
            for seq in live:
                nxt[seq.slot] = self._chunk_limit(
                    seq, K, ahead=int(limits[seq.slot])
                )
            if not nxt.any():
                break
            ok = True
            for seq in live:
                ok = ok and self.scheduler.try_reserve(
                    seq, int(limits[seq.slot]) + int(nxt[seq.slot])
                )
            if not ok:
                break  # pool too tight to reserve without preemption
            limits = nxt
            tables = self._sync_tables(live)
        prev_limits, prev_toks = pending
        self._drain_tokens(live, prev_limits, np.asarray(prev_toks))
        self.stats.decode_s += time.perf_counter() - t0

    # -- batched speculative decode (ragged verify over the paged cache) ------

    def _draft_ready(self, seq: SequenceState) -> bool:
        """May this lane use the model drafter this round?  Requires
        fresh draft KV (a catch-up gap the K+2-wide scan can absorb) and
        draft-pool coverage for every scan write (positions through
        `fed + spec_k + 1`).  A lane that fell past the absorbable gap
        (chunked-fallback rounds advanced the target without the draft)
        goes permanently stale — the documented quality concession: it
        keeps the n-gram drafter rather than paying a re-prefill."""
        if self.draft_gen is None or seq.draft_stale:
            return False
        gap = seq.fed - seq.draft_fed
        if not 0 <= gap <= self.cfg.spec_k + 1:
            seq.draft_stale = True
            return False
        return self._ensure_draft_blocks(seq, seq.fed + self.cfg.spec_k + 2)

    def _run_draft_scan(
        self, seqs: List[SequenceState], K: int,
    ) -> List[Tuple[SequenceState, List[int]]]:
        """ONE jitted draft-model dispatch proposing K greedy tokens per
        lane (`_draft_scan_fn`: ragged catch-up + K-1 step scan).  Costs
        one extra host read, paid only on rounds where some lane actually
        uses the model drafter; n-gram-hit rounds never dispatch it."""
        t0 = time.perf_counter()
        B = self.scheduler.max_batch
        F = K + 2
        toks_in = np.zeros((B, F), np.int32)
        pos0 = np.zeros((B,), np.int32)
        n_in = np.zeros((B,), np.int32)
        for seq in seqs:
            # decode-phase invariant: tokens[fed] IS the pending token, so
            # the catch-up feed is every token the draft has not seen yet
            feed = seq.tokens[seq.draft_fed : seq.fed + 1]
            toks_in[seq.slot, : len(feed)] = feed
            pos0[seq.slot] = seq.draft_fed
            n_in[seq.slot] = len(feed)
        tables = self._sync_draft_tables(seqs)
        fn = self._draft_scan_fn(B, F)
        self._introspect(
            "draft_scan", (B, F), fn,
            (self._draft_params, toks_in, self._draft_kv, tables, pos0,
             n_in),
        )
        kv = self._draft_kv
        self._draft_kv = None  # donated
        try:
            d, self._draft_kv = fn(
                self._draft_params, jnp.asarray(toks_in), kv,
                jnp.asarray(tables), jnp.asarray(pos0), jnp.asarray(n_in),
            )
        except Exception:
            self._draft_kv = kv  # see _run_mixed: keep failures diagnosable
            raise
        d = np.asarray(d)  # mdi-lint: disable=host-sync -- the draft proposals feed the verify batch built host-side; only model-draft rounds pay this read
        self.stats.host_syncs += 1
        # the scan's F-wide feed plus K-1 single steps, all draft-model
        # positions; useful credit rides the verify's accepted tokens
        self.stats.observe_dispatch(B * F + B * max(K - 1, 0), 0)
        if self.obs is not None:
            self.obs.step(
                "draft_scan", width=B * F, live=len(seqs), t_start=t0,
                kv_utilization=self.pool.utilization,
                queue_depth=self._queue_depth(), spec_k=K,
            )
        return [(seq, [int(t) for t in d[seq.slot]]) for seq in seqs]

    def _run_spec_decode(self, seqs: List[SequenceState]) -> bool:
        """Batched speculative serving step: draft up to `spec_k` tokens
        per slot — prompt-lookup first (`ngram_draft`, the machinery
        `generate()`'s B=1 fast path uses), the optional draft model where
        the lookup misses — score every slot's [pending] + draft in ONE
        ragged verify forward over the paged cache, and emit each slot's
        accepted prefix + bonus/resampled token.  The verify rule follows
        `ServingConfig.spec_verify_sampled()`: exact-match against greedy
        successors at temperature 0 (bit-identical streams, the historical
        path), the rejection-sampled accept/resample of `ops/sampling.
        speculative_verify` at temperature>0 (distribution-preserving).
        Returns False when NO slot drafted — the caller falls back to a
        plain chunked burst (a (K+1)-wide verify would burn (K+1)x the
        step cost to emit one token per slot)."""
        K = self.cfg.spec_k
        sampled = self.cfg.spec_verify_sampled()
        candidates = [
            s for s in seqs if self.scheduler.slots[s.slot] is s
        ]
        drafts: Dict[int, List[int]] = {}
        source: Dict[int, str] = {}
        model_lanes: List[SequenceState] = []
        for seq in candidates:
            # draft only with window room for all K+1 writes and at least
            # 2 tokens of budget left (a 1-token tail gains nothing); cap
            # the draft at remaining-1 so the reservation below never
            # exceeds the blocks_needed(prompt+max_new) worst case that
            # admission guaranteed — an uncapped draft on a hand-sized
            # pool could demand coverage no preemption can free (livelock)
            room = self.max_seq_length - seq.fed - 1
            remaining = seq.req.max_new_tokens - seq.n_generated
            if room >= K + 1 and remaining >= 2:
                d = ngram_draft(seq.tokens, K)[: remaining - 1]
                if d:
                    drafts[seq.slot] = [int(t) for t in d]
                    source[seq.slot] = "ngram"
                elif self._draft_ready(seq):
                    model_lanes.append(seq)
        if model_lanes:
            for seq, d in self._run_draft_scan(model_lanes, K):
                remaining = seq.req.max_new_tokens - seq.n_generated
                d = d[: remaining - 1]
                if d:
                    drafts[seq.slot] = d
                    source[seq.slot] = "model"
        if not drafts:
            return False
        t0 = time.perf_counter()
        live = self._live_reserved(
            candidates, lambda s: len(drafts.get(s.slot, ())) + 1
        )
        if not live:
            self.stats.decode_s += time.perf_counter() - t0
            return True
        B = self.scheduler.max_batch
        toks_in = np.zeros((B, K + 1), np.int32)
        pos = np.zeros((B,), np.int32)
        dlen = np.zeros((B,), np.int32)
        fed0 = {id(s): s.fed for s in live}
        for seq in live:
            row = [int(seq.next_tok)] + pad_draft(drafts.get(seq.slot, []), K)
            toks_in[seq.slot] = row
            pos[seq.slot] = seq.fed
            dlen[seq.slot] = len(drafts.get(seq.slot, ()))
        tables = self._sync_tables(live)
        if sampled:
            fn = self._verify_sample_fn(B, K + 1)
            self._introspect(
                "verify_sample", (B, K + 1), fn,
                (self._params, toks_in, self._kv, tables, pos, dlen,
                 self.gen.key, self._t_op, self._p_op),
                {"mode": self._sample_mode, "top_k": self.cfg.top_k},
            )
            kv = self._kv
            self._kv = None  # donated
            try:
                out, n_emit, self._kv, self.gen.key = fn(
                    self._params, jnp.asarray(toks_in), kv,
                    jnp.asarray(tables), jnp.asarray(pos),
                    jnp.asarray(dlen), self.gen.key, self._t_op,
                    self._p_op,
                    mode=self._sample_mode, top_k=self.cfg.top_k,
                )
            except Exception:
                self._kv = kv  # see _run_mixed: keep failures diagnosable
                raise
            out = np.asarray(out)  # mdi-lint: disable=host-sync -- the verify boundary read (tokens + per-slot emit counts in one sync)
            n_emit = np.asarray(n_emit)
            bursts = {
                seq.slot: [
                    int(t) for t in out[seq.slot, : int(n_emit[seq.slot])]
                ]
                for seq in live
            }
        else:
            fn = self._verify_fn(B, K + 1)
            self._introspect(
                "verify", (B, K + 1), fn,
                (self._params, toks_in, self._kv, tables, pos),
            )
            kv = self._kv
            self._kv = None  # donated
            try:
                g, self._kv = fn(
                    self._params, jnp.asarray(toks_in), kv,
                    jnp.asarray(tables), jnp.asarray(pos),
                )
            except Exception:
                self._kv = kv  # see _run_mixed: keep failures diagnosable
                raise
            g = np.asarray(g)
            # accept only over the REAL draft length: a 0-padded row must
            # not luck into matching the model's 0-token successor
            bursts = {
                seq.slot: accept_draft(
                    pad_draft(drafts.get(seq.slot, []), K), g[seq.slot],
                    len(drafts.get(seq.slot, ())),
                )
                for seq in live
            }
        self.stats.decode_steps += 1
        self.stats.host_syncs += 1
        accepted_total = sum(len(b) - 1 for b in bursts.values())
        if self.obs is not None:
            self.obs.step(
                "verify", width=B * (K + 1), live=len(live), t_start=t0,
                kv_utilization=self.pool.utilization,
                queue_depth=self._queue_depth(),
                spec_k=K, accepted=accepted_total,
            )
        # useful side credited below per slot as len(burst) — the pending
        # row plus ACCEPTED draft rows; rejected draft rows are padding
        # (the padded_token_frac contract)
        self.stats.observe_dispatch(B * (K + 1), 0)
        self.stats.observe_kv_utilization(self.pool.utilization)
        self.stats.observe_resident(len(self.scheduler.running()))
        for seq in live:
            d = drafts.get(seq.slot, [])
            burst = bursts[seq.slot]
            src = source.get(seq.slot)
            accepted = len(burst) - 1
            self.stats.spec_drafted += len(d)
            self.stats.spec_accepted += accepted
            if src == "model":
                self.stats.spec_drafted_model += len(d)
                self.stats.spec_accepted_model += accepted
            elif src == "ngram":
                self.stats.spec_drafted_ngram += len(d)
                self.stats.spec_accepted_ngram += accepted
            if self.obs is not None and src is not None:
                self.obs.spec(len(d), accepted, src)
            self.stats.tokens_useful += len(burst)
            for t in burst:
                seq.fed += 1
                self._emit(seq, int(t))
                if seq.done:
                    break
            if src == "model" and not seq.done:
                # the scan wrote draft KV for proposals d_1..d_{K-1}; the
                # accepted prefix of those is now real sequence — the next
                # catch-up resumes right after it
                seq.draft_fed = fed0[id(seq)] + 1 + min(accepted, K - 1)
        self.stats.decode_s += time.perf_counter() - t0
        return True

    def prime(self) -> None:
        """Dispatch the conditionally-reached speculative executables once
        with inert operands so they compile at WARMUP time.  The
        mixed/decode executables compile on any real warmup trace, but a
        verify only fires when a draft actually HITS and the draft scan
        only on an n-gram miss — workload-dependent events a short warmup
        trace may never produce, leaving the executable cold and its first
        mid-serve hit compiling inside the timed region (the
        zero-post-warmup-recompile contract).  Every table row points at
        block 0 (the reserved trash block), so the donated pool writes are
        discarded by construction and no live sequence state changes; the
        jit cache is per-Generator, so priming one engine warms every
        engine sharing its `gen`."""
        K = self.cfg.spec_k
        if not K:
            return
        B = self.scheduler.max_batch
        tables = np.zeros((B, self.max_blocks_per_seq), np.int32)
        toks = np.zeros((B, K + 1), np.int32)
        zB = np.zeros((B,), np.int32)
        kv = self._kv
        self._kv = None  # donated
        try:
            if self.cfg.spec_verify_sampled():
                fn = self._verify_sample_fn(B, K + 1)
                _, _, self._kv, self.gen.key = fn(
                    self._params, jnp.asarray(toks), kv,
                    jnp.asarray(tables), jnp.asarray(zB), jnp.asarray(zB),
                    self.gen.key, self._t_op, self._p_op,
                    mode=self._sample_mode, top_k=self.cfg.top_k,
                )
            else:
                fn = self._verify_fn(B, K + 1)
                _, self._kv = fn(
                    self._params, jnp.asarray(toks), kv,
                    jnp.asarray(tables), jnp.asarray(zB),
                )
        except Exception:
            self._kv = kv  # see _run_mixed: keep failures diagnosable
            raise
        if self.draft_gen is None:
            return
        dtables = np.zeros_like(self._draft_tables)
        dkv = self._draft_kv
        self._draft_kv = None  # donated
        try:
            _, self._draft_kv = self._draft_scan_fn(B, K + 2)(
                self._draft_params, jnp.zeros((B, K + 2), jnp.int32),
                dkv, jnp.asarray(dtables), jnp.asarray(zB),
                jnp.asarray(zB),
            )
        except Exception:
            self._draft_kv = dkv
            raise
        T = self.token_budget
        trash_pos = self.max_blocks_per_seq * self.pool.block_size
        dkv = self._draft_kv
        self._draft_kv = None  # donated
        try:
            self._draft_kv = self._draft_mixed_fn(B, T)(
                self._draft_params, jnp.zeros((1, T), jnp.int32), dkv,
                jnp.asarray(dtables),
                jnp.full((1, T), trash_pos, jnp.int32),
                jnp.zeros((T,), jnp.int32), jnp.asarray(zB),
                jnp.asarray(zB),
            )
        except Exception:
            self._draft_kv = dkv
            raise

    def step(self) -> bool:  # mdi-thread: engine
        """Run one scheduler action; False when nothing was runnable.

        Any pending prefill work rides the unified mixed step together
        with every decode lane; pure-decode turns run the multi-token
        machinery (chunked scan / speculative verify) unchanged."""
        action = self.scheduler.next_batch(self.token_budget)
        # queue-depth high-water mark AFTER admission: what next_batch
        # could not seat this step (the open-system congestion signal;
        # two host-side len() reads, no device work)
        self.stats.queue_depth_peak = max(
            self.stats.queue_depth_peak, self._queue_depth()
        )
        if action is None:
            return False
        if action[0] == "mixed":
            self._run_mixed(action[1])
        elif self.cfg.spec_k and self._run_spec_decode(action[1]):
            pass  # speculative verify served this decode turn
        elif self.cfg.decode_chunk > 1:
            self._run_decode_chunk(action[1])
        else:
            self._run_decode(action[1])
        # host tier: swap gathers issued by this step's preemptions/spills
        # materialize now, their device→host copy overlapped behind the
        # dispatch above (the step's own sync already paid the wait)
        self._drain_swaps()
        return True

    def run(self, stream_cb=None,  # mdi-thread: engine
            step_hook=None) -> Tuple[Dict[str, List[int]], ServingStats]:
        """Drive the loop until every queued request finishes.  Returns
        {rid: full token list (prompt + generation, stop-trimmed)} — the
        same shape `Generator.generate` returns per prompt — and stats.

        `stream_cb(rid, token)` fires per generated token when given.
        `step_hook(i)` fires after the i-th engine step (1-based) —
        `mdi-serve --xprof-steps` hangs its bounded profiler window off
        this (utils/profiling.StepWindowProfiler).
        """
        self._stream_cb = stream_cb
        t0 = time.perf_counter()
        n_steps = 0
        if self.obs is not None:
            self.obs.attach_compile_hook()
        try:
            while self.scheduler.has_work:
                if not self.step():
                    break
                if step_hook is not None:
                    n_steps += 1
                    step_hook(n_steps)
        finally:
            self.stats.preemptions = self.scheduler.preemptions
            self.stats.prefix_cache_hits = self.pool.prefix_hits
            if self.host_tier is not None:
                self._drain_swaps()  # park in-flight snapshots in the slabs
                tier = self.host_tier
                self.stats.swaps_out = tier.swaps_out
                self.stats.swaps_in = tier.swaps_in
                self.stats.swap_out_bytes = tier.swap_out_bytes
                self.stats.swap_in_bytes = tier.swap_in_bytes
                self.stats.prefix_hits_host = self.pool.prefix_hits_host
            self.stats.wall_s += time.perf_counter() - t0
            self._stream_cb = None
            if self.obs is not None:
                self.obs.detach_compile_hook()
                # publish every report already cached on the Generator for
                # this engine's pool dtype: a fresh observer on a WARM
                # model gets the warmup-time executable cost sheets
                # without a single new lower/compile
                for (_l, _k, variant), rep in self.gen._exec_reports.items():
                    if variant == self.kv_dtype_name:
                        self.obs.publish_device_report(rep)
                hits = self.obs.metrics.counter(
                    "serving_prefix_hit_blocks_total",
                    "pool blocks reused copy-free",
                )
                if self.pool.prefix_hits > hits.value:  # observer may be
                    hits.set_to(self.pool.prefix_hits)  # shared across engines
                for k, v in self.pool.snapshot().items():
                    self.obs.metrics.gauge(
                        f"serving_kv_pool_{k}", f"KVPool.{k} at run end"
                    ).set(v)
        return dict(self._results), self.stats
