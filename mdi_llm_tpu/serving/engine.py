"""Continuous-batching serving engine over the paged KV pool.

Request-level scheduling on top of the existing jitted forward machinery:
where `Generator.generate` allocates one contiguous `[B, S]` cache per call
and holds the batch shape for the whole run, `ServingEngine` keeps ONE
pooled block cache (`transformer.init_paged_kv_cache`) shared by every
in-flight request, admits requests from a queue into `max_batch` decode
slots, runs chunked prefill interleaved with batched decode, retires
finished sequences mid-batch, and reuses blocks across requests (including
copy-free prefix sharing for common prompt heads — chat system prompts,
`utils/prompts.py` styles).

Greedy parity contract (pinned by tests/test_serving.py): because the
paged attention op masks strictly by absolute position and its lax
fallback runs the exact `ops/attention.py` softmax chain, the per-request
greedy token streams are identical to sequential `Generator.generate`
calls — scheduling order, chunking, lane assignment and block placement
are all invisible to the math.

Device dispatch shapes stay bounded: prefill chunks use the same
power-of-two buckets as `generation.py` (one compile per bucket) at B=1,
and decode is a fixed `(max_batch, 1)` step (dead lanes ride along as
padding writing into the pool's trash block).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mdi_llm_tpu.config import ServingConfig
from mdi_llm_tpu.generation import (
    Generator,
    _bucket,
    detect_stop_tokens,
    find_eot,
)
from mdi_llm_tpu.models import transformer
from mdi_llm_tpu.ops.sampling import (
    sample,
    sample_mode,
    sample_traced,
    sampling_operands,
)
from mdi_llm_tpu.serving.kv_pool import KVPool
from mdi_llm_tpu.serving.scheduler import Request, Scheduler, SequenceState

__all__ = ["ServingEngine", "ServingStats"]


@dataclass
class ServingStats:
    tokens_generated: int = 0
    prefill_tokens: int = 0
    prefill_chunks: int = 0
    decode_steps: int = 0
    requests_finished: int = 0
    preemptions: int = 0
    prefix_cache_hits: int = 0  # blocks reused copy-free
    wall_s: float = 0.0
    decode_s: float = 0.0
    prefill_s: float = 0.0
    # block-pool utilization, sampled at every decode step as a running
    # aggregate (a long-lived engine must not grow per-step state)
    _kv_util_sum: float = 0.0
    _kv_util_n: int = 0
    _kv_util_peak: float = 0.0

    def observe_kv_utilization(self, util: float) -> None:
        self._kv_util_sum += util
        self._kv_util_n += 1
        self._kv_util_peak = max(self._kv_util_peak, util)

    @property
    def tokens_per_s(self) -> float:
        return self.tokens_generated / self.wall_s if self.wall_s else 0.0

    @property
    def kv_utilization_mean(self) -> float:
        return self._kv_util_sum / self._kv_util_n if self._kv_util_n else 0.0

    @property
    def kv_utilization_peak(self) -> float:
        return self._kv_util_peak


class ServingEngine:
    """Paged-KV continuous-batching loop bound to one `Generator`'s model.

    Build via `Generator.serve(...)`.  Typical use::

        engine = gen.serve(block_size=16, max_batch=8)
        engine.add_request("a", prompt_tokens, max_new_tokens=128)
        results, stats = engine.run()
    """

    def __init__(self, gen: Generator, serving: ServingConfig):
        if gen.mesh is not None:
            raise ValueError(
                "ServingEngine is single-device for now (the pooled block "
                "cache has no sharding layout); build the Generator without "
                "a mesh"
            )
        self.gen = gen
        self.cfg = serving
        bs = serving.block_size
        if bs < 1:
            raise ValueError("block_size must be positive")
        self.max_seq_length = gen.max_seq_length
        # blocks per sequence table: full coverage of the engine window
        self.max_blocks_per_seq = -(-self.max_seq_length // bs)
        # pool size: ServingConfig owns the formula (max_blocks, or every
        # slot grown to the full window plus the trash block) so the
        # mdi-audit memory checker budgets exactly what gets allocated
        num_blocks = serving.num_pool_blocks(self.max_seq_length)
        self.pool = KVPool(num_blocks, bs, prefix_caching=serving.prefix_caching)
        self.scheduler = Scheduler(
            self.pool, serving.max_batch, serving.prefill_chunk,
            self.max_seq_length,
        )
        self._kv = transformer.init_paged_kv_cache(
            gen.cfg, num_blocks, bs, dtype=gen.cache_dtype
        )
        self._fns: Dict[Any, Any] = {}
        # sampling knobs are engine-lifetime constants: upload the traced
        # operands once, not two tiny transfers per decode step
        self._t_op, self._p_op = sampling_operands(
            serving.temperature, serving.top_p
        )
        self._sample_mode = sample_mode(
            serving.temperature, serving.top_k, serving.top_p
        )
        self.stats = ServingStats()
        self._results: Dict[str, List[int]] = {}
        self._stream_cb = None

    # -- compiled phases -----------------------------------------------------

    def _prefill_fn(self, T: int):
        key_ = ("prefill", T)
        if key_ not in self._fns:
            gen = self.gen

            @partial(jax.jit, donate_argnums=(2,))
            def prefill(params, tokens, kv, tables, pos0, true_len):
                logits, kv = transformer.forward(
                    gen.cfg, params, tokens, pos0, kv=kv, rope=gen.rope,
                    moe_impl=gen._moe_impl, paged_tables=tables,
                    paged_kernel=self.cfg.use_kernel,
                )
                last = jnp.take_along_axis(
                    logits, (true_len - 1)[:, None, None], axis=1
                )[:, 0]
                return last, kv

            self._fns[key_] = prefill
        return self._fns[key_]

    def _decode_fn(self, B: int):
        key_ = ("decode", B)
        if key_ not in self._fns:
            gen = self.gen

            # float knobs ride as traced operands; the cache keys only on
            # (mode, top_k) — a per-request temperature sweep would otherwise
            # compile one decode executable per distinct float
            @partial(
                jax.jit, donate_argnums=(2,),
                static_argnames=("mode", "top_k"),
            )
            def decode(params, tok, kv, tables, input_pos, key,
                       temperature, top_p, mode, top_k):
                logits, kv = transformer.forward(
                    gen.cfg, params, tok[:, None], input_pos, kv=kv,
                    rope=gen.rope, moe_impl=gen._moe_impl,
                    unroll=gen.scan_unroll, paged_tables=tables,
                    paged_kernel=self.cfg.use_kernel,
                )
                key, sub = jax.random.split(key)
                nxt = sample_traced(
                    logits[:, -1], sub, temperature, top_p,
                    mode=mode, top_k=top_k,
                )
                return nxt.astype(jnp.int32), kv, key

            self._fns[key_] = decode
        return self._fns[key_]

    # -- request surface -----------------------------------------------------

    def add_request(
        self,
        rid: str,
        prompt: Sequence[int],
        max_new_tokens: int,
        stop_sequences: Sequence[Sequence[int]] = (),
    ) -> str:
        """Queue a request; raises ValueError if it can never fit."""
        self.scheduler.add(Request(
            rid=rid, prompt=[int(t) for t in prompt],
            max_new_tokens=int(max_new_tokens),
            stop_sequences=stop_sequences,
        ))
        return rid

    def _table_row(self, seq: SequenceState) -> np.ndarray:
        row = np.zeros((self.max_blocks_per_seq,), np.int32)
        row[: len(seq.blocks)] = seq.blocks
        return row

    # -- execution -----------------------------------------------------------

    def _run_prefill(self, seq: SequenceState, chunk: int) -> None:
        t0 = time.perf_counter()
        bs = self.pool.block_size
        # grow the table to cover this chunk's writes (admission already
        # reserved enough blocks, so alloc can only fail after preemptions
        # shrank the pool guarantee — grow defensively like decode does)
        while self.pool.blocks_needed(seq.fed + chunk) > len(seq.blocks):
            got = self.pool.alloc(1)
            if got is None:
                if not self.scheduler.preempt_latest(exclude=seq):
                    raise RuntimeError("KV pool exhausted during prefill")
                if self.scheduler.slots[seq.slot] is not seq:
                    return  # self-preempted; it will resume from the queue
                continue
            seq.blocks.extend(got)
        Tb = min(_bucket(chunk), self.max_seq_length)
        toks = np.zeros((1, Tb), np.int32)
        toks[0, :chunk] = seq.tokens[seq.fed : seq.fed + chunk]
        kv = self._kv
        self._kv = None  # donated
        try:
            last, self._kv = self._prefill_fn(Tb)(
                self.gen.params, jnp.asarray(toks), kv,
                jnp.asarray(self._table_row(seq)[None, :]),
                jnp.asarray([seq.fed], jnp.int32),
                jnp.asarray([chunk], jnp.int32),
            )
        except Exception:
            # keep the engine debuggable after a failed dispatch: restore
            # the pool handle (if the donation consumed it, later use fails
            # with jax's clear deleted-buffer error, not a paged-cache one)
            self._kv = kv
            raise
        seq.fed += chunk
        self.stats.prefill_tokens += chunk
        self.stats.prefill_chunks += 1
        if seq.fed >= seq.prefill_target:
            # prompt (as far as it was actually FED) is in the pool: publish
            # its full blocks for prefix reuse.  Only now — registering
            # before the KV is written would let a concurrent request attend
            # garbage — and only up to `fed`: a resumed sequence's prefill
            # stops one token short (the pending token decodes later), so a
            # block-aligned prompt would otherwise register a block whose
            # last slot is still unwritten.
            self.pool.register_prefix(
                seq.blocks, seq.req.prompt[: seq.fed]
            )
            if seq.resume_tok is not None:
                seq.next_tok = seq.resume_tok  # preserved across preemption
            else:
                self.gen.key, sub = jax.random.split(self.gen.key)
                tok = sample(
                    last, sub, temperature=self.cfg.temperature,
                    top_k=self.cfg.top_k, top_p=self.cfg.top_p,
                )
                self._emit(seq, int(np.asarray(tok)[0]))
        self.stats.prefill_s += time.perf_counter() - t0

    def _emit(self, seq: SequenceState, tok: int) -> None:
        """Append one generated token, stream it, and retire on stop/limit."""
        seq.tokens.append(tok)
        seq.next_tok = tok
        self.stats.tokens_generated += 1
        if self._stream_cb is not None:
            self._stream_cb(seq.req.rid, tok)
        gen_tokens = seq.generated()
        if (
            len(gen_tokens) >= seq.req.max_new_tokens
            or detect_stop_tokens(gen_tokens, seq.req.stop_sequences)
            or len(seq.tokens) >= self.max_seq_length
        ):
            self._finish(seq)

    def _finish(self, seq: SequenceState) -> None:
        gen_tokens = seq.generated()
        cut = find_eot(gen_tokens, seq.req.stop_sequences)
        self._results[seq.req.rid] = seq.tokens[: seq.n_prompt + cut]
        self.scheduler.retire(seq)
        self.stats.requests_finished += 1

    def _run_decode(self, seqs: List[SequenceState]) -> None:
        t0 = time.perf_counter()
        # every live sequence needs a slot for this step's KV write; growth
        # may preempt — drop any sequence that lost its own slot
        live: List[SequenceState] = []
        for seq in seqs:
            if self.scheduler.slots[seq.slot] is seq and \
                    self.scheduler.ensure_block_for(seq):
                live.append(seq)
        live = [s for s in live if self.scheduler.slots[s.slot] is s]
        if not live:
            return
        B = self.scheduler.max_batch
        tok = np.zeros((B,), np.int32)
        pos = np.zeros((B,), np.int32)
        tables = np.zeros((B, self.max_blocks_per_seq), np.int32)
        for seq in live:
            tok[seq.slot] = seq.next_tok
            pos[seq.slot] = seq.fed
            tables[seq.slot] = self._table_row(seq)
        kv = self._kv
        self._kv = None  # donated
        try:
            nxt, self._kv, self.gen.key = self._decode_fn(B)(
                self.gen.params, jnp.asarray(tok), kv, jnp.asarray(tables),
                jnp.asarray(pos), self.gen.key, self._t_op, self._p_op,
                mode=self._sample_mode, top_k=self.cfg.top_k,
            )
        except Exception:
            self._kv = kv  # see _run_prefill: keep failures diagnosable
            raise
        nxt = np.asarray(nxt)
        self.stats.decode_steps += 1
        self.stats.observe_kv_utilization(self.pool.utilization)
        for seq in live:
            seq.fed += 1
            self._emit(seq, int(nxt[seq.slot]))
        self.stats.decode_s += time.perf_counter() - t0

    def step(self) -> bool:
        """Run one scheduler action; False when nothing was runnable."""
        action = self.scheduler.next_action()
        if action is None:
            return False
        if action[0] == "prefill":
            _, seq, chunk = action
            self._run_prefill(seq, chunk)
        else:
            self._run_decode(action[1])
        return True

    def run(self, stream_cb=None) -> Tuple[Dict[str, List[int]], ServingStats]:
        """Drive the loop until every queued request finishes.  Returns
        {rid: full token list (prompt + generation, stop-trimmed)} — the
        same shape `Generator.generate` returns per prompt — and stats.

        `stream_cb(rid, token)` fires per generated token when given.
        """
        self._stream_cb = stream_cb
        t0 = time.perf_counter()
        try:
            while self.scheduler.has_work:
                if not self.step():
                    break
        finally:
            self.stats.preemptions = self.scheduler.preemptions
            self.stats.prefix_cache_hits = self.pool.prefix_hits
            self.stats.wall_s += time.perf_counter() - t0
            self._stream_cb = None
        return dict(self._results), self.stats
