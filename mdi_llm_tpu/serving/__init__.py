"""Paged-KV serving subsystem: block pool, continuous-batching scheduler,
and the `ServingEngine` request loop (see docs/perf.md "Serving")."""

from mdi_llm_tpu.serving.kv_pool import KVPool
from mdi_llm_tpu.serving.scheduler import Request, Scheduler, SequenceState
from mdi_llm_tpu.serving.engine import ServingEngine, ServingStats

__all__ = [
    "KVPool",
    "Request",
    "Scheduler",
    "SequenceState",
    "ServingEngine",
    "ServingStats",
]
