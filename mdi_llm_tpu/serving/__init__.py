"""Paged-KV serving subsystem: block pool, continuous-batching scheduler
with pluggable policies, and the `ServingEngine` request loop (see
docs/perf.md "Serving" and docs/serving.md for the open-system layer)."""

from mdi_llm_tpu.serving.kv_pool import KVPool
from mdi_llm_tpu.serving.policy import (
    POLICIES,
    DeadlinePolicy,
    FairSharePolicy,
    FCFSPolicy,
    PriorityPolicy,
    SchedulingPolicy,
    make_policy,
)
from mdi_llm_tpu.serving.scheduler import Request, Scheduler, SequenceState
from mdi_llm_tpu.serving.engine import ServingEngine, ServingStats

__all__ = [
    "KVPool",
    "POLICIES",
    "DeadlinePolicy",
    "FairSharePolicy",
    "FCFSPolicy",
    "PriorityPolicy",
    "Request",
    "Scheduler",
    "SchedulingPolicy",
    "SequenceState",
    "ServingEngine",
    "ServingStats",
    "make_policy",
]
