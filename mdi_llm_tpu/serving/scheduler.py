"""Continuous-batching request scheduler.

Policy layer between the request queue and the device loop
(`serving.engine.ServingEngine`): policy-ordered admission into a fixed
set of decode slots (`serving/policy.py` — FCFS by default, priority /
per-tenant fair-share / TTFT-deadline pluggable), token-budget
mixed-batch composition (Sarathi-style: decode lanes first, then prefill
chunks split to fit, packed in policy order), mid-batch retirement, and
recompute-style preemption when the block pool runs dry.

The scheduler never touches device arrays — it owns `SequenceState`
bookkeeping (token lists, block tables, feed positions) and the `KVPool`
accounting, and hands the engine one action at a time (`next_batch`):

    ("mixed", [(seq, n_tokens), ...])  ONE unified ragged forward: every
                                  decode-ready lane's pending token plus
                                  prefill chunks filling the token budget
    ("decode", [seqs])            no prefill work pending — the engine's
                                  chunked/speculative decode paths take over
    None                          nothing runnable (queue empty or blocked)

Feed-position invariants (`SequenceState`):
- `fed` tokens have their K/V in the pool; the next token to feed is
  `tokens[fed]` at absolute position `fed`.
- prefill phase: `fed < prefill_target`; on completion the engine samples
  the first output token from the chunk's last logits (fresh requests) or
  restores the preserved `resume_tok` (preempted requests).
- decode phase: `fed == len(tokens) - 1` — exactly the final sampled token
  is pending, matching `Generator.generate`'s loop.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from mdi_llm_tpu.serving.kv_pool import KVPool
from mdi_llm_tpu.serving.policy import FCFSPolicy, SchedulingPolicy

__all__ = ["Request", "SequenceState", "Scheduler"]


@dataclass
class Request:
    rid: str
    prompt: List[int]
    max_new_tokens: int
    stop_sequences: Sequence[Sequence[int]] = ()
    # open-system scheduling attributes (serving/policy.py): ignored by
    # the default FCFS policy, so replay traces behave exactly as before
    priority: int = 0  # higher admits first under PriorityPolicy
    tenant: str = ""  # fair-share accounting key (FairSharePolicy)
    ttft_slo_s: Optional[float] = None  # TTFT deadline relative to arrival
    arrival_s: Optional[float] = None  # stamped by the policy at add()


class SequenceState:
    """One admitted request's feed/block bookkeeping."""

    def __init__(self, req: Request, blocks: List[int], n_cached: int,
                 slot: int, resume_tokens: Optional[List[int]] = None):
        self.req = req
        # full logical token list (prompt + generated so far)
        self.tokens: List[int] = list(resume_tokens or req.prompt)
        self.blocks = blocks  # shared cached prefix + exclusively owned
        self.n_cached = n_cached  # tokens covered by reused prefix blocks
        self.fed = n_cached  # tokens whose K/V is in the pool
        self.slot = slot
        # resumed sequences already know their pending token; fresh ones
        # sample it from the prefill logits
        self.resume_tok: Optional[int] = (
            self.tokens[-1] if resume_tokens else None
        )
        self.prefill_target = (
            len(self.tokens) - 1 if resume_tokens else len(self.tokens)
        )
        self.next_tok: Optional[int] = None  # sampled, not yet fed
        # a fully-prefix-cached resume needs no prefill at all: the pending
        # token is restored immediately so next_batch sees it decode-ready
        if resume_tokens and self.fed >= self.prefill_target:
            self.next_tok = self.resume_tok
        self.done = False
        self.admit_order = -1  # stamped by the scheduler at admission
        # draft-model bookkeeping (engine-owned; inert without a draft pool):
        # `draft_fed` counts tokens whose K/V the DRAFT model has seen,
        # `draft_blocks` is the sequence's table into the draft pool, and
        # `draft_stale` marks sequences the draft can never catch up on —
        # prefix-cache hits and swap restores hand the TARGET pool KV the
        # draft was never fed (a documented quality concession: those lanes
        # keep the n-gram drafter, never the model drafter).
        self.draft_fed = 0
        self.draft_blocks: List[int] = []
        self.draft_stale = n_cached > 0

    @property
    def n_prompt(self) -> int:
        return len(self.req.prompt)

    @property
    def n_generated(self) -> int:
        return len(self.tokens) - self.n_prompt

    @property
    def needs_prefill(self) -> bool:
        return self.fed < self.prefill_target

    def generated(self) -> List[int]:
        return self.tokens[self.n_prompt:]


class Scheduler:
    def __init__(self, pool: KVPool, max_batch: int, prefill_chunk: int,
                 max_seq_length: int,
                 policy: Optional[SchedulingPolicy] = None):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.pool = pool
        self.max_batch = max_batch
        self.prefill_chunk = max(1, prefill_chunk)
        self.max_seq_length = max_seq_length
        # scheduling policy (serving/policy.py): decides which waiting
        # request takes the next free slot and in what order prefilling
        # sequences split the unified step's token budget.  Pure host-side
        # reordering — dispatch shapes and sync cadence cannot change.
        # The default is FCFS, bit-identical to the pre-policy scheduler.
        self.policy = policy if policy is not None else FCFSPolicy()
        self.waiting: Deque[Request] = deque()
        # preempted sequences resume before fresh admissions (they hold
        # progress the pool already paid for once)
        self.preempted: Deque[Tuple[Request, List[int]]] = deque()
        self.slots: List[Optional[SequenceState]] = [None] * max_batch
        self.finished: List[SequenceState] = []
        self._admit_counter = 0  # admission recency for preemption order
        self.preemptions = 0
        # host-RAM tier seam (serving/host_tier.py): the scheduler stays
        # device- and tier-blind — when ServingConfig.host_pool_mib > 0
        # the engine installs these hooks, and swap-vs-recompute becomes a
        # per-victim cost-model decision instead of always-recompute.
        # rid -> SwapRecord for queued preempted entries whose KV lives in
        # host slots (the deque keeps its historical (req, toks) tuples so
        # the open-system frontend's cancellation scan is untouched).
        self.swap_records: Dict[str, object] = {}
        # seq -> Optional[SwapRecord]: engine gathers the victim's blocks
        # to host slots (enqueued BEFORE the release below frees them) and
        # returns the record, or None to fall back to recompute
        self.swap_out_hook: Optional[Callable[[SequenceState], Optional[object]]] = None
        # (record, hbm_blocks) -> None: engine schedules the payload
        # restore into freshly allocated blocks and reclaims the host slots
        self.swap_in_hook: Optional[Callable[[object, List[int]], None]] = None
        # record -> None: release host slots without restoring (cancel path)
        self.swap_drop_hook: Optional[Callable[[object], None]] = None
        self.swaps_out = 0  # preemptions resolved by swap, not recompute
        self.swaps_in = 0  # admissions resumed from host-tier payloads
        # draft-model KV pool (serving/engine.py installs it when
        # ServingConfig.draft_model is set): the scheduler only RELEASES
        # draft blocks on retire/preempt so the two pools' lifetimes stay
        # in lockstep; allocation is engine-side (non-preempting).
        self.draft_pool: Optional[KVPool] = None
        # observability hook (obs.ServingObserver or None): the scheduler
        # owns the request lifecycle edges — submitted/admitted/resumed/
        # preempted/retired — so it reports them; all hooks are plain
        # host-side appends taken where the bookkeeping already happens
        # (zero device work; see docs/observability.md)
        self.observer = None

    # -- queue ---------------------------------------------------------------

    def validate(self, req: Request) -> None:  # mdi-thread: any
        """The add-time feasibility wall, callable WITHOUT mutating any
        scheduler state: pure reads of pool/window constants, so the
        open-system front-end can pre-check a submission from its own
        thread (HTTP 400) before the engine thread ever sees it."""
        total = len(req.prompt) + req.max_new_tokens
        if len(req.prompt) < 1:
            raise ValueError(f"request {req.rid}: empty prompt")
        if req.max_new_tokens < 1:
            # 0 would break the generate() parity contract: prefill always
            # samples one token before the generated-length check fires (and
            # with max_new >= 1 the add-time footprint check below also
            # covers admission's blocks_needed(prompt + 1) reservation)
            raise ValueError(f"request {req.rid}: max_new_tokens must be >= 1")
        if total > self.max_seq_length:
            raise ValueError(
                f"request {req.rid}: prompt+max_new_tokens {total} exceeds "
                f"max_seq_length {self.max_seq_length}"
            )
        # worst-case block footprint must fit the pool even running alone
        if self.pool.blocks_needed(total) > self.pool.num_blocks - 1:
            raise ValueError(
                f"request {req.rid}: needs {self.pool.blocks_needed(total)} "
                f"blocks, pool has {self.pool.num_blocks - 1}"
            )

    def add(self, req: Request) -> None:  # mdi-thread: engine
        self.validate(req)
        self.policy.on_submitted(req)  # stamps arrival_s for deadlines
        self.waiting.append(req)
        if self.observer is not None:
            self.observer.request_submitted(
                req.rid, len(req.prompt), req.max_new_tokens
            )

    @property
    def has_work(self) -> bool:  # mdi-thread: engine
        return bool(
            self.waiting or self.preempted
            or any(s is not None for s in self.slots)
        )

    def running(self) -> List[SequenceState]:  # mdi-thread: engine
        return [s for s in self.slots if s is not None]

    # -- admission -----------------------------------------------------------

    def _free_slot(self) -> Optional[int]:  # mdi-thread: engine
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def _try_admit_one(self, req: Request,
                       resume_tokens: Optional[List[int]]) -> Optional[SequenceState]:
        slot = self._free_slot()
        if slot is None:
            return None
        tokens = resume_tokens or req.prompt
        if resume_tokens and req.rid in self.swap_records:
            return self._try_admit_swapped(req, resume_tokens, slot)
        cached, n_cached = self.pool.match_prefix(tokens)
        # cover every prefill write plus the first decode write
        target = len(tokens) - 1 if resume_tokens else len(tokens)
        need = self.pool.blocks_needed(target + 1) - len(cached)
        owned = self.pool.alloc(max(0, need))
        if owned is None:
            self.pool.release(cached)
            return None
        seq = SequenceState(req, cached + owned, n_cached, slot,
                            resume_tokens=resume_tokens)
        seq.admit_order = self._admit_counter
        self._admit_counter += 1
        self.slots[slot] = seq
        if self.observer is not None:
            self.observer.request_admitted(
                req.rid, slot, seq.admit_order, n_cached=n_cached,
                resumed=resume_tokens is not None,
            )
        return seq

    def _try_admit_swapped(self, req: Request, resume_tokens: List[int],
                           slot: int) -> Optional[SequenceState]:
        """Resume a swapped-out victim: allocate its whole table fresh
        (the restored payload carries the KV, so the prefix cache is
        bypassed — sharing a matched block would alias restore writes into
        it), schedule the host→HBM restore, and admit the sequence
        fully-cached: `fed` lands on the swap record's token coverage, so
        a mid-decode victim re-enters with ZERO re-prefill (its pending
        token is set immediately) and a mid-prefill victim re-prefills
        only the tail it had not fed yet."""
        record = self.swap_records[req.rid]
        target = len(resume_tokens) - 1  # the pending token rides along
        owned = self.pool.alloc(self.pool.blocks_needed(target + 1))
        if owned is None:
            return None  # record kept; the next admit() retries
        del self.swap_records[req.rid]
        n_cached = min(record.n_tokens, target)
        self.swap_in_hook(
            record, owned[: self.pool.blocks_needed(record.n_tokens)]
        )
        self.swaps_in += 1
        seq = SequenceState(req, owned, n_cached, slot,
                            resume_tokens=resume_tokens)
        seq.admit_order = self._admit_counter
        self._admit_counter += 1
        self.slots[slot] = seq
        if self.observer is not None:
            self.observer.request_admitted(
                req.rid, slot, seq.admit_order, n_cached=n_cached,
                resumed=True, restored=True,
            )
        return seq

    def drop_swap_record(self, rid: str) -> None:  # mdi-thread: engine
        """Forget a queued entry's swap record, releasing its host slots
        (the open-system frontend's cancel path, after it removes the
        entry from `preempted`).  No-op when the rid holds no record."""
        record = self.swap_records.pop(rid, None)
        if record is not None and self.swap_drop_hook is not None:
            self.swap_drop_hook(record)

    def admit(self) -> List[SequenceState]:  # mdi-thread: engine
        """Policy-ordered admission, preempted sequences first (they hold
        progress the pool already paid for once, whatever the policy).
        Admission stops at the first pick that does not fit — the policy's
        choice blocks the queue rather than being skipped, so block
        accounting stays conservative and the pick can never be starved
        by later arrivals it ranked above (FCFS keeps its historical
        head-of-line no-starvation guarantee as the default policy)."""
        admitted = []
        while self.preempted:
            req, toks = self.preempted[0]
            seq = self._try_admit_one(req, toks)
            if seq is None:
                return admitted
            self.preempted.popleft()
            admitted.append(seq)
        while self.waiting:
            idx = self.policy.select_next(self.waiting, self.running())
            if idx is None:
                return admitted
            seq = self._try_admit_one(self.waiting[idx], None)
            if seq is None:
                return admitted
            del self.waiting[idx]
            admitted.append(seq)
        return admitted

    # -- lifecycle -----------------------------------------------------------

    def retire(self, seq: SequenceState) -> None:  # mdi-thread: engine
        """Mid-batch retirement: free the slot and the blocks (copy-free —
        prefix-registered blocks stay warm in the pool's cached set)."""
        seq.done = True
        self.slots[seq.slot] = None
        self.pool.release(seq.blocks)
        seq.blocks = []
        self._release_draft(seq)
        self.finished.append(seq)
        self.policy.on_retired(seq)  # fair-share usage accounting
        if self.observer is not None:
            self.observer.request_finished(seq.req.rid)

    def _release_draft(self, seq: SequenceState) -> None:
        """Return a sequence's draft-pool blocks (no-op without a draft
        pool).  Draft KV is always recomputable from the token list, so
        retire and preempt both drop it wholesale."""
        if self.draft_pool is not None and seq.draft_blocks:
            self.draft_pool.release(seq.draft_blocks)
        seq.draft_blocks = []
        seq.draft_fed = 0

    def preempt_latest(self, exclude: Optional[SequenceState] = None) -> bool:  # mdi-thread: engine
        """Recompute-style preemption: kick the lowest-priority lane back
        to the queue (its tokens re-prefill on resume).  Within a priority
        class the most recently ADMITTED sequence yields (not the highest
        slot index — slots churn): the newest sequence has the least
        paid-for KV to recompute.  Under plain FCFS every lane has
        priority 0 and this reduces to the pure recency rule, so pool
        pressure can never evict a high-priority stream to keep a
        low-priority one decoding (priority inversion)."""
        victims = [s for s in self.running() if s is not exclude]
        if not victims:
            # fall back to self-preemption: the caller's own sequence yields
            victims = self.running()
        if not victims:
            return False
        seq = min(victims, key=lambda s: (s.req.priority, -s.admit_order))
        # host tier: offer the victim to the engine's swap path BEFORE the
        # release below recycles its blocks — the gather snapshotting the
        # payload is enqueued while the blocks are still owned, so device
        # in-order execution reads them ahead of any new owner's writes.
        # None (cost model says recompute, tier full, or no tier) keeps
        # the historical recompute behavior bit-for-bit.
        record = None
        if self.swap_out_hook is not None:
            record = self.swap_out_hook(seq)
        self.slots[seq.slot] = None
        self.pool.release(seq.blocks)
        seq.blocks = []
        self._release_draft(seq)
        # resume from the full token list; the pending token rides along
        toks = list(seq.tokens)
        if seq.next_tok is not None and (not toks or toks[-1] != seq.next_tok):
            toks.append(seq.next_tok)
        if record is not None:
            self.swap_records[seq.req.rid] = record
            self.swaps_out += 1
        self.preempted.appendleft((seq.req, toks))
        self.preemptions += 1
        if self.observer is not None:
            self.observer.request_preempted(
                seq.req.rid, seq.n_generated, swapped=record is not None
            )
        return True

    def ensure_blocks_for(self, seq: SequenceState, n_writes: int = 1) -> bool:  # mdi-thread: engine
        """Grow a decoding sequence's table to cover its next `n_writes`
        positions (`fed .. fed+n_writes-1` — a K-step decode chunk or a
        speculative verify's K+1 tokens), one block at a time; preempt
        others until it fits.  False if the sequence itself was preempted.

        Rollback contract for speculative reservation: blocks reserved
        ahead of the written tokens are rolled back to the pool through the
        normal release path — `retire` (early stop mid-chunk) and
        `preempt_latest` both release the sequence's WHOLE table, and the
        engine caps `n_writes` at the slot's remaining budget/window so a
        live sequence never holds coverage it cannot use."""
        target = seq.fed + max(1, int(n_writes))
        while self.pool.blocks_needed(target) > len(seq.blocks):
            got = self.pool.alloc(1)
            if got is not None:
                seq.blocks.extend(got)
                continue
            if not self.preempt_latest(exclude=seq):
                raise RuntimeError("KV pool exhausted with nothing to preempt")
            if self.slots[seq.slot] is not seq:  # self-preempted
                return False
        return True

    # back-compat alias (the per-step decode path reserves one write)
    def ensure_block_for(self, seq: SequenceState) -> bool:  # mdi-thread: engine
        return self.ensure_blocks_for(seq, 1)

    def try_reserve(self, seq: SequenceState, n_writes: int) -> bool:  # mdi-thread: engine
        """Non-preempting variant of `ensure_blocks_for`, for reservations
        made while a dispatched chunk is still in flight (double-buffering):
        preempting here would free blocks the device is actively writing.
        Partial growth on failure is safe — the extra blocks ride on the
        sequence and roll back with its table."""
        need = self.pool.blocks_needed(
            seq.fed + max(1, int(n_writes))
        ) - len(seq.blocks)
        if need <= 0:
            return True
        got = self.pool.alloc(need)
        if got is None:
            return False
        seq.blocks.extend(got)
        return True

    # -- action selection ----------------------------------------------------

    def next_batch(self, token_budget: int):  # mdi-thread: engine
        """One step of the continuous-batching policy: admit whatever fits,
        then compose the step's token batch under `token_budget` — decode
        lanes FIRST (one pending token each, so a long prompt can never
        starve a live decode), then prefill chunks packed into the
        remaining budget in admission order, each capped at
        `prefill_chunk` and split across steps when the remainder is
        smaller than the prompt's tail.

        Returns ``("mixed", [(seq, n_tokens), ...])`` whenever any prefill
        work rides along (the engine runs ONE unified ragged forward),
        ``("decode", [seqs])`` when only decode lanes are live (the
        engine's chunked/speculative multi-token paths take over), or
        ``None`` when nothing is runnable.  With ``token_budget >
        max_batch`` (enforced by the engine and mdi-audit) at least one
        prefill token fits every mixed step, so prefill always makes
        progress."""
        self.admit()
        # packing order is the policy's second seam: FCFS returns
        # admission order (the historical behavior); DeadlinePolicy puts
        # the least-slack TTFT deadline first so an urgent prompt takes
        # the leftover budget before relaxed ones.  Reordering only —
        # chunking and the dispatch shape are untouched.
        prefilling = self.policy.order_prefill(
            [s for s in self.running() if s.needs_prefill],
            now=self.policy.clock(),
        )
        decoding = [
            s for s in self.running()
            if not s.needs_prefill and s.next_tok is not None
        ]
        if not prefilling:
            return ("decode", decoding) if decoding else None
        entries: List[Tuple[SequenceState, int]] = [(s, 1) for s in decoding]
        budget = token_budget - len(entries)
        for seq in prefilling:
            if budget <= 0:
                break
            chunk = min(self.prefill_chunk, seq.prefill_target - seq.fed,
                        budget)
            entries.append((seq, chunk))
            budget -= chunk
        return ("mixed", entries)
