"""Pipeline-parallel serving: the paper's recurrent ring fused with
continuous batching.

`PipelinedServingEngine` maps the recurrent pipeline execution model
(`parallel/pipeline.py`'s ring of layer stages connected by
`jax.lax.ppermute`) onto the continuous-batching serving stack
(`serving/engine.py`): the model's layers split over `pp` stages
(`parallel/partition.stage_layers` — the reference's starter/secondary
policy), every stage owns ITS OWN shard of the paged KV pool (stage s
holds the K/V blocks of stage s's layers and nothing else), and the
scheduler's decode lanes become the pipeline's fill — the paper's
"n_samples >= n_stages keeps every stage busy" invariant, re-read as
`max_batch >= pp` (mdi-audit's `pipeline-underfill` warns with the
bubble fraction when a plan violates it).

Execution model — one donated ring per host sync
------------------------------------------------
Every inherited host-side dispatch (`_run_mixed`, `_run_decode`,
`_run_decode_chunk`, `_run_spec_decode`) maps onto ONE jitted call whose
body is a `jax.lax.scan` of ring ticks inside a `jax.shard_map` manual
over the `pp` axis only (a composed `tp` axis stays automatic, so GSPMD
lays each stage's matmuls out under the Megatron shardings — the same
partial-manual idiom as `PipelineEngine`).  Per tick, each stage runs
its (zero-padded to `l_max`, hence single-trace) block stack over one
microbatch and `ppermute`s the activation to the next stage:

- **mixed** `(1, token_budget)`: the packed ragged batch splits into
  `pp` equal token segments; segment m enters stage 0 at tick m, the
  last stage accumulates finished hidden states, and after `2*pp - 1`
  ticks the accumulator is `psum` to every device.  The head + ONE
  `jax.random.split` + sample run OUTSIDE the shard_map at the exact
  single-device shapes, so the sampled-token math and the RNG cadence
  are the base engine's, bit for bit.
- **decode** `(B,)` / **verify** `(B, K+1)`: lanes split into `pp`
  groups of `ceil(B/pp)`; same 2*pp-1-tick sweep, head/sample (or
  argmax) outside.
- **decode_chunk** `(B, K)`: the TRUE recurrent ring.  Each lane group
  is a payload {x, tok, pos, done, step} circling the ring; when a
  payload returns to stage 0 it is sampled (head at `(Bg, 1, D)`),
  advanced one decode step, re-embedded and immediately relaunched —
  `K*pp + pp` ticks serve K tokens for every lane with zero stage
  idling once the ring fills.  The K per-step subkeys are pre-split
  OUTSIDE the ring in the base engine's exact order, so the returned
  key state matches the single-device engine; per-group sampling
  consumes subkey k for group step k (greedy streams — the serving
  parity contract — are key-independent and exactly preserved).

Contract inheritance
--------------------
All host-side machinery is inherited unchanged — scheduler, block
tables, prefix cache, preemption, double-buffering, stats, obs hooks —
so the host-sync cadence is bit-identical to the single-device engine
by construction, and the dispatch shapes stay bounded and
prompt-independent (zero post-warmup recompiles; pinned by
tests/test_pp_serving.py's CompileGuard twin).  Invalid ring ticks
(fill/drain bubbles, batch padding) write through ZEROED block tables,
which the paged-attention op redirects to the pool's reserved trash
block — the same mechanism dead decode lanes already ride.

The Pallas paged kernels are not wired through the ring
(`use_kernel=True` is refused actionably); the exact lax fallback —
what the parity contract is stated against — serves every stage.

jax compatibility: pp-only meshes run on both shard_map generations
(the ring is then fully manual).  Composing tp requires the modern
`jax.shard_map(..., axis_names=)` — the older experimental partial-auto
shard_map crashes XLA's SPMD partitioner on the ring's in-scan KV-pool
scatters, so tp x pp on such builds is refused at engine construction
with the upgrade path spelled out.

Static analysis (mdi-ir / mdi-flow)
-----------------------------------
The ring engine enumerates the SAME `ExecutableSpec` set as the base
engine (inherited `enumerate_executables`, including the argnum roles
params=0 / kv=2), so both analyzers see the pp executables with zero
pipeline-specific seams.  mdi-flow's liveness model descends into each
ring body — the `shard_map` interior is already per-shard, so its scan
carry (the circling payload), the stage's padded block stack and the
per-stage KV-pool shard are counted ONCE per device, while the
inherited kv donation (argnum 2, `donate_argnums=(2,)` on every ring
fn above) aliases the pool in place exactly like the single-device
engine; the tier-1 self-check pins the pp=2 compile set
donation-clean.  Per-stage param bytes use
`parallel/partition.stage_layers` (l_max blocks + replicated
embeddings/head), mirroring mdi-audit's pipeline budget.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from mdi_llm_tpu.config import ServingConfig
from mdi_llm_tpu.models import transformer
from mdi_llm_tpu.ops.sampling import sample_traced
from mdi_llm_tpu.parallel.partition import (
    pad_stage_blocks,
    split_params,
    stage_layers,
)
from mdi_llm_tpu.serving.engine import (
    ServingEngine,
    _pin_kv,
    validate_serving_mesh,
)

__all__ = ["PipelinedServingEngine"]


def _shard_map_api() -> Optional[str]:
    """Which shard_map generation this jax build ships: "new"
    (`jax.shard_map(..., axis_names=, check_vma=)`), "experimental"
    (`jax.experimental.shard_map.shard_map(..., auto=, check_rep=)`),
    or None (no manual-region support at all)."""
    if hasattr(jax, "shard_map"):
        return "new"
    try:
        from jax.experimental.shard_map import shard_map  # noqa: F401

        return "experimental"
    except ImportError:
        return None


def _ring_shard_map(f, mesh, in_specs, out_specs, check):
    """Build the ring's shard_map, manual over the "pp" axis only (any
    composed tp axis stays automatic so GSPMD lays out each stage's
    matmuls), across both jax shard_map generations."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            axis_names={"pp"}, check_vma=check,
        )
    from jax.experimental.shard_map import shard_map

    auto = frozenset(mesh.axis_names) - {"pp"}
    # partial-auto shard_map predates check_rep support for auto axes
    return shard_map(
        f, mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=bool(check) and not auto, auto=auto,
    )


def _stage_run(cfg, blocks, rope, kv_loc, x, pos, tables, moe_impl, unroll,
               ragged=None):
    """One stage's block stack over one microbatch: rope gathers with the
    documented mode="clip" (serving positions include the past-coverage
    trash position), then `transformer.run_blocks` through the stage's
    slice of the paged pool.  `tables` already zeroed for invalid ticks —
    the zero row is the trash-block redirect."""
    cos = jnp.take(rope[0], pos, axis=0, mode="clip")
    sin = jnp.take(rope[1], pos, axis=0, mode="clip")
    return transformer.run_blocks(
        cfg, blocks, x, pos, cos, sin, kv=kv_loc,
        moe_impl=moe_impl, unroll=unroll,
        paged_tables=tables, paged_kernel=False, paged_ragged=ragged,
    )


class PipelinedServingEngine(ServingEngine):
    """Continuous-batching engine over a `pp` (optionally x `tp`) mesh.

    Build via `Generator.serve(...)` on a Generator whose mesh has a
    `pp` axis of size >= 2 (`make_mesh({"pp": N})` or
    `make_mesh({"pp": N, "tp": M})`); `serve()` routes here
    automatically.  The request surface, scheduler, results and stats
    are the base engine's — only the device execution backend changes.
    """

    # stage-stacked pool leaves are (S, l_max, NB, ...) — blocks on axis 2
    _kv_block_axis = 2

    def __init__(self, gen, serving: ServingConfig, obs=None, policy=None):
        if serving.draft_model:
            raise ValueError(
                "draft_model is not supported by the pipeline-parallel "
                "engine: the draft pool has no per-stage ring — serve "
                "the draft-model config on a tp-only (or single-device) "
                "mesh"
            )
        mesh = gen.mesh
        if mesh is None or int(dict(mesh.shape).get("pp", 1)) <= 1:
            raise ValueError(
                "PipelinedServingEngine needs a mesh with a 'pp' axis of "
                "size >= 2 (make_mesh({'pp': N[, 'tp': M]})); for "
                "single-device or tp-only serving use ServingEngine"
            )
        validate_serving_mesh(mesh)
        api = _shard_map_api()
        if api is None:
            raise ValueError(
                "pipeline-parallel serving needs shard_map (the stage "
                "ring is a manual-pp region); this jax build has neither "
                "jax.shard_map nor jax.experimental.shard_map — drop the "
                "pp axis for tp/single-device serving"
            )
        if api != "new" and int(dict(mesh.shape).get("tp", 1)) > 1:
            raise ValueError(
                "composed tp x pp serving needs the modern jax.shard_map "
                "(partial-auto rings on this older jax crash XLA's SPMD "
                "partitioner: KV-pool scatters inside the tick scan of a "
                "manual-pp-with-auto-tp region are unpartitionable) — "
                "upgrade jax, or serve with pp only / tp only on this "
                "build"
            )
        if serving.use_kernel:
            raise ValueError(
                "pipeline-parallel serving (pp > 1) runs the exact lax "
                "paged-attention fallback inside the stage ring; "
                "use_kernel=True is unsupported — leave use_kernel "
                "unset/False, or drop the pp axis to use the Pallas "
                "kernels under tp-only serving"
            )
        S = int(mesh.shape["pp"])
        tp = int(dict(mesh.shape).get("tp", 1))
        # raises actionably when n_layer < pp (every stage needs a block)
        self._stage_counts = stage_layers(gen.cfg.n_layer, S)
        self._pp = S
        self._tp_size = tp
        self._l_max = max(self._stage_counts)
        tp_ax = "tp" if tp > 1 else None
        # stage-stacked pool layout: payload (S, l_max, NB, BS, G, hs),
        # int8 scales (S, l_max, NB, G) — stage axis manual over pp, the
        # KV-group axis sharded over tp exactly like the flat pool
        # (parallel.sharding.paged_kv_spec)
        self._pool_spec = P("pp", None, None, None, tp_ax, None)
        self._scale_spec = P("pp", None, None, tp_ax)
        super().__init__(gen, serving, obs=obs, policy=policy)
        # pin the stacked layout (overrides the flat 5-D/3-D pair the
        # base __init__ took from the Generator)
        self._kv_sharding_pair = (
            NamedSharding(mesh, self._pool_spec),
            NamedSharding(mesh, self._scale_spec),
        )
        # per-stage weights: starter/secondary split, zero-padded to
        # l_max layers (zero blocks are exact identities) and stacked on
        # a leading stage axis laid out over pp; with tp the weight dims
        # additionally follow the Megatron specs so GSPMD (tp is an auto
        # axis of the ring shard_map) places the per-stage all-reduces
        stages = split_params(gen.cfg, gen.params, S)
        abstract = getattr(gen, "abstract", False)
        if abstract:
            # shape-level mirror of pad_stage_blocks: the padded/stacked
            # result is (S, l_max, ...) per leaf regardless of per-stage
            # layer counts, so zero-stride stubs stand in for the stacked
            # weights without materializing a byte (the mdi-ir contract)
            def _stage_stub(leaf):
                leaf = np.asarray(leaf)
                shape = (S, self._l_max) + tuple(leaf.shape[1:])
                return np.broadcast_to(np.zeros((), leaf.dtype), shape)

            blocks_np = jax.tree_util.tree_map(
                _stage_stub, stages[0]["blocks"]
            )
        else:
            blocks_np = pad_stage_blocks(stages, self._l_max)
        repl_sh = NamedSharding(mesh, P())
        if tp > 1:
            from mdi_llm_tpu.parallel.sharding import (
                adapt_specs_to_tree,
                param_specs,
            )

            bspecs = adapt_specs_to_tree(
                param_specs(gen.cfg, "tp")["blocks"], blocks_np,
                leading_axes=1, axis_sizes={"tp": tp},
            )
            if abstract:
                stage_blocks = jax.tree_util.tree_map(
                    lambda a, sp: jax.ShapeDtypeStruct(
                        a.shape, a.dtype,
                        sharding=NamedSharding(mesh, P("pp", *sp)),
                    ),
                    blocks_np, bspecs,
                )
            else:
                stage_blocks = jax.tree_util.tree_map(
                    lambda a, sp: jax.device_put(
                        a, NamedSharding(mesh, P("pp", *sp))
                    ),
                    blocks_np, bspecs,
                )
        else:
            pipe_sh = NamedSharding(mesh, P("pp"))
            if abstract:
                stage_blocks = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(
                        a.shape, a.dtype, sharding=pipe_sh
                    ),
                    blocks_np,
                )
            else:
                stage_blocks = jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, pipe_sh), blocks_np
                )
        # embedding / final norm / head replicated on every stage (only
        # stage 0 reads them meaningfully; the ring samples at
        # single-device shapes outside the shard_map)
        head_params = {
            k: stages[0][k]
            for k in ("wte", "wpe", "ln_f", "lm_head") if k in stages[0]
        }
        if abstract:
            head_params = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    np.shape(a), np.asarray(a).dtype, sharding=repl_sh
                ),
                head_params,
            )
            rope = tuple(
                jax.ShapeDtypeStruct(r.shape, r.dtype, sharding=repl_sh)
                for r in gen.rope
            )
        else:
            head_params = jax.tree_util.tree_map(
                lambda a: jax.device_put(np.asarray(a), repl_sh), head_params
            )
            rope = tuple(
                jax.device_put(np.asarray(r), repl_sh) for r in gen.rope
            )
        # the bundle every inherited dispatch passes (engine._params seam)
        self._params = {
            "blocks": stage_blocks, "head": head_params, "rope": rope,
        }
        self._x_dtype = transformer.param_dtype(gen.params)
        self._check_vma = jax.process_count() == 1 and tp == 1

    # -- backend seams --------------------------------------------------------

    def _fn_cache_key(self):
        # staged rings trace differently from the flat engine at the same
        # (B, T) keys — namespace them apart on the shared Generator cache
        return ("serve-pp", self._pp, self._tp_size)

    def _init_pool(self, num_blocks: int, bs: int):
        """Per-stage pool shards stacked on a leading stage axis: stage s
        holds `l_max` layer slots (its own layer count, zero-padded so the
        ring stays single-trace) of `num_blocks` blocks.  The host-side
        `KVPool` allocator is unchanged and device-blind — a block id
        indexes every stage's shard at once, each stage just stores its
        own layers' K/V under that id."""
        # the eval_shape is a jax trace: cache the template alongside the
        # compiled phases (self._fns is not assigned yet at this point in
        # base __init__) so a second engine on the same Generator stays
        # trace-free after warmup
        fns = self.gen._serve_fns.setdefault(self._fn_cache_key(), {})
        tkey = ("pool_tmpl", num_blocks, bs,
                jnp.dtype(self._pool_dtype).name, self._l_max)
        if tkey not in fns:
            fns[tkey] = jax.eval_shape(
                lambda: transformer.init_paged_kv_cache(
                    self.gen.cfg, num_blocks, bs, dtype=self._pool_dtype,
                    n_layer=self._l_max,
                )
            )
        tmpl = fns[tkey]
        mesh = self.gen.mesh
        abstract = getattr(self.gen, "abstract", False)

        def alloc(leaf):
            shape = (self._pp,) + tuple(leaf.shape)
            spec = self._pool_spec if len(shape) >= 5 else self._scale_spec
            sh = NamedSharding(mesh, spec)
            if abstract:  # the stacked layout + shardings, zero bytes
                return jax.ShapeDtypeStruct(shape, leaf.dtype, sharding=sh)
            return jax.device_put(np.zeros(shape, leaf.dtype), sh)

        return jax.tree_util.tree_map(alloc, tmpl)

    # -- pipeline topology (bench / cli reporting) ----------------------------

    @property
    def n_stages(self) -> int:
        return self._pp

    def pipeline_fill(self) -> Dict[str, Any]:
        """The fill model the bench row and mdi-audit's
        `pipeline-underfill` check both report: lanes (= max_batch, the
        scheduler's pipeline fill), stages, and the steady-state bubble
        fraction 1 - min(lanes, stages)/stages when the lanes cannot
        cover the ring."""
        lanes = self.scheduler.max_batch
        fill = min(lanes, self._pp) / self._pp
        return {
            "stages": self._pp,
            "lanes": lanes,
            "stage_layers": list(self._stage_counts),
            "bubble_fraction": round(max(0.0, 1.0 - fill), 4),
            # steady-state busy fraction per stage: the ring sweeps are
            # symmetric, so underfill idles every stage equally
            "stage_occupancy": [round(fill, 4)] * self._pp,
        }

    # -- shared ring plumbing -------------------------------------------------

    def _ring_consts(self):
        """Engine-lifetime constants the ring closures capture — NO self
        (the fn cache lives on the Generator and must not pin this
        engine's pool)."""
        gen = self.gen
        return dict(
            gen=gen, cfg=gen.cfg, mesh=gen.mesh, S=self._pp,
            moe_impl=gen._moe_impl, unroll=gen.scan_unroll,
            kv_sharding=self._kv_sharding_pair, x_dtype=self._x_dtype,
            check_vma=self._check_vma,
            trash_pos=self.max_blocks_per_seq * self.pool.block_size,
        )

    # -- compiled phases (pp overrides; signatures match the base engine) -----

    def _mixed_fn(self, B: int, T: int):
        """Unified ragged mixed step over the stage ring: the packed
        (1, T) batch pads to pp equal token segments inside the jit
        (padding tokens carry the trash position, exactly like the batch
        tail the base engine already pads), segment m enters stage 0 at
        tick m, and the last stage's finished hidden states psum back
        replicated.  Head + split + sample run outside the shard_map at
        the base engine's exact shapes."""
        key_ = ("mixed", B, T)
        if key_ not in self._fns:
            c = self._ring_consts()
            cfg, mesh, S = c["cfg"], c["mesh"], c["S"]
            seg = -(-T // S)
            T_pad = seg * S
            perm = [(i, (i + 1) % S) for i in range(S)]

            @partial(
                jax.jit, donate_argnums=(2,),
                static_argnames=("mode", "top_k"),
            )
            def mixed(params, tokens, kv, tables, pos, q_slot, q_start,
                      q_len, last_idx, key, temperature, top_p, mode, top_k):
                tok_sg = jnp.pad(
                    tokens, ((0, 0), (0, T_pad - T))
                ).reshape(S, seg)
                pos_sg = jnp.pad(
                    pos, ((0, 0), (0, T_pad - T)),
                    constant_values=c["trash_pos"],
                ).reshape(S, seg)
                qs_sg = jnp.pad(q_slot, (0, T_pad - T)).reshape(S, seg)

                def ring(sid, blocks, head, rope, kv, tok_sg, pos_sg, qs_sg,
                         tables, q_start, q_len):
                    s = sid[0]  # stage id arrives as data: axis_index lowers to
                    # PartitionId, which GSPMD rejects when tp is auto
                    blocks = jax.tree_util.tree_map(lambda a: a[0], blocks)
                    kv_loc = jax.tree_util.tree_map(lambda a: a[0], kv)
                    D = cfg.n_embd
                    x0 = jnp.zeros((1, seg, D), c["x_dtype"])
                    acc0 = jnp.zeros((1, T_pad, D), c["x_dtype"])

                    def body(carry, t):
                        x, acc, kv_loc = carry
                        m = t - s
                        valid = jnp.logical_and(m >= 0, m < S)
                        mc = jnp.clip(m, 0, S - 1)
                        tok_m = jax.lax.dynamic_slice_in_dim(
                            tok_sg, mc, 1, 0)[0]
                        pos_m = jax.lax.dynamic_slice_in_dim(
                            pos_sg, mc, 1, 0)[0]
                        qs_m = jax.lax.dynamic_slice_in_dim(
                            qs_sg, mc, 1, 0)[0]
                        emb = transformer.embed(
                            cfg, head, tok_m[None], pos_m[None]
                        )
                        is0 = s == 0
                        x_in = jnp.where(is0, emb.astype(x.dtype), x)
                        tbl = jnp.where(valid, tables, 0)
                        x_out, kv_loc = _stage_run(
                            cfg, blocks, rope, kv_loc, x_in, pos_m[None],
                            tbl, c["moe_impl"], c["unroll"],
                            ragged=(qs_m, q_start, q_len),
                        )
                        is_last = s == S - 1
                        start = mc * seg
                        cur = jax.lax.dynamic_slice(
                            acc, (0, start, 0), (1, seg, D))
                        upd = jnp.where(
                            jnp.logical_and(valid, is_last), x_out, cur)
                        acc = jax.lax.dynamic_update_slice(
                            acc, upd, (0, start, 0))
                        x_n = jax.lax.ppermute(x_out, "pp", perm)
                        return (x_n, acc, kv_loc), None

                    (x, acc, kv_loc), _ = jax.lax.scan(
                        body, (x0, acc0, kv_loc),
                        jnp.arange(2 * S - 1, dtype=jnp.int32),
                    )
                    acc = jax.lax.psum(acc, "pp")
                    kv_out = jax.tree_util.tree_map(
                        lambda a: a[None], kv_loc)
                    return acc, kv_out

                pipe, repl = P("pp"), P()
                sm = _ring_shard_map(
                    ring, mesh,
                    in_specs=(
                        pipe,
                        jax.tree_util.tree_map(
                            lambda _: pipe, params["blocks"]),
                        jax.tree_util.tree_map(
                            lambda _: repl, params["head"]),
                        (repl, repl),
                        jax.tree_util.tree_map(lambda _: pipe, kv),
                        repl, repl, repl, repl, repl, repl,
                    ),
                    out_specs=(
                        repl,
                        jax.tree_util.tree_map(lambda _: pipe, kv),
                    ),
                    check=c["check_vma"],
                )
                hidden, kv = sm(
                    jnp.arange(S, dtype=jnp.int32),
                    params["blocks"], params["head"], params["rope"], kv,
                    tok_sg, pos_sg, qs_sg, tables, q_start, q_len,
                )
                kv = _pin_kv(kv, c["kv_sharding"])
                logits = transformer.head(
                    cfg, params["head"], hidden[:, :T])
                key, sub = jax.random.split(key)
                nxt = sample_traced(
                    logits[0, last_idx], sub, temperature, top_p,
                    mode=mode, top_k=top_k,
                )
                return nxt.astype(jnp.int32), kv, key

            self._fns[key_] = mixed
        return self._fns[key_]

    def _decode_fn(self, B: int):
        """One decode step over the stage ring: lanes split into pp
        groups of ceil(B/pp) (padding lanes ride zeroed table rows into
        the trash block), group g enters stage 0 at tick g, the last
        stage accumulates, psum replicates, and head/sample run outside
        at the (B, V) base shapes with the base key cadence."""
        key_ = ("decode", B)
        if key_ not in self._fns:
            c = self._ring_consts()
            cfg, mesh, S = c["cfg"], c["mesh"], c["S"]
            Bg = -(-B // S)
            Bp = Bg * S
            perm = [(i, (i + 1) % S) for i in range(S)]

            @partial(
                jax.jit, donate_argnums=(2,),
                static_argnames=("mode", "top_k"),
            )
            def decode(params, tok, kv, tables, input_pos, key,
                       temperature, top_p, mode, top_k):
                tok_p = jnp.pad(tok, (0, Bp - B))
                pos_p = jnp.pad(input_pos, (0, Bp - B))
                tbl_p = jnp.pad(tables, ((0, Bp - B), (0, 0)))

                def ring(sid, blocks, head, rope, kv, tok_p, pos_p, tbl_p):
                    s = sid[0]  # stage id arrives as data: axis_index lowers to
                    # PartitionId, which GSPMD rejects when tp is auto
                    blocks = jax.tree_util.tree_map(lambda a: a[0], blocks)
                    kv_loc = jax.tree_util.tree_map(lambda a: a[0], kv)
                    D = cfg.n_embd
                    x0 = jnp.zeros((Bg, 1, D), c["x_dtype"])
                    acc0 = jnp.zeros((Bp, 1, D), c["x_dtype"])

                    def body(carry, t):
                        x, acc, kv_loc = carry
                        g = t - s
                        valid = jnp.logical_and(g >= 0, g < S)
                        gc = jnp.clip(g, 0, S - 1)
                        off = gc * Bg
                        tok_g = jax.lax.dynamic_slice_in_dim(
                            tok_p, off, Bg)
                        pos_g = jax.lax.dynamic_slice_in_dim(
                            pos_p, off, Bg)
                        tbl_g = jax.lax.dynamic_slice(
                            tbl_p, (off, 0), (Bg, tbl_p.shape[1]))
                        pos2 = pos_g[:, None]
                        emb = transformer.embed(
                            cfg, head, tok_g[:, None], pos2)
                        is0 = s == 0
                        x_in = jnp.where(is0, emb.astype(x.dtype), x)
                        tbl = jnp.where(valid, tbl_g, 0)
                        x_out, kv_loc = _stage_run(
                            cfg, blocks, rope, kv_loc, x_in, pos2, tbl,
                            c["moe_impl"], c["unroll"],
                        )
                        is_last = s == S - 1
                        cur = jax.lax.dynamic_slice(
                            acc, (off, 0, 0), (Bg, 1, D))
                        upd = jnp.where(
                            jnp.logical_and(valid, is_last), x_out, cur)
                        acc = jax.lax.dynamic_update_slice(
                            acc, upd, (off, 0, 0))
                        x_n = jax.lax.ppermute(x_out, "pp", perm)
                        return (x_n, acc, kv_loc), None

                    (x, acc, kv_loc), _ = jax.lax.scan(
                        body, (x0, acc0, kv_loc),
                        jnp.arange(2 * S - 1, dtype=jnp.int32),
                    )
                    acc = jax.lax.psum(acc, "pp")
                    kv_out = jax.tree_util.tree_map(
                        lambda a: a[None], kv_loc)
                    return acc, kv_out

                pipe, repl = P("pp"), P()
                sm = _ring_shard_map(
                    ring, mesh,
                    in_specs=(
                        pipe,
                        jax.tree_util.tree_map(
                            lambda _: pipe, params["blocks"]),
                        jax.tree_util.tree_map(
                            lambda _: repl, params["head"]),
                        (repl, repl),
                        jax.tree_util.tree_map(lambda _: pipe, kv),
                        repl, repl, repl,
                    ),
                    out_specs=(
                        repl,
                        jax.tree_util.tree_map(lambda _: pipe, kv),
                    ),
                    check=c["check_vma"],
                )
                hidden, kv = sm(
                    jnp.arange(S, dtype=jnp.int32),
                    params["blocks"], params["head"], params["rope"], kv,
                    tok_p, pos_p, tbl_p,
                )
                kv = _pin_kv(kv, c["kv_sharding"])
                logits = transformer.head(cfg, params["head"], hidden[:B])
                key, sub = jax.random.split(key)
                nxt = sample_traced(
                    logits[:, -1], sub, temperature, top_p,
                    mode=mode, top_k=top_k,
                )
                return nxt.astype(jnp.int32), kv, key

            self._fns[key_] = decode
        return self._fns[key_]

    def _verify_fn(self, B: int, T: int):
        """Batched speculative verify over the stage ring: the (B, T)
        draft batch group-sweeps the ring exactly like decode, the head +
        greedy argmax run outside at the base shapes (no RNG — verify is
        greedy by contract)."""
        key_ = ("verify", B, T)
        if key_ not in self._fns:
            c = self._ring_consts()
            cfg, mesh, S = c["cfg"], c["mesh"], c["S"]
            Bg = -(-B // S)
            Bp = Bg * S
            perm = [(i, (i + 1) % S) for i in range(S)]

            @partial(jax.jit, donate_argnums=(2,))
            def verify(params, tokens, kv, tables, pos0):
                tok_p = jnp.pad(tokens, ((0, Bp - B), (0, 0)))
                pos_p = jnp.pad(pos0, (0, Bp - B))
                tbl_p = jnp.pad(tables, ((0, Bp - B), (0, 0)))

                def ring(sid, blocks, head, rope, kv, tok_p, pos_p, tbl_p):
                    s = sid[0]  # stage id arrives as data: axis_index lowers to
                    # PartitionId, which GSPMD rejects when tp is auto
                    blocks = jax.tree_util.tree_map(lambda a: a[0], blocks)
                    kv_loc = jax.tree_util.tree_map(lambda a: a[0], kv)
                    D = cfg.n_embd
                    x0 = jnp.zeros((Bg, T, D), c["x_dtype"])
                    acc0 = jnp.zeros((Bp, T, D), c["x_dtype"])
                    ramp = jnp.arange(T, dtype=pos_p.dtype)[None, :]

                    def body(carry, t):
                        x, acc, kv_loc = carry
                        g = t - s
                        valid = jnp.logical_and(g >= 0, g < S)
                        gc = jnp.clip(g, 0, S - 1)
                        off = gc * Bg
                        tok_g = jax.lax.dynamic_slice(
                            tok_p, (off, 0), (Bg, T))
                        pos_g = jax.lax.dynamic_slice_in_dim(
                            pos_p, off, Bg)
                        tbl_g = jax.lax.dynamic_slice(
                            tbl_p, (off, 0), (Bg, tbl_p.shape[1]))
                        pos2 = pos_g[:, None] + ramp
                        emb = transformer.embed(cfg, head, tok_g, pos2)
                        is0 = s == 0
                        x_in = jnp.where(is0, emb.astype(x.dtype), x)
                        tbl = jnp.where(valid, tbl_g, 0)
                        x_out, kv_loc = _stage_run(
                            cfg, blocks, rope, kv_loc, x_in, pos2, tbl,
                            c["moe_impl"], c["unroll"],
                        )
                        is_last = s == S - 1
                        cur = jax.lax.dynamic_slice(
                            acc, (off, 0, 0), (Bg, T, D))
                        upd = jnp.where(
                            jnp.logical_and(valid, is_last), x_out, cur)
                        acc = jax.lax.dynamic_update_slice(
                            acc, upd, (off, 0, 0))
                        x_n = jax.lax.ppermute(x_out, "pp", perm)
                        return (x_n, acc, kv_loc), None

                    (x, acc, kv_loc), _ = jax.lax.scan(
                        body, (x0, acc0, kv_loc),
                        jnp.arange(2 * S - 1, dtype=jnp.int32),
                    )
                    acc = jax.lax.psum(acc, "pp")
                    kv_out = jax.tree_util.tree_map(
                        lambda a: a[None], kv_loc)
                    return acc, kv_out

                pipe, repl = P("pp"), P()
                sm = _ring_shard_map(
                    ring, mesh,
                    in_specs=(
                        pipe,
                        jax.tree_util.tree_map(
                            lambda _: pipe, params["blocks"]),
                        jax.tree_util.tree_map(
                            lambda _: repl, params["head"]),
                        (repl, repl),
                        jax.tree_util.tree_map(lambda _: pipe, kv),
                        repl, repl, repl,
                    ),
                    out_specs=(
                        repl,
                        jax.tree_util.tree_map(lambda _: pipe, kv),
                    ),
                    check=c["check_vma"],
                )
                hidden, kv = sm(
                    jnp.arange(S, dtype=jnp.int32),
                    params["blocks"], params["head"], params["rope"], kv,
                    tok_p, pos_p, tbl_p,
                )
                kv = _pin_kv(kv, c["kv_sharding"])
                logits = transformer.head(cfg, params["head"], hidden[:B])
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), kv

            self._fns[key_] = verify
        return self._fns[key_]

    def _decode_chunk_fn(self, B: int, K: int):
        """K decode steps as ONE recurrent ring call — the paper's
        execution model verbatim: lane-group payloads circle the stages;
        whenever a payload returns to stage 0 it is sampled, advanced one
        step (the base engine's limit/stop/freeze masks, applied
        per-group), re-embedded and relaunched without leaving the
        device.  K*pp + pp ticks serve K tokens on every lane; the host
        syncs once, exactly like the base chunked scan, and the
        double-buffer chain works unchanged off the returned final
        (token, position) carry.

        RNG: the K per-step subkeys are pre-split outside the ring in the
        base engine's order (so the returned key state is bit-identical);
        group g's step k consumes subkey k.  Stochastic per-lane draws
        under a (Bg,)-shaped sample differ from the base (B,)-shaped one
        — greedy streams, the serving parity contract, are exact."""
        key_ = ("decode_chunk", B, K)
        if key_ not in self._fns:
            c = self._ring_consts()
            cfg, mesh, S = c["cfg"], c["mesh"], c["S"]
            Bg = -(-B // S)
            Bp = Bg * S
            n_ticks = K * S + S
            perm = [(i, (i + 1) % S) for i in range(S)]

            @partial(
                jax.jit, donate_argnums=(2,),
                static_argnames=("mode", "top_k"),
            )
            def decode_chunk(params, tok0, kv, tables, pos0, limit,
                             stop_tok, key, temperature, top_p, mode,
                             top_k):
                # pre-split the K step subkeys in the base engine's exact
                # order so the returned key state matches bit for bit
                subs = []
                for _ in range(K):
                    key, sub = jax.random.split(key)
                    subs.append(sub)
                subs = jnp.stack(subs)
                tok_p = jnp.pad(tok0, (0, Bp - B))
                pos_p = jnp.pad(pos0, (0, Bp - B))
                tbl_p = jnp.pad(tables, ((0, Bp - B), (0, 0)))
                lim_p = jnp.pad(limit, (0, Bp - B))
                stop_p = jnp.pad(stop_tok, (0, Bp - B), constant_values=-1)

                def ring(sid, blocks, head, rope, kv, tok_p, pos_p, tbl_p,
                         lim_p, stop_p, subs, temperature, top_p):
                    s = sid[0]  # stage id arrives as data: axis_index lowers to
                    # PartitionId, which GSPMD rejects when tp is auto
                    is0 = s == 0
                    blocks = jax.tree_util.tree_map(lambda a: a[0], blocks)
                    kv_loc = jax.tree_util.tree_map(lambda a: a[0], kv)
                    D = cfg.n_embd
                    payload0 = (
                        jnp.zeros((Bg, 1, D), c["x_dtype"]),  # x
                        jnp.zeros((Bg,), jnp.int32),          # tok
                        jnp.zeros((Bg,), jnp.int32),          # pos
                        jnp.zeros((Bg,), jnp.int32),          # done (0/1)
                        jnp.zeros((1,), jnp.int32),           # step k
                        jnp.zeros((1,), jnp.int32),           # group g
                        jnp.zeros((1,), jnp.int32),           # valid (0/1)
                    )
                    out0 = jnp.zeros((K, Bp), jnp.int32)
                    fin_t0 = jnp.zeros((Bp,), jnp.int32)
                    fin_p0 = jnp.zeros((Bp,), jnp.int32)

                    def body(carry, t):
                        (x, tok, pos, done, kstep, g, valid), kv_loc, \
                            out, fin_t, fin_p = carry
                        # ---- stage 0, returning payload: head + sample
                        # + one decode-step advance (base masks) ----
                        returning = jnp.logical_and(
                            jnp.logical_and(is0, t >= S), valid[0] > 0)
                        logits = transformer.head(cfg, head, x)[:, -1]
                        kidx = jnp.clip(kstep[0], 0, K - 1)
                        samp = sample_traced(
                            logits, subs[kidx], temperature, top_p,
                            mode=mode, top_k=top_k,
                        ).astype(jnp.int32)
                        off = g[0] * Bg
                        lim_g = jax.lax.dynamic_slice_in_dim(
                            lim_p, off, Bg)
                        stop_g = jax.lax.dynamic_slice_in_dim(
                            stop_p, off, Bg)
                        active = jnp.logical_and(
                            jnp.logical_and(returning, kidx < lim_g),
                            done == 0,
                        )
                        nxt = jnp.where(active, samp, tok)
                        done = jnp.where(
                            jnp.logical_and(active, nxt == stop_g),
                            1, done,
                        )
                        pos = pos + active.astype(pos.dtype)
                        # record row kstep for the group (frozen lanes
                        # record their held token, mirroring the base
                        # scan; the host drains only up to each limit)
                        cur = jax.lax.dynamic_slice(
                            out, (kidx, off), (1, Bg))
                        rec = jnp.where(returning, nxt, cur[0])
                        out = jax.lax.dynamic_update_slice(
                            out, rec[None], (kidx, off))
                        k2 = jnp.where(returning, kstep + 1, kstep)
                        finishing = jnp.logical_and(returning, k2[0] >= K)
                        cur_t = jax.lax.dynamic_slice_in_dim(
                            fin_t, off, Bg)
                        fin_t = jax.lax.dynamic_update_slice(
                            fin_t, jnp.where(finishing, nxt, cur_t),
                            (off,),
                        )
                        cur_p = jax.lax.dynamic_slice_in_dim(
                            fin_p, off, Bg)
                        fin_p = jax.lax.dynamic_update_slice(
                            fin_p, jnp.where(finishing, pos, cur_p),
                            (off,),
                        )
                        valid2 = jnp.where(
                            finishing, jnp.zeros_like(valid), valid)
                        # ---- stage 0, fill phase: inject group t ----
                        inject = jnp.logical_and(is0, t < S)
                        off_inj = jnp.clip(t, 0, S - 1) * Bg
                        tok_inj = jax.lax.dynamic_slice_in_dim(
                            tok_p, off_inj, Bg)
                        pos_inj = jax.lax.dynamic_slice_in_dim(
                            pos_p, off_inj, Bg)
                        tok3 = jnp.where(inject, tok_inj, nxt)
                        pos3 = jnp.where(inject, pos_inj, pos)
                        done3 = jnp.where(inject, jnp.zeros_like(done),
                                          done)
                        k3 = jnp.where(inject, jnp.zeros_like(k2), k2)
                        g3 = jnp.where(
                            inject, jnp.clip(t, 0, S - 1)[None], g)
                        valid3 = jnp.where(
                            inject, jnp.ones_like(valid2), valid2)
                        launch = jnp.logical_or(inject, returning)
                        emb = transformer.embed(
                            cfg, head, tok3[:, None], pos3[:, None])
                        x3 = jnp.where(launch, emb.astype(x.dtype), x)
                        # ---- this stage's blocks over the payload ----
                        off_run = g3[0] * Bg
                        tbl_g = jax.lax.dynamic_slice(
                            tbl_p, (off_run, 0), (Bg, tbl_p.shape[1]))
                        tbl = jnp.where(valid3[0] > 0, tbl_g, 0)
                        x4, kv_loc = _stage_run(
                            cfg, blocks, rope, kv_loc, x3, pos3[:, None],
                            tbl, c["moe_impl"], c["unroll"],
                        )
                        # ---- hand the payload to the next stage ----
                        pay = tuple(
                            jax.lax.ppermute(a, "pp", perm)
                            for a in (x4, tok3, pos3, done3, k3, g3,
                                      valid3)
                        )
                        return (pay, kv_loc, out, fin_t, fin_p), None

                    (pay, kv_loc, out, fin_t, fin_p), _ = jax.lax.scan(
                        body, (payload0, kv_loc, out0, fin_t0, fin_p0),
                        jnp.arange(n_ticks, dtype=jnp.int32),
                    )
                    out = jax.lax.psum(out, "pp")
                    fin_t = jax.lax.psum(fin_t, "pp")
                    fin_p = jax.lax.psum(fin_p, "pp")
                    kv_out = jax.tree_util.tree_map(
                        lambda a: a[None], kv_loc)
                    return out, fin_t, fin_p, kv_out

                pipe, repl = P("pp"), P()
                sm = _ring_shard_map(
                    ring, mesh,
                    in_specs=(
                        pipe,
                        jax.tree_util.tree_map(
                            lambda _: pipe, params["blocks"]),
                        jax.tree_util.tree_map(
                            lambda _: repl, params["head"]),
                        (repl, repl),
                        jax.tree_util.tree_map(lambda _: pipe, kv),
                        repl, repl, repl, repl, repl, repl, repl, repl,
                    ),
                    out_specs=(
                        repl, repl, repl,
                        jax.tree_util.tree_map(lambda _: pipe, kv),
                    ),
                    check=c["check_vma"],
                )
                toks, fin_t, fin_p, kv = sm(
                    jnp.arange(S, dtype=jnp.int32),
                    params["blocks"], params["head"], params["rope"], kv,
                    tok_p, pos_p, tbl_p, lim_p, stop_p, subs,
                    temperature, top_p,
                )
                kv = _pin_kv(kv, c["kv_sharding"])
                return toks[:, :B], fin_t[:B], fin_p[:B], kv, key

            self._fns[key_] = decode_chunk
        return self._fns[key_]
