"""Pluggable scheduling policies for the continuous-batching scheduler.

The policy layer answers the two HOST-SIDE ordering questions the
scheduler asks every step, and nothing else:

1. **admission** — which waiting request gets the next free slot
   (`select_next`), and
2. **prefill packing** — in what order prefilling sequences split the
   unified step's leftover token budget (`order_prefill`).

Everything device-shaped is out of scope by construction: a policy
reorders host-side lists the scheduler already owns, so the engine's
dispatch shapes (`(1, token_budget)` mixed, `(max_batch, decode_chunk)`
scan), the host-sync cadence and the zero-post-warmup-recompile
guarantee are structurally untouched whatever policy runs (pinned by
tests/test_policy.py running the parity engine under every policy).

Shipped policies (`POLICIES` registry, `--policy` on mdi-serve /
mdi-server):

- ``fcfs``      — head-of-line admission + admission-order prefill:
                  bit-identical to the pre-policy scheduler.
- ``priority``  — strict priority classes (higher `Request.priority`
                  admits first; FCFS inside a class).  Starvation of low
                  classes under sustained high-class load is the
                  POINT — pair with quotas where that is unacceptable.
- ``fair``      — per-tenant fair share by token accounting: the next
                  slot goes to the waiting tenant with the least served
                  work (prompt + generated tokens, finished AND live),
                  so one tenant flooding the queue cannot starve the
                  others (deficit-style, O(waiting + slots) per pick).
- ``deadline``  — TTFT-SLO-aware: admission is earliest-deadline-first
                  over `Request.ttft_slo_s`, and prefill packing puts
                  the request with the least deadline slack FIRST, so a
                  request about to miss its TTFT SLO takes the step's
                  prefill budget before relaxed ones.  Requests without
                  a deadline rank behind all deadlines, FCFS among
                  themselves.  Prefill chunks already split to the token
                  budget, so this is a pure reordering — no new
                  dispatch shape.

`clock` is injectable (tests drive fake time); production defaults to
`time.monotonic`.  Policies never preempt on their own — preemption
stays the pool-pressure mechanism it was (`Scheduler.preempt_latest`).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence

__all__ = [
    "SchedulingPolicy",
    "FCFSPolicy",
    "PriorityPolicy",
    "FairSharePolicy",
    "DeadlinePolicy",
    "POLICIES",
    "make_policy",
]


class SchedulingPolicy:
    """Base policy = FCFS semantics; subclasses override the two hooks.

    The scheduler calls, in order, per step:

    - `on_submitted(req)` once at `Scheduler.add` (stamps arrival time);
    - `select_next(waiting, running)` repeatedly while slots are free —
      return an INDEX into `waiting` (the scheduler admits that request
      or, if it does not fit, stops admission for this step: a pick that
      cannot fit blocks the queue rather than being skipped, so block
      accounting stays conservative and a policy bug cannot starve its
      own pick);
    - `order_prefill(prefilling, now)` once per mixed step — return the
      sequences in packing order (first gets budget first);
    - `on_retired(seq)` at retirement (fair-share usage accounting).
    """

    name = "fcfs"

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self.clock = clock

    # -- hooks ---------------------------------------------------------------

    def on_submitted(self, req) -> None:
        if req.arrival_s is None:
            req.arrival_s = self.clock()

    def select_next(self, waiting: Sequence, running: Sequence) -> Optional[int]:
        return 0 if waiting else None

    def order_prefill(self, prefilling: List, now: float) -> List:
        return sorted(prefilling, key=lambda s: s.admit_order)

    def on_retired(self, seq) -> None:
        pass


class FCFSPolicy(SchedulingPolicy):
    """Head-of-line admission, admission-order prefill packing — the
    scheduler's historical behavior, now spelled as a policy."""

    name = "fcfs"


class PriorityPolicy(SchedulingPolicy):
    """Strict priority classes: the highest `Request.priority` waiting
    admits first; FCFS (arrival order) inside a class.  Prefill packing
    follows the same ranking so a high-priority prompt also takes the
    step's prefill budget first."""

    name = "priority"

    def select_next(self, waiting: Sequence, running: Sequence) -> Optional[int]:
        if not waiting:
            return None
        # max priority, then earliest arrival (enumerate index breaks ties
        # by queue position, which IS arrival order within the deque)
        return max(
            range(len(waiting)),
            key=lambda i: (waiting[i].priority, -i),
        )

    def order_prefill(self, prefilling: List, now: float) -> List:
        return sorted(
            prefilling,
            key=lambda s: (-s.req.priority, s.admit_order),
        )


class FairSharePolicy(SchedulingPolicy):
    """Per-tenant fair share by served-token accounting.

    Each tenant's usage = tokens the engine has served on its behalf —
    prompt tokens prefilled plus tokens generated — summed over retired
    requests (accumulated at `on_retired`) AND currently-running ones
    (read live off the slots, so a tenant cannot hide usage in flight).
    The next free slot goes to the waiting request whose tenant has the
    least usage; ties break FCFS.  A tenant that floods the queue only
    ever gets served up to parity with the others — the classic
    starving-tenant scenario is the pinned test.

    Accounting is windowless by default (usage accumulates for the
    frontend's lifetime); `decay(factor)` lets a long-lived server age
    history so a tenant idle for hours is not owed an unbounded debt.
    """

    name = "fair"

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        super().__init__(clock)
        self.usage: Dict[str, float] = {}

    def _live_usage(self, running: Sequence) -> Dict[str, float]:
        live: Dict[str, float] = {}
        for s in running:
            live[s.req.tenant] = (
                live.get(s.req.tenant, 0.0) + s.fed + s.n_generated
            )
        return live

    def select_next(self, waiting: Sequence, running: Sequence) -> Optional[int]:
        if not waiting:
            return None
        live = self._live_usage(running)

        def owed(i: int):
            t = waiting[i].tenant
            return (self.usage.get(t, 0.0) + live.get(t, 0.0), i)

        return min(range(len(waiting)), key=owed)

    def on_retired(self, seq) -> None:
        t = seq.req.tenant
        self.usage[t] = self.usage.get(t, 0.0) + seq.n_prompt + seq.n_generated

    def decay(self, factor: float) -> None:
        """Age the accounting window: usage *= factor (0 <= factor < 1
        forgives history; a periodic 0.5 gives a half-life of one call
        interval).  Host-side O(tenants)."""
        self.usage = {t: u * factor for t, u in self.usage.items() if u * factor > 1e-9}


class DeadlinePolicy(SchedulingPolicy):
    """TTFT-deadline-aware admission and prefill packing (EDF).

    A request with `ttft_slo_s` carries an absolute deadline
    `arrival_s + ttft_slo_s`; admission picks the earliest deadline
    waiting, and prefill packing orders live prefills by remaining slack
    so the step's token budget flows to the request closest to missing
    its TTFT SLO.  Deadline-free requests rank after every deadline,
    FCFS among themselves — a relaxed request can never displace an
    urgent one, but also never starves once no deadlines are pending.
    """

    name = "deadline"

    _FAR = float("inf")

    @staticmethod
    def _deadline(req) -> float:
        if req.ttft_slo_s is None or req.arrival_s is None:
            return DeadlinePolicy._FAR
        return req.arrival_s + req.ttft_slo_s

    def select_next(self, waiting: Sequence, running: Sequence) -> Optional[int]:
        if not waiting:
            return None
        return min(
            range(len(waiting)),
            key=lambda i: (self._deadline(waiting[i]), i),
        )

    def order_prefill(self, prefilling: List, now: float) -> List:
        return sorted(
            prefilling,
            key=lambda s: (self._deadline(s.req) - now, s.admit_order),
        )


POLICIES: Dict[str, type] = {
    "fcfs": FCFSPolicy,
    "priority": PriorityPolicy,
    "fair": FairSharePolicy,
    "deadline": DeadlinePolicy,
}


def make_policy(name: Optional[str],
                clock: Callable[[], float] = time.monotonic) -> SchedulingPolicy:
    """Build a policy by registry name (None/"fcfs" → FCFS).  Raises
    ValueError naming the known policies on an unknown name — the same
    wall `--policy` hits at the CLI."""
    if name is None:
        return FCFSPolicy(clock)
    try:
        cls = POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown scheduling policy {name!r}: choose from "
            f"{sorted(POLICIES)}"
        ) from None
    return cls(clock)
