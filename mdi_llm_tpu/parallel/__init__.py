"""Parallelism: stage partitioning, device meshes, pipeline runtime,
sharding rules (tensor/data/sequence/expert)."""
