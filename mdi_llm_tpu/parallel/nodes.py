"""Node-topology configs and multi-process bootstrap.

TPU-native replacement for the reference's node orchestration
(`/root/reference/src/sub/model_dist.py:124-573`): where the reference wires
starter/secondary processes together with a CherryPy HTTP control plane
(`POST /init` carrying a pickled model config + optional weights,
`PUT /stop`) and hand-rolled TCP sockets for activations, here every node is
a `jax.distributed` process contributing its chips to one global mesh, and
activations move as `ppermute` collectives inside the jitted ring
(parallel/pipeline.py).  The HTTP init/stop lifecycle collapses into
`jax.distributed.initialize` + normal process exit.

Two config schemas are accepted (`parse_nodes_config`):

- The reference's `settings_distr/*.json` schema
  (`nodes.starter{addr, communication.port, inference.{port_in,port_out}}`,
  `nodes.secondary[i]{...}` — see SURVEY.md §2.1 "Node configs"): the
  starter's address + communication port become the jax.distributed
  coordinator; inference ports are accepted and ignored (there is no
  host-level data plane to bind).
- A TPU-native schema: `{"coordinator": "host:port", "num_processes": N,
  "pipeline_stages": S}` (examples/mesh_configs/).

Weights: the reference optionally ships pickled parameter chunks inside the
HTTP init message (`model_dist.py:402-484`).  Here every process loads the
checkpoint from (shared) storage itself — on TPU pods checkpoints live on
NFS/GCS, and shipping weights through a Python control plane would serialize
through one host's RAM.  Run parameters (prompt tokens, sample counts,
temperature, ...) ARE shipped starter→secondary, as the reference does, via
`broadcast_run_spec` (a device all-gather of a pickled spec buffer — the
analog of the reference's pickled init/inference messages).
"""

from __future__ import annotations

import json
import pickle
from dataclasses import dataclass, field
from pathlib import Path
from typing import List, Optional

import jax
import numpy as np

# Max pickled run-spec size shipped starter->secondaries.  The reference's
# wire protocol caps message size with MSGLENGTH (config.py:100-101); this is
# our analog.  1 MiB ≈ 250k prompt tokens, far above any realistic prompt.
RUN_SPEC_BYTES = 1 << 20


@dataclass
class NodeInfo:
    addr: str
    comm_port: int
    device: Optional[str] = None  # per-node platform override (≡ node JSON "device")


@dataclass
class NodesConfig:
    starter: NodeInfo
    secondary: List[NodeInfo] = field(default_factory=list)
    pipeline_stages: Optional[int] = None  # None → one stage per chip
    tp_devices: int = 1  # tensor-parallel devices per stage (pipe x tp)

    @property
    def n_nodes(self) -> int:
        return 1 + len(self.secondary)

    @property
    def coordinator(self) -> str:
        return f"{self.starter.addr}:{self.starter.comm_port}"


def _node_from_ref(d: dict, default_port: int) -> NodeInfo:
    comm = d.get("communication", {}) or {}
    return NodeInfo(
        addr=d.get("addr", "127.0.0.1"),
        comm_port=int(comm.get("port", default_port)),
        device=d.get("device"),
    )


def parse_nodes_config(path) -> NodesConfig:
    """Parse either the reference `settings_distr` schema or the TPU-native
    mesh schema into a NodesConfig."""
    raw = json.loads(Path(path).read_text())
    if "nodes" in raw:  # reference schema
        nodes = raw["nodes"]
        starter = _node_from_ref(nodes.get("starter", {}), default_port=8088)
        secondary = [
            _node_from_ref(s, default_port=8089 + i)
            for i, s in enumerate(nodes.get("secondary", []) or [])
        ]
        # parallelism keys are top-level in both schemas
        return NodesConfig(
            starter=starter,
            secondary=secondary,
            pipeline_stages=raw.get("pipeline_stages"),
            tp_devices=int(raw.get("tp_devices", 1)),
        )
    # TPU-native schema
    coord = raw.get("coordinator", "127.0.0.1:8476")
    addr, _, port = coord.rpartition(":")
    if not addr or not port.isdigit():
        raise SystemExit(
            f"{path}: \"coordinator\" must be host:port, got {coord!r}"
        )
    n_proc = int(raw.get("num_processes", 1))
    starter = NodeInfo(addr=addr or "127.0.0.1", comm_port=int(port))
    secondary = [NodeInfo(addr="?", comm_port=0) for _ in range(n_proc - 1)]
    return NodesConfig(
        starter=starter,
        secondary=secondary,
        pipeline_stages=raw.get("pipeline_stages"),
        tp_devices=int(raw.get("tp_devices", 1)),
    )


def init_distributed(
    cfg: NodesConfig, process_id: int, retries: int = 5, backoff_s: float = 2.0
) -> None:
    """Join the job as process `process_id` (starter=0, secondary i → i+1).
    No-op for single-node configs (≡ standalone.json, gptserver.py:276-278).

    Bounded retries with backoff ≡ the reference's HTTP-init retry loop
    (`model_dist.py:499-573`, ≤100 tries / 2 s) — a secondary launched
    before the starter's coordinator port is up should wait, not die.
    """
    if cfg.n_nodes == 1:
        return
    import logging
    import time

    log = logging.getLogger("mdi_llm_tpu")
    for attempt in range(retries):
        try:
            jax.distributed.initialize(
                coordinator_address=cfg.coordinator,
                num_processes=cfg.n_nodes,
                process_id=process_id,
            )
            return
        except Exception as e:  # noqa: BLE001 — grpc surfaces various types
            if attempt == retries - 1:
                raise
            log.warning(
                "distributed init attempt %d/%d failed (%s); retrying in %.0fs",
                attempt + 1,
                retries,
                e,
                backoff_s,
            )
            time.sleep(backoff_s)


def check_params_consistency(params, rtol: float = 1e-3) -> None:
    """Assert every process holds the same weights (cheap strided-subsample
    signature, all-gathered host-side).  Catches the silent-garbage failure
    mode where nodes random-init with different seeds/dtypes or load stale
    checkpoint copies — a risk the reference avoids by shipping weights in
    the init message (`model_dist.py:402-484`), which we deliberately don't.
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    sig = []
    for leaf in jax.tree_util.tree_leaves(params):
        a = np.asarray(leaf).ravel()  # mdi-lint: disable=host-sync -- one-shot startup checksum, not a step loop
        stride = max(1, a.size // 4096)
        sig.append(float(np.sum(a[::stride], dtype=np.float64)))
    sig = np.asarray(sig, np.float64)
    all_sigs = np.asarray(multihost_utils.process_allgather(sig))
    ref = all_sigs[0]
    scale = np.maximum(np.abs(ref), 1.0)
    bad = [
        p
        for p in range(1, all_sigs.shape[0])
        if np.any(np.abs(all_sigs[p] - ref) / scale > rtol)
    ]
    if bad:
        raise RuntimeError(
            f"parameter mismatch across processes {bad} vs process 0 — all "
            "nodes must load the same checkpoint (or random-init from the "
            "same seed/dtype)"
        )


def broadcast_run_spec(spec: Optional[dict]) -> dict:
    """Ship the run spec (prompt token ids + generation knobs) from the
    starter to every secondary.  Pass the dict on process 0 and None
    elsewhere.  ≡ the pickled inference-start message of the reference
    control plane (`gptserver.py:358-394`); pickle is fine for the same
    reason it was there — all processes belong to one trusted job.
    """
    if jax.process_count() == 1:
        assert spec is not None
        return spec
    from jax.experimental import multihost_utils

    buf = np.zeros(RUN_SPEC_BYTES, np.uint8)
    if spec is not None:
        payload = pickle.dumps(spec)
        if 4 + len(payload) > RUN_SPEC_BYTES:
            raise ValueError(f"run spec too large: {len(payload)} bytes")
        buf[:4] = np.frombuffer(len(payload).to_bytes(4, "little"), np.uint8)
        buf[4 : 4 + len(payload)] = np.frombuffer(payload, np.uint8)
    out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
    n = int.from_bytes(bytes(out[:4]), "little")
    return pickle.loads(bytes(out[4 : 4 + n]))
