"""Token-dispatch expert parallelism (EP) over an `ep` mesh axis.

The reference's MoE (`/root/reference/src/sub/model.py:823-853`, `LLaMAMoE`)
routes each token through its top-k experts on ONE device — experts are
never sharded (SURVEY.md §2.4 "Expert parallelism: absent").  The dense
TPU formulation (`models/transformer.moe_forward`) runs every expert on
every token, which keeps shapes static but burns `n_expert`× FLOPs per
token.  This module is the sparse, sharded redesign — the GShard/Switch
dispatch pattern, TPU-native:

- experts are sharded over the `ep` axis (leading expert-axis shard, same
  layout `parallel/sharding.param_specs(ep_axis=...)` produces);
- tokens are split across `ep` devices; each device routes its shard
  (top-k + renormalize, identical math to the dense path), packs tokens
  into a per-expert capacity-bounded dispatch buffer, and exchanges the
  buffers with `jax.lax.all_to_all` over ICI;
- each device runs ONLY its local experts on the tokens routed to them
  (SwiGLU, same einsum contractions as the dense path, so quantized expert
  trees work unchanged), then a second `all_to_all` returns the outputs to
  the tokens' home devices for the weighted combine.

Capacity: per (expert, source-device) slots
`C = max(1, ceil(cf * n_local * k / E))`.  With `capacity_factor=None`
capacity is exact (`C = n_local`, the worst case where every local token
picks the same expert) — zero drops, bit-comparable to the dense path, the
right default for decode where `n_local` is tiny.  A finite factor bounds
the buffers (total expert FLOPs ≈ `cf·k/E` of dense) and silently drops
overflow assignments — dropped assignments simply contribute nothing to
the combine (their router weight is lost, matching Switch-Transformer
semantics), so throughput-oriented prefill can trade exactness for speed.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from mdi_llm_tpu.config import Config
from mdi_llm_tpu.ops.quant import quantized_einsum

Params = Any


def expert_capacity(
    cfg: Config, n_local: int, capacity_factor: Optional[float]
) -> int:
    """Per-(expert, source-device) dispatch slots."""
    if capacity_factor is None:
        return max(1, n_local)
    need = capacity_factor * n_local * cfg.n_expert_per_token / cfg.n_expert
    return max(1, math.ceil(need))


def _local_moe(
    cfg: Config, ep: int, C: int, axis: str, with_aux: bool,
    dp_axis: Optional[str], xs, valid, p
):
    """Per-device body (inside shard_map): route, dispatch, compute, combine.

    xs: (1, n, D) local token shard; valid: (1, n) bool (False for padding
    rows, which must neither consume capacity nor emit output); p: mlp param
    dict with experts' leading axis sharded to the local E/ep slice.
    With `with_aux`, also returns the load-balancing auxiliary loss
    (globally psum-reduced over `axis`, so every device holds the same
    scalar) — see `models/transformer.moe_forward` for the formula.
    """
    x = xs[0]
    n, D = x.shape
    E, k = cfg.n_expert, cfg.n_expert_per_token
    E_loc = E // ep

    # -- routing: identical math to the dense path (transformer.moe_forward)
    router = quantized_einsum("ni,ei->ne", x, p["gate"]).astype(jnp.float32)
    probs = jax.nn.softmax(router, axis=-1)
    topv, topi = jax.lax.top_k(probs, k)  # (n, k)
    topv = topv / jnp.sum(topv, axis=-1, keepdims=True)

    flat_e = topi.reshape(-1)  # (n*k,) global expert ids
    vmask = valid[0].reshape(-1)  # (n,)
    flat_valid = jnp.repeat(vmask, k)  # (n*k,)
    flat_w = jnp.where(flat_valid, topv.reshape(-1), 0.0)
    flat_tok = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)

    # -- capacity assignment: rank of each assignment within its expert
    onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.int32) * flat_valid[:, None]
    ranks = jnp.cumsum(onehot, axis=0) - onehot  # (n*k, E)
    pos_in_e = jnp.take_along_axis(ranks, flat_e[:, None], axis=1)[:, 0]
    keep = (pos_in_e < C) & flat_valid
    pos_c = jnp.minimum(pos_in_e, C - 1)
    contrib = keep.astype(x.dtype)

    # -- pack the dispatch buffer: (E, C, D); dropped/padded assignments add 0
    disp = jnp.zeros((E, C, D), x.dtype).at[flat_e, pos_c].add(
        x[flat_tok] * contrib[:, None]
    )

    # -- ship token slices to their experts' owner devices (experts are
    # owner-major on the leading axis: expert e lives on device e // E_loc)
    recv = jax.lax.all_to_all(
        disp.reshape(ep, E_loc, C, D), axis, split_axis=0, concat_axis=0
    )  # (ep=source device, E_loc, C, D)
    buf = recv.transpose(1, 0, 2, 3).reshape(E_loc, ep * C, D)

    # -- local experts only: same contractions as the dense path, so the
    # quantized storage layouts dispatch identically
    pe = p["experts"]
    h1 = quantized_einsum("emd,eid->emi", buf, pe["fc_1"])
    h2 = quantized_einsum("emd,eid->emi", buf, pe["fc_2"])
    h = jax.nn.silu(h1) * h2
    outb = quantized_einsum("emi,edi->emd", h, pe["proj"])

    # -- return trip + weighted combine at each token's home device
    back = jax.lax.all_to_all(
        outb.reshape(E_loc, ep, C, D).transpose(1, 0, 2, 3),
        axis, split_axis=0, concat_axis=0,
    )  # (ep=expert owner, E_loc, C, D)
    outd = back.reshape(E, C, D)
    y = outd[flat_e, pos_c] * (flat_w[:, None] * contrib[:, None]).astype(x.dtype)
    out = jnp.zeros((n, D), x.dtype).at[flat_tok].add(y)
    if not with_aux:
        return out[None]

    # load-balancing stats over the GLOBAL token population: pre-drop
    # assignment counts (router intent, independent of capacity) and mean
    # router probability per expert, psum-reduced over the ep axis so the
    # formula matches the dense path exactly
    assign = jnp.sum(onehot.astype(jnp.float32), axis=0)  # (E,)
    prob_sum = jnp.sum(
        probs * vmask[:, None].astype(probs.dtype), axis=0
    )  # (E,)
    n_valid = jnp.sum(vmask.astype(jnp.float32))
    red = (dp_axis, axis) if dp_axis else axis
    assign, prob_sum, n_valid = jax.lax.psum(
        (assign, prob_sum, n_valid), red
    )
    f = assign / jnp.maximum(n_valid * k, 1.0)
    pm = prob_sum / jnp.maximum(n_valid, 1.0)
    aux = E * jnp.sum(f * pm)
    return out[None], aux[None]


def ep_moe_forward(
    cfg: Config,
    p: Params,
    x: jnp.ndarray,  # (B, T, D)
    mesh: Mesh,
    axis: str = "ep",
    capacity_factor: Optional[float] = None,
    with_aux: bool = False,
    dp_axis: Optional[str] = None,
):
    """Expert-parallel MoE layer: drop-in for `transformer.moe_forward`
    (pass as `moe_impl=` through `transformer.forward`).  Tokens are split
    over the `axis` devices, experts dispatched via all_to_all; output is
    replicated like the input.  Returns the (B, T, D) output, or
    `(output, aux)` with `with_aux`.

    `with_aux` additionally returns the load-balancing auxiliary loss
    (same formula as the dense path — see `transformer.moe_forward`), used
    by MoE training.  The whole dispatch is differentiable (`all_to_all`
    transposes to the reverse all_to_all), so this path trains.

    `dp_axis` (training on a (dp, ep) mesh): split tokens over BOTH axes so
    each device routes N/(dp·ep) tokens instead of every dp replica
    redundantly routing N/ep — the dispatch all_to_all stays within each dp
    row, expert shards are dp-replicated (their gradient psum over dp falls
    out of the shard_map transpose), and the aux stats reduce over both
    axes.  Without it, a dp-sharded activation would also be all-gathered
    by GSPMD at every MoE layer just to feed the ep-only split."""
    ep = int(mesh.shape[axis])
    E = cfg.n_expert
    if E % ep:
        raise ValueError(f"n_expert={E} not divisible by {axis}={ep}")
    dp = int(mesh.shape[dp_axis]) if dp_axis else 1
    splits = dp * ep
    B, T, D = x.shape
    N = B * T
    n_loc = -(-N // splits)
    Np = n_loc * splits
    C = expert_capacity(cfg, n_loc, capacity_factor)

    xf = x.reshape(N, D)
    if Np > N:
        xf = jnp.pad(xf, ((0, Np - N), (0, 0)))
    xs = xf.reshape(splits, n_loc, D)
    valid = (jnp.arange(Np) < N).reshape(splits, n_loc)

    def leaf_spec(shard_first):
        return lambda a: P(axis, *([None] * (a.ndim - 1))) if shard_first else P(
            *([None] * a.ndim)
        )

    p_specs = {
        "gate": jax.tree_util.tree_map(leaf_spec(False), p["gate"]),
        "experts": jax.tree_util.tree_map(leaf_spec(True), p["experts"]),
    }
    tok = (dp_axis, axis) if dp_axis else axis
    body = partial(_local_moe, cfg, ep, C, axis, with_aux, dp_axis)
    out_specs = (
        (P(tok, None, None), P(tok)) if with_aux else P(tok, None, None)
    )
    out = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(tok, None, None), P(tok, None), p_specs),
        out_specs=out_specs,
        check_vma=False,
    )(xs, valid, {"gate": p["gate"], "experts": p["experts"]})
    if with_aux:
        out, aux = out
        return out.reshape(Np, D)[:N].reshape(B, T, D), aux[0]
    return out.reshape(Np, D)[:N].reshape(B, T, D)
