"""GSPMD sharding rules for training and batched inference.

The reference's only training parallelism is torch DDP over NCCL
(`/root/reference/src/train.py:88-103,250-251`).  Here parallelism is
declarative: a mesh with `dp` (data), `tp` (tensor), and optionally `ep`
(expert) axes plus PartitionSpecs per parameter leaf; XLA inserts the
collectives (psum for DP grads ≡ DDP all-reduce, all-gather/reduce-scatter
for TP) over ICI.

Rules (Megatron-style, laid over the stacked-layer pytree):
- qkv / fc up-projections: shard output features on `tp` (column parallel)
- attn proj / mlp down-projection: shard input features on `tp` (row parallel)
- embeddings + lm_head: shard vocab on `tp`
- MoE experts: shard the expert axis on `ep` (defaults to the `tp` axis)
- norms, biases of row-parallel layers: replicated
- batch: shard on `dp`; sequence axis optionally on `sp` (ring attention)
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from mdi_llm_tpu.config import Config


def param_specs(
    cfg: Config, tp_axis: Optional[str] = "tp", ep_axis: Optional[str] = None
) -> Dict[str, Any]:
    """PartitionSpec pytree matching the params pytree layout.

    Every block leaf has a leading layer axis (never sharded).  Pass
    tp_axis=None for pure data parallelism (fully replicated params).
    """
    t = tp_axis
    e = ep_axis or tp_axis

    def lin_col(bias: bool):  # output features sharded
        d = {"weight": P(None, t, None)}
        if bias:
            d["bias"] = P(None, t)
        return d

    def lin_row(bias: bool):  # input features sharded
        d = {"weight": P(None, None, t)}
        if bias:
            d["bias"] = P(None, None)
        return d

    def norm():
        d = {"weight": P(None, None)}
        if cfg.norm_class_name == "LayerNorm" and cfg.bias:
            d["bias"] = P(None, None)
        return d

    attn = {"qkv": lin_col(cfg.bias), "proj": lin_row(cfg.bias)}
    if cfg.mlp_class_name == "GptNeoxMLP":
        mlp = {"fc": lin_col(cfg.bias), "proj": lin_row(cfg.bias)}
    elif cfg.mlp_class_name in ("LLaMAMLP", "GemmaMLP"):
        mlp = {
            "fc_1": {"weight": P(None, t, None)},
            "fc_2": {"weight": P(None, t, None)},
            "proj": {"weight": P(None, None, t)},
        }
    else:  # LLaMAMoE: shard experts over ep
        mlp = {
            "gate": {"weight": P(None, None, None)},
            "experts": {
                "fc_1": {"weight": P(None, e, None, None)},
                "fc_2": {"weight": P(None, e, None, None)},
                "proj": {"weight": P(None, e, None, None)},
            },
        }
    blocks = {"norm_1": norm(), "attn": attn, "mlp": mlp}
    if not cfg.shared_attention_norm:
        blocks["norm_2"] = norm()

    specs: Dict[str, Any] = {
        "wte": {"weight": P(t, None)},
        "blocks": blocks,
        "ln_f": {
            "weight": P(None),
            **({"bias": P(None)} if cfg.norm_class_name == "LayerNorm" and cfg.bias else {}),
        },
    }
    if cfg.pos_embedding == "learned":
        specs["wpe"] = {"weight": P(None, None)}
    if not cfg.tie_embeddings:
        specs["lm_head"] = {"weight": P(t, None)}
        if cfg.lm_head_bias:
            specs["lm_head"]["bias"] = P(t)
    elif cfg.lm_head_bias:
        specs["lm_head"] = {"bias": P(t)}
    return specs


def validate_tp_divisibility(cfg: Config, tp: int, check_vocab: bool = False):
    """Reject configs whose sharded dimensions don't divide by tp.  The rule
    table mirrors param_specs: attention heads/KV groups always shard; MoE
    configs shard the expert axis while dense ones shard the intermediate
    dim; the vocab axis only matters where embeddings/head are tp-sharded
    (Generator — the pipeline ring keeps head params replicated)."""
    if tp <= 1:
        return
    moe = cfg.mlp_class_name == "LLaMAMoE"
    dims = [
        ("n_head", cfg.n_head),
        ("n_query_groups", cfg.n_query_groups),
        ("n_expert", cfg.n_expert) if moe
        else ("intermediate_size", cfg.intermediate_size),
    ]
    if check_vocab:
        dims.append(("padded_vocab_size", cfg.padded_vocab_size))
    bad = [name for name, dim in dims if dim % tp]
    if bad:
        raise ValueError(f"tp={tp} does not divide {', '.join(bad)} of {cfg.name}")


def adapt_specs_to_tree(
    specs: Any,
    params: Any,
    leading_axes: int = 0,
    axis_sizes: Optional[Dict[str, int]] = None,
):
    """Adapt a `param_specs` tree (standard "weight" leaves) to the ACTUAL
    params tree, which may hold quantized storage layouts
    (weight_q/weight_q8 int8, weight_q4 packed nibbles, + scale —
    ops/quant.py).  The quantized layouts keep the weight's axis order, so
    one rule covers every mode:

    - `weight_q*` inherits the weight's spec unchanged (the int4 packed
      axis is still the contracted input axis — same sharding);
    - `scale` inherits the FIRST `ndim` entries of the weight's spec:
      per-out-channel scales (L, out) follow the out-dim sharding of
      column-parallel weights and replicate for row-parallel ones (where
      the weight spec's entry 1 is None), while int4 group scales
      (L, out, groups) additionally shard their group axis exactly when
      the contracted axis is sharded.

    `leading_axes` accounts for extra stacked axes the caller prepends to
    every leaf (the pipeline's stage axis): scale truncation then uses
    `leaf.ndim - leading_axes`.  `axis_sizes` (mesh axis name → size)
    un-shards any scale dim the mesh cannot divide — the int4 group axis
    collapses to a single group whenever the input dim is <= the group
    width (w4_group_size), and a size-1 dim cannot shard; the matmul stays
    exact either way, the spec is only a layout.
    """

    def fit_spec(base, v):
        """`base` truncated to the leaf's dims, with any mesh-indivisible
        sharding dropped (applies to weight_q4 too: its packed axis is
        HALF the input dim, so a tp-divisible input dim does not guarantee
        a tp-divisible packed axis)."""
        entries = list(base[: np.ndim(v) - leading_axes])
        if axis_sizes:
            shape = np.shape(v)[leading_axes:]
            entries = [
                a
                if a is None or shape[i] % axis_sizes.get(a, 1) == 0
                else None
                for i, a in enumerate(entries)
            ]
        return P(*entries)

    def walk(s_node, p_node):
        if not isinstance(p_node, dict):
            return s_node
        if any(k.startswith("weight_q") for k in p_node):
            base = s_node["weight"]
            out = {}
            for k, v in p_node.items():
                if k == "scale" or k.startswith("weight_q"):
                    out[k] = fit_spec(base, v)
                else:  # bias etc. keep their standard spec
                    out[k] = s_node[k]
            return out
        return {k: walk(s_node[k], v) for k, v in p_node.items()}

    return walk(specs, params)


def shard_params(
    params: Any,
    cfg: Config,
    mesh: Mesh,
    tp_axis: Optional[str] = "tp",
    ep_axis: Optional[str] = None,
):
    """Place a params pytree onto `mesh` under the TP/EP rules.  Quantized
    trees (weight_q/scale leaves) are handled by adapting the standard
    specs to the storage layout — see `adapt_specs_to_tree`."""
    tp = tp_axis if (tp_axis and tp_axis in mesh.axis_names) else None
    ep = ep_axis if (ep_axis and ep_axis in mesh.axis_names) else None
    sizes = {a: int(mesh.shape[a]) for a in mesh.axis_names}
    specs = adapt_specs_to_tree(param_specs(cfg, tp, ep), params, axis_sizes=sizes)
    return jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)), params, specs
    )


def batch_spec(dp_axis: str = "dp", sp_axis: Optional[str] = None) -> P:
    return P(dp_axis, sp_axis)


def paged_kv_spec(tp_axis: Optional[str] = "tp") -> P:
    """PartitionSpec for the serving engine's pooled block cache
    (`transformer.init_paged_kv_cache`: k/v each (L, num_blocks, block_size,
    G, hs)): shard the KV-group axis on `tp`, exactly like the dense
    (L, B, G, S, hs) decode cache — each device holds its head-slice of
    EVERY block, so the host-side allocator (block ids, free lists, prefix
    hashes) needs no notion of devices.  Requires n_query_groups % tp == 0
    (`validate_tp_divisibility` — n_query_groups is already in its rule
    table; mdi-audit's `bad-serving-mesh` check mirrors it statically)."""
    return P(None, None, None, tp_axis, None)


def paged_kv_scale_spec(tp_axis: Optional[str] = "tp") -> P:
    """PartitionSpec for the int8 pool's per-block-per-group scale arrays
    (`init_paged_kv_cache(dtype="int8")`: (L, num_blocks, G) f32): the
    KV-group axis shards on `tp` exactly like the payload's
    (`paged_kv_spec`), so each device dequantizes its own group-slice with
    its own scale slice and the allocator stays device-count-blind."""
    return P(None, None, tp_axis)


def block_table_spec() -> P:
    """Block tables ((n_slots, max_blocks) int32) are replicated: every
    device resolves the same block ids — only the KV bytes shard."""
    return P(None, None)
