"""Device-mesh construction helpers.

The reference's "network topology JSON" (`src/settings_distr/*.json`: node
addresses, ports) maps on TPU to a `jax.sharding.Mesh` over the device grid:
pipeline stages live on a 1-D `pipe` axis (ICI/DCN neighbors), and training
uses `dp`/`tp`(/`sp`) axes.  Multi-host: `jax.distributed.initialize` makes
all processes see the global device list, replacing the reference's HTTP
`/init` bootstrap (`model_dist.py:402-497`).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    axes: Dict[str, int], devices: Optional[Sequence] = None
) -> Mesh:
    """Build a Mesh with the given {axis_name: size}.  Sizes must multiply to
    the device count used; pass -1 for one axis to infer it."""
    devices = list(devices if devices is not None else jax.devices())
    sizes = dict(axes)
    for name, v in sizes.items():
        if not isinstance(v, int) or (v < 1 and v != -1):
            raise ValueError(
                f"mesh axis {name!r} must have size >= 1 (or -1 to infer), "
                f"got {v!r}"
            )
    infer = [k for k, v in sizes.items() if v == -1]
    if len(infer) > 1:
        raise ValueError(f"only one axis size may be -1, got {infer}")
    known = int(np.prod([v for v in sizes.values() if v != -1]))
    if infer:
        if known > len(devices) or len(devices) % known:
            explicit = {k: v for k, v in sizes.items() if v != -1}
            raise ValueError(
                f"cannot infer axis {infer[0]!r}: explicit sizes {explicit} "
                f"multiply to {known}, which does not divide the "
                f"{len(devices)} available devices"
            )
        sizes[infer[0]] = len(devices) // known
    total = int(np.prod(list(sizes.values())))
    if total > len(devices):
        raise ValueError(
            f"mesh {sizes} needs {total} devices, have {len(devices)}"
        )
    grid = np.asarray(devices[:total]).reshape(*sizes.values())
    return Mesh(grid, tuple(sizes.keys()))


def pipeline_mesh(
    n_stages: int, devices: Optional[Sequence] = None, tp: int = 1
) -> Mesh:
    """1-D stage ring, optionally × a tensor-parallel axis within each stage
    (the classic serving topology: tp inside a host's ICI domain, pipeline
    across)."""
    if tp > 1:
        return make_mesh({"pipe": n_stages, "tp": tp}, devices)
    return make_mesh({"pipe": n_stages}, devices)
