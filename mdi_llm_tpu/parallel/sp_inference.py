"""Sequence-parallel (ring-attention) inference: context scales with the
number of devices.

New design territory relative to the reference (SURVEY.md §5.7 — its context
is bounded by one device's memory):

- **Prefill**: the prompt is split into P contiguous chunks over the `sp`
  mesh axis.  Each device embeds its chunk, runs the block stack with ring
  attention (`ops/ring_attention.ring_attention`), and writes its chunk's
  K/V into its LOCAL cache shard — no device ever materializes the full
  sequence.
- **Decode**: the new token is replicated; each device computes
  online-softmax partials over its local cache shard and the partials merge
  with one `pmax`/`psum` pair (`ops/ring_attention.ring_decode`) — the
  distributed analog of flash-decoding.  The token's K/V is appended
  round-robin to the devices' shards, so cache growth is balanced: per-chip
  memory is O((prompt + generated) / P).
- Slot→position indirection (`kp`): each local cache slot carries its
  absolute sequence position (sentinel = empty), making the round-robin
  placement transparent to attention masking.

Golden parity with single-device generation is pinned by
tests/test_sp_inference.py.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from mdi_llm_tpu.config import TEMPERATURE, TOP_K, Config
from mdi_llm_tpu.generation import (
    GenerationStats,
    _bucket,
    detect_stop_tokens,
    find_eot,
)
from mdi_llm_tpu.models import transformer
from mdi_llm_tpu.ops.sampling import sample
from mdi_llm_tpu.parallel.mesh import make_mesh
from mdi_llm_tpu.utils.context_managers import catch_loop_errors

POS_SENTINEL = np.int32(1 << 30)  # empty cache slot: never <= a real q_pos


class SPGenerator:
    """Compile-once sequence-parallel generation driver.

    Weights are replicated over the `sp` axis; the KV cache (and so the
    context) is sharded over it.  The per-device cache budget is
    `ceil(prompt/P) + ceil(max_new/P)` slots versus `prompt + max_new` for a
    single device."""

    def __init__(
        self,
        cfg: Config,
        params: Any,
        n_devices: Optional[int] = None,
        devices: Optional[Sequence] = None,
        mesh=None,
        max_seq_length: Optional[int] = None,
        cache_dtype=None,
        rng_seed: int = 1337,
        decode_chunk: int = 32,
        use_flash=False,  # run prefill's ring attention through the
        # Pallas flash kernel.  Explicit opt-in (not auto): the fused
        # sp ring is interpret/trace-tested but has not yet executed on
        # real TPU hardware — same reasoning as Trainer's sp opt-in.
        # True is soft-gated on a TPU backend (warn + fall back on CPU,
        # where the kernel cannot lower); "force" skips the gate for
        # trace/interpret testing.  Flip to an auto default once a TPU
        # run validates it.
        flash_min_len: int = 2048,  # engage flash only when the LOCAL
        # sequence chunk is at least this long (v5e measurement in
        # generation.py: XLA's fused attention wins below ~2k)
        quantize: Optional[str] = None,  # None | int8 | w8a8 | int4 —
        # quantized weights replicate over sp while the KV cache (the part
        # that actually grows with context) stays sequence-sharded: the
        # realistic long-context serving shape for 8B-class models.
        # quantized_einsum dispatches on leaf names inside the shard_map,
        # so every storage mode works unchanged.
    ):
        if mesh is None:
            mesh = make_mesh(
                {"sp": n_devices or len(devices or jax.devices())}, devices
            )
        self.mesh = mesh
        self.P = int(mesh.devices.size)
        self.cfg = cfg
        self.max_seq_length = int(min(max_seq_length or cfg.block_size, cfg.block_size))
        from mdi_llm_tpu.ops.quant import FLAG_TO_MODE, quantize_params

        if quantize not in (None, "none") and quantize not in FLAG_TO_MODE:
            raise ValueError(f"unknown quantize mode {quantize!r}")
        if quantize in FLAG_TO_MODE:
            params = quantize_params(params, mode=FLAG_TO_MODE[quantize])
        if cache_dtype is None:
            cache_dtype = transformer.param_dtype(params)
        self.cache_dtype = cache_dtype
        self.decode_chunk = int(decode_chunk)
        if use_flash and use_flash != "force" and jax.default_backend() != "tpu":
            # fail soft, not with a raw Pallas lowering error mid-compile
            # (matches Generator's auto gate and bench.run_prefill).
            # use_flash="force" skips the gate (trace tests, interpret runs).
            import sys

            print(
                "warning: --sp-flash needs a TPU backend; falling back to "
                "the XLA ring-attention path",
                file=sys.stderr,
            )
            use_flash = False
        self.use_flash = bool(use_flash)
        self.flash_min_len = int(flash_min_len)
        self.key = jax.random.PRNGKey(rng_seed)
        repl = NamedSharding(mesh, P())
        self.params = jax.device_put(params, repl)
        self.rope = tuple(
            jax.device_put(np.asarray(r), repl) for r in transformer.get_rope_cache(cfg)
        )
        self._prefill_jit: Dict[Tuple, Any] = {}
        self._decode_jit: Dict[Tuple, Any] = {}
        self._last_kp: Optional[np.ndarray] = None  # debug observable: the
        # slot→position map after the most recent generate() (see
        # slot_owner_map)

    def slot_owner_map(self) -> Optional[np.ndarray]:
        """Debug observable for the round-robin cache-append math: the
        slot→absolute-position map after the most recent `generate`,
        shaped (B, P, C) — entry [b, d, j] is the sequence position whose
        K/V lives in device d's local slot j for sample b (POS_SENTINEL =
        empty).  Slots j < Tl were written by prefill (device d's prompt
        chunk); slots j >= Tl by decode step s = (j - Tl)·P + d, i.e.
        owner d = s % P at local row Tl + s // P.  Tests assert this map
        directly at the `new % P` boundaries so an owner-math regression
        cannot hide behind tiny-model logit tolerance."""
        if self._last_kp is None:
            return None
        B = self._last_kp.shape[0]
        return self._last_kp.reshape(B, self.P, -1)

    # -- sharding specs ------------------------------------------------------

    @property
    def _kv_spec(self):
        return {"k": P(None, None, None, "sp", None), "v": P(None, None, None, "sp", None)}

    def _init_kv(self, B: int, C: int):
        cfg = self.cfg
        shape = (cfg.n_layer, B, cfg.n_query_groups, self.P * C, cfg.head_size)
        sh = NamedSharding(self.mesh, P(None, None, None, "sp", None))
        return {
            "k": jax.device_put(jnp.zeros(shape, self.cache_dtype), sh),
            "v": jax.device_put(jnp.zeros(shape, self.cache_dtype), sh),
        }

    # -- compiled phases -----------------------------------------------------

    def _get_prefill(self, B, Tl, C, temperature, top_k, top_p):
        key = (B, Tl, C, temperature, top_k, top_p)
        if key not in self._prefill_jit:
            cfg = self.cfg

            def body(params, rope, toks, lens, kv, rkey):
                d = jax.lax.axis_index("sp")
                start = (d * Tl).astype(jnp.int32)
                input_pos = jnp.full((B,), start, jnp.int32)
                gpos = start + jnp.arange(Tl, dtype=jnp.int32)
                kp = jnp.concatenate(
                    [
                        jnp.where(gpos[None, :] < lens[:, None], gpos[None, :], POS_SENTINEL),
                        jnp.full((B, C - Tl), POS_SENTINEL, jnp.int32),
                    ],
                    axis=1,
                )
                logits, kv = transformer.forward(
                    cfg, params, toks, input_pos, kv=kv, rope=rope,
                    sp_axis="sp", sp_meta=(kp, jnp.int32(0), jnp.bool_(False)),
                    # gate on the LOCAL chunk length: that's the tile the
                    # kernel actually sees under sequence sharding
                    use_flash=self.use_flash and Tl >= self.flash_min_len,
                )
                # gather each sample's last-prompt-token logits to all devices
                own = (lens - 1) // Tl == d  # (B,)
                idx = jnp.clip(lens - 1 - start, 0, Tl - 1)
                last = jnp.take_along_axis(logits, idx[:, None, None], axis=1)[:, 0]
                last = jax.lax.psum(
                    jnp.where(own[:, None], last.astype(jnp.float32), 0.0), "sp"
                )
                tok = sample(
                    last, rkey, temperature=temperature, top_k=top_k, top_p=top_p
                ).astype(jnp.int32)
                return kv, kp, tok

            repl = P()
            sm = jax.shard_map(
                body,
                mesh=self.mesh,
                in_specs=(
                    jax.tree_util.tree_map(lambda _: repl, self.params),
                    (repl, repl),
                    P(None, "sp"),
                    repl,
                    self._kv_spec,
                    repl,
                ),
                out_specs=(self._kv_spec, P(None, "sp"), repl),
            )
            self._prefill_jit[key] = jax.jit(sm, donate_argnums=(4,))
        return self._prefill_jit[key]

    def _get_decode(self, B, Tl, C, n_steps, temperature, top_k, top_p):
        key = (B, Tl, C, n_steps, temperature, top_k, top_p)
        if key not in self._decode_jit:
            cfg, Pn = self.cfg, self.P

            def body(params, rope, kv, kp, tok, pos, step0, rkey):
                d = jax.lax.axis_index("sp")

                def step(carry, i):
                    kv, kp, tok, pos, rkey = carry
                    owner = (step0 + i) % Pn
                    loc = Tl + (step0 + i) // Pn
                    write_on = owner == d
                    kp = jnp.where(
                        write_on,
                        jax.lax.dynamic_update_slice(kp, pos[:, None], (0, loc)),
                        kp,
                    )
                    logits, kv = transformer.forward(
                        cfg, params, tok[:, None], pos, kv=kv, rope=rope,
                        sp_axis="sp", sp_meta=(kp, loc, write_on),
                    )
                    rkey, sub = jax.random.split(rkey)
                    tok = sample(
                        logits[:, -1], sub,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                    ).astype(jnp.int32)
                    pos = pos + 1
                    return (kv, kp, tok, pos, rkey), tok

                carry, toks = jax.lax.scan(
                    step, (kv, kp, tok, pos, rkey), jnp.arange(n_steps, dtype=jnp.int32)
                )
                kv, kp, tok, pos, _ = carry
                return kv, kp, tok, pos, toks  # toks (n_steps, B)

            repl = P()
            sm = jax.shard_map(
                body,
                mesh=self.mesh,
                in_specs=(
                    jax.tree_util.tree_map(lambda _: repl, self.params),
                    (repl, repl),
                    self._kv_spec,
                    P(None, "sp"),
                    repl,
                    repl,
                    repl,
                    repl,
                ),
                out_specs=(self._kv_spec, P(None, "sp"), repl, repl, repl),
            )
            self._decode_jit[key] = jax.jit(sm, donate_argnums=(2, 3))
        return self._decode_jit[key]

    # -- public API ----------------------------------------------------------

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: int,
        temperature: float = TEMPERATURE,
        top_k: Optional[int] = TOP_K,
        top_p: Optional[float] = None,
        stop_sequences: Sequence[Sequence[int]] = (),
    ) -> Tuple[List[List[int]], GenerationStats]:
        Pn = self.P
        stats = GenerationStats()
        B = len(prompts)
        lens = [len(p) for p in prompts]
        if min(lens) < 1:
            raise ValueError("empty prompt")
        if max(lens) + max_new_tokens > self.max_seq_length:
            raise ValueError(
                f"prompt+generation length {max(lens) + max_new_tokens} exceeds "
                f"max_seq_length {self.max_seq_length}"
            )
        t0 = time.perf_counter()
        # bucket the prompt length so repeated calls with nearby lengths
        # reuse the compiled prefill/decode programs (≡ Generator._bucket)
        Tl = -(-_bucket(max(lens)) // Pn)  # local prompt chunk
        C = Tl + -(-max_new_tokens // Pn)  # local cache budget
        toks_np = np.zeros((B, Tl * Pn), np.int32)
        for b, p in enumerate(prompts):
            toks_np[b, : lens[b]] = np.asarray(p, np.int32)

        kv = self._init_kv(B, C)
        prefill = self._get_prefill(B, Tl, C, temperature, top_k, top_p)
        self.key, sub = jax.random.split(self.key)
        kv, kp, tok = prefill(
            self.params, self.rope, jnp.asarray(toks_np),
            jnp.asarray(lens, jnp.int32), kv, sub,
        )
        stats.prefill_s = time.perf_counter() - t0

        out = [list(p) for p in prompts]
        done = [False] * B
        tok_np = np.asarray(tok)
        for b in range(B):
            out[b].append(int(tok_np[b]))
            if detect_stop_tokens(out[b][lens[b] :], stop_sequences):
                done[b] = True
        n = 1

        # the decode step processes `tok` (just sampled) at its own position,
        # which for the first generated token is the prompt length
        pos = jnp.asarray(lens, jnp.int32)
        step0 = 0
        with catch_loop_errors() as guard:
            while n < max_new_tokens and not all(done):
                c = min(self.decode_chunk, max_new_tokens - n)
                decode = self._get_decode(B, Tl, C, c, temperature, top_k, top_p)
                self.key, sub = jax.random.split(self.key)
                kv, kp, tok, pos, toks = decode(
                    self.params, self.rope, kv, kp, tok, pos,
                    jnp.int32(step0), sub,
                )
                step0 += c
                toks_np = np.asarray(toks)
                for i in range(c):
                    n += 1
                    for b in range(B):
                        if not done[b]:
                            out[b].append(int(toks_np[i, b]))
                            if detect_stop_tokens(out[b][lens[b] :], stop_sequences):
                                done[b] = True
                    stats.tok_time.append(
                        (
                            sum(len(o) - l for o, l in zip(out, lens)),
                            time.perf_counter() - t0,
                        )
                    )
        stats.interrupted = guard.interrupted
        self._last_kp = np.asarray(kp)
        stats.decode_s = time.perf_counter() - t0 - stats.prefill_s
        trimmed = []
        for o, l in zip(out, lens):
            cut = find_eot(o[l:], stop_sequences)
            trimmed.append(o[: l + cut])
        stats.tokens_generated = sum(len(o) - l for o, l in zip(out, lens))
        return trimmed, stats

    def generate_chat(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        temperature: float = TEMPERATURE,
        top_k: Optional[int] = TOP_K,
        top_p: Optional[float] = None,
        stop_sequences: Sequence[Sequence[int]] = (),
    ):
        """Streaming single-sample generation over the sp mesh — same
        contract as `Generator.generate_chat` (tokens yielded as sampled,
        stop-sequence prefixes buffered so a partial marker never prints),
        so the chat REPL drives long-context sequence-sharded serving the
        same way it drives every other backend.  Tokens surface per decode
        chunk (`decode_chunk`; pass a small one for lower time-to-first-
        byte at a modest dispatch-rate cost)."""
        from mdi_llm_tpu.generation import stop_filtered_stream

        return stop_filtered_stream(
            self._generate_stream(
                prompt, max_new_tokens, temperature, top_k, top_p, stop_sequences
            ),
            stop_sequences,
        )

    def _generate_stream(
        self, prompt, max_new_tokens, temperature, top_k, top_p, stop_sequences
    ):
        Pn = self.P
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        if len(prompt) + max_new_tokens > self.max_seq_length:
            raise ValueError(
                f"prompt+generation length {len(prompt) + max_new_tokens} "
                f"exceeds max_seq_length {self.max_seq_length}"
            )
        lens = jnp.asarray([len(prompt)], jnp.int32)
        Tl = -(-_bucket(len(prompt)) // Pn)
        C = Tl + -(-max_new_tokens // Pn)
        toks_np = np.zeros((1, Tl * Pn), np.int32)
        toks_np[0, : len(prompt)] = np.asarray(prompt, np.int32)

        kv = self._init_kv(1, C)
        prefill = self._get_prefill(1, Tl, C, temperature, top_k, top_p)
        self.key, sub = jax.random.split(self.key)
        kv, kp, tok = prefill(
            self.params, self.rope, jnp.asarray(toks_np), lens, kv, sub
        )
        history = [int(np.asarray(tok)[0])]
        yield history[0]
        if detect_stop_tokens(history, stop_sequences):
            return
        n = 1
        pos = lens
        step0 = 0
        while n < max_new_tokens:
            c = min(self.decode_chunk, max_new_tokens - n)
            decode = self._get_decode(1, Tl, C, c, temperature, top_k, top_p)
            self.key, sub = jax.random.split(self.key)
            kv, kp, tok, pos, toks = decode(
                self.params, self.rope, kv, kp, tok, pos, jnp.int32(step0), sub
            )
            step0 += c
            chunk = np.asarray(toks)
            for i in range(c):
                n += 1
                t = int(chunk[i, 0])
                history.append(t)
                yield t
                if detect_stop_tokens(history, stop_sequences):
                    return
