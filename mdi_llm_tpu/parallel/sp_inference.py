"""Sequence-parallel (ring-attention) inference: context scales with the
number of devices.

New design territory relative to the reference (SURVEY.md §5.7 — its context
is bounded by one device's memory):

- **Prefill**: the prompt is split into P contiguous chunks over the `sp`
  mesh axis.  Each device embeds its chunk, runs the block stack with ring
  attention (`ops/ring_attention.ring_attention`), and writes its chunk's
  K/V into its LOCAL cache shard — no device ever materializes the full
  sequence.
- **Decode**: the new token is replicated; each device computes
  online-softmax partials over its local cache shard and the partials merge
  with one `pmax`/`psum` pair (`ops/ring_attention.ring_decode`) — the
  distributed analog of flash-decoding.  The token's K/V is appended
  round-robin to the devices' shards, so cache growth is balanced: per-chip
  memory is O((prompt + generated) / P).
- Slot→position indirection (`kp`): each local cache slot carries its
  absolute sequence position (sentinel = empty), making the round-robin
  placement transparent to attention masking.

Golden parity with single-device generation is pinned by
tests/test_sp_inference.py.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from mdi_llm_tpu.config import TEMPERATURE, TOP_K, Config
from mdi_llm_tpu.generation import (
    GenerationStats,
    _bucket,
    detect_stop_tokens,
    find_eot,
    accept_draft,
    ngram_draft,
    pad_draft,
    stop_filtered_stream,
)
from mdi_llm_tpu.models import transformer
from mdi_llm_tpu.ops.sampling import sample
from mdi_llm_tpu.parallel.mesh import make_mesh
from mdi_llm_tpu.utils.context_managers import catch_loop_errors

POS_SENTINEL = np.int32(1 << 30)  # empty cache slot: never <= a real q_pos


class SPGenerator:
    """Compile-once sequence-parallel generation driver.

    Weights are replicated over the `sp` axis; the KV cache (and so the
    context) is sharded over it.  The per-device cache budget is
    `ceil(prompt/P) + ceil(max_new/P)` slots versus `prompt + max_new` for a
    single device."""

    def __init__(
        self,
        cfg: Config,
        params: Any,
        n_devices: Optional[int] = None,
        devices: Optional[Sequence] = None,
        mesh=None,
        max_seq_length: Optional[int] = None,
        cache_dtype=None,
        rng_seed: int = 1337,
        decode_chunk: int = 32,
        use_flash=False,  # run prefill's ring attention through the
        # Pallas flash kernel.  Explicit opt-in (not auto): the fused
        # sp ring is interpret/trace-tested but has not yet executed on
        # real TPU hardware — same reasoning as Trainer's sp opt-in.
        # True is soft-gated on a TPU backend (warn + fall back on CPU,
        # where the kernel cannot lower); "force" skips the gate for
        # trace/interpret testing.  Flip to an auto default once a TPU
        # run validates it.
        flash_min_len: int = 2048,  # engage flash only when the LOCAL
        # sequence chunk is at least this long (v5e measurement in
        # generation.py: XLA's fused attention wins below ~2k)
        quantize: Optional[str] = None,  # None | int8 | w8a8 | int4 —
        # quantized weights replicate over sp while the KV cache (the part
        # that actually grows with context) stays sequence-sharded: the
        # realistic long-context serving shape for 8B-class models.
        # quantized_einsum dispatches on leaf names inside the shard_map,
        # so every storage mode works unchanged.
    ):
        if mesh is None:
            mesh = make_mesh(
                {"sp": n_devices or len(devices or jax.devices())}, devices
            )
        self.mesh = mesh
        self.P = int(mesh.devices.size)
        self.cfg = cfg
        self.max_seq_length = int(min(max_seq_length or cfg.block_size, cfg.block_size))
        from mdi_llm_tpu.ops.quant import FLAG_TO_MODE, quantize_params

        if quantize not in (None, "none") and quantize not in FLAG_TO_MODE:
            raise ValueError(f"unknown quantize mode {quantize!r}")
        if quantize in FLAG_TO_MODE:
            params = quantize_params(params, mode=FLAG_TO_MODE[quantize])
        if cache_dtype is None:
            cache_dtype = transformer.param_dtype(params)
        self.cache_dtype = cache_dtype
        self.decode_chunk = int(decode_chunk)
        if use_flash and use_flash != "force" and jax.default_backend() != "tpu":
            # fail soft, not with a raw Pallas lowering error mid-compile
            # (matches Generator's auto gate and bench.run_prefill).
            # use_flash="force" skips the gate (trace tests, interpret runs).
            import sys

            print(
                "warning: --sp-flash needs a TPU backend; falling back to "
                "the XLA ring-attention path",
                file=sys.stderr,
            )
            use_flash = False
        self.use_flash = bool(use_flash)
        self.flash_min_len = int(flash_min_len)
        self.key = jax.random.PRNGKey(rng_seed)
        repl = NamedSharding(mesh, P())
        self.params = jax.device_put(params, repl)
        self.rope = tuple(
            jax.device_put(np.asarray(r), repl) for r in transformer.get_rope_cache(cfg)
        )
        self._prefill_jit: Dict[Tuple, Any] = {}
        self._decode_jit: Dict[Tuple, Any] = {}
        self._last_kp: Optional[np.ndarray] = None  # debug observable: the
        # slot→position map after the most recent generate() (see
        # slot_owner_map)

    def slot_owner_map(self) -> Optional[np.ndarray]:
        """Debug observable for the round-robin cache-append math: the
        slot→absolute-position map after the most recent `generate`,
        shaped (B, P, C) — entry [b, d, j] is the sequence position whose
        K/V lives in device d's local slot j for sample b (POS_SENTINEL =
        empty).  Slots j < Tl were written by prefill (device d's prompt
        chunk); slots j >= Tl by decode step s = (j - Tl)·P + d, i.e.
        owner d = s % P at local row Tl + s // P.  Tests assert this map
        directly at the `new % P` boundaries so an owner-math regression
        cannot hide behind tiny-model logit tolerance."""
        if self._last_kp is None:
            return None
        B = self._last_kp.shape[0]
        return self._last_kp.reshape(B, self.P, -1)

    # -- sharding specs ------------------------------------------------------

    @property
    def _kv_spec(self):
        return {"k": P(None, None, None, "sp", None), "v": P(None, None, None, "sp", None)}

    def _init_kv(self, B: int, C: int):
        cfg = self.cfg
        shape = (cfg.n_layer, B, cfg.n_query_groups, self.P * C, cfg.head_size)
        sh = NamedSharding(self.mesh, P(None, None, None, "sp", None))
        return {
            "k": jax.device_put(jnp.zeros(shape, self.cache_dtype), sh),
            "v": jax.device_put(jnp.zeros(shape, self.cache_dtype), sh),
        }

    # -- compiled phases -----------------------------------------------------

    def _get_prefill(self, B, Tl, C, temperature, top_k, top_p):
        key = (B, Tl, C, temperature, top_k, top_p)
        if key not in self._prefill_jit:
            cfg = self.cfg

            def body(params, rope, toks, lens, kv, rkey):
                d = jax.lax.axis_index("sp")
                start = (d * Tl).astype(jnp.int32)
                input_pos = jnp.full((B,), start, jnp.int32)
                gpos = start + jnp.arange(Tl, dtype=jnp.int32)
                kp = jnp.concatenate(
                    [
                        jnp.where(gpos[None, :] < lens[:, None], gpos[None, :], POS_SENTINEL),
                        jnp.full((B, C - Tl), POS_SENTINEL, jnp.int32),
                    ],
                    axis=1,
                )
                logits, kv = transformer.forward(
                    cfg, params, toks, input_pos, kv=kv, rope=rope,
                    sp_axis="sp", sp_meta=(kp, jnp.int32(0), jnp.bool_(False)),
                    # gate on the LOCAL chunk length: that's the tile the
                    # kernel actually sees under sequence sharding
                    use_flash=self.use_flash and Tl >= self.flash_min_len,
                )
                # gather each sample's last-prompt-token logits to all devices
                own = (lens - 1) // Tl == d  # (B,)
                idx = jnp.clip(lens - 1 - start, 0, Tl - 1)
                last = jnp.take_along_axis(logits, idx[:, None, None], axis=1)[:, 0]
                last = jax.lax.psum(
                    jnp.where(own[:, None], last.astype(jnp.float32), 0.0), "sp"
                )
                tok = sample(
                    last, rkey, temperature=temperature, top_k=top_k, top_p=top_p
                ).astype(jnp.int32)
                return kv, kp, tok

            repl = P()
            sm = jax.shard_map(
                body,
                mesh=self.mesh,
                in_specs=(
                    jax.tree_util.tree_map(lambda _: repl, self.params),
                    (repl, repl),
                    P(None, "sp"),
                    repl,
                    self._kv_spec,
                    repl,
                ),
                out_specs=(self._kv_spec, P(None, "sp"), repl),
            )
            self._prefill_jit[key] = jax.jit(sm, donate_argnums=(4,))
        return self._prefill_jit[key]

    def _get_decode(self, B, Tl, C, n_steps, temperature, top_k, top_p):
        key = (B, Tl, C, n_steps, temperature, top_k, top_p)
        if key not in self._decode_jit:
            cfg, Pn = self.cfg, self.P

            def body(params, rope, kv, kp, tok, pos, step0, rkey):
                d = jax.lax.axis_index("sp")

                def step(carry, i):
                    kv, kp, tok, pos, rkey = carry
                    owner = (step0 + i) % Pn
                    loc = Tl + (step0 + i) // Pn
                    write_on = owner == d
                    kp = jnp.where(
                        write_on,
                        jax.lax.dynamic_update_slice(kp, pos[:, None], (0, loc)),
                        kp,
                    )
                    logits, kv = transformer.forward(
                        cfg, params, tok[:, None], pos, kv=kv, rope=rope,
                        sp_axis="sp", sp_meta=(kp, loc, write_on),
                    )
                    rkey, sub = jax.random.split(rkey)
                    tok = sample(
                        logits[:, -1], sub,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                    ).astype(jnp.int32)
                    pos = pos + 1
                    return (kv, kp, tok, pos, rkey), tok

                carry, toks = jax.lax.scan(
                    step, (kv, kp, tok, pos, rkey), jnp.arange(n_steps, dtype=jnp.int32)
                )
                kv, kp, tok, pos, _ = carry
                return kv, kp, tok, pos, toks  # toks (n_steps, B)

            repl = P()
            sm = jax.shard_map(
                body,
                mesh=self.mesh,
                in_specs=(
                    jax.tree_util.tree_map(lambda _: repl, self.params),
                    (repl, repl),
                    self._kv_spec,
                    P(None, "sp"),
                    repl,
                    repl,
                    repl,
                    repl,
                ),
                out_specs=(self._kv_spec, P(None, "sp"), repl, repl, repl),
            )
            self._decode_jit[key] = jax.jit(sm, donate_argnums=(2, 3))
        return self._decode_jit[key]

    # -- public API ----------------------------------------------------------

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: int,
        temperature: float = TEMPERATURE,
        top_k: Optional[int] = TOP_K,
        top_p: Optional[float] = None,
        stop_sequences: Sequence[Sequence[int]] = (),
    ) -> Tuple[List[List[int]], GenerationStats]:
        Pn = self.P
        stats = GenerationStats()
        B = len(prompts)
        lens = [len(p) for p in prompts]
        if min(lens) < 1:
            raise ValueError("empty prompt")
        if max(lens) + max_new_tokens > self.max_seq_length:
            raise ValueError(
                f"prompt+generation length {max(lens) + max_new_tokens} exceeds "
                f"max_seq_length {self.max_seq_length}"
            )
        t0 = time.perf_counter()
        # bucket the prompt length so repeated calls with nearby lengths
        # reuse the compiled prefill/decode programs (≡ Generator._bucket)
        Tl = -(-_bucket(max(lens)) // Pn)  # local prompt chunk
        C = Tl + -(-max_new_tokens // Pn)  # local cache budget
        toks_np = np.zeros((B, Tl * Pn), np.int32)
        for b, p in enumerate(prompts):
            toks_np[b, : lens[b]] = np.asarray(p, np.int32)

        kv = self._init_kv(B, C)
        prefill = self._get_prefill(B, Tl, C, temperature, top_k, top_p)
        self.key, sub = jax.random.split(self.key)
        kv, kp, tok = prefill(
            self.params, self.rope, jnp.asarray(toks_np),
            jnp.asarray(lens, jnp.int32), kv, sub,
        )
        stats.prefill_s = time.perf_counter() - t0

        out = [list(p) for p in prompts]
        done = [False] * B
        tok_np = np.asarray(tok)
        for b in range(B):
            out[b].append(int(tok_np[b]))
            if detect_stop_tokens(out[b][lens[b] :], stop_sequences):
                done[b] = True
        n = 1

        # the decode step processes `tok` (just sampled) at its own position,
        # which for the first generated token is the prompt length
        pos = jnp.asarray(lens, jnp.int32)
        step0 = 0
        with catch_loop_errors() as guard:
            while n < max_new_tokens and not all(done):
                c = min(self.decode_chunk, max_new_tokens - n)
                decode = self._get_decode(B, Tl, C, c, temperature, top_k, top_p)
                self.key, sub = jax.random.split(self.key)
                kv, kp, tok, pos, toks = decode(
                    self.params, self.rope, kv, kp, tok, pos,
                    jnp.int32(step0), sub,
                )
                step0 += c
                toks_np = np.asarray(toks)  # mdi-lint: disable=host-sync -- chunk-boundary read: one sync per c ring steps
                for i in range(c):
                    n += 1
                    for b in range(B):
                        if not done[b]:
                            out[b].append(int(toks_np[i, b]))
                            if detect_stop_tokens(out[b][lens[b] :], stop_sequences):
                                done[b] = True
                    stats.tok_time.append(
                        (
                            sum(len(o) - l for o, l in zip(out, lens)),
                            time.perf_counter() - t0,
                        )
                    )
        stats.interrupted = guard.interrupted
        self._last_kp = np.asarray(kp)
        stats.decode_s = time.perf_counter() - t0 - stats.prefill_s
        trimmed = []
        for o, l in zip(out, lens):
            cut = find_eot(o[l:], stop_sequences)
            trimmed.append(o[: l + cut])
        stats.tokens_generated = sum(len(o) - l for o, l in zip(out, lens))
        return trimmed, stats

    def generate_chat(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        temperature: float = TEMPERATURE,
        top_k: Optional[int] = TOP_K,
        top_p: Optional[float] = None,
        stop_sequences: Sequence[Sequence[int]] = (),
    ):
        """Streaming single-sample generation over the sp mesh — same
        contract as `Generator.generate_chat` (tokens yielded as sampled,
        stop-sequence prefixes buffered so a partial marker never prints),
        so the chat REPL drives long-context sequence-sharded serving the
        same way it drives every other backend.  Tokens surface per decode
        chunk (`decode_chunk`; pass a small one for lower time-to-first-
        byte at a modest dispatch-rate cost)."""
        return stop_filtered_stream(
            self._generate_stream(
                prompt, max_new_tokens, temperature, top_k, top_p, stop_sequences
            ),
            stop_sequences,
        )

    def _generate_stream(
        self, prompt, max_new_tokens, temperature, top_k, top_p, stop_sequences
    ):
        Pn = self.P
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        if len(prompt) + max_new_tokens > self.max_seq_length:
            raise ValueError(
                f"prompt+generation length {len(prompt) + max_new_tokens} "
                f"exceeds max_seq_length {self.max_seq_length}"
            )
        lens = jnp.asarray([len(prompt)], jnp.int32)
        Tl = -(-_bucket(len(prompt)) // Pn)
        C = Tl + -(-max_new_tokens // Pn)
        toks_np = np.zeros((1, Tl * Pn), np.int32)
        toks_np[0, : len(prompt)] = np.asarray(prompt, np.int32)

        kv = self._init_kv(1, C)
        prefill = self._get_prefill(1, Tl, C, temperature, top_k, top_p)
        self.key, sub = jax.random.split(self.key)
        kv, kp, tok = prefill(
            self.params, self.rope, jnp.asarray(toks_np), lens, kv, sub
        )
        history = [int(np.asarray(tok)[0])]
        yield history[0]
        if detect_stop_tokens(history, stop_sequences):
            return
        n = 1
        pos = lens
        step0 = 0
        while n < max_new_tokens:
            c = min(self.decode_chunk, max_new_tokens - n)
            decode = self._get_decode(1, Tl, C, c, temperature, top_k, top_p)
            self.key, sub = jax.random.split(self.key)
            kv, kp, tok, pos, toks = decode(
                self.params, self.rope, kv, kp, tok, pos, jnp.int32(step0), sub
            )
            step0 += c
            chunk = np.asarray(toks)  # mdi-lint: disable=host-sync -- chunk-boundary read: one sync per c ring steps
            for i in range(c):
                n += 1
                t = int(chunk[i, 0])
                history.append(t)
                yield t
                if detect_stop_tokens(history, stop_sequences):
                    return

    def _get_append(self, Tl, C, Tp, B=1):
        """Teacher-forced cache append for `SPChatSession`: feed Tp given
        tokens (the first `true_len` real) through the decode path one at a
        time, writing each real token's K/V at its round-robin slot
        (owner = step % P at local row Tl + step // P — the same math as
        `_get_decode`), and return the logits at the last real token PLUS
        the greedy successor at every step — which makes the same kernel
        the speculative verify pass (feed [tok]+draft, compare successors
        against the draft, ≡ Generator._verify_fn).  Padded steps
        (i >= true_len) run the forward but mask both the cache write and
        the kp stamp, so the pow2 bucket Tp adds no attendable garbage
        and the compile-shape set stays bounded."""
        key = ("append", B, Tl, C, Tp)
        if key not in self._decode_jit:
            cfg, Pn = self.cfg, self.P

            def body(params, rope, kv, kp, toks_in, true_len, pos, step0):
                d = jax.lax.axis_index("sp")

                def step(carry, i):
                    kv, kp, pos, last = carry
                    tok = jax.lax.dynamic_slice_in_dim(toks_in, i, 1, axis=1)
                    real = i < true_len
                    owner = (step0 + i) % Pn
                    loc = Tl + (step0 + i) // Pn
                    write_on = jnp.logical_and(owner == d, real)
                    kp = jnp.where(
                        write_on,
                        jax.lax.dynamic_update_slice(kp, pos[:, None], (0, loc)),
                        kp,
                    )
                    logits, kv = transformer.forward(
                        cfg, params, tok, pos, kv=kv, rope=rope,
                        sp_axis="sp", sp_meta=(kp, loc, write_on),
                    )
                    last = jnp.where(
                        i == true_len - 1, logits[:, -1].astype(jnp.float32), last
                    )
                    g = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                    pos = pos + real.astype(jnp.int32)
                    return (kv, kp, pos, last), g

                last0 = jnp.zeros((B, cfg.padded_vocab_size), jnp.float32)
                (kv, kp, pos, last), greedy = jax.lax.scan(
                    step, (kv, kp, pos, last0), jnp.arange(Tp, dtype=jnp.int32)
                )
                # every device computed the same replicated logits; psum/P
                # is unnecessary — the forward under shard_map already
                # reduces attention over the ring, so `last`/`greedy` are
                # identical on all devices
                return kv, kp, pos, last, greedy  # greedy: (Tp, B)

            repl = P()
            sm = jax.shard_map(
                body,
                mesh=self.mesh,
                in_specs=(
                    jax.tree_util.tree_map(lambda _: repl, self.params),
                    (repl, repl),
                    self._kv_spec,
                    P(None, "sp"),
                    repl,
                    repl,
                    repl,
                    repl,
                ),
                out_specs=(self._kv_spec, P(None, "sp"), repl, repl, repl),
            )
            self._decode_jit[key] = jax.jit(sm, donate_argnums=(2, 3))
        return self._decode_jit[key]

    def chat_session(self) -> "SPChatSession":
        """A stateful long-context conversation handle with cross-turn
        sequence-sharded KV reuse."""
        return SPChatSession(self)


class SPChatSession:
    """Cross-turn KV reuse over the sp mesh — the long-context variant of
    `generation.ChatSession`.  The first turn (and any window rebuild) runs
    the ring-attention prefill; every later turn APPENDS its tokens to the
    sequence-sharded cache through the round-robin decode path
    (`_get_append`), so turn cost is O(turn length) decode-rate work
    instead of O(conversation) ring prefill — on the 32k-context 8B
    serving shape this is the difference between a sub-second and a
    multi-second turn start.

    State invariant between sends (single sample, B=1): `history` is the
    logical conversation; the cache holds slots for all of it except the
    trailing `_pending` tokens.  Stop-trimmed reply tokens that were
    already fed are rolled back by CLEARING their kp stamps (sp attention
    is kp-masked, so — unlike the single-chip session's absolute-position
    masking — a stale stamped slot WOULD be attendable; the explicit clear
    restores invisibility) and rewinding the step counter, after which the
    next turn's appends rewrite those slots."""

    def __init__(self, gen: SPGenerator):
        self.gen = gen
        self.reset()

    def reset(self) -> None:
        self.history: List[int] = []
        self._kv = None
        self._kp = None
        self._Tl = 0
        self._C = 0
        self._pos = 0    # logical tokens with live cache slots
        self._steps = 0  # decode/append round-robin steps consumed
        self._pending: List[int] = []

    def rollback(self, history: Sequence[int]) -> None:
        """Restore a logical conversation (Ctrl-C contract): the cache is
        rebuilt by one ring prefill on the next send."""
        self.reset()
        self.history = list(history)
        self._pending = list(history)

    @property
    def capacity(self) -> int:
        return self.gen.max_seq_length

    def send(
        self,
        turn: Sequence[int],
        max_new_tokens: int,
        temperature: float = TEMPERATURE,
        top_k: Optional[int] = TOP_K,
        top_p: Optional[float] = None,
        stop_sequences: Sequence[Sequence[int]] = (),
        speculative: Optional[int] = None,
    ) -> Iterator[int]:
        """Stream the stop-filtered reply to `turn`; session state updates
        as the iterator is consumed (exhaust it before the next send)."""
        turn = list(turn)
        max_new = int(max_new_tokens)
        if speculative and temperature != 0.0:
            raise ValueError("speculative chat requires temperature=0")
        if not turn:
            raise ValueError("empty turn")
        if max_new + 1 >= self.gen.max_seq_length:
            raise ValueError("max_new_tokens too large for max_seq_length")
        return self._send(
            turn, max_new, temperature, top_k, top_p, stop_sequences,
            speculative=int(speculative) if speculative else None,
        )

    def _clear_steps(self, kp, first_step: int, n: int):
        """Mark the slots of step indices [first_step, first_step + n)
        empty again (speculative draft rejection and stop-trim rollback).
        Runs as a jitted device-side scatter — this sits on the speculative
        hot path (once per burst with any rejected draft), so a host
        round-trip of the kp array would eat the speedup.  Indices are
        computed host-side and padded to a pow2 bucket (duplicates write
        the same sentinel, so padding by repetition is harmless)."""
        gen = self.gen
        cols = [
            (s % gen.P) * self._C + self._Tl + s // gen.P
            for s in range(first_step, first_step + n)
        ]
        nb = _bucket(len(cols), minimum=4)
        cols = (cols + [cols[0]] * nb)[:nb]
        key = ("clear", nb, self._C)
        if key not in gen._decode_jit:
            C, Pn = self._C, gen.P

            def body(kp_local, idx):
                d = jax.lax.axis_index("sp")
                local = idx - d * C
                ok = jnp.logical_and(local >= 0, local < C)
                li = jnp.clip(local, 0, C - 1)
                vals = jnp.where(ok, POS_SENTINEL, kp_local[0, li])
                return kp_local.at[0, li].set(vals)

            sm = jax.shard_map(
                body, mesh=gen.mesh,
                in_specs=(P(None, "sp"), P()),
                out_specs=P(None, "sp"),
            )
            gen._decode_jit[key] = jax.jit(sm, donate_argnums=(0,))
        return gen._decode_jit[key](kp, jnp.asarray(cols, jnp.int32))

    def _send(self, turn, max_new, temperature, top_k, top_p, stop_sequences,
              speculative=None):
        gen = self.gen
        cap = gen.max_seq_length
        Pn = gen.P
        self.history.extend(turn)
        feed = self._pending + turn
        fresh = self._kv is None
        if not fresh:
            logical_ok = self._pos + len(feed) + max_new + 1 <= cap
            slots_ok = (
                self._steps + _bucket(len(feed)) + max_new
                <= Pn * (self._C - self._Tl)
            )
            if not (logical_ok and slots_ok):
                fresh = True
        sampling = dict(temperature=temperature, top_k=top_k, top_p=top_p)
        if fresh:
            window = self.history[-(cap - max_new - 1):]
            self.history = list(window)
            feed = window
            lens = len(feed)
            # decode/append region sized for the session maximum (cap), so
            # the (Tl, C) compile-shape set stays bounded across rebuilds
            Tl = -(-min(_bucket(lens), cap) // Pn)
            C = Tl + -(-cap // Pn)
            toks_np = np.zeros((1, Tl * Pn), np.int32)
            toks_np[0, :lens] = np.asarray(feed, np.int32)
            kv = gen._init_kv(1, C)
            gen.key, sub = jax.random.split(gen.key)
            kv, kp, tok = gen._get_prefill(1, Tl, C, **sampling)(
                gen.params, gen.rope, jnp.asarray(toks_np),
                jnp.asarray([lens], jnp.int32), kv, sub,
            )
            self._kv, self._kp = kv, kp
            self._Tl, self._C = Tl, C
            self._pos, self._steps = lens, 0
            first = int(np.asarray(tok)[0])  # tok stays the device array
        else:
            L = len(feed)
            Tp = _bucket(L)
            toks_np = np.zeros((1, Tp), np.int32)
            toks_np[0, :L] = np.asarray(feed, np.int32)
            kv, self._kv = self._kv, None  # donated
            kp, self._kp = self._kp, None  # donated
            # _pos/_steps advance host-side below; the returned pos
            # duplicates that bookkeeping
            kv, kp, _pos_out, last, _g = gen._get_append(self._Tl, self._C, Tp)(
                gen.params, gen.rope, kv, kp, jnp.asarray(toks_np),
                jnp.int32(L), jnp.asarray([self._pos], jnp.int32),
                jnp.int32(self._steps),
            )
            self._kv, self._kp = kv, kp
            self._pos += L
            self._steps += L
            gen.key, sub = jax.random.split(gen.key)
            tok = sample(last, sub, **sampling).astype(jnp.int32)
            first = int(np.asarray(tok)[0])
        self._pending = []
        prompt_end = self._pos
        step_base = self._steps

        emitted: List[int] = [first]
        fed_total = [0]

        def spec_stream():
            """Greedy speculative stream: the append kernel doubles as the
            verify pass (feed [tok]+draft, compare its per-step greedy
            successors against the draft).  Rejected draft tokens already
            wrote slots + kp stamps — cleared immediately, and the step/pos
            counters rewind to the accepted prefix, so the contiguous-slot
            invariant the outer reconcile relies on is preserved."""
            nonlocal tok
            K = speculative
            pos = prompt_end
            yield first
            miss_skip = 0
            while len(emitted) < max_new:
                if detect_stop_tokens(emitted, stop_sequences):
                    return
                # slots were budgeted upfront (len(feed) + max_new); drafting
                # additionally needs the K+1-wide append to fit
                slots_left = Pn * (self._C - self._Tl) - (
                    step_base + fed_total[0]
                ) - 1
                draft = []
                if miss_skip == 0 and slots_left >= K + 1:
                    draft = ngram_draft(self.history + emitted, K)
                    if not draft:
                        miss_skip = 4
                if draft:
                    draft = pad_draft(draft, K)
                    L = K + 1
                    Tp = _bucket(L)
                    toks_np = np.zeros((1, Tp), np.int32)
                    toks_np[0, :L] = [int(tok[0])] + draft
                    kv_in, self._kv = self._kv, None  # donated
                    kp_in, self._kp = self._kp, None  # donated
                    kv, kp, _p, _last, g = gen._get_append(
                        self._Tl, self._C, Tp
                    )(
                        gen.params, gen.rope, kv_in, kp_in,
                        jnp.asarray(toks_np), jnp.int32(L),
                        jnp.asarray([pos], jnp.int32),
                        jnp.int32(step_base + fed_total[0]),
                    )
                    self._kv, self._kp = kv, kp
                    burst = accept_draft(draft, np.asarray(g)[:L, 0], K)  # mdi-lint: disable=host-sync -- one read per speculative verify burst
                    a = len(burst) - 1
                    # the append fed all L tokens; only tok + the accepted
                    # a drafts are valid — clear the rejected tail's stamps
                    # and rewind to keep slots contiguous
                    accepted_fed = a + 1
                    if L > accepted_fed:
                        self._kp = self._clear_steps(
                            self._kp,
                            step_base + fed_total[0] + accepted_fed,
                            L - accepted_fed,
                        )
                    fed_total[0] += accepted_fed
                    pos += accepted_fed
                    stopped = False
                    for t in burst[: max_new - len(emitted)]:
                        emitted.append(t)
                        yield t
                        if detect_stop_tokens(emitted, stop_sequences):
                            stopped = True
                            break
                    tok = jnp.asarray([emitted[-1]], jnp.int32)
                    if stopped:
                        return
                else:
                    miss_skip = max(0, miss_skip - 1)
                    decode = gen._get_decode(1, self._Tl, self._C, 1, **sampling)
                    gen.key, sub = jax.random.split(gen.key)
                    kv_in, self._kv = self._kv, None  # donated
                    kp_in, self._kp = self._kp, None  # donated
                    kv, kp, tok_j, _pj, toks = decode(
                        gen.params, gen.rope, kv_in, kp_in,
                        jnp.asarray(tok, jnp.int32),
                        jnp.asarray([pos], jnp.int32),
                        jnp.int32(step_base + fed_total[0]), sub,
                    )
                    self._kv, self._kp = kv, kp
                    tok = tok_j
                    fed_total[0] += 1
                    pos += 1
                    emitted.append(int(np.asarray(toks)[0, 0]))  # mdi-lint: disable=host-sync -- per-token stream fallback between drafts
                    yield emitted[-1]

        def raw_stream():
            nonlocal tok
            pos = jnp.asarray([prompt_end], jnp.int32)
            yield first
            if detect_stop_tokens(emitted, stop_sequences):
                return
            n = 1
            step0 = step_base
            while n < max_new:
                c = min(gen.decode_chunk, max_new - n)
                decode = gen._get_decode(1, self._Tl, self._C, c, **sampling)
                gen.key, sub = jax.random.split(gen.key)
                kv_in, self._kv = self._kv, None  # donated
                kp_in, self._kp = self._kp, None  # donated
                kv, kp, tok, pos, toks = decode(
                    gen.params, gen.rope, kv_in, kp_in, tok, pos,
                    jnp.int32(step0), sub,
                )
                self._kv, self._kp = kv, kp
                step0 += c
                fed_total[0] += c
                chunk = np.asarray(toks)  # mdi-lint: disable=host-sync -- chunk-boundary read: one sync per c ring steps
                for i in range(c):
                    n += 1
                    t = int(chunk[i, 0])
                    emitted.append(t)
                    yield t
                    if detect_stop_tokens(emitted, stop_sequences):
                        return

        reply: List[int] = []
        stream = spec_stream() if speculative else raw_stream()
        for t in stop_filtered_stream(stream, stop_sequences):
            reply.append(t)
            yield t
        # reconcile (see class docstring): fed reply tokens beyond the
        # trimmed reply get their kp stamps cleared so their slots go back
        # to invisible; the final sampled-but-unfed token (or trimmed
        # tail) carries over as pending
        self.history.extend(reply)
        keep = min(len(reply), fed_total[0])
        excess = fed_total[0] - keep
        if excess > 0:
            self._kp = self._clear_steps(self._kp, step_base + keep, excess)
        self._pos = prompt_end + keep
        self._steps = step_base + keep
        self._pending = reply[keep:]
