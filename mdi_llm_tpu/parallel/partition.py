"""Layer→stage partition policy.

TPU-native generalization of the reference's static `N_LAYERS_NODES` table
(`/root/reference/src/sub/config.py:56-98`, JSON twin `sub/split_map.json`):
{n_nodes → {n_layer → starter/secondary layer counts}}, where the starter
(stage 0) gets fewer layers because it also owns the embedding, final norm,
LM head, and sampling.

Here the table is a *policy function* for arbitrary (n_layer, n_stages),
with the reference's hand-tuned entries preserved verbatim as overrides so
existing deployments map 1:1.  Stage parameters are leading-axis slices of
the stacked block pytree (`models.transformer.slice_blocks`) — no renaming
or re-indexing (cf. reference `split_parameters`, utils.py:241-385).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from mdi_llm_tpu.config import Config
from mdi_llm_tpu.models.transformer import Params, slice_blocks

# Reference-parity overrides: {n_stages: {n_layer: [stage0, stage1, ...]}}
# computed from N_LAYERS_NODES's (start, secondary) pairs; the last stage
# absorbs the remainder (reference gives all secondaries the same count and
# relies on exact divisibility; entries below reproduce its counts exactly).
_REFERENCE_TABLE: Dict[int, Dict[int, List[int]]] = {
    1: {n: [n] for n in (5, 7, 9, 12, 22, 24, 32, 36, 48)},
    2: {
        5: [2, 3],
        7: [3, 4],
        9: [4, 5],
        12: [5, 7],
        22: [10, 12],
        24: [10, 14],
        32: [14, 18],
        36: [16, 20],
        48: [22, 26],
    },
    3: {
        5: [1, 2, 2],
        7: [1, 3, 3],
        9: [1, 4, 4],
        12: [2, 5, 5],
        22: [6, 8, 8],
        24: [4, 10, 10],
        32: [8, 12, 12],
        36: [10, 13, 13],
        48: [14, 17, 17],
    },
    4: {22: [4, 6, 6, 6], 32: [5, 9, 9, 9]},
    5: {22: [2, 5, 5, 5, 5], 32: [4, 7, 7, 7, 7]},
}


def stage_layers(
    n_layer: int, n_stages: int, starter_fraction: float = 0.8
) -> List[int]:
    """Number of transformer blocks per pipeline stage.

    Uses the reference's hand-tuned table when it has an entry; otherwise a
    balanced split that discounts stage 0 by `starter_fraction` (stage 0
    also runs embed/head/sampling).  Always sums to `n_layer`, every stage
    gets ≥ 1 layer.
    """
    if n_stages < 1:
        raise ValueError(f"n_stages must be >= 1, got {n_stages}")
    if n_layer < n_stages:
        raise ValueError(
            f"cannot split {n_layer} layers over {n_stages} stages: every "
            f"stage needs >= 1 transformer block — use n_stages <= {n_layer} "
            "(--pipeline-stages) or a deeper model"
        )
    ref = _REFERENCE_TABLE.get(n_stages, {}).get(n_layer)
    if ref is not None:
        counts = list(ref)
    elif n_stages == 1:
        counts = [n_layer]
    else:
        # weighted balanced split: stage 0 weight = starter_fraction, others 1.0
        weights = [starter_fraction] + [1.0] * (n_stages - 1)
        total_w = sum(weights)
        counts = [max(1, int(n_layer * w / total_w)) for w in weights]
        # distribute the remainder to the non-starter stages, last first
        i = n_stages - 1
        while sum(counts) < n_layer:
            counts[i] += 1
            i = n_stages - 1 if i <= 1 else i - 1
        while sum(counts) > n_layer:
            j = max(range(n_stages), key=lambda s: (counts[s], s))
            counts[j] -= 1
    # an empty stage would surface much later as a shape error inside the
    # jitted pipeline step — reject it here with the plan that produced it
    if len(counts) != n_stages or sum(counts) != n_layer or min(counts) < 1:
        raise ValueError(
            f"stage split {counts} is invalid for n_layer={n_layer}, "
            f"n_stages={n_stages}: every stage must own >= 1 layer and the "
            f"counts must sum to n_layer"
        )
    return counts


def stage_bounds(n_layer: int, n_stages: int, **kw) -> List[tuple]:
    """[(start, stop) layer index per stage]."""
    counts = stage_layers(n_layer, n_stages, **kw)
    bounds = []
    acc = 0
    for c in counts:
        bounds.append((acc, acc + c))
        acc += c
    return bounds


def split_params(
    cfg: Config, params: Params, n_stages: int, **kw
) -> List[Params]:
    """Carve a full model pytree into per-stage pytrees.

    Stage 0: embeddings + its block slice + final norm + LM head (≡ reference
    `StarterNode`, submodels.py:132-220); other stages: block slice only
    (≡ `SecondaryNode`).  Pure slicing — weights stay in the stacked layout.

    Raises ValueError (via `stage_layers`) for n_stages > n_layer or any
    plan yielding an empty stage, instead of letting the pipeline step fail
    later with an opaque shape error.
    """
    bounds = stage_bounds(cfg.n_layer, n_stages, **kw)
    stages: List[Params] = []
    for s, (lo, hi) in enumerate(bounds):
        stage: Params = {"blocks": slice_blocks(params["blocks"], lo, hi)}
        if s == 0:
            for k in ("wte", "wpe", "ln_f", "lm_head"):
                if k in params:
                    stage[k] = params[k]
        stages.append(stage)
    return stages


def pad_stage_blocks(stages: List[Params], l_max: int):
    """Zero-pad every stage's block stack to `l_max` layers and stack into
    per-leaf arrays with a leading stage axis (S, l_max, ...).  Zero-weight
    blocks are exact identities (residual adds zero), so no layer mask is
    needed — the uniform shape keeps SPMD pipeline programs single-trace."""

    def pad(leaf):
        leaf = np.asarray(leaf)
        pad_width = [(0, l_max - leaf.shape[0])] + [(0, 0)] * (leaf.ndim - 1)
        return np.pad(leaf, pad_width)

    padded = [jax.tree_util.tree_map(pad, s["blocks"]) for s in stages]
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs, axis=0), *padded)


def unpad_stage_blocks(stage_blocks: Params, counts: Sequence[int]) -> Params:
    """Inverse of `split_params` + `pad_stage_blocks`: drop each stage's zero
    padding and concatenate back into the standard stacked-(L, ...) layout."""

    def unsplit(leaf):
        leaf = np.asarray(leaf)
        return np.concatenate(
            [leaf[s, : counts[s]] for s in range(len(counts))], axis=0
        )

    return jax.tree_util.tree_map(unsplit, stage_blocks)


def save_stage_manifest(
    out_dir, cfg: Config, n_stages: int, quantize: str = "none", **kw
) -> Path:
    """Write `stage_map.json` describing the partition (≡ split_map.json).
    `quantize` records the chunks' storage mode so tooling can tell an int4
    chunk dir from bf16 without relying on directory-name convention."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    manifest = {
        "n_stages": n_stages,
        "n_layer": cfg.n_layer,
        "stage_layers": stage_layers(cfg.n_layer, n_stages, **kw),
        "model": cfg.name,
        "quantize": quantize,
    }
    p = out_dir / "stage_map.json"
    p.write_text(json.dumps(manifest, indent=2) + "\n")
    return p
