"""Recurrent pipeline-parallel generation over a 1-D device mesh.

TPU-native re-design of the reference's distributed inference runtime
(`/root/reference/src/sub/gptserver.py` `_starter_loop`/`_secondary_loop`,
`connections.py` socket ring, `model_dist.py` orchestration):

- The "network of nodes" is a 1-D `pipe` mesh axis; stage s holds its slice
  of transformer blocks (zero-padded to the per-stage max so SPMD stays
  uniform — zero-weight blocks are exact identities thanks to the residual
  structure).
- The TCP/pickle activation hop (`connections.py:325-342`) becomes a single
  `jax.lax.ppermute` inside a jitted step: one (1, n_embd) activation per
  stage boundary per micro-step, the same wire economy the reference gets
  from its rotating KV caches (README.md:239-246).
- "Recurrent pipeline parallelism" (`model_dist.py:56-71`): with S stages
  and a ring of S in-flight samples, every micro-step advances one sample
  per stage; a full rotation (S micro-steps, scanned inside one jit call)
  yields one new token for every in-flight sample.  Samples beyond S run in
  waves over the same cache slots.
- Stage 0 plays the reference starter (submodels.py:132-220): on each
  micro-step it applies final-norm + LM head + sampling to the activation
  returning from the last stage, embeds the sampled token, and feeds it back
  into the ring.  Other stages run blocks only (≡ SecondaryNode).
- The reference's HTTP control plane + host queues collapse into a tiny
  host-side "override" channel: per micro-step the host may replace the
  payload entering stage 0 (used to seed a wave's first tokens after
  prefill; the mechanism also supports mid-flight sample swap).
- Per-sample rotating KV caches (`gptserver.py:751-784`): each stage keeps a
  cache slot per in-flight sample `(L_stage, n_slots, G, seq, hs)`; the slot
  id travels with the activation.  A trailing dummy slot absorbs writes from
  bubble (invalid) payloads.

Correctness is pinned by golden-token tests: pipeline generation must equal
single-device greedy generation token-for-token (SURVEY.md §7).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from mdi_llm_tpu.config import TEMPERATURE, TOP_K, Config
from mdi_llm_tpu.generation import (
    GenerationStats,
    _bucket,
    _run_cache_len,
    detect_stop_tokens,
    find_eot,
)
from mdi_llm_tpu.models import transformer
from mdi_llm_tpu.ops.sampling import sample
from mdi_llm_tpu.utils.context_managers import catch_loop_errors
from mdi_llm_tpu.parallel.mesh import pipeline_mesh
from mdi_llm_tpu.parallel.partition import (
    pad_stage_blocks as _pad_stage_blocks,
    split_params,
    stage_layers,
)


class PipelineEngine:
    """Compile-once pipeline generation driver.

    `params` is a full-model pytree (stacked layers); it is partitioned with
    the same policy table as the reference (`partition.stage_layers`) and
    laid out over `mesh` ("pipe" axis).
    """

    def __init__(
        self,
        cfg: Config,
        params: Any,
        n_stages: Optional[int] = None,
        mesh: Optional[Mesh] = None,
        max_seq_length: Optional[int] = None,
        cache_dtype=None,  # None → params dtype
        rng_seed: int = 1337,
        devices: Optional[Sequence] = None,
        quantize: Optional[str] = None,  # None | "int8" | "w8a8" | "int4"
        samples_per_slot: int = 1,  # M: samples traveling together per ring slot
        rotations_per_call: int = 16,  # steady-state ring rotations per jit call
        tp: int = 1,  # tensor-parallel devices per stage (pipe x tp mesh)
        overlap_chunks: bool = False,  # dispatch the next steady chunk
        # before fetching the previous chunk's emissions, hiding transfer +
        # host bookkeeping under device compute.  Off by default: on the
        # remote-attached (axon-tunnel) backend the overlapped dispatch was
        # observed to stall; enable on directly-attached TPUs
    ):
        from mdi_llm_tpu.ops.quant import FLAG_TO_MODE, quantize_params
        from mdi_llm_tpu.parallel.sharding import validate_tp_divisibility

        if quantize not in (None, "none") and quantize not in FLAG_TO_MODE:
            raise ValueError(f"unknown quantize mode {quantize!r}")
        # derive the effective mesh/tp before quantizing: the stage-block
        # placement below adapts the Megatron specs to the quantized
        # storage layout (sharding.adapt_specs_to_tree) using mesh-derived
        # sizes
        if mesh is None:
            n_dev = len(devices or jax.devices())
            if tp < 1:
                raise ValueError(f"tp={tp} must be a positive device count")
            if n_stages is None and n_dev % tp:
                raise ValueError(
                    f"tp={tp} must divide the {n_dev} available devices "
                    "when n_stages is not given"
                )
            # with explicit n_stages only the first n_stages*tp devices are
            # used; make_mesh's total<=n_dev check covers the rest
            mesh = pipeline_mesh(n_stages or n_dev // tp, devices, tp=tp)
        self.mesh = mesh
        S = int(mesh.shape["pipe"])
        self.n_stages = S
        self.tp = int(mesh.shape.get("tp", 1))
        validate_tp_divisibility(cfg, self.tp)
        if quantize in FLAG_TO_MODE:
            params = quantize_params(params, mode=FLAG_TO_MODE[quantize])
        if cache_dtype is None:
            cache_dtype = transformer.param_dtype(params)
        self.cfg = cfg
        self.max_seq_length = int(min(max_seq_length or cfg.block_size, cfg.block_size))
        self.cache_dtype = cache_dtype
        self.key = jax.random.PRNGKey(rng_seed)

        counts = stage_layers(cfg.n_layer, S)
        self.l_max = max(counts)
        stages = split_params(cfg, params, S)

        pipe_sh = NamedSharding(mesh, P("pipe"))
        repl_sh = NamedSharding(mesh, P())
        blocks_np = _pad_stage_blocks(stages, self.l_max)
        if self.tp > 1:
            # stage axis manual over "pipe"; weight dims additionally laid
            # out under the Megatron specs so GSPMD (tp is an auto axis of
            # the ring shard_map) inserts the all-reduces within each stage.
            # Quantized storage layouts map onto the same specs name-
            # agnostically (leading_axes=1 accounts for the stage axis)
            from mdi_llm_tpu.parallel.sharding import (
                adapt_specs_to_tree,
                param_specs,
            )

            bspecs = adapt_specs_to_tree(
                param_specs(cfg, "tp")["blocks"], blocks_np, leading_axes=1,
                axis_sizes={"tp": self.tp},
            )
            self.stage_blocks = jax.tree_util.tree_map(
                lambda a, s: jax.device_put(
                    a, NamedSharding(mesh, P("pipe", *s))
                ),
                blocks_np,
                bspecs,
            )
        else:
            self.stage_blocks = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, pipe_sh), blocks_np
            )
        # embedding / final norm / head replicated on every stage (vocab
        # sharding over the pipe axis is the planned optimization)
        head_params = {
            k: stages[0][k] for k in ("wte", "wpe", "ln_f", "lm_head") if k in stages[0]
        }
        self.head_params = jax.tree_util.tree_map(
            lambda a: jax.device_put(np.asarray(a), repl_sh), head_params
        )
        rope = transformer.get_rope_cache(cfg)
        self.rope = tuple(jax.device_put(np.asarray(r), repl_sh) for r in rope)

        # M > 1 generalizes the reference's one-sample-per-node economics
        # (README.md:33-37: full utilization needs n_samples >= n_nodes):
        # each ring slot carries M samples batched through the stage's
        # blocks, so full utilization yields S*M concurrent samples and the
        # stage weights are read once per M samples per micro-step.
        self.M = int(samples_per_slot)
        if self.M < 1:
            raise ValueError("samples_per_slot must be >= 1")
        # Steady-state decode batches this many full ring rotations into one
        # jit call (the override scan axis is simply R*S micro-steps long),
        # amortizing host dispatch — critical when the chip sits behind an
        # RPC tunnel, the same economics as Generator's chunk_size.
        self.rotations_per_call = max(1, int(rotations_per_call))
        self.overlap_chunks = bool(overlap_chunks)
        self.n_slots = S + 1  # one cache slot per ring position + dummy
        # Multi-node jobs (cli/starter.py + cli/secondary.py): every process
        # must be able to read the emitted tokens, so the ring all-gathers
        # them in-computation and outputs them replicated.
        self.multiprocess = jax.process_count() > 1
        self._prefill_jit: Dict[Tuple, Any] = {}
        self._decode_jit: Dict[Tuple, Any] = {}
        self._empty_chunk_cache: Dict[int, Any] = {}

    # ------------------------------------------------------------------
    # state builders
    # ------------------------------------------------------------------

    def _init_kv(self, seq_len: Optional[int] = None):
        shape = (
            self.n_stages,
            self.l_max,
            self.n_slots,
            self.M,
            self.cfg.n_query_groups,
            seq_len or self.max_seq_length,
            self.cfg.head_size,
        )
        sh = NamedSharding(
            self.mesh,
            P("pipe", None, None, None, "tp" if self.tp > 1 else None),
        )
        return {
            "k": jax.device_put(jnp.zeros(shape, self.cache_dtype), sh),
            "v": jax.device_put(jnp.zeros(shape, self.cache_dtype), sh),
        }

    def _init_payload(self, T: int, dtype):
        sh = NamedSharding(self.mesh, P("pipe"))
        S, M = self.n_stages, self.M
        return {
            "x": jax.device_put(jnp.zeros((S, M, T, self.cfg.n_embd), dtype), sh),
            "sid": jax.device_put(jnp.full((S, 1), self.n_slots - 1, jnp.int32), sh),
            "pos": jax.device_put(jnp.zeros((S, M), jnp.int32), sh),
            "valid": jax.device_put(jnp.zeros((S, M), jnp.int32), sh),
        }

    # ------------------------------------------------------------------
    # per-stage block execution (local view inside shard_map)
    # ------------------------------------------------------------------

    def _run_stage_blocks(self, blocks, rope, kv_k, kv_v, x, sid, input_pos):
        """Run the local (padded) block stack on x (M, T, D) — the M samples
        sharing ring slot `sid` (scalar) — with per-sample cache offsets
        `input_pos` (M,).  kv_k/kv_v are the stage's full cache
        (l_max, n_slots, M, G, seq, hs); returns (x_out, kv_k, kv_v)."""
        cfg = self.cfg
        T = x.shape[1]
        pos = input_pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]  # (M, T)
        cos = jnp.take(rope[0], pos, axis=0)
        sin = jnp.take(rope[1], pos, axis=0)
        k_slot = jax.lax.dynamic_slice_in_dim(kv_k, sid, 1, axis=1)[:, 0]
        v_slot = jax.lax.dynamic_slice_in_dim(kv_v, sid, 1, axis=1)[:, 0]
        x_out, kv_new = transformer.run_blocks(
            cfg, blocks, x, pos, cos, sin, {"k": k_slot, "v": v_slot}, input_pos
        )
        kv_k = jax.lax.dynamic_update_slice_in_dim(
            kv_k, kv_new["k"][:, None], sid, axis=1
        )
        kv_v = jax.lax.dynamic_update_slice_in_dim(
            kv_v, kv_new["v"][:, None], sid, axis=1
        )
        return x_out, kv_k, kv_v

    # ------------------------------------------------------------------
    # jitted phases
    # ------------------------------------------------------------------

    def _get_prefill(self, W: int, T: int, temperature, top_k, top_p):
        key = (W, T, temperature, top_k, top_p)
        if key not in self._prefill_jit:
            self._prefill_jit[key] = self._build_prefill(W, T, temperature, top_k, top_p)
        return self._prefill_jit[key]

    def _get_decode(self, temperature, top_k, top_p):
        key = (temperature, top_k, top_p)
        if key not in self._decode_jit:
            self._decode_jit[key] = self._build_decode(temperature, top_k, top_p)
        return self._decode_jit[key]

    def _build_prefill(self, W: int, T: int, temperature, top_k, top_p):
        """W = number of slot groups (each carrying M samples)."""
        cfg, S, M, mesh = self.cfg, self.n_stages, self.M, self.mesh
        n_steps = W + S
        dummy = self.n_slots - 1

        def ring(blocks, head, rope, kv, payload, prompts, lens, gvalid, slot_ids, key):
            stage = jax.lax.axis_index("pipe")
            perm = [(i, (i + 1) % S) for i in range(S)]
            # strip the local stage axis (size 1) from the sharded operands
            blocks = jax.tree_util.tree_map(lambda a: a[0], blocks)

            def body(carry, step):
                kv_k, kv_v, x, sid, pos, valid, key = carry
                sid0, pos0, val0 = sid[0], pos, valid  # (), (M,), (M,)

                # ---- stage 0: head + first-token sample on the returning
                # activations (gather each sample's last valid position) ----
                idx = jnp.clip(pos0 - 1, 0, T - 1)  # (M,)
                x_ret = jnp.take_along_axis(x, idx[:, None, None], axis=1)
                logits = transformer.head(cfg, head, x_ret)[:, 0]  # (M, V)
                key, sub = jax.random.split(key)
                tok = sample(
                    logits, sub, temperature=temperature, top_k=top_k, top_p=top_p
                ).astype(jnp.int32)  # (M,)
                emit = (tok, sid0.reshape(1), val0)

                # ---- stage 0: inject prompt group `step` into the ring ----
                inj_valid = (step < W).astype(jnp.int32)
                inj_idx = jnp.minimum(step, W - 1)
                inj_tokens = jax.lax.dynamic_slice_in_dim(
                    prompts, inj_idx, 1, axis=0
                )[0]  # (M, T)
                pos_grid = jnp.broadcast_to(
                    jnp.arange(T, dtype=jnp.int32)[None, :], (M, T)
                )
                emb = transformer.embed(cfg, head, inj_tokens, pos_grid)  # (M,T,D)
                g_lens = jax.lax.dynamic_slice_in_dim(lens, inj_idx, 1, axis=0)[0]
                g_val = jax.lax.dynamic_slice_in_dim(gvalid, inj_idx, 1, axis=0)[0]
                g_slot = jax.lax.dynamic_slice_in_dim(slot_ids, inj_idx, 1, axis=0)[0]

                is0 = stage == 0
                x_proc = jnp.where(is0, emb.astype(x.dtype), x)
                sid_proc = jnp.where(
                    is0, jnp.where(inj_valid == 1, g_slot, dummy), sid0
                )
                pos_proc = jnp.where(is0, g_lens, pos0)
                val_proc = jnp.where(is0, g_val * inj_valid, val0)

                x_out, kv_k, kv_v = self._run_stage_blocks(
                    blocks, rope, kv_k, kv_v, x_proc, sid_proc,
                    jnp.zeros((M,), jnp.int32),
                )
                x_n = jax.lax.ppermute(x_out, "pipe", perm)
                sid_n = jax.lax.ppermute(sid_proc.reshape(1), "pipe", perm)
                pos_n = jax.lax.ppermute(pos_proc, "pipe", perm)
                val_n = jax.lax.ppermute(val_proc, "pipe", perm)
                return (kv_k, kv_v, x_n, sid_n, pos_n, val_n, key), emit

            carry = (
                kv["k"][0],
                kv["v"][0],
                payload["x"][0],
                payload["sid"][0],
                payload["pos"][0],
                payload["valid"][0],
                key,
            )
            carry, emits = jax.lax.scan(
                body, carry, jnp.arange(n_steps, dtype=jnp.int32)
            )
            kv_out = {"k": carry[0][None], "v": carry[1][None]}
            if self.multiprocess:
                emits = jax.tree_util.tree_map(
                    lambda e: jax.lax.all_gather(e, "pipe", axis=1, tiled=True),
                    emits,
                )
            return kv_out, emits

        pipe, repl = P("pipe"), P()
        emit_spec = repl if self.multiprocess else P(None, "pipe")
        sm = jax.shard_map(
            ring,
            mesh=mesh,
            in_specs=(
                jax.tree_util.tree_map(lambda _: pipe, self.stage_blocks),
                jax.tree_util.tree_map(lambda _: repl, self.head_params),
                (repl, repl),
                {"k": pipe, "v": pipe},
                {"x": pipe, "sid": pipe, "pos": pipe, "valid": pipe},
                repl,
                repl,
                repl,
                repl,
                repl,
            ),
            out_specs=(
                {"k": pipe, "v": pipe},
                (emit_spec, emit_spec, emit_spec),
            ),
            # manual over the stage ring only; a "tp" mesh axis (if any)
            # stays automatic so GSPMD lays the per-stage matmuls out under
            # the Megatron weight shardings
            axis_names={"pipe"},
            check_vma=not self.multiprocess and self.tp == 1,
        )
        # donate the KV buffers only: the injection payload is consumed but
        # not among the outputs, so donating it just trips XLA's
        # unusable-donation warning
        return jax.jit(sm, donate_argnums=(3,))

    def _build_decode(self, temperature, top_k, top_p):
        cfg, S, mesh = self.cfg, self.n_stages, self.mesh

        def ring(blocks, head, rope, kv, payload, overrides, key):
            stage = jax.lax.axis_index("pipe")
            perm = [(i, (i + 1) % S) for i in range(S)]
            blocks = jax.tree_util.tree_map(lambda a: a[0], blocks)

            def body(carry, ov):
                kv_k, kv_v, x, sid, pos, valid, key = carry
                sid0, pos0, val0 = sid[0], pos, valid  # (), (M,), (M,)

                # stage 0: head + sample on the returning activations (T=1)
                logits = transformer.head(cfg, head, x)[:, -1]  # (M, V)
                key, sub = jax.random.split(key)
                tok = sample(
                    logits, sub, temperature=temperature, top_k=top_k, top_p=top_p
                ).astype(jnp.int32)  # (M,)
                emit = (tok, sid0.reshape(1), val0)

                # per-sample override lanes (seed a slot after prefill, or
                # feed the next queued prompt's tokens into a freed lane)
                use_ov = ov["flag"] == 1  # (M,)
                tok_sel = jnp.where(use_ov, ov["tok"], tok)
                pos_sel = jnp.where(use_ov, ov["pos"], pos0 + 1)
                val_sel = jnp.where(use_ov, ov["val"], val0)
                sid_sel = jnp.where(jnp.any(use_ov), ov["sid"], sid0)

                emb = transformer.embed(
                    cfg, head, tok_sel[:, None], pos_sel[:, None]
                )  # (M, 1, D)

                is0 = stage == 0
                x_proc = jnp.where(is0, emb.astype(x.dtype), x)
                sid_proc = jnp.where(is0, sid_sel, sid0)
                pos_proc = jnp.where(is0, pos_sel, pos0)
                val_proc = jnp.where(is0, val_sel, val0)

                x_out, kv_k, kv_v = self._run_stage_blocks(
                    blocks, rope, kv_k, kv_v, x_proc, sid_proc, pos_proc
                )
                x_n = jax.lax.ppermute(x_out, "pipe", perm)
                sid_n = jax.lax.ppermute(sid_proc.reshape(1), "pipe", perm)
                pos_n = jax.lax.ppermute(pos_proc, "pipe", perm)
                val_n = jax.lax.ppermute(val_proc, "pipe", perm)
                return (kv_k, kv_v, x_n, sid_n, pos_n, val_n, key), emit

            carry = (
                kv["k"][0],
                kv["v"][0],
                payload["x"][0],
                payload["sid"][0],
                payload["pos"][0],
                payload["valid"][0],
                key,
            )
            carry, emits = jax.lax.scan(body, carry, overrides)
            kv_out = {"k": carry[0][None], "v": carry[1][None]}
            payload_out = {
                "x": carry[2][None],
                "sid": carry[3][None],
                "pos": carry[4][None],
                "valid": carry[5][None],
            }
            if self.multiprocess:
                emits = jax.tree_util.tree_map(
                    lambda e: jax.lax.all_gather(e, "pipe", axis=1, tiled=True),
                    emits,
                )
            return kv_out, payload_out, emits

        pipe, repl = P("pipe"), P()
        emit_spec = repl if self.multiprocess else P(None, "pipe")
        sm = jax.shard_map(
            ring,
            mesh=mesh,
            in_specs=(
                jax.tree_util.tree_map(lambda _: pipe, self.stage_blocks),
                jax.tree_util.tree_map(lambda _: repl, self.head_params),
                (repl, repl),
                {"k": pipe, "v": pipe},
                {"x": pipe, "sid": pipe, "pos": pipe, "valid": pipe},
                repl,
                repl,
            ),
            out_specs=(
                {"k": pipe, "v": pipe},
                {"x": pipe, "sid": pipe, "pos": pipe, "valid": pipe},
                (emit_spec, emit_spec, emit_spec),
            ),
            axis_names={"pipe"},
            check_vma=not self.multiprocess and self.tp == 1,
        )
        return jax.jit(sm, donate_argnums=(3, 4))

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: int,
        temperature: float = TEMPERATURE,
        top_k: Optional[int] = TOP_K,
        top_p: Optional[float] = None,
        stop_sequences: Sequence[Sequence[int]] = (),
        stream_cb=None,
    ) -> Tuple[List[List[int]], GenerationStats]:
        """Generate continuations for n_samples prompts using recurrent
        pipeline parallelism with continuous sample scheduling.

        `stream_cb(sample_idx, token)` is invoked per generated token as its
        emission is collected from the ring (≡ the reference starter
        surfacing tokens as they arrive, gptserver.py:904-956).  Tokens
        stream in collection order — per chunk of ring rotations in the
        steady state, so latency-sensitive callers should lower
        `rotations_per_call`.  Like the Generator's callback, tokens past a
        sample's stop sequence are not streamed, but the final
        stop-sequence tokens themselves are (the returned lists are
        truncated; streaming consumers buffer-and-trim, see cli/chat.py).

        The first n_stages × samples_per_slot prompts are prefilled in
        parallel and seeded onto the ring; whenever an in-flight sample
        finishes (stop sequence or token budget), its lane is refilled with
        the next queued prompt — the ring never idles while work remains,
        reproducing the reference's round-robin sample scheduling
        (`gptserver.py:912-1001`, README.md:33-37).  Fully-freed slots are
        refilled by a pipelined parallel prefill call (refill latency is
        generation-bound, not prompt-length-bound); only a free lane of a
        partially-busy slot (samples_per_slot > 1) falls back to feeding its
        prompt one token per rotation through the override channel."""
        stats = GenerationStats()
        t_all = time.perf_counter()
        if not prompts:
            return [], stats
        results = self._generate_continuous(
            list(prompts), max_new_tokens, temperature, top_k, top_p,
            stop_sequences, stats, t_all, stream_cb,
        )
        stats.decode_s = time.perf_counter() - t_all - stats.prefill_s
        stats.tokens_generated = sum(
            len(o) - len(p) for o, p in zip(results, prompts)
        )
        return results, stats

    def _stage0_emits(self, emits):
        """Host view of one call's emissions: stage 0's tokens (R, M),
        slot ids (R,), valid flags (R, M).  One batched device_get — on a
        remote-attached chip each separate host transfer costs a full RTT
        (~40 ms measured), while one fetch of all three arrays is free."""
        # mdi-lint: disable-next-line=host-sync -- the ONE intended sync per chunk: all three emission arrays in a single batched fetch (one RTT)
        toks, sids, vals = jax.device_get(emits)
        return toks[:, : self.M], sids[:, 0], vals[:, : self.M]

    def _empty_overrides(self):
        S, M = self.n_stages, self.M
        return {
            "flag": np.zeros((S, M), np.int32),
            "sid": np.full((S,), self.n_slots - 1, np.int32),
            "tok": np.zeros((S, M), np.int32),
            "pos": np.zeros((S, M), np.int32),
            "val": np.zeros((S, M), np.int32),
        }

    def _empty_chunk_dev(self, n_rot: int):
        """Device-resident empty overrides covering n_rot full rotations
        (the decode ring scans the override leading axis, so R rotations is
        just an R*S-long micro-step axis); uploaded once per R."""
        if n_rot not in self._empty_chunk_cache:
            ov = self._empty_overrides()
            self._empty_chunk_cache[n_rot] = {
                k: jnp.asarray(np.concatenate([v] * n_rot, axis=0))
                for k, v in ov.items()
            }
        return self._empty_chunk_cache[n_rot]

    def _generate_continuous(
        self, prompts, max_new_tokens, temperature, top_k, top_p,
        stop_sequences, stats, t_all, stream_cb=None,
    ):
        S, M = self.n_stages, self.M
        N = len(prompts)
        lens = [len(p) for p in prompts]
        if min(lens) < 1:
            raise ValueError("empty prompt")
        if max(lens) + max_new_tokens > self.max_seq_length:
            raise ValueError(
                f"prompt+generation length {max(lens) + max_new_tokens} exceeds "
                f"max_seq_length {self.max_seq_length}"
            )

        # ---- initial batch: first S*M samples, packed into groups of M ----
        n_init = min(N, S * M)
        n_groups = -(-n_init // M)
        Tb = min(_bucket(max(lens[:n_init])), self.max_seq_length)
        prompts_np = np.zeros((n_groups, M, Tb), np.int32)
        lens_np = np.ones((n_groups, M), np.int32)
        valid_np = np.zeros((n_groups, M), np.int32)
        for i in range(n_init):
            g, m = divmod(i, M)
            prompts_np[g, m, : lens[i]] = np.asarray(prompts[i], np.int32)
            lens_np[g, m] = lens[i]
            valid_np[g, m] = 1

        # cache sized to this run (every ring micro-step reads whole cache
        # slots, so shorter buffers directly cut HBM traffic); must cover
        # both the generation horizon and any prompt bucket width (initial
        # or batch-refill)
        cache_len = _run_cache_len(
            self.max_seq_length,
            max(lens) + max_new_tokens,
            min(_bucket(max(lens)), self.max_seq_length),
        )
        kv = self._init_kv(cache_len)
        dtype = transformer.param_dtype(self.stage_blocks)

        out = [list(p) for p in prompts]
        done = [False] * N

        def accept(j, tok):
            """Append one generated token and surface it to the stream."""
            out[j].append(tok)
            if stream_cb is not None:
                stream_cb(j, tok)

        def budget(j):
            """Remaining tokens sample j may still emit."""
            gen = len(out[j]) - lens[j]
            return min(max_new_tokens - gen, self.max_seq_length - len(out[j]))

        def run_prefill(p_np, l_np, v_np, slots_np):
            """One pipelined-prefill call: process whole prompt groups at
            once and return {sample_lane: first_token} keyed by (slot, m)."""
            nonlocal kv
            t_p = time.perf_counter()
            W, _, T = p_np.shape
            prefill = self._get_prefill(W, T, temperature, top_k, top_p)
            self.key, sub = jax.random.split(self.key)
            kv, emits = prefill(
                self.stage_blocks,
                self.head_params,
                self.rope,
                kv,
                self._init_payload(T, dtype),
                jnp.asarray(p_np),
                jnp.asarray(l_np),
                jnp.asarray(v_np),
                jnp.asarray(slots_np),
                sub,
            )
            toks_e, sids_e, vals_e = self._stage0_emits(emits)
            firsts = {}
            slot_set = set(int(s) for s in slots_np)
            for t_row, s, v_row in zip(toks_e, sids_e, vals_e):
                s = int(s)
                if s in slot_set:
                    for m in range(M):
                        if v_row[m]:
                            firsts[(s, m)] = int(t_row[m])
            stats.prefill_s += time.perf_counter() - t_p
            return firsts

        # ---- phase 1: pipelined parallel prefill of the initial batch ----
        firsts = run_prefill(
            prompts_np, lens_np, valid_np, np.arange(n_groups, dtype=np.int32)
        )
        assert len(firsts) == n_init, f"prefill returned {len(firsts)}/{n_init}"

        # scheduler state
        queue = list(range(n_init, N))  # samples not yet on the ring
        active: Dict[Tuple[int, int], int] = {}  # lane -> generating sample
        filling: Dict[Tuple[int, int], List[int]] = {}  # lane -> [sample, next_idx]
        # emissions arriving in call r were fed in call r-1: lane -> sample
        fed_prev: Dict[Tuple[int, int], int] = {}
        fed_cur: Dict[Tuple[int, int], int] = {}

        for (g, m), tok in firsts.items():
            j = g * M + m
            accept(j, tok)
            if detect_stop_tokens(out[j][lens[j] :], stop_sequences) or budget(j) <= 0:
                done[j] = True
            else:
                active[(g, m)] = j

        decode = self._get_decode(temperature, top_k, top_p)
        payload = None  # built by the first re-seed
        # empty overrides are constant: upload once, reuse when nothing fills
        empty_dev = self._empty_chunk_dev(1)

        def batch_refills():
            """Parallel-prefill queued prompts into fully-free slots (whole
            slots only: a prefill rewrites all M cache lanes of its slot).
            Returns True if the ring must be re-seeded."""
            busy_slots = {g for (g, m) in (*active, *filling)}
            free = [g for g in range(S) if g not in busy_slots]
            if not queue or not free:
                return False
            K = min(len(free), -(-len(queue) // M))
            take = queue[: K * M]
            del queue[: K * M]
            Tb2 = min(_bucket(max(lens[j] for j in take)), self.max_seq_length)
            # pad the group count to a power of two so refill prefills hit a
            # bounded set of compiled shapes; padded groups are all-invalid
            # and write only the dummy cache slot
            Kp = 1 << (K - 1).bit_length()
            p_np = np.zeros((Kp, M, Tb2), np.int32)
            l_np = np.ones((Kp, M), np.int32)
            v_np = np.zeros((Kp, M), np.int32)
            slots_np = np.full((Kp,), self.n_slots - 1, np.int32)
            slots_np[:K] = free[:K]
            lane_of = {}
            for i, j in enumerate(take):
                k_, m = divmod(i, M)
                p_np[k_, m, : lens[j]] = np.asarray(prompts[j], np.int32)
                l_np[k_, m] = lens[j]
                v_np[k_, m] = 1
                lane_of[(free[k_], m)] = j
            firsts = run_prefill(p_np, l_np, v_np, slots_np)
            assert len(firsts) == len(take), (
                f"refill prefill returned {len(firsts)}/{len(take)}"
            )
            for lane, tok in firsts.items():
                j = lane_of[lane]
                accept(j, tok)
                if (
                    detect_stop_tokens(out[j][lens[j] :], stop_sequences)
                    or budget(j) <= 0
                ):
                    done[j] = True
                else:
                    active[lane] = j
            return True

        def schedule_token_refills():
            """Assign queued samples to free lanes of partially-busy slots;
            their prompts are fed one token per rotation (fully-free slots
            are handled by batch_refills)."""
            if not queue:
                return
            busy = set(active) | set(filling)
            for g in range(S):
                n_busy = sum((g, m) in busy for m in range(M))
                if n_busy == 0 or n_busy == M:
                    continue
                for m in range(M):
                    if not queue:
                        return
                    if (g, m) not in busy:
                        filling[(g, m)] = [queue.pop(0), 0]
                        stats.token_fills += 1

        def build_reseed_ov():
            """After a prefill pause the ring payload is discarded; re-feed
            every surviving lane's last token (KV rewrite is idempotent —
            same values at the same positions) plus the refilled lanes'
            first tokens, all in one seeding rotation."""
            ov = self._empty_overrides()
            fed = {}
            for (g, m), j in active.items():
                ov["flag"][g, m] = 1
                ov["sid"][g] = g
                ov["tok"][g, m] = out[j][-1]
                ov["pos"][g, m] = len(out[j]) - 1
                ov["val"][g, m] = 1
                fed[(g, m)] = j
            for (g, m), st in filling.items():
                j, idx = st
                ov["flag"][g, m] = 1
                ov["sid"][g] = g
                fed[(g, m)] = j
                if idx == 0:
                    # nothing fed yet: feed the first prompt token now
                    ov["tok"][g, m] = prompts[j][0]
                    ov["pos"][g, m] = 0
                    ov["val"][g, m] = 1 if lens[j] == 1 else 0
                    st[1] = 1
                else:
                    # re-feed the (possibly mid-ring) last prompt token
                    ov["tok"][g, m] = prompts[j][idx - 1]
                    ov["pos"][g, m] = idx - 1
                    ov["val"][g, m] = 0
            return {k: jnp.asarray(v) for k, v in ov.items()}, fed

        def build_step_ov():
            """Feed one prompt token per filling lane this rotation."""
            fed = dict(active)
            if not filling:
                return empty_dev, fed
            ov = self._empty_overrides()
            for (g, m), st in filling.items():
                j, idx = st
                ov["flag"][g, m] = 1
                ov["sid"][g] = g
                ov["tok"][g, m] = prompts[j][idx]
                ov["pos"][g, m] = idx
                ov["val"][g, m] = 1 if idx == lens[j] - 1 else 0
                fed[(g, m)] = j
                st[1] = idx + 1
            return ov, fed

        def collect(emits, fed_map):
            """Accept one call's emissions into `out` (tokens fed one
            rotation before each emission row, per fed_map)."""
            toks_e, sids_e, vals_e = self._stage0_emits(emits)
            for t_row, s, v_row in zip(toks_e, sids_e, vals_e):
                s = int(s)
                for m in range(M):
                    j = fed_map.get((s, m))
                    if j is None or not v_row[m] or done[j]:
                        continue
                    accept(j, int(t_row[m]))
                    if (
                        detect_stop_tokens(out[j][lens[j] :], stop_sequences)
                        or budget(j) <= 0
                    ):
                        done[j] = True
                        active.pop((s, m), None)
            if fed_map:
                stats.tok_time.append(
                    (
                        sum(len(o) - l for o, l in zip(out, lens)),
                        time.perf_counter() - t_all,
                    )
                )
            # a lane whose last prompt token was just fed switches to
            # generating (auto-feed inside the jit)
            for lane in list(filling):
                j, idx = filling[lane]
                if idx >= lens[j]:
                    del filling[lane]
                    active[lane] = j

        # Double buffering: in the steady state the next chunk is dispatched
        # BEFORE the previous chunk's emissions are fetched, so the
        # device-to-host transfer and the host bookkeeping hide under the
        # next chunk's compute (on a remote-attached chip the serialized
        # fetch alone costs a large fraction of the chunk).  `pending` holds
        # the in-flight chunk's (emits, fed_map); refill/reseed boundaries
        # flush it first so scheduling always sees accepted tokens.
        pending = None

        def flush_pending():
            nonlocal pending
            if pending is not None:
                em, fm = pending
                pending = None
                collect(em, fm)

        need_reseed = True  # initial seeding uses the same re-seed path
        # hard bound on rotations (scheduler-bug backstop: every sample costs
        # at most lens + max_new_tokens rotations, plus seeding and drain,
        # plus chunk-overshoot slack: one chunk per mid-chunk finish and one
        # in-flight chunk of lookahead)
        max_rot = (
            2 + 2 * S + N + sum(l + max_new_tokens for l in lens)
            + (N + 2) * self.rotations_per_call
        )
        # Ctrl-C mid-ring returns partial results (single-process; in a
        # multi-process job an interrupt tears down the whole SPMD group)
        with catch_loop_errors() as guard:
            while active or filling or queue or pending:
                if stats.rotations >= max_rot:
                    raise RuntimeError(
                        f"pipeline scheduler exceeded {max_rot} rotations with "
                        f"{len(active)} active / {len(filling)} filling / "
                        f"{len(queue)} queued samples"
                    )
                if queue:
                    # refill decisions need current lane state, and a refill
                    # prefill would block on the in-flight chunk inside its
                    # own timer anyway — flush first (no overlap lost: the
                    # device serializes the prefill behind the chunk)
                    flush_pending()
                if batch_refills():
                    need_reseed = True
                schedule_token_refills()
                if not (active or filling):
                    flush_pending()
                    continue  # everything finished; the while condition
                    # re-checks the queue (refills strictly drain it)
                n_rot = 1
                steady = not (need_reseed or filling)
                if not steady:
                    # boundary iteration: overrides are built from accepted
                    # state, so the in-flight chunk (whose tokens are valid
                    # continuations) must land first
                    flush_pending()
                    if need_reseed:
                        fed_prev = {}
                        payload = self._init_payload(1, dtype)
                        ov_dev, fed_cur = build_reseed_ov()
                        need_reseed = False
                    else:
                        fed_prev = fed_cur
                        ov, fed_cur = build_step_ov()
                        ov_dev = (
                            ov if ov is empty_dev
                            else {k: jnp.asarray(v) for k, v in ov.items()}
                        )
                else:
                    # steady state (no refills pending): every surviving lane
                    # auto-feeds its own sampled token inside the jit, so R
                    # rotations can run in one dispatch with empty overrides.
                    # The lane->sample map is constant across the chunk; a
                    # sample finishing mid-chunk just has its surplus tokens
                    # discarded (same tradeoff as Generator chunk_size).
                    # Bounded by the largest remaining budget (stale by at
                    # most the in-flight chunk — surplus writes clamp into
                    # finished lanes' own cache slots), floored to a power of
                    # two so the set of compiled scan lengths stays small.
                    maxbud = max(budget(j) for j in active.values())
                    n_rot = max(1, min(self.rotations_per_call, maxbud))
                    n_rot = 1 << (n_rot.bit_length() - 1)
                    fed_prev = {**fed_cur, **dict(active)}
                    fed_cur = fed_prev
                    ov_dev = self._empty_chunk_dev(n_rot)
                    if not self.overlap_chunks:
                        flush_pending()
                self.key, sub = jax.random.split(self.key)
                kv, payload, emits = decode(
                    self.stage_blocks, self.head_params, self.rope,
                    kv, payload, ov_dev, sub,
                )
                stats.rotations += n_rot
                if steady and self.overlap_chunks:
                    flush_pending()  # previous chunk, hidden under this one
                    pending = (emits, fed_prev)
                else:
                    collect(emits, fed_prev)

        stats.interrupted = stats.interrupted or guard.interrupted
        trimmed = []
        for o, l in zip(out, lens):
            gen = o[l:]
            cut = find_eot(gen, stop_sequences)
            trimmed.append(o[: l + cut])
        return trimmed
