"""Serving roofline accounting: analytic FLOPs/bytes per token, device
peaks, and achieved MFU/MBU.

Training has had an MFU number since the first bench round
(`training.estimate_flops_per_token` + a peak constant); serving rows
reported bare tokens/s — a number that cannot be compared across chips
or against the Ragged Paged Attention paper's roofline-stated wins.
This module is the inference complement:

- analytic per-token decode/prefill FLOPs from `Config` (forward-only:
  the 2·params matmul term minus the gather-only embedding, plus the
  4·L·H·hs·S attention term — exactly one third of the training
  estimate's 6N + 12·L·H·hs·T split);
- analytic HBM bytes per decode token, `kv_dtype`/`block_bytes`-aware:
  the int8 paged pool gets credit for its smaller blocks (scale side
  arrays included) because the byte model routes through
  `ServingConfig.block_bytes` — THE per-block formula the engine
  allocates by, so the roofline can never disagree with the audit;
- a device-peak table keyed on `jax.Device.device_kind`
  (v4/v5e/v5p/v6e; unknown kinds — CPU, GPU, new TPUs — map to None and
  every derived utilization reports null rather than a lie);
- achieved MFU/MBU from measured tokens/s.

The analytic FLOPs model is cross-checked against the XLA compiler's own
`cost_analysis` (`obs/device.py`) within `XLA_AGREEMENT_RTOL` — pinned
by tests/test_roofline.py on the CPU backend, so the hand model can
never silently rot away from what the executables actually compute.

Peak sources (public spec sheets; dense bf16, no sparsity):
v4 275 TFLOP/s / 1228 GB/s · v5e 197 / 819 · v5p 459 / 2765 ·
v6e (Trillium) 918 / 1640.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from mdi_llm_tpu.config import Config, ServingConfig, dtype_bytes

__all__ = [
    "DEVICE_PEAKS",
    "DEVICE_VMEM_BYTES",
    "XLA_AGREEMENT_RTOL",
    "normalize_device_kind",
    "device_peaks",
    "device_vmem_bytes",
    "decode_flops_per_token",
    "prefill_flops_per_token",
    "decode_hbm_bytes_per_token",
    "param_bytes",
    "serving_roofline",
    "crosscheck_flops",
]

# Dense bf16 peak compute and HBM bandwidth per chip, by TPU generation.
# bench's training MFU and the serving MFU/MBU derivation both read THIS
# table (the pre-PR-10 train row hardcoded the v5e number whatever chip
# actually ran).
DEVICE_PEAKS: Dict[str, Dict[str, float]] = {
    "v4": {"bf16_tflops": 275.0, "hbm_gbps": 1228.0},
    "v5e": {"bf16_tflops": 197.0, "hbm_gbps": 819.0},
    "v5p": {"bf16_tflops": 459.0, "hbm_gbps": 2765.0},
    "v6e": {"bf16_tflops": 918.0, "hbm_gbps": 1640.0},
}

# The train row's historical reference chip: when the device kind is
# unknown (CPU fallback, new hardware) bench still reports an MFU against
# this peak, clearly labelled "assumed" — a comparable number beats null
# for the flagship training row, while SERVING utilization stays null on
# unknown kinds (it is a hardware claim, not a trend line).
ASSUMED_TRAIN_PEAK_KIND = "v5e"

# Analytic-vs-XLA agreement bound for the FLOPs model (crosscheck_flops,
# pinned by tests/test_roofline.py).  The analytic model counts matmul +
# attention terms only; the compiled program adds norms/rope/softmax/
# sampling and subtracts whatever fusion/DCE eliminates — measured gap on
# the CPU backend is ~10-15%, pinned at 25% so a real model drift (a
# forgotten projection, a doubled attention term) fails loudly.
XLA_AGREEMENT_RTOL = 0.25


# Per-core VMEM budgets by TPU generation, for the ragged paged-attention
# kernel's tuning-table validation (ops/tuning.py, mdi-audit's
# bad-kernel-tuning): a tuning entry whose scratch estimate exceeds THIS
# refuses before any compile.  Every current generation ships ~16 MiB of
# VMEM per core; unknown kinds use the table minimum — conservative,
# never a guess.
DEVICE_VMEM_BYTES: Dict[str, int] = {
    "v4": 16 * (1 << 20),
    "v5e": 16 * (1 << 20),
    "v5p": 16 * (1 << 20),
    "v6e": 16 * (1 << 20),
}


def normalize_device_kind(device_kind: Optional[str]) -> Optional[str]:
    """Map a `jax.Device.device_kind` string to its canonical generation
    key (the DEVICE_PEAKS / DEVICE_VMEM_BYTES / tuning-table key), or None
    for kinds the tables do not know (CPU, GPU, future TPUs)."""
    if not device_kind:
        return None
    kind = str(device_kind).lower()
    if "v6" in kind:  # "TPU v6 lite" / "TPU v6e" — only the e variant exists
        return "v6e"
    if "v5p" in kind:
        return "v5p"
    if "v5e" in kind or "v5 lite" in kind or "v5lite" in kind:
        return "v5e"
    if "v5" in kind:  # bare "TPU v5" is how v5p reports itself
        return "v5p"
    if "v4" in kind:
        return "v4"
    return None


def device_peaks(device_kind: Optional[str]) -> Optional[Dict[str, float]]:
    """Map a `jax.Device.device_kind` string to its peak row, or None for
    kinds the table does not know (CPU, GPU, future TPUs) — callers must
    treat None as "report null utilization", never assume a chip."""
    norm = normalize_device_kind(device_kind)
    return DEVICE_PEAKS[norm] if norm else None


def device_vmem_bytes(device_kind: Optional[str] = None) -> int:
    """The per-core VMEM budget for `device_kind`; unknown/None kinds get
    the table minimum.  Unlike `device_peaks` this never returns None — it
    bounds a compile-refusing check, so a conservative floor beats null."""
    norm = normalize_device_kind(device_kind)
    if norm:
        return DEVICE_VMEM_BYTES[norm]
    return min(DEVICE_VMEM_BYTES.values())


def _linear_flops_per_token(cfg: Config) -> float:
    """Matmul FLOPs per token through the weights: 2 MACs per weight for
    every LINEAR parameter.  The token embedding is a gather (no FLOPs),
    so one V·D is subtracted from `estimate_params`; the lm_head matmul
    always runs — for tied embeddings it reuses the subtracted wte, so
    the V·D goes back in."""
    N = cfg.estimate_params()
    emb = cfg.padded_vocab_size * cfg.n_embd
    lin = N - emb
    if cfg.tie_embeddings:
        lin += emb
    return 2.0 * lin


def decode_flops_per_token(cfg: Config, context: int) -> float:
    """Forward FLOPs to generate ONE token with `context` KV positions
    resident: 2·params(linear) + 4·L·H·hs·context (QKᵀ and A·V, 2 FLOPs
    per MAC each).  The inference third of
    `training.estimate_flops_per_token`'s 6N + 12·L·H·hs·T."""
    attn = 4.0 * cfg.n_layer * cfg.n_head * cfg.head_size * int(context)
    return _linear_flops_per_token(cfg) + attn


def prefill_flops_per_token(cfg: Config, prompt_len: int) -> float:
    """Mean forward FLOPs per PROMPT token: position p attends p+1
    positions, so the causal average over a T-token prompt is (T+1)/2."""
    return decode_flops_per_token(cfg, (int(prompt_len) + 1) // 2)


def param_bytes(params: Any) -> int:
    """Exact HBM bytes of a live parameter tree (quantized trees included:
    int8/int4 storage leaves count at their stored width).  Host-side
    metadata only — no sync, no transfer."""
    import math

    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        n = int(math.prod(getattr(leaf, "shape", ()) or (1,)))
        total += n * dtype_bytes(leaf.dtype)
    return total


def decode_hbm_bytes_per_token(
    cfg: Config,
    serving: Optional[ServingConfig],
    batch: int,
    context: int,
    weight_bytes: int,
    dtype: str = "bfloat16",
) -> Dict[str, float]:
    """Analytic HBM traffic to decode ONE token at batch `batch` with
    `context` resident KV positions.

    Decode is bandwidth-bound: every step streams all weights once
    (shared by the whole batch → weight_bytes / batch per token) and
    reads the sequence's live KV.  With a paged pool the read granularity
    is whole blocks — ceil(context / block_size) × `ServingConfig.
    block_bytes` (payload at the POOL dtype plus the int8 scale arrays),
    which is exactly how int8 pools earn their MBU credit; pass
    `serving=None` for a dense contiguous cache (2·L·G·hs·context at
    `dtype`).  The per-token KV write (one position's k+v) rides along;
    activations never round-trip HBM at decode widths and are ignored.
    """
    batch = max(1, int(batch))
    context = int(context)
    if serving is not None:
        bb = serving.block_bytes(cfg, dtype)
        n_blocks = -(-context // serving.block_size) if context else 0
        kv_read = float(n_blocks * bb["total_bytes"])
        kv_write = bb["kv_bytes"] / serving.block_size
        kv_dtype = bb["kv_dtype"]
    else:
        item = dtype_bytes(dtype)
        kv_read = float(
            2 * cfg.n_layer * cfg.n_query_groups * cfg.head_size * context * item
        )
        kv_write = float(2 * cfg.n_layer * cfg.n_query_groups * cfg.head_size * item)
        kv_dtype = dtype
    weights = weight_bytes / batch
    return {
        "weight_bytes": weights,
        "kv_read_bytes": kv_read,
        "kv_write_bytes": kv_write,
        "kv_dtype": kv_dtype,
        "total_bytes": weights + kv_read + kv_write,
    }


def serving_roofline(
    cfg: Config,
    serving: Optional[ServingConfig],
    tokens_per_s: float,
    context: int,
    batch: int,
    weight_bytes: int,
    device_kind: Optional[str],
    n_chips: int = 1,
    dtype: str = "bfloat16",
) -> Dict[str, Any]:
    """Achieved MFU/MBU of a serving run: measured `tokens_per_s` (TOTAL
    across chips) times the analytic per-token FLOPs/bytes at the run's
    mean `context` and effective `batch`, over `n_chips` × the device
    peak.  Unknown `device_kind` → `mfu`/`mbu` are None (the peaks row is
    absent), but the achieved absolute rates still report — a CPU row
    carries its TFLOP/s even though "utilization of a CPU" is undefined
    here.  Embedded as `detail.device.roofline` by bench serve rows and
    the mdi-serve stats line (docs/observability.md)."""
    peaks = device_peaks(device_kind)
    flops_tok = decode_flops_per_token(cfg, context)
    bytes_tok = decode_hbm_bytes_per_token(
        cfg, serving, batch, context, weight_bytes, dtype=dtype
    )
    achieved_flops = tokens_per_s * flops_tok
    achieved_bytes = tokens_per_s * bytes_tok["total_bytes"]
    n_chips = max(1, int(n_chips))
    out: Dict[str, Any] = {
        "device_kind": device_kind,
        "peaks": peaks,
        "n_chips": n_chips,
        "context_mean": int(context),
        "batch": int(batch),
        "flops_per_token": flops_tok,
        "hbm_bytes_per_token": bytes_tok,
        "achieved_tflops_per_s": achieved_flops / 1e12,
        "achieved_hbm_gbps": achieved_bytes / 1e9,
        "mfu": None,
        "mbu": None,
    }
    if peaks is not None:
        out["mfu"] = achieved_flops / (n_chips * peaks["bf16_tflops"] * 1e12)
        out["mbu"] = achieved_bytes / (n_chips * peaks["hbm_gbps"] * 1e9)
    return out


def crosscheck_flops(report, analytic_flops: float,
                     rtol: float = XLA_AGREEMENT_RTOL) -> Dict[str, Any]:
    """Compare an `ExecutableReport`'s XLA-counted FLOPs against the
    analytic model's number for the same dispatch.  Returns the agreement
    record embedded in `detail.device.crosscheck`; `agrees` is None when
    the backend reported no FLOPs (nothing to judge), else whether the
    relative error is within `rtol` — the tripwire that keeps the
    analytic model honest (tests/test_roofline.py pins it on CPU)."""
    xla = getattr(report, "flops", None)
    out: Dict[str, Any] = {
        "executable": getattr(report, "name", str(report)),
        "xla_flops": xla,
        "analytic_flops": float(analytic_flops),
        "rtol": rtol,
        "rel_err": None,
        "agrees": None,
    }
    if xla is not None and analytic_flops > 0:
        rel = abs(xla - analytic_flops) / analytic_flops
        out["rel_err"] = rel
        out["agrees"] = rel <= rtol
    return out
