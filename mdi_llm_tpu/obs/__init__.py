"""Serving observability: metrics, request tracing, latency percentiles.

`ServingObserver` is the one object the serving stack talks to — build
it, pass it to `Generator.serve(obs=...)` (or `ServingEngine` directly),
and the engine/scheduler feed it at the host-sync boundaries they
already own:

    from mdi_llm_tpu.obs import ServingObserver

    obs = ServingObserver()
    engine = gen.serve(block_size=16, max_batch=8, obs=obs)
    ...
    results, stats = engine.run()
    json.dump(obs.metrics_dict(stats), open("metrics.json", "w"))
    obs.tracer.write_chrome_trace("trace.json")     # open in Perfetto

It bundles four parts (docs/observability.md):

- `obs.metrics`  — `MetricsRegistry`: counters/gauges/fixed-bucket
  histograms with JSON + Prometheus exposition (`obs/metrics.py`);
- `obs.tracer`   — `TraceRecorder`: bounded ring of request-lifecycle
  and engine-step events, Chrome-trace/Perfetto export
  (`obs/tracing.py`);
- latency derivation — per-request TTFT/TPOT/E2E/queue-wait over the
  completed-request window, aggregated to p50/p95/p99
  (`latency_summary`);
- `obs.device`   — `DeviceReportRegistry`: XLA executable introspection
  (`obs/device.py` `ExecutableReport`: cost_analysis FLOPs/bytes +
  memory_analysis temp/argument/output bytes per serving executable).
  Pass `device=True` to CAPTURE (one side-band AOT compile per
  executable, during warmup); the default observer still RECEIVES
  reports captured earlier on the same Generator, for free.

Overhead contract (pinned by tests/test_obs.py): every hook is a plain
host-side append — enabling the observer adds ZERO extra host syncs,
ZERO device ops and ZERO post-warmup recompiles to a serving run, and
holds O(ring) memory however long the engine lives.  Timestamps are
taken once per host-sync boundary and shared by everything drained
there (`mark`), so token attribution rides syncs the engine performs
anyway.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, Optional

from mdi_llm_tpu.obs.device import DeviceReportRegistry, ExecutableReport
from mdi_llm_tpu.obs.metrics import (
    LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    latency_summary,
    percentiles,
)
from mdi_llm_tpu.obs.tracing import RequestTiming, TraceRecorder

__all__ = [
    "Counter",
    "DeviceReportRegistry",
    "ExecutableReport",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "RequestTiming",
    "ServingObserver",
    "TraceRecorder",
    "LATENCY_BUCKETS_S",
    "latency_summary",
    "percentiles",
]

LATENCY_METRICS = ("ttft_s", "tpot_s", "e2e_s", "queue_wait_s")

# host-tier restore-issue wait histogram bounds, in MILLISECONDS (restores
# are issued at host-sync boundaries and hidden behind the next dispatch,
# so the interesting range sits well under the request-latency buckets)
RESTORE_WAIT_BUCKETS_MS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
    500.0, 1000.0,
)


class ServingObserver:
    """Observability hub for one serving engine (or several sharing it).

    `clock` is injectable for deterministic tests; `ring` bounds both the
    trace-event and completed-request windows; `rss_interval_s` (None =
    off) samples the host process tree's RSS via
    `cli.mem_monitor.sample_rss` at most once per interval, at sync
    boundaries only (`mdi-serve --sample-rss`).  `device=True` enables
    XLA executable CAPTURE (`obs/device.py`): the engine AOT-introspects
    each executable once, at warmup, caching reports on its Generator —
    the default (False) observer never triggers a capture but still
    receives reports already cached there.
    """

    def __init__(self, ring: int = 65536,
                 clock: Callable[[], float] = time.perf_counter,
                 rss_interval_s: Optional[float] = None,
                 device: bool = False):
        self.clock = clock
        self.metrics = MetricsRegistry()
        self.device = DeviceReportRegistry(capture_enabled=device)
        self.tracer = TraceRecorder(capacity=ring, clock=clock)
        self.rss_interval_s = rss_interval_s
        self._last_rss_ts: Optional[float] = None
        self._rss_peak = 0
        self._rss_broken = False  # psutil missing/unusable: sample once, warn
        self._now: Optional[float] = None  # last host-sync stamp
        self._compile_hook = None
        # pre-register the latency histograms so an idle engine still
        # exposes the full catalog
        for name in LATENCY_METRICS:
            self.metrics.histogram(
                f"serving_request_{name.replace('_s', '_seconds')}",
                f"per-request {name[:-2].replace('_', ' ')} distribution",
            )

    # -- host-sync boundary --------------------------------------------------

    @property
    def now(self) -> float:
        """The last sync-boundary stamp (falls back to the clock so
        lifecycle hooks fired outside a step still get a timestamp)."""
        return self._now if self._now is not None else self.clock()

    def step(self, kind: str, width: int, live: int,  # mdi-thread: engine
             t_start: Optional[float] = None,
             kv_utilization: Optional[float] = None,
             queue_depth: Optional[int] = None,
             **extra) -> float:
        """Record one engine dispatch at its host-sync boundary: stamps
        "now" ONCE (all tokens/retirements drained at this boundary share
        it), appends the step span, and refreshes the step gauges.
        Returns the stamp so the engine can chain spans."""
        prev = self._now
        now = self.clock()
        self._now = now
        start = t_start if t_start is not None else (prev if prev is not None else now)
        self.tracer.step(kind, start, now, width, live, extra or None)
        m = self.metrics
        m.counter("serving_steps_total",
                  "engine dispatches (all kinds)").inc()
        m.counter(f"serving_steps_{kind}_total",
                  f"{kind} dispatches").inc()
        m.counter("serving_host_syncs_total",
                  "host reads of device results").inc()
        m.counter("serving_tokens_dispatched_total",
                  "device token-axis positions computed").inc(width)
        m.gauge("serving_live_lanes", "slots carrying a sequence").set(live)
        if kv_utilization is not None:
            m.gauge("serving_kv_utilization",
                    "fraction of pool blocks held by live sequences"
                    ).set(kv_utilization)
            peak = m.gauge("serving_kv_utilization_peak",
                           "high-water pool utilization")
            peak.set(max(peak.value, kv_utilization))
        if queue_depth is not None:
            m.gauge("serving_queue_depth",
                    "requests waiting or preempted").set(queue_depth)
        self._maybe_sample_rss(now)
        return now

    def _maybe_sample_rss(self, now: float) -> None:  # mdi-thread: engine
        if self.rss_interval_s is None or self._rss_broken:
            return
        if (self._last_rss_ts is not None
                and now - self._last_rss_ts < self.rss_interval_s):
            return
        self._last_rss_ts = now
        try:
            from mdi_llm_tpu.cli.mem_monitor import sample_rss

            rss = sample_rss(os.getpid())
        except Exception:  # psutil missing: degrade to no gauge, once
            self._rss_broken = True
            return
        self._rss_peak = max(self._rss_peak, rss)
        self.metrics.gauge("host_rss_bytes",
                           "process-tree resident set size").set(rss)
        self.metrics.gauge("host_rss_peak_bytes",
                           "high-water process-tree RSS").set(self._rss_peak)

    # -- request lifecycle (scheduler/engine hooks) --------------------------

    def request_submitted(self, rid: str, n_prompt: int,  # mdi-thread: engine
                          max_new_tokens: int) -> None:
        self.tracer.request_submitted(rid, n_prompt, max_new_tokens)
        self.metrics.counter("serving_requests_submitted_total",
                             "requests queued").inc()

    def request_admitted(self, rid: str, slot: int, admit_order: int,  # mdi-thread: engine
                         n_cached: int = 0, resumed: bool = False,
                         restored: bool = False) -> None:
        self.tracer.request_admitted(rid, slot, admit_order,
                                     n_cached=n_cached, resumed=resumed,
                                     restored=restored)
        name = ("serving_requests_resumed_total" if resumed
                else "serving_requests_admitted_total")
        self.metrics.counter(name, "admissions into decode slots").inc()
        if restored:
            self.metrics.counter("serving_requests_restored_total",
                                 "resumes served from host-tier swap "
                                 "payloads (zero re-prefill)").inc()
        if n_cached and not restored:
            self.metrics.counter("serving_prefix_cached_tokens_total",
                                 "prompt tokens served from the prefix "
                                 "cache").inc(n_cached)

    def request_rejected(self, rid: str) -> None:  # mdi-thread: any
        """Open-system backpressure: an arrival bounced at the admission
        queue bound (server/frontend.py → HTTP 429).  Counter only — a
        rejected request never opens a timing record, so the latency
        percentiles describe SERVED traffic (the SLO convention: rejected
        load is reported separately, not averaged in)."""
        self.metrics.counter("serving_requests_rejected_total",
                             "arrivals rejected by admission "
                             "backpressure").inc()

    def request_preempted(self, rid: str, n_generated: int,  # mdi-thread: engine
                          swapped: bool = False) -> None:
        self.tracer.request_preempted(rid, n_generated, swapped=swapped)
        self.metrics.counter("serving_preemptions_total",
                             "recompute-style preemptions").inc()
        if swapped:
            self.metrics.counter("serving_preemptions_swapped_total",
                                 "preemptions resolved by host-tier swap "
                                 "instead of recompute").inc()

    # -- host-tier transfers (serving/host_tier.py) --------------------------

    def tier_swap_out(self, n_blocks: int, nbytes: int) -> None:  # mdi-thread: engine
        """One victim's blocks gathered toward host slots (bytes counted
        at issue time; materialization rides a later sync boundary)."""
        self.metrics.counter("serving_swap_out_bytes_total",
                             "KV bytes swapped HBM → host").inc(nbytes)
        self.metrics.counter("serving_swap_out_blocks_total",
                             "KV blocks swapped HBM → host").inc(n_blocks)

    def tier_swap_in(self, n_blocks: int, nbytes: int) -> None:  # mdi-thread: engine
        self.metrics.counter("serving_swap_in_bytes_total",
                             "KV bytes restored host → HBM").inc(nbytes)
        self.metrics.counter("serving_swap_in_blocks_total",
                             "KV blocks restored host → HBM").inc(n_blocks)

    def restore_wait(self, seconds: float) -> None:  # mdi-thread: engine
        """Host time spent issuing one restore batch (upload + scatter
        enqueue — the part not hidden behind the next dispatch)."""
        self.metrics.histogram(
            "serving_restore_wait_ms",
            "host-side wait per host→HBM restore issue",
            buckets=RESTORE_WAIT_BUCKETS_MS,
        ).observe(seconds * 1e3)

    def spec(self, drafted: int, accepted: int, source: str) -> None:  # mdi-thread: engine
        """One lane's speculative verify outcome, split by draft source
        (``"ngram"`` prompt lookup vs ``"model"`` draft model): per-source
        and total drafted/accepted counters plus the lifetime
        `serving_spec_accept_rate` gauge.  Called per live lane per verify
        round at the round's boundary sync — host-side counter bumps only."""
        m = self.metrics
        m.counter(f"serving_spec_drafted_{source}_total",
                  f"draft tokens proposed by the {source} drafter"
                  ).inc(drafted)
        m.counter(f"serving_spec_accepted_{source}_total",
                  f"{source}-drafted tokens accepted by verify"
                  ).inc(accepted)
        d = m.counter("serving_spec_drafted_total",
                      "draft tokens scored by speculative verify")
        a = m.counter("serving_spec_accepted_total",
                      "draft tokens accepted by speculative verify")
        d.inc(drafted)
        a.inc(accepted)
        if d.value:
            m.gauge("serving_spec_accept_rate",
                    "accepted/drafted over the observer's lifetime"
                    ).set(a.value / d.value)

    def prefill_chunk(self, rid: str, n_tokens: int) -> None:  # mdi-thread: engine
        self.tracer.prefill_chunk(rid, n_tokens, self.now)
        self.metrics.counter("serving_prefill_tokens_total",
                             "prompt tokens fed").inc(n_tokens)

    def tokens(self, rid: str, n: int = 1) -> None:  # mdi-thread: engine
        self.tracer.tokens(rid, n, self.now)
        self.metrics.counter("serving_tokens_generated_total",
                             "tokens emitted to streams").inc(n)

    def request_finished(self, rid: str) -> None:  # mdi-thread: engine
        self.tracer.request_finished(rid, self.now)
        self.metrics.counter("serving_requests_finished_total",
                             "requests retired complete").inc()
        t = self.tracer.completed[-1] if self.tracer.completed else None
        if t is None or t.rid != rid:
            return
        for name, v in (("ttft_s", t.ttft), ("tpot_s", t.tpot),
                        ("e2e_s", t.e2e), ("queue_wait_s", t.queue_wait)):
            if v is not None:
                self.metrics.histogram(
                    f"serving_request_{name.replace('_s', '_seconds')}"
                ).observe(v)

    # -- compile events (CompileGuard companion) -----------------------------

    def attach_compile_hook(self) -> None:
        """Count jit traces / XLA backend compiles into the registry while
        the engine runs (utils/profiling.py's jax.monitoring listener —
        the same event stream CompileGuard consumes)."""
        if self._compile_hook is not None:
            return
        from mdi_llm_tpu.utils import profiling

        traces = self.metrics.counter(
            "jax_jit_traces_total", "jit cache misses (jaxpr traces)")
        compiles = self.metrics.counter(
            "jax_backend_compiles_total", "XLA backend compilations")

        def hook(event: str) -> None:
            if event == profiling._TRACE_EVENT:
                traces.inc()
            elif event == profiling._BACKEND_COMPILE_EVENT:
                compiles.inc()

        profiling.add_compile_listener(hook)
        self._compile_hook = hook

    def detach_compile_hook(self) -> None:
        if self._compile_hook is None:
            return
        from mdi_llm_tpu.utils import profiling

        profiling.remove_compile_listener(self._compile_hook)
        self._compile_hook = None

    # -- device-side introspection (obs/device.py) ---------------------------

    def publish_device_report(self, report) -> None:
        """Register an `ExecutableReport` and mirror its headline numbers
        into the metrics registry (`xla_<label>_{flops,bytes_accessed,
        temp_bytes}` gauges — one per dispatch path; the full per-shape
        fidelity lives in `metrics_dict()["device"]`).  Publishing is a
        host-side append: it never lowers, compiles or syncs anything."""
        self.device.add(report)
        for suffix, value in (
            ("flops", report.flops),
            ("bytes_accessed", report.bytes_accessed),
            ("temp_bytes", report.temp_bytes),
        ):
            if value is not None:
                self.metrics.gauge(
                    f"xla_{report.label}_{suffix}",
                    f"XLA {suffix.replace('_', ' ')} of the {report.label} "
                    "executable (cost/memory_analysis)",
                ).set(value)

    # -- exposition ----------------------------------------------------------

    def latency_summaries(self) -> Dict[str, Dict[str, float]]:
        """{metric: {count,p50,p95,p99,mean,max}} over the
        completed-request window — EXACT percentiles (metrics.percentiles
        over the ring), not the histogram approximation."""
        lats = self.tracer.latencies()
        return {name: latency_summary(lats[name]) for name in LATENCY_METRICS}

    def metrics_dict(self, stats=None) -> Dict:
        """The `--metrics-out` JSON: latency percentile block + registry
        snapshot (+ the engine's canonical `ServingStats.to_dict()` and
        the per-request detail rows still in the window)."""
        out: Dict = {
            "latency": self.latency_summaries(),
            "metrics": self.metrics.to_dict(),
            "requests": [t.to_dict() for t in self.tracer.completed],
            "ring": {"capacity": self.tracer.capacity,
                     "events": len(self.tracer.events),
                     "events_dropped": self.tracer.dropped,
                     "completed_window": len(self.tracer.completed)},
        }
        if len(self.device):
            out["device"] = self.device.to_dict()
        if stats is not None:
            out["serving_stats"] = stats.to_dict()
        return out
