"""XLA executable introspection: what each serving dispatch costs ON PAPER.

PR 7 gave serving host-side eyes (request tracing, latency percentiles);
this module looks below the host-sync boundary WITHOUT adding any device
work: for a jitted function and the shapes it dispatches at, an AOT
`fn.lower(*abstract_args).compile()` yields the XLA compiler's own
`cost_analysis()` (FLOPs, bytes accessed, transcendentals) and
`memory_analysis()` (argument/output/temp/generated-code bytes) for the
EXACT executable the engine runs — captured as an `ExecutableReport`.

Contract (the device-side half of the PR 7 overhead contract, pinned by
tests/test_device_obs.py):

- **Zero device work.**  Arguments are abstracted to `ShapeDtypeStruct`s
  (shapes + dtypes + shardings, no buffers), so capture never transfers,
  executes or syncs anything.
- **Zero effect on the jit cache.**  AOT lowering is side-band: the jitted
  function's own dispatch cache is neither read nor written, so the
  engine's executables, donation behaviour and CompileGuard counters for
  the REAL dispatches are untouched.  The capture itself does trace and
  compile (that is where the numbers come from) — which is why callers
  capture at most ONCE per (label, key, variant) and do it during warmup:
  the serving engine caches reports on the Generator
  (`Generator._exec_reports`), so the post-warmup steady state never
  lowers anything and the zero-post-warmup-recompile contract holds with
  device observability enabled.
- **Never raises.**  Backends without the AOT cost APIs (or executables
  that refuse to lower abstractly) produce a report with `error` set and
  every number None — observability must not take the engine down.

Reports flow into the PR 7 surfaces: `ServingObserver.device` (a
`DeviceReportRegistry`), gauges in the `MetricsRegistry`
(`xla_<label>_flops` etc.), the `--metrics-out` JSON and the
`detail.device` block of bench serve rows (docs/observability.md
"Device-side observability").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

__all__ = [
    "ExecutableReport",
    "ExecutableSpec",
    "DeviceReportRegistry",
    "abstractify",
    "introspect",
]


class ExecutableSpec(NamedTuple):
    """One serving/generation executable, fully described for side-band AOT
    work: the jitted callable plus the abstract argument signature it is
    dispatched at.  Produced by the enumeration seams
    (`ServingEngine.enumerate_executables`,
    `Generator.enumerate_executables`) and consumed by `mdi-ir`
    (analysis/ir.py) to trace/lower every executable without a backend.

    `args` are `ShapeDtypeStruct` pytrees (see `abstractify`);
    `static_kwargs` holds the jit static arguments (None when the fn has
    none); `donate` mirrors the fn's `donate_argnums`; `roles` names the
    semantically special positional args (``{argnum: "params" | "kv"}``)
    so byte-attribution passes (mdi-flow, analysis/liveness.py) can tell
    the model weights and the paged pool apart from run operands without
    guessing by size."""

    label: str  # dispatch path: mixed / decode / decode_chunk / verify / ...
    key: Tuple  # static-shape key, e.g. (B, T)
    fn: Any  # the jitted callable (supports .trace(*args, **static_kwargs))
    args: Tuple  # abstract positional args, in dispatch order
    static_kwargs: Optional[Dict[str, Any]]  # jit static args, or None
    donate: Tuple[int, ...]  # donated positional indices (donate_argnums)
    roles: Optional[Dict[int, str]] = None  # argnum -> "params"/"kv"/...

    @property
    def name(self) -> str:
        ks = ",".join(str(k) for k in self.key)
        return f"{self.label}({ks})"


def abstractify(tree):
    """Map every array leaf of an argument pytree to a
    `jax.ShapeDtypeStruct` carrying its shape, dtype and (for committed
    jax arrays) sharding — the abstract signature `jax.jit(...).lower`
    accepts in place of real buffers.  Shardings matter under a tp mesh:
    without them the AOT compile would build (and cost) the UNSHARDED
    program, not the one the engine runs."""
    import jax
    import jax.numpy as jnp

    def leaf(x):
        shape = jnp.shape(x)
        dtype = getattr(x, "dtype", None)
        if dtype is None:  # python scalar leaf (engine args never are, but
            dtype = jnp.result_type(x)  # stay total for external callers)
        sharding = getattr(x, "sharding", None)
        if sharding is not None:
            try:
                return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)
            except TypeError:  # older ShapeDtypeStruct without sharding kwarg
                pass
        return jax.ShapeDtypeStruct(shape, dtype)

    return jax.tree_util.tree_map(leaf, tree)


@dataclass
class ExecutableReport:
    """One compiled executable's static cost sheet.

    `flops`/`bytes_accessed`/`transcendentals` come from
    `compiled.cost_analysis()` (the XLA HLO cost model — counted over the
    optimized program, so fusion/DCE effects are included);
    `*_bytes` from `compiled.memory_analysis()`.  `None` means the
    backend did not report that number (`error` says why when the whole
    capture failed)."""

    label: str  # dispatch path: mixed / decode / decode_chunk / verify / ...
    key: Tuple  # static-shape key, e.g. (B, T)
    variant: str = ""  # e.g. the pool kv dtype — same shapes, different HLO
    backend: str = ""
    flops: Optional[float] = None
    transcendentals: Optional[float] = None
    bytes_accessed: Optional[float] = None
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    alias_bytes: Optional[int] = None
    generated_code_bytes: Optional[int] = None
    error: Optional[str] = None

    @property
    def name(self) -> str:
        """Stable human/JSON key: `label(k0,k1)[variant]`."""
        ks = ",".join(str(k) for k in self.key)
        tag = f"[{self.variant}]" if self.variant else ""
        return f"{self.label}({ks}){tag}"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "label": self.label,
            "key": list(self.key),
            "variant": self.variant,
            "backend": self.backend,
            "flops": self.flops,
            "transcendentals": self.transcendentals,
            "bytes_accessed": self.bytes_accessed,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "alias_bytes": self.alias_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "error": self.error,
        }


def _first_module(analysis):
    """`cost_analysis()` returns a dict on recent jax and a one-per-module
    list on older releases; normalize to one dict (multi-module programs
    put the entry computation first)."""
    if isinstance(analysis, (list, tuple)):
        return analysis[0] if analysis else {}
    return analysis or {}


def introspect(fn, args, static_kwargs=None, label="", key=(),
               variant="") -> ExecutableReport:
    """AOT-compile `fn` at `args`' shapes and read the compiler's cost and
    memory analyses into an `ExecutableReport`.  `fn` must be a
    `jax.jit`-wrapped callable; `args` the positional arguments of one
    real dispatch (arrays or numpy arrays — only shapes/dtypes/shardings
    are read); `static_kwargs` the static keyword arguments.  Never
    raises: failures come back as a report with `error` set."""
    import jax

    rep = ExecutableReport(label=label, key=tuple(key), variant=variant,
                           backend=jax.default_backend())
    try:
        compiled = fn.lower(*abstractify(args), **(static_kwargs or {})).compile()
    except Exception as exc:  # refused abstract lowering, AOT API missing…
        rep.error = f"{type(exc).__name__}: {exc}"
        return rep
    try:
        cost = _first_module(compiled.cost_analysis())
        rep.flops = cost.get("flops")
        rep.transcendentals = cost.get("transcendentals")
        rep.bytes_accessed = cost.get("bytes accessed")
    except Exception as exc:  # pragma: no cover - backend-dependent API
        rep.error = f"cost_analysis: {type(exc).__name__}: {exc}"
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            rep.argument_bytes = int(mem.argument_size_in_bytes)
            rep.output_bytes = int(mem.output_size_in_bytes)
            rep.temp_bytes = int(mem.temp_size_in_bytes)
            rep.alias_bytes = int(mem.alias_size_in_bytes)
            rep.generated_code_bytes = int(mem.generated_code_size_in_bytes)
    except Exception as exc:  # pragma: no cover - backend-dependent API
        err = f"memory_analysis: {type(exc).__name__}: {exc}"
        rep.error = f"{rep.error}; {err}" if rep.error else err
    return rep


class DeviceReportRegistry:
    """Report store keyed on (label, key, variant), one capture each.

    `capture_enabled=False` builds a publish-only registry: `capture`
    becomes a no-op (no AOT compiles ever), but `add` still accepts
    reports captured elsewhere — how a fresh observer on a warm Generator
    gets the warmup-time reports without compiling anything
    (`ServingEngine` publishes its Generator's cache at run end)."""

    def __init__(self, capture_enabled: bool = True):
        self.capture_enabled = capture_enabled
        self._reports: "Dict[Tuple, ExecutableReport]" = {}

    def __len__(self) -> int:
        return len(self._reports)

    def capture(self, label, key, fn, args, static_kwargs=None,
                variant="") -> Optional[ExecutableReport]:
        k = (label, tuple(key), variant)
        if not self.capture_enabled:
            return self._reports.get(k)
        if k not in self._reports:
            self._reports[k] = introspect(
                fn, args, static_kwargs, label=label, key=key, variant=variant
            )
        return self._reports[k]

    def add(self, report: ExecutableReport) -> None:
        """Publish an externally-captured report (first one wins)."""
        self._reports.setdefault(
            (report.label, report.key, report.variant), report
        )

    def get(self, label, key, variant="") -> Optional[ExecutableReport]:
        return self._reports.get((label, tuple(key), variant))

    def reports(self) -> List[ExecutableReport]:
        return list(self._reports.values())

    def to_dict(self) -> Dict[str, Dict]:
        """{report.name: report dict}, insertion-ordered — the
        `detail.device.executables` / `--metrics-out` "device" block."""
        return {r.name: r.to_dict() for r in self._reports.values()}
