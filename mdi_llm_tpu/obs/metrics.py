"""Host-side metrics registry: counters, gauges, fixed-bucket histograms.

The measurement half of the serving observability layer (`obs/`): the
engine, scheduler, KV pool and CompileGuard feed a `MetricsRegistry`
entirely from host-side bookkeeping they already maintain — recording a
metric never touches a device array, so enabling metrics adds zero host
syncs and zero compiles (pinned by tests/test_obs.py).

Memory contract for long-lived engines: every metric is O(1) state — a
counter is one int, a gauge one float, a histogram a FIXED bucket vector
plus sum/count.  Exact per-request percentiles (TTFT/TPOT/...) come from
`percentiles()` over the tracer's bounded completed-request ring
(`obs/tracing.py`), not from unbounded value lists here; the histograms
exist for the Prometheus-style exposition where a scraper wants
monotonic cumulative buckets.

Exposition: `MetricsRegistry.to_dict()` (JSON, what `mdi-serve
--metrics-out` writes) and `MetricsRegistry.render_prometheus()`
(text/plain; version 0.0.4 — `metric_bucket{le="..."}` cumulative
buckets, `_sum`/`_count`, the `+Inf` bucket always present).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "LATENCY_BUCKETS_S",
    "percentiles",
    "latency_summary",
]

# default histogram buckets for second-valued serving latencies: log-ish
# spread from 1 ms to 2 min, fixed so a long-lived engine's memory never
# grows with traffic (the O(1) contract above)
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


class Counter:
    """Monotonically increasing count (requests, tokens, compiles).

    Mutations take an internal lock: counters cross the thread seam —
    e.g. `requests_rejected` increments on the submitting thread while
    the engine thread bumps token counters — and `self.value += n` is a
    read-modify-write that would lose updates (mdi-race audit, PR 13).
    Reading `value` is a single GIL-atomic load and stays lock-free."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value: float = 0
        self._lock = threading.Lock()

    def inc(self, n: float = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {n})")
        with self._lock:
            self.value += n

    def set_to(self, v: float) -> None:
        """Advance to an externally-maintained running total (the engine's
        `ServingStats` aggregates) — still monotonic, never backwards."""
        with self._lock:
            if v < self.value:
                raise ValueError(
                    f"counter {self.name} cannot move backwards "
                    f"({self.value} -> {v})"
                )
            self.value = v


class Gauge:
    """Point-in-time value (KV utilization, live lanes, host RSS)."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value: float = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Fixed-bucket histogram: counts per upper bound + sum + count.

    Buckets are non-cumulative internally; `cumulative()` produces the
    Prometheus-style `le` view.  `percentile(q)` interpolates inside the
    containing bucket — approximate by construction (use
    `metrics.percentiles` over raw values when exactness matters)."""

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = LATENCY_BUCKETS_S):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(f"histogram {name}: buckets must be ascending")
        self.name = name
        self.help = help
        self.bounds: Tuple[float, ...] = tuple(float(b) for b in buckets)
        self.counts: List[int] = [0] * (len(self.bounds) + 1)  # +overflow
        self.sum: float = 0.0
        self.count: int = 0
        self._lock = threading.Lock()  # observe is a multi-field RMW

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.sum += v
            self.count += 1
            for i, b in enumerate(self.bounds):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(le, cumulative_count), ...] ending with (+inf, count)."""
        out: List[Tuple[float, int]] = []
        acc = 0
        with self._lock:
            counts, total = list(self.counts), self.count
        for b, c in zip(self.bounds, counts):
            acc += c
            out.append((b, acc))
        out.append((math.inf, total))
        return out

    def percentile(self, q: float) -> float:
        """Approximate q-th percentile (q in [0, 100]) by linear
        interpolation within the containing bucket (0 lower edge for the
        first; the overflow bucket reports its lower bound)."""
        if not 0 <= q <= 100:
            raise ValueError("q must be in [0, 100]")
        with self._lock:
            counts, total = list(self.counts), self.count
        if total == 0:
            return 0.0
        rank = q / 100.0 * total
        acc = 0
        lo = 0.0
        for b, c in zip(self.bounds, counts):
            if acc + c >= rank and c > 0:
                frac = (rank - acc) / c
                return lo + (b - lo) * min(1.0, max(0.0, frac))
            acc += c
            lo = b
        return lo  # overflow bucket: best available bound


def percentiles(values: Sequence[float], qs: Sequence[float]) -> List[float]:
    """Exact percentiles over `values` with linear interpolation between
    order statistics (numpy's default 'linear' method, reimplemented so
    the math under test is THIS module's, not numpy's)."""
    if not values:
        return [0.0 for _ in qs]
    xs = sorted(float(v) for v in values)
    n = len(xs)
    out: List[float] = []
    for q in qs:
        if not 0 <= q <= 100:
            raise ValueError("q must be in [0, 100]")
        pos = q / 100.0 * (n - 1)
        lo = int(math.floor(pos))
        hi = min(lo + 1, n - 1)
        out.append(xs[lo] + (xs[hi] - xs[lo]) * (pos - lo))
    return out


def latency_summary(values: Sequence[float]) -> Dict[str, float]:
    """The canonical percentile block: p50/p95/p99 + mean/max/count, the
    shape `mdi-serve --metrics-out`, bench serve rows and the suite JSON
    all embed (docs/observability.md "Metric catalog")."""
    if not values:
        return {"count": 0, "p50": 0.0, "p95": 0.0, "p99": 0.0,
                "mean": 0.0, "max": 0.0}
    p50, p95, p99 = percentiles(values, (50, 95, 99))
    return {
        "count": len(values),
        "p50": p50,
        "p95": p95,
        "p99": p99,
        "mean": sum(values) / len(values),
        "max": max(values),
    }


class MetricsRegistry:
    """Name-keyed metric store with get-or-create accessors.

    One registry per observer; the engine/scheduler/pool never hold
    metric objects directly — they go through `ServingObserver`'s hooks
    so a disabled observer costs one `is None` check."""

    def __init__(self) -> None:
        self._metrics: "Dict[str, object]" = {}
        self._lock = threading.Lock()  # get-or-create races across threads

    def _get(self, cls, name: str, help: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS_S) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> Optional[object]:
        return self._metrics.get(name)

    # -- exposition ----------------------------------------------------------

    def to_dict(self) -> Dict[str, Dict]:
        """JSON-ready snapshot: {"counters", "gauges", "histograms"}."""
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        hists: Dict[str, Dict] = {}
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if isinstance(m, Counter):
                counters[name] = m.value
            elif isinstance(m, Gauge):
                gauges[name] = m.value
            elif isinstance(m, Histogram):
                hists[name] = {
                    "buckets": [
                        ["+Inf" if math.isinf(le) else le, c]
                        for le, c in m.cumulative()
                    ],
                    "sum": m.sum,
                    "count": m.count,
                }
        return {"counters": counters, "gauges": gauges, "histograms": hists}

    def render_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4)."""
        lines: List[str] = []
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            if isinstance(m, Counter):
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {_fmt(m.value)}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {_fmt(m.value)}")
            elif isinstance(m, Histogram):
                lines.append(f"# TYPE {name} histogram")
                for le, c in m.cumulative():
                    tag = "+Inf" if math.isinf(le) else _fmt(le)
                    lines.append(f'{name}_bucket{{le="{tag}"}} {c}')
                lines.append(f"{name}_sum {_fmt(m.sum)}")
                lines.append(f"{name}_count {m.count}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    if isinstance(v, int) or (isinstance(v, float) and v.is_integer()):
        return str(int(v))
    return repr(float(v))
