"""Request-lifecycle + engine-step tracing over a bounded ring buffer.

The timeline half of the serving observability layer (`obs/`): a
`TraceRecorder` captures two event families —

- **request lifecycle**: submitted → admitted → prefill chunk(s) → first
  token → decode/verify → retired | preempted → resumed, one
  `RequestTiming` per request carrying the timestamps the latency
  metrics derive from (TTFT, TPOT, E2E, queue-wait);
- **engine steps**: every mixed/decode-chunk/verify dispatch as a span
  with its packed token width and live-lane count.

Timestamp contract (the reason this is a serving feature, not a logger):
every timestamp is taken on the HOST at a boundary the engine already
synchronizes at — request queue operations (pure host bookkeeping) and
the one `np.asarray` read each dispatch already performs.  Recording
never touches a device array, adds no host syncs, and perturbs no jit
trace (pinned by tests/test_obs.py's CompileGuard + host_syncs test).

Memory contract: the event ring and the completed-request ring are both
`deque(maxlen=...)` — a long-lived engine holds O(ring) trace state no
matter how many requests flow through; only LIVE requests keep an open
`RequestTiming` outside the rings.

Export: `to_chrome_trace()` emits Chrome Trace Event JSON (the
`traceEvents` array format) loadable in Perfetto (https://ui.perfetto.dev)
or chrome://tracing.  Requests render as one track each — track rank ==
scheduler admission order — with a complete-event span from admission to
retirement and instant events for the lifecycle edges; engine steps
render on a separate process track.  docs/observability.md documents the
schema and the Perfetto how-to.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Deque, Dict, List, Optional

__all__ = ["RequestTiming", "TraceRecorder"]

# Chrome trace pid lanes (arbitrary ids; named via metadata events)
_PID_REQUESTS = 1
_PID_ENGINE = 2


@dataclass
class RequestTiming:
    """One request's lifecycle timestamps (seconds on the recorder clock).

    Derived latencies (`None` until the inputs exist):

    - queue_wait  = admitted - submitted         (first admission)
    - ttft        = first_token - submitted      (time to first token)
    - tpot        = (last_token - first_token) / (n_tokens - 1)
                                                 (steady decode cadence)
    - e2e         = finished - submitted
    """

    rid: str
    submitted_ts: float
    n_prompt: int = 0
    max_new_tokens: int = 0
    admitted_ts: Optional[float] = None
    first_token_ts: Optional[float] = None
    last_token_ts: Optional[float] = None
    finished_ts: Optional[float] = None
    n_tokens: int = 0
    prefill_chunks: int = 0
    preemptions: int = 0
    admit_order: int = -1
    slot: int = -1

    @property
    def queue_wait(self) -> Optional[float]:
        if self.admitted_ts is None:
            return None
        return self.admitted_ts - self.submitted_ts

    @property
    def ttft(self) -> Optional[float]:
        if self.first_token_ts is None:
            return None
        return self.first_token_ts - self.submitted_ts

    @property
    def tpot(self) -> Optional[float]:
        if self.first_token_ts is None or self.n_tokens < 2:
            return None
        return (self.last_token_ts - self.first_token_ts) / (self.n_tokens - 1)

    @property
    def e2e(self) -> Optional[float]:
        if self.finished_ts is None:
            return None
        return self.finished_ts - self.submitted_ts

    def to_dict(self) -> Dict[str, object]:
        return {
            "rid": self.rid,
            "admit_order": self.admit_order,
            "n_prompt": self.n_prompt,
            "n_tokens": self.n_tokens,
            "prefill_chunks": self.prefill_chunks,
            "preemptions": self.preemptions,
            "queue_wait_s": self.queue_wait,
            "ttft_s": self.ttft,
            "tpot_s": self.tpot,
            "e2e_s": self.e2e,
        }


class TraceRecorder:
    """Bounded ring of trace events + per-request timing records.

    `clock` is injectable (tests drive a fake clock; production uses
    `time.perf_counter`).  All mutating methods are plain host-side
    appends/dict writes — no locks (the serving loop is single-threaded),
    no device access, O(1) per call.
    """

    def __init__(self, capacity: int = 65536,
                 clock: Callable[[], float] = time.perf_counter):
        if capacity < 1:
            raise ValueError("ring capacity must be >= 1")
        self.capacity = capacity
        self.clock = clock
        # event ring: dicts already shaped like Chrome trace events, with
        # ts in recorder-clock SECONDS (export converts to relative µs)
        self.events: Deque[Dict] = deque(maxlen=capacity)
        # completed-request ring: the window percentile metrics read
        self.completed: Deque[RequestTiming] = deque(maxlen=capacity)
        # open requests: submitted/admitted but not yet retired (bounded by
        # requests in flight through the system, not by traffic history)
        self.open: Dict[str, RequestTiming] = {}
        self.t0 = clock()  # trace epoch: export rebases ts to this
        self.dropped = 0  # events pushed out of the ring (bounding proof)

    # -- low-level event append ---------------------------------------------

    def _push(self, ev: Dict) -> None:
        if len(self.events) == self.capacity:
            self.dropped += 1
        self.events.append(ev)

    def instant(self, name: str, ts: float, pid: int, tid: int,
                args: Optional[Dict] = None) -> None:
        self._push({"name": name, "ph": "i", "ts": ts, "pid": pid,
                    "tid": tid, "s": "t", "args": args or {}})

    def span(self, name: str, ts: float, dur: float, pid: int, tid: int,
             args: Optional[Dict] = None) -> None:
        self._push({"name": name, "ph": "X", "ts": ts, "dur": max(0.0, dur),
                    "pid": pid, "tid": tid, "args": args or {}})

    # -- request lifecycle ---------------------------------------------------

    def request_submitted(self, rid: str, n_prompt: int,
                          max_new_tokens: int) -> None:
        now = self.clock()
        self.open[rid] = RequestTiming(
            rid=rid, submitted_ts=now, n_prompt=n_prompt,
            max_new_tokens=max_new_tokens,
        )
        self.instant("submitted", now, _PID_REQUESTS, 0, {"rid": rid})

    def request_admitted(self, rid: str, slot: int, admit_order: int,
                         n_cached: int = 0, resumed: bool = False,
                         restored: bool = False) -> None:
        now = self.clock()
        t = self.open.get(rid)
        if t is None:  # admitted without a submit record: synthesize one
            t = RequestTiming(rid=rid, submitted_ts=now)
            self.open[rid] = t
        if t.admitted_ts is None:
            t.admitted_ts = now  # queue-wait measures the FIRST admission
            t.admit_order = admit_order
        t.slot = slot
        # host-tier resumes (serving/host_tier.py) render as their own
        # lifecycle edge: the KV came back from host slots, not re-prefill
        name = ("resumed_restored" if restored
                else "resumed" if resumed else "admitted")
        self.instant(name, now,
                     _PID_REQUESTS, max(0, t.admit_order),
                     {"rid": rid, "slot": slot, "admit_order": admit_order,
                      "prefix_cached_tokens": n_cached})

    def request_preempted(self, rid: str, n_generated: int,
                          swapped: bool = False) -> None:
        now = self.clock()
        t = self.open.get(rid)
        if t is not None:
            t.preemptions += 1
        self.instant("preempted_swapped" if swapped else "preempted",
                     now, _PID_REQUESTS,
                     max(0, t.admit_order) if t else 0,
                     {"rid": rid, "n_generated": n_generated})

    def prefill_chunk(self, rid: str, n_tokens: int, ts: float) -> None:
        t = self.open.get(rid)
        if t is not None:
            t.prefill_chunks += 1
        self.instant("prefill_chunk", ts, _PID_REQUESTS,
                     max(0, t.admit_order) if t else 0,
                     {"rid": rid, "n_tokens": n_tokens})

    def tokens(self, rid: str, n: int, ts: float) -> None:
        """Credit `n` generated tokens at host-sync time `ts` (one stamp
        per sync, shared by every token drained at that boundary)."""
        t = self.open.get(rid)
        if t is None:
            return
        if t.first_token_ts is None:
            t.first_token_ts = ts
            self.instant("first_token", ts, _PID_REQUESTS,
                         max(0, t.admit_order), {"rid": rid})
        t.last_token_ts = ts
        t.n_tokens += n

    def request_finished(self, rid: str, ts: Optional[float] = None) -> None:
        t = self.open.pop(rid, None)
        if t is None:
            return
        t.finished_ts = self.clock() if ts is None else ts
        self.completed.append(t)
        start = t.admitted_ts if t.admitted_ts is not None else t.submitted_ts
        self.span(
            rid, start, t.finished_ts - start, _PID_REQUESTS,
            max(0, t.admit_order),
            {"admit_order": t.admit_order, "n_prompt": t.n_prompt,
             "n_tokens": t.n_tokens, "preemptions": t.preemptions,
             "ttft_s": t.ttft, "tpot_s": t.tpot,
             "queue_wait_s": t.queue_wait},
        )

    # -- engine steps --------------------------------------------------------

    def step(self, kind: str, t_start: float, t_end: float, width: int,
             live: int, extra: Optional[Dict] = None) -> None:
        """One engine dispatch span: `kind` in {mixed, decode,
        decode_chunk, verify}, `width` the packed device token-axis
        positions, `live` the lanes that carried a real sequence."""
        args = {"packed_width": width, "live_lanes": live}
        if extra:
            args.update(extra)
        self.span(kind, t_start, t_end - t_start, _PID_ENGINE, 0, args)

    # -- latency windows -----------------------------------------------------

    def latencies(self) -> Dict[str, List[float]]:
        """Per-metric value lists over the completed-request window (the
        inputs to `metrics.latency_summary`)."""
        out: Dict[str, List[float]] = {
            "ttft_s": [], "tpot_s": [], "e2e_s": [], "queue_wait_s": [],
        }
        for t in self.completed:
            if t.ttft is not None:
                out["ttft_s"].append(t.ttft)
            if t.tpot is not None:
                out["tpot_s"].append(t.tpot)
            if t.e2e is not None:
                out["e2e_s"].append(t.e2e)
            if t.queue_wait is not None:
                out["queue_wait_s"].append(t.queue_wait)
        return out

    # -- export --------------------------------------------------------------

    def to_chrome_trace(self) -> Dict:
        """Chrome Trace Event JSON: ts/dur in MICROSECONDS rebased to the
        trace epoch, request tracks sorted by admission order, still-open
        requests exported as spans up to "now" so a live engine snapshot
        is viewable too."""
        now = self.clock()
        events: List[Dict] = [
            {"name": "process_name", "ph": "M", "pid": _PID_REQUESTS,
             "tid": 0, "args": {"name": "requests"}},
            {"name": "process_name", "ph": "M", "pid": _PID_ENGINE,
             "tid": 0, "args": {"name": "engine steps"}},
        ]
        # request tracks: name + explicit sort rank == admission order
        tracks: Dict[int, str] = {}
        for t in list(self.completed) + list(self.open.values()):
            if t.admit_order >= 0:
                tracks[t.admit_order] = t.rid
        for order in sorted(tracks):
            events.append({"name": "thread_name", "ph": "M",
                           "pid": _PID_REQUESTS, "tid": order,
                           "args": {"name": f"{order:04d} {tracks[order]}"}})
            events.append({"name": "thread_sort_index", "ph": "M",
                           "pid": _PID_REQUESTS, "tid": order,
                           "args": {"sort_index": order}})

        def us(ts: float) -> float:
            return round((ts - self.t0) * 1e6, 3)

        for ev in self.events:
            out = dict(ev)
            out["ts"] = us(out["ts"])
            if "dur" in out:
                out["dur"] = round(out["dur"] * 1e6, 3)
            events.append(out)
        # still-open requests: partial spans so the snapshot renders
        for t in self.open.values():
            if t.admitted_ts is None:
                continue
            events.append({
                "name": t.rid, "ph": "X", "ts": us(t.admitted_ts),
                "dur": round((now - t.admitted_ts) * 1e6, 3),
                "pid": _PID_REQUESTS, "tid": max(0, t.admit_order),
                "args": {"admit_order": t.admit_order, "open": True,
                         "n_tokens": t.n_tokens},
            })
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"ring_capacity": self.capacity,
                              "events_dropped": self.dropped}}

    def write_chrome_trace(self, path) -> None:
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(self.to_chrome_trace()) + "\n")
