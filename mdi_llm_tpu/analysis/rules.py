"""mdi-lint rule implementations.

Every rule encodes one way a JAX/TPU hot path silently degrades: a hidden
host sync, a Python branch on a tracer, a donated buffer read after the
call, a jit cache keyed on float values.  `docs/analysis.md` documents each
rule with a bad/good snippet pair; `tests/test_lint.py` pins every rule
with a triggering and a passing fixture.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from mdi_llm_tpu.analysis.core import (
    Finding,
    JittedFn,
    ModuleInfo,
    _dotted,
    jit_spec_of_call,
    jit_spec_of_decorator,
    rule,
)

# numpy module aliases as conventionally imported in this repo
_NP_NAMES = {"np", "numpy"}
# methods whose mere invocation forces a device->host transfer / sync
_SYNC_METHODS = {"item", "block_until_ready"}
# jax functions that force a device->host transfer / sync
_SYNC_FUNCS = {"jax.device_get", "device_get", "jax.block_until_ready"}


def _is_host_sync_call(call: ast.Call) -> Optional[str]:
    """Describe the host sync a Call performs, or None."""
    d = _dotted(call.func)
    if d in _SYNC_FUNCS:
        return f"`{d}` forces a device->host transfer"
    if isinstance(call.func, ast.Attribute) and call.func.attr in _SYNC_METHODS:
        return f"`.{call.func.attr}()` blocks on the device"
    return None


def _is_np_materialize(call: ast.Call) -> Optional[str]:
    d = _dotted(call.func)
    if "." in d:
        root, attr = d.split(".", 1)
        if root in _NP_NAMES and attr in ("asarray", "array", "copy"):
            return f"`{d}` materializes the operand on the host"
    return None


def _is_np_fetch(call: ast.Call) -> Optional[str]:
    """The device-fetch idiom: a bare single-argument `np.asarray(x)` /
    `np.array(x)` on a name.  In this codebase that shape is how jitted
    outputs come back to the host (blocking on the device), while host-side
    data conversions always pass a dtype (`np.asarray(p, np.int32)`) or a
    literal — those are skipped to keep the rule quiet off the hot path."""
    d = _dotted(call.func)
    if "." not in d:
        return None
    root, attr = d.split(".", 1)
    if (
        root in _NP_NAMES
        and attr in ("asarray", "array")
        and len(call.args) == 1
        and not call.keywords
        and isinstance(call.args[0], ast.Name)
    ):
        return f"`{d}` on a device value blocks on the device"
    return None


# ---------------------------------------------------------------------------
# host syncs
# ---------------------------------------------------------------------------


@rule(
    "host-sync-in-jit",
    "host transfer/sync (.item, device_get, np.asarray, ...) inside a jitted function",
)
def host_sync_in_jit(mod: ModuleInfo) -> Iterator[Finding]:
    for j in mod.jitted:
        for node in ast.walk(j.node):
            if not isinstance(node, ast.Call):
                continue
            why = _is_host_sync_call(node) or _is_np_materialize(node)
            if why:
                yield mod.finding(
                    "host-sync-in-jit",
                    node,
                    f"{why} inside jitted `{j.node.name}`; on a tracer this "
                    "either fails or silently falls back to per-call host "
                    "round-trips — keep the body device-only",
                )


@rule(
    "host-sync",
    "device_get/.item()/np.asarray-fetch on a hot path (worst inside a step loop)",
)
def host_sync(mod: ModuleInfo) -> Iterator[Finding]:
    jit_nodes = mod.jit_body_nodes()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or node in jit_nodes:
            continue
        loop = mod.enclosing_loop(node)
        why = _is_host_sync_call(node)
        if not why and loop is not None:
            # the np.asarray fetch idiom is only a hot-path hazard when it
            # repeats per iteration (serving/decode chunk loops); a one-shot
            # fetch after a loop is the recommended batched shape
            why = _is_np_fetch(node)
        if not why:
            continue
        where = (
            "inside a per-step loop — each iteration stalls the device "
            "pipeline for a full host round-trip"
            if loop is not None
            else "on the host path"
        )
        yield mod.finding(
            "host-sync",
            node,
            f"{why} {where}; hoist/batch it (one read per chunk, not per "
            "token), or suppress with a justification if the sync is the "
            "point",
        )


# ---------------------------------------------------------------------------
# tracer-branch
# ---------------------------------------------------------------------------


def _safe_name_use(mod: ModuleInfo, name_node: ast.Name) -> bool:
    """A use of a traced param inside a branch test that is NOT a trace-time
    value branch: attribute access (x.shape/x.ndim/x.dtype are concrete),
    `x is [not] None`, and isinstance(x, ...) are all static structure."""
    parent = mod.parents.get(name_node)
    if isinstance(parent, ast.Attribute):
        return True
    if isinstance(parent, ast.Compare):
        ops_ok = all(isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops)
        if ops_ok:
            return True
    if isinstance(parent, ast.Call):
        d = _dotted(parent.func)
        if d in ("isinstance", "len", "type", "hasattr", "getattr"):
            return True
    return False


@rule(
    "tracer-branch",
    "Python if/while on a traced jit argument (works only via retrace, or raises)",
)
def tracer_branch(mod: ModuleInfo) -> Iterator[Finding]:
    for j in mod.jitted:
        static = j.static_params()
        traced = set(j.param_names) - static
        if not traced:
            continue
        for node in ast.walk(j.node):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            for sub in ast.walk(node.test):
                if (
                    isinstance(sub, ast.Name)
                    and isinstance(sub.ctx, ast.Load)
                    and sub.id in traced
                    and not _safe_name_use(mod, sub)
                ):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield mod.finding(
                        "tracer-branch",
                        node,
                        f"Python `{kind}` on traced argument `{sub.id}` of "
                        f"jitted `{j.node.name}`: a tracer has no bool — this "
                        "raises at trace time (or recompiles per value if the "
                        "arg is made static); use lax.cond/jnp.where, or add "
                        f"`{sub.id}` to static_argnames only if its value set "
                        "is tiny and hashable",
                    )
                    break  # one finding per branch statement


# ---------------------------------------------------------------------------
# donation-after-use
# ---------------------------------------------------------------------------


def _stmt_chain(mod: ModuleInfo, node: ast.AST) -> Optional[ast.stmt]:
    """The statement directly containing `node`."""
    cur = node
    while cur in mod.parents:
        parent = mod.parents[cur]
        if hasattr(parent, "body") and isinstance(cur, ast.stmt):
            return cur
        cur = parent
    return None


def _names_loaded(node: ast.AST) -> Set[str]:
    return {
        n.id
        for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


def _names_stored(node: ast.AST) -> Set[str]:
    return {
        n.id
        for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
    }


@rule(
    "donation-after-use",
    "buffer passed at a donate_argnums position is read after the jitted call",
)
def donation_after_use(mod: ModuleInfo) -> Iterator[Finding]:
    # jitted callables resolvable by name within this module
    donors: dict = {}
    for j in mod.jitted:
        if j.spec.donate_argnums or j.spec.donate_argnames:
            donors[j.node.name] = j
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            spec = jit_spec_of_call(node.value)
            if spec is None or not (spec.donate_argnums or spec.donate_argnames):
                continue
            # name = jax.jit(f, donate_argnums=...) — alias carries the spec
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and node.value.args:
                    fn = node.value.args[0]
                    if isinstance(fn, ast.Name) and fn.id in donors:
                        donors[tgt.id] = donors[fn.id]

    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
            continue
        j = donors.get(node.func.id)
        if j is None:
            continue
        donated = j.donated_params()
        params = j.param_names
        donated_args: List[str] = []
        for i, arg in enumerate(node.args):
            if i < len(params) and params[i] in donated and isinstance(arg, ast.Name):
                donated_args.append(arg.id)
        for kw in node.keywords:
            if kw.arg in donated and isinstance(kw.value, ast.Name):
                donated_args.append(kw.value.id)
        if not donated_args:
            continue
        stmt = _stmt_chain(mod, node)
        if stmt is None:
            continue
        parent = mod.parents.get(stmt)
        body = getattr(parent, "body", None)
        if not isinstance(body, list) or stmt not in body:
            continue
        # donated name re-bound by the call's own statement (x = f(x)) is safe
        rebound = _names_stored(stmt)
        live = [n for n in donated_args if n not in rebound]
        for later in body[body.index(stmt) + 1 :]:
            if not live:
                break
            loaded = _names_loaded(later)
            for name in list(live):
                if name in loaded:
                    yield mod.finding(
                        "donation-after-use",
                        later,
                        f"`{name}` was donated to jitted `{j.node.name}` "
                        f"(line {stmt.lineno}) and is read afterwards: the "
                        "buffer is deleted by donation — rebind the result "
                        "or drop the donation",
                    )
                    live.remove(name)
            live = [n for n in live if n not in _names_stored(later)]


# ---------------------------------------------------------------------------
# recompile hazards
# ---------------------------------------------------------------------------

# names that in this codebase always carry float sampling/scaling knobs; a
# float static arg keys the jit cache on the VALUE (0.7 vs 0.8 = 2 compiles)
_FLOATY_NAMES = {
    "temperature", "top_p", "scale", "eps", "rate", "ratio",
    "threshold", "prob", "penalty", "alpha", "dropout",
}


def _param_is_floaty(fn: ast.FunctionDef, name: str) -> Optional[str]:
    a = fn.args
    params = a.posonlyargs + a.args + a.kwonlyargs
    defaults = list(a.defaults)
    # align defaults with the tail of posonly+args
    pos = a.posonlyargs + a.args
    default_of = {}
    for p, d in zip(pos[len(pos) - len(defaults) :], defaults):
        default_of[p.arg] = d
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if d is not None:
            default_of[p.arg] = d
    for p in params:
        if p.arg != name:
            continue
        ann = getattr(p, "annotation", None)
        if ann is not None and _dotted(ann) == "float":
            return "annotated `float`"
        d = default_of.get(name)
        if isinstance(d, ast.Constant) and isinstance(d.value, float):
            return f"float default {d.value!r}"
        if name in _FLOATY_NAMES:
            return "a float-valued knob by convention"
    return None


@rule(
    "static-float-arg",
    "static_argnames/nums entry that carries a float (one XLA compile per distinct value)",
)
def static_float_arg(mod: ModuleInfo) -> Iterator[Finding]:
    for j in mod.jitted:
        params = j.param_names
        statics = set(j.spec.static_argnames)
        for i in j.spec.static_argnums:
            if 0 <= i < len(params):
                statics.add(params[i])
        for name in sorted(statics):
            why = _param_is_floaty(j.node, name)
            if why:
                anchor = j.spec.call if j.spec.call is not None else j.node
                yield mod.finding(
                    "static-float-arg",
                    anchor,
                    f"static arg `{name}` of jitted `{j.node.name}` is {why}: "
                    "the jit cache keys on its value, so every distinct "
                    "float triggers a full recompile — pass it as a traced "
                    "operand (see ops/sampling.py sample_traced)",
                )


@rule(
    "jit-in-loop",
    "jax.jit called inside a loop body (fresh cache per iteration = recompile every time)",
)
def jit_in_loop(mod: ModuleInfo) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        spec = None
        anchor = node
        if isinstance(node, ast.Call):
            spec = jit_spec_of_call(node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                spec = jit_spec_of_decorator(dec)
                if spec is not None:
                    break
        if spec is None:
            continue
        if mod.enclosing_loop(anchor) is not None:
            yield mod.finding(
                "jit-in-loop",
                anchor,
                "jit created inside a loop body: each iteration builds a "
                "fresh wrapper with an empty cache, so every call recompiles "
                "— hoist the jit out of the loop (cache it on the instance "
                "like generation.py's `_decode_fns`)",
            )


# ---------------------------------------------------------------------------
# dtype hygiene
# ---------------------------------------------------------------------------

_LAX_BINOPS = {
    "add", "sub", "mul", "div", "max", "min", "pow", "rem", "atan2",
    "eq", "ne", "lt", "le", "gt", "ge",
}


@rule(
    "lax-scalar-operand",
    "bare Python number passed to a strict jax.lax binary op (dtype promotion trap)",
)
def lax_scalar_operand(mod: ModuleInfo) -> Iterator[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        parts = d.split(".")
        if len(parts) < 2 or parts[-2] != "lax" or parts[-1] not in _LAX_BINOPS:
            continue
        for arg in node.args:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, (int, float)):
                yield mod.finding(
                    "lax-scalar-operand",
                    arg,
                    f"bare Python scalar {arg.value!r} passed to `{d}`: lax "
                    "ops are strict about dtypes — a weak f64/f32 scalar "
                    "either errors or silently upcasts a bf16 model value; "
                    "wrap it with jnp.asarray(x, operand.dtype)",
                )


# ---------------------------------------------------------------------------
# closures over module state
# ---------------------------------------------------------------------------

_MUTABLE_CTORS = {"list", "dict", "set", "defaultdict", "deque", "Counter", "OrderedDict"}


def _module_mutable_globals(mod: ModuleInfo) -> Set[str]:
    out: Set[str] = set()
    for stmt in mod.tree.body:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if value is None:
            continue
        mutable = isinstance(value, (ast.List, ast.Dict, ast.Set)) or (
            isinstance(value, ast.Call)
            and _dotted(value.func).split(".")[-1] in _MUTABLE_CTORS
        )
        if not mutable:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


@rule(
    "mutable-global-in-jit",
    "module-level mutable state captured by a jitted function (baked in at trace time)",
)
def mutable_global_in_jit(mod: ModuleInfo) -> Iterator[Finding]:
    mutables = _module_mutable_globals(mod)
    if not mutables:
        return
    for j in mod.jitted:
        locals_: Set[str] = set(j.param_names)
        for n in ast.walk(j.node):
            locals_ |= _names_stored(n)
        seen: Set[str] = set()  # one finding per (fn, global) is plenty
        for n in ast.walk(j.node):
            if (
                isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load)
                and n.id in mutables
                and n.id not in locals_
                and n.id not in seen
            ):
                seen.add(n.id)
                yield mod.finding(
                    "mutable-global-in-jit",
                    n,
                    f"jitted `{j.node.name}` closes over module-level mutable "
                    f"`{n.id}`: its contents are baked in at trace time — "
                    "later mutations are silently ignored by the compiled "
                    "program; pass it as an argument instead",
                )


# ---------------------------------------------------------------------------
# timing hygiene
# ---------------------------------------------------------------------------

# wall-clock sources whose value inside a traced function is the TRACE
# time, baked into the compiled program as a constant — not the run time
_TIMING_FUNCS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.perf_counter_ns", "time.monotonic_ns",
    "time.time_ns", "datetime.now", "datetime.datetime.now",
    "datetime.utcnow", "datetime.datetime.utcnow",
}


@rule(
    "timing-in-jit",
    "wall-clock call (time.perf_counter/time.time/...) inside a jitted function "
    "(measures trace time, not run time)",
)
def timing_in_jit(mod: ModuleInfo) -> Iterator[Finding]:
    for j in mod.jitted:
        for node in ast.walk(j.node):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d not in _TIMING_FUNCS:
                continue
            yield mod.finding(
                "timing-in-jit",
                node,
                f"`{d}()` inside jitted `{j.node.name}` runs ONCE at trace "
                "time and is baked into the executable as a constant: it "
                "measures tracing, not the compiled run (and the steady "
                "state never re-evaluates it) — time on the host around "
                "the jitted call at a sync boundary (the obs/ serving "
                "observer pattern), or use jax.profiler for device spans",
            )

# a public ops/ function whose body performs at least this many jax-namespace
# calls is a "kernel" and must open a named_scope so device traces (and
# CompileGuard investigations) attribute its cost
_NAMED_SCOPE_MIN_OPS = 8
_JAX_ROOTS = {"jnp", "jax", "lax", "pl", "pltpu", "plgpu"}


def _jax_op_calls(fn: ast.FunctionDef) -> int:
    n = 0
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d.split(".")[0] in _JAX_ROOTS:
                n += 1
    return n


def _has_named_scope(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d.endswith("named_scope") or d.endswith("annotate_function") or (
                "profiler" in d and d.endswith("TraceAnnotation")
            ):
                return True
    return False


@rule(
    "missing-named-scope",
    "public ops/ kernel without a jax.named_scope (invisible in device traces)",
)
def missing_named_scope(mod: ModuleInfo) -> Iterator[Finding]:
    if "ops/" not in mod.path.replace("\\", "/"):
        return
    for stmt in mod.tree.body:
        if not isinstance(stmt, ast.FunctionDef) or stmt.name.startswith("_"):
            continue
        if _jax_op_calls(stmt) < _NAMED_SCOPE_MIN_OPS:
            continue
        if not _has_named_scope(stmt):
            yield mod.finding(
                "missing-named-scope",
                stmt,
                f"public kernel `{stmt.name}` never opens a jax.named_scope: "
                "its ops are anonymous in TensorBoard/Perfetto device traces "
                "— wrap the body in `with jax.named_scope(...)`",
            )
