"""`mdi-flow`: jaxpr buffer-liveness analysis of the serving compile set.

The fifth analysis family, after mdi-lint (source AST), mdi-audit
(plan/shape arithmetic), mdi-race (thread roles) and mdi-ir (trace
hygiene): a backend-free data-flow pass over the abstract jaxprs of every
executable the serving engine can dispatch.  mdi-ir proves WHAT compiles
(compile-set closure, donation marks, IR hygiene); mdi-flow proves WHAT
IS LIVE WHEN — per-buffer live ranges through `scan`/`while`/`cond`
sub-jaxprs and the pp ring's `shard_map` bodies, donation-aware aliasing,
and a static peak-HBM high-water per executable.  Peak memory today is
either a heuristic (mdi-audit's analytic activation term) or observed
only after a real XLA compile (`memory_analysis`), so a live-range or
donation regression ships silently and surfaces as an OOM on hardware we
rarely have; this pass makes the byte claims provable in CI with zero
backend compiles and zero device transfers (only `jitted.trace(...)`
over `ShapeDtypeStruct`s — it never even `.lower()`s).

The static model mirrors XLA's `memory_analysis` accounting
(args + outputs + temps − donation aliases) so it can be CALIBRATED, not
just plausible:

- **arguments / outputs** — summed over the flat jaxpr invars/outvars;
  donated inputs greedily matched to outputs by (shape, dtype) dedupe as
  `alias_bytes` exactly like XLA's input-output aliasing.
- **temps** — a def/last-use liveness sweep over every equation:
  interior values (neither invars nor outvars) contribute bytes from
  their defining equation to their last read; a nested jaxpr (scan body,
  while/cond branch, pjit call, shard_map region) contributes its OWN
  interior peak at the enclosing equation's program point — one
  allocation per body, matching XLA's loop-body buffer reuse.
- **per-device attribution** — input/output leaves divide by the mesh
  axis sizes their declared sharding actually divides (the kv pool's
  `NamedSharding` rides on the `ShapeDtypeStruct`s; params scale by the
  Megatron `param_specs` fraction); `shard_map` interiors are already
  per-shard by construction; other interiors are counted whole —
  conservative, never optimistic.

The calibration test (tests/test_flow.py) compiles the real mixed and
decode_chunk executables on CPU and pins the static high-water within a
CI tolerance of XLA's own `memory_analysis` — in float32, because the
CPU backend materializes f32 upcasts of bf16 params (an emulation
artifact TPUs don't have).

Rules (FLOW_RULES):

- **missed-donation** [warning] — a large (>= `--min-bytes`) non-donated
  input whose (shape, dtype) matches an output no donated buffer aliases:
  donating it would drop a whole buffer from the high-water.
- **live-range-bloat** [warning] — a large buffer threaded through a
  `scan`/`while`/`cond`/`shard_map` whose body never reads it: the
  extending site (primitive + equation) holds it live across every
  iteration for nothing — dead carry/operand payload.
- **hbm-over-budget** [error] — the engine's per-device static
  high-water (params + paged pool via the byte-exact `ServingConfig`
  formulas, plus the worst executable's live temps) exceeds `--hbm-gb`.
- **peak-memory-regression** [error] — an executable's static peak grew
  beyond the committed golden budget (goldens/flow-goldens.json) by more
  than the tolerance; `--update-goldens` re-baselines deliberately.
- **jaxpr-drift** [warning] — an executable's canonicalized jaxpr digest
  no longer matches the committed golden; the finding carries an
  op-level diff (primitive-count deltas) so silent IR churn becomes a
  reviewable artifact.
- **trace-failure** [error] — an enumerated executable refused to trace
  abstractly; no liveness claim can be made about it.

CLI: ``mdi-flow --model pythia-14m --tp 2`` (or ``python -m
mdi_llm_tpu.analysis flow ...``); ``--hbm-gb``, ``--goldens`` /
``--update-goldens``, ``--min-bytes``, ``--format json``, ``--baseline``
/ ``--update-baseline``, ``--suppress RULE=justification``,
``--list-checks``.  Exit 0 clean, 1 on findings, 2 on usage errors.
Wired as a bench / mdi-serve preflight via `flow_preflight` +
`enforce_flow_preflight` (`detail.liveness` per serve row), and into the
`mdi-check` aggregate gate.  See docs/analysis.md, "Buffer liveness
(mdi-flow)".
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import re
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mdi_llm_tpu.analysis.core import Baseline, Finding
from mdi_llm_tpu.analysis.ir import (
    _iter_jaxprs,
    sharding_denom,
    trace_serving,
)
from mdi_llm_tpu.config import Config, ServingConfig

__all__ = [
    "FLOW_RULES",
    "ExecProfile",
    "FlowReport",
    "analyze_flow",
    "enforce_flow_preflight",
    "flow_detail",
    "flow_preflight",
    "jaxpr_digest",
    "load_goldens",
    "main",
    "profile_executable",
    "write_goldens",
]

ERROR, WARNING = "error", "warning"

# rule -> (severity, one-line summary); --list-checks prints this
FLOW_RULES: Dict[str, Tuple[str, str]] = {
    "missed-donation": (WARNING, (
        "a large non-donated input's (shape, dtype) matches an un-aliased "
        "output: donating it would drop one whole buffer from the static "
        "high-water"
    )),
    "live-range-bloat": (WARNING, (
        "a large buffer is threaded through a scan/while/cond/shard_map "
        "whose body never reads it: the extending site holds it live "
        "across every iteration as dead payload"
    )),
    "hbm-over-budget": (ERROR, (
        "the per-device static high-water (params + pool + worst "
        "executable's live temps) exceeds the --hbm-gb budget"
    )),
    "peak-memory-regression": (ERROR, (
        "an executable's static peak grew beyond its committed golden "
        "budget by more than the tolerance (--update-goldens re-baselines "
        "deliberately)"
    )),
    "jaxpr-drift": (WARNING, (
        "an executable's canonical jaxpr digest drifted from the "
        "committed golden; the finding carries the op-level diff"
    )),
    "trace-failure": (ERROR, (
        "an enumerated executable refused to trace abstractly — no "
        "liveness claim can be made about it"
    )),
}

DEFAULT_MIN_BYTES = 1 * 1024 * 1024  # missed-donation / live-range-bloat
# floor: engine control operands (tables, positions, keys) sit far below
# 1 MiB; params and pool leaves sit far above
DEFAULT_GOLDEN_TOLERANCE = 0.10  # peak-memory-regression trip point
GiB = float(1024**3)

DEFAULT_GOLDENS = Path("goldens") / "flow-goldens.json"  # repo-root relative

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


# ---------------------------------------------------------------------------
# byte accounting over avals
# ---------------------------------------------------------------------------


def _itemsize(dtype) -> int:
    try:
        return np.dtype(dtype).itemsize
    except TypeError:
        # extended dtypes (jax PRNG key<fry> etc.) refuse np.dtype; their
        # physical layout is a pair of uint32s
        return int(getattr(dtype, "itemsize", None) or 8)


def _aval_nbytes(v) -> int:
    aval = getattr(v, "aval", v)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(np.prod(shape, dtype=np.int64)) * _itemsize(dtype)


def _aval_sig(v) -> Tuple[Tuple[int, ...], str]:
    aval = getattr(v, "aval", v)
    return tuple(getattr(aval, "shape", ())), str(getattr(aval, "dtype", "?"))


def _fmt_bytes(n: int) -> str:
    return f"{n / 2**20:.1f} MiB" if n >= 2**20 else f"{n} B"


def _fmt_sig(v) -> str:
    shape, dtype = _aval_sig(v)
    return f"{dtype}{shape}"


def _is_var(v) -> bool:
    """True for jaxpr Vars (things with a live range); Literals and
    DropVars have none."""
    name = type(v).__name__
    return name not in ("Literal", "DropVar") and hasattr(v, "aval")


# ---------------------------------------------------------------------------
# liveness sweep
# ---------------------------------------------------------------------------


def _sub_jaxprs(eqn) -> List[Any]:
    """The inner Jaxpr objects of one equation (scan/while/cond bodies,
    pjit calls, shard_map regions, custom_* rules) — duck-typed like
    ir._iter_jaxprs, so no jax-internal imports."""
    out: List[Any] = []
    for val in eqn.params.values():
        vals = val if isinstance(val, (list, tuple)) else (val,)
        for v in vals:
            inner = getattr(v, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                out.append(inner)
            elif hasattr(v, "eqns"):
                out.append(v)
    return out


def interior_peak_bytes(jaxpr) -> int:
    """Peak bytes of equation-defined temporaries live at any program
    point of `jaxpr`, nested jaxprs contributing their own interior peak
    at the enclosing equation's point (one allocation per loop body —
    XLA reuses body buffers across iterations).  This jaxpr's
    invars/constvars/outvars are excluded: the caller accounts for them
    (as arguments/outputs at the top level, as operands one level up
    otherwise)."""
    eqns = list(jaxpr.eqns)
    n = len(eqns)
    if n == 0:
        return 0
    outset = {id(v) for v in jaxpr.outvars if _is_var(v)}
    defpt: Dict[int, int] = {}
    lastuse: Dict[int, int] = {}
    var_bytes: Dict[int, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            if not _is_var(v):
                continue
            defpt[id(v)] = i
            var_bytes[id(v)] = _aval_nbytes(v)
        for v in eqn.invars:
            if _is_var(v) and id(v) in defpt:
                lastuse[id(v)] = i
    for vid, d in defpt.items():
        lastuse.setdefault(vid, d)
    inner = [
        sum(interior_peak_bytes(j) for j in _sub_jaxprs(e)) for e in eqns
    ]
    delta = [0] * (n + 1)
    for vid, d in defpt.items():
        if vid in outset:
            continue  # an output, not a temp — the caller counts it
        delta[d] += var_bytes[vid]
        delta[lastuse[vid] + 1] -= var_bytes[vid]
    peak = cur = 0
    for i in range(n):
        cur += delta[i]
        peak = max(peak, cur + inner[i])
    return peak


# ---------------------------------------------------------------------------
# donation aliasing
# ---------------------------------------------------------------------------


def _flat_arg_meta(spec) -> Tuple[List[Any], List[int], List[Optional[str]]]:
    """Flatten `spec.args` to leaves aligned with the jaxpr's flat invars.
    Returns (leaves, argnum-per-leaf, role-per-leaf)."""
    import jax

    leaves: List[Any] = []
    argnums: List[int] = []
    roles: List[Optional[str]] = []
    role_map = dict(getattr(spec, "roles", None) or {})
    for argnum, arg in enumerate(spec.args):
        for leaf in jax.tree_util.tree_leaves(arg):
            leaves.append(leaf)
            argnums.append(argnum)
            roles.append(role_map.get(argnum))
    return leaves, argnums, roles


def _alias_matching(
    jaxpr, donate: Sequence[int], argnums: List[int]
) -> Tuple[int, List[int], List[bool]]:
    """Greedily match donated input leaves to outputs by (shape, dtype) —
    the same dedupe XLA's input-output aliasing performs.  Returns
    (alias_bytes, per-invar alias bytes, per-outvar matched flags).
    Pass-through outvars (an outvar that IS an invar) are skipped on both
    sides: aliasing them frees nothing."""
    invars = list(jaxpr.invars)
    in_ids = {id(v) for v in invars}
    matched_out = [False] * len(jaxpr.outvars)
    avail: Dict[Tuple[Tuple[int, ...], str], List[int]] = {}
    for j, ov in enumerate(jaxpr.outvars):
        if not _is_var(ov) or id(ov) in in_ids:
            matched_out[j] = True  # pass-through: not an alias target
            continue
        avail.setdefault(_aval_sig(ov), []).append(j)
    alias_per_invar = [0] * len(invars)
    donate_set = set(int(d) for d in donate or ())
    total = 0
    for i, iv in enumerate(invars):
        if i >= len(argnums) or argnums[i] not in donate_set:
            continue
        slots = avail.get(_aval_sig(iv))
        if slots:
            j = slots.pop(0)
            matched_out[j] = True
            alias_per_invar[i] = _aval_nbytes(iv)
            total += alias_per_invar[i]
    return total, alias_per_invar, matched_out


# ---------------------------------------------------------------------------
# rules over one executable
# ---------------------------------------------------------------------------


def _check_missed_donation(
    spec, jaxpr, argnums, matched_out, path: str, min_bytes: int
) -> List[Finding]:
    """Non-donated inputs >= min_bytes whose signature matches an output
    that no donated buffer already aliases."""
    avail: Dict[Tuple[Tuple[int, ...], str], int] = {}
    for j, ov in enumerate(jaxpr.outvars):
        if not matched_out[j] and _is_var(ov):
            sig = _aval_sig(ov)
            avail[sig] = avail.get(sig, 0) + 1
    if not avail:
        return []
    donate_set = set(int(d) for d in spec.donate or ())
    out: List[Finding] = []
    for i, iv in enumerate(jaxpr.invars):
        if i < len(argnums) and argnums[i] in donate_set:
            continue
        nb = _aval_nbytes(iv)
        if nb < min_bytes:
            continue
        sig = _aval_sig(iv)
        if avail.get(sig, 0) <= 0:
            continue
        avail[sig] -= 1
        argn = argnums[i] if i < len(argnums) else i
        out.append(Finding(
            rule="missed-donation", path=path, line=0, col=0,
            message=(
                f"{spec.name} takes a {_fmt_bytes(nb)} {_fmt_sig(iv)} "
                f"input (argnum {argn}) and returns a same-signature "
                "output without donating it: both copies stay live — add "
                f"argnum {argn} to donate_argnums to drop "
                f"{_fmt_bytes(nb)} from the high-water"
            ),
            line_text=f"missed-donation:{argn}:{_fmt_sig(iv)}",
        ))
    return out


_LOOP_PRIMS = ("scan", "while", "cond", "shard_map", "pjit")


def _loop_bindings(eqn) -> List[Tuple[Any, List[Any]]]:
    """Map each outer operand of a structured-control equation to the
    inner invars that receive it, per the primitive's binding rule.
    Returns [] for primitives we don't model (nothing is flagged)."""
    prim = eqn.primitive.name
    try:
        if prim == "scan":
            inner = eqn.params["jaxpr"].jaxpr.invars
            if len(inner) != len(eqn.invars):
                return []
            return [(ov, [iv]) for ov, iv in zip(eqn.invars, inner)]
        if prim == "while":
            cc = int(eqn.params["cond_nconsts"])
            bc = int(eqn.params["body_nconsts"])
            cond = eqn.params["cond_jaxpr"].jaxpr.invars
            body = eqn.params["body_jaxpr"].jaxpr.invars
            out: List[Tuple[Any, List[Any]]] = []
            for i, ov in enumerate(eqn.invars):
                if i < cc:
                    out.append((ov, [cond[i]]))
                elif i < cc + bc:
                    out.append((ov, [body[i - cc]]))
                else:
                    j = i - cc - bc
                    out.append((ov, [cond[cc + j], body[bc + j]]))
            return out
        if prim == "cond":
            branches = eqn.params["branches"]
            operands = eqn.invars[1:]  # invars[0] is the branch index
            if any(
                len(b.jaxpr.invars) != len(operands) for b in branches
            ):
                return []
            return [
                (ov, [b.jaxpr.invars[j] for b in branches])
                for j, ov in enumerate(operands)
            ]
        if prim in ("shard_map", "pjit"):
            inner = eqn.params["jaxpr"].jaxpr.invars
            if len(inner) != len(eqn.invars):
                return []
            return [(ov, [iv]) for ov, iv in zip(eqn.invars, inner)]
    except (KeyError, AttributeError, TypeError):
        return []
    return []


def _inner_used_ids(jaxprs: List[Any]) -> set:
    """ids of vars READ by at least one equation of the given jaxprs (a
    pass-through carry — invar straight to outvar — does not count as a
    read: that is exactly the dead-payload shape live-range-bloat
    flags)."""
    used: set = set()
    for j in jaxprs:
        for eqn in j.eqns:
            for v in eqn.invars:
                if _is_var(v):
                    used.add(id(v))
    return used


def _check_live_range_bloat(
    spec, closed, path: str, min_bytes: int
) -> List[Finding]:
    out: List[Finding] = []
    seen: set = set()
    for jaxpr, _ in _iter_jaxprs(closed):
        for idx, eqn in enumerate(jaxpr.eqns):
            if eqn.primitive.name not in _LOOP_PRIMS:
                continue
            bindings = _loop_bindings(eqn)
            if not bindings:
                continue
            used = _inner_used_ids(_sub_jaxprs(eqn))
            for ov, inner_vars in bindings:
                if not _is_var(ov):
                    continue
                nb = _aval_nbytes(ov)
                if nb < min_bytes:
                    continue
                if any(id(iv) in used for iv in inner_vars):
                    continue
                key = (id(eqn), id(ov))
                if key in seen:
                    continue
                seen.add(key)
                prim = eqn.primitive.name
                out.append(Finding(
                    rule="live-range-bloat", path=path, line=0, col=0,
                    message=(
                        f"{spec.name} threads a {_fmt_bytes(nb)} "
                        f"{_fmt_sig(ov)} buffer through `{prim}` (eqn "
                        f"#{idx}) whose body never reads it: the {prim} "
                        "holds it live across every iteration as dead "
                        "carry/operand payload — drop it from the "
                        "operands"
                    ),
                    line_text=f"bloat:{prim}:{_fmt_sig(ov)}",
                ))
    return out


# ---------------------------------------------------------------------------
# canonical digests (golden jaxpr hashes)
# ---------------------------------------------------------------------------


def jaxpr_digest(closed) -> Tuple[str, Dict[str, int]]:
    """(canonical digest, primitive-name counts) for a ClosedJaxpr.  The
    digest hashes the jaxpr's pretty-printed form with memory addresses
    scrubbed (function reprs inside custom_jvp/callback params embed
    `0x...`), so it is stable across processes; the op counts feed the
    human-reviewable diff when a golden digest drifts."""
    text = _ADDR_RE.sub("0x~", str(getattr(closed, "jaxpr", closed)))
    digest = hashlib.sha256(text.encode("utf-8", "replace")).hexdigest()[:16]
    ops: Dict[str, int] = {}
    for jaxpr, _ in _iter_jaxprs(closed):
        for eqn in jaxpr.eqns:
            ops[eqn.primitive.name] = ops.get(eqn.primitive.name, 0) + 1
    return digest, ops


def _op_diff(golden: Dict[str, int], current: Dict[str, int]) -> str:
    deltas = []
    for op in sorted(set(golden) | set(current)):
        d = current.get(op, 0) - golden.get(op, 0)
        if d:
            deltas.append(f"{'+' if d > 0 else ''}{d} {op}")
    return ", ".join(deltas) if deltas else "op counts unchanged"


# ---------------------------------------------------------------------------
# per-device attribution
# ---------------------------------------------------------------------------


_sharding_denom = sharding_denom  # shared with mdi-ir (analysis/ir.py)


def _params_device_fraction(gen) -> Optional[float]:
    """Per-device fraction of the param bytes under the generator's mesh
    (Megatron `param_specs` adapted to the storage tree — the same
    arithmetic mdi-audit budgets with).  None when there is no mesh or
    the spec tree doesn't cover the params (callers then fall back to
    whole-leaf counting)."""
    mesh = getattr(gen, "mesh", None)
    if mesh is None:
        return None
    try:
        sizes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
        if all(s <= 1 for s in sizes.values()):
            return None
        from mdi_llm_tpu.analysis.audit import _sharded_nbytes
        from mdi_llm_tpu.analysis.plan import iter_leaves
        from mdi_llm_tpu.parallel.sharding import (
            adapt_specs_to_tree,
            param_specs,
        )

        tp_axis = "tp" if sizes.get("tp", 1) > 1 else None
        specs = adapt_specs_to_tree(
            param_specs(gen.cfg, tp_axis=tp_axis), gen.params,
            axis_sizes=sizes,
        )
        pairs = [
            (leaf, spec)
            for (_, leaf), (_, spec) in zip(
                iter_leaves(gen.params), iter_leaves(specs)
            )
        ]
        total = sum(int(leaf.nbytes) for leaf, _ in pairs)
        if not total:
            return None
        dev = sum(
            _sharded_nbytes(leaf, spec if spec is not None else (), sizes)
            for leaf, spec in pairs
        )
        return dev / total
    except Exception:
        return None  # conservative: count params whole per device


# ---------------------------------------------------------------------------
# one executable's profile
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ExecProfile:
    """The liveness profile of ONE executable: the static byte model
    (args + outputs − aliases + interior temp peak, global and
    per-device) plus the canonical jaxpr digest."""

    name: str
    label: str
    key: Tuple
    argument_bytes: int
    output_bytes: int
    alias_bytes: int
    temp_peak_bytes: int
    device_argument_bytes: int
    device_output_bytes: int
    device_alias_bytes: int
    digest: str
    ops: Dict[str, int]
    eqns: int

    @property
    def peak_bytes(self) -> int:
        return (self.argument_bytes + self.output_bytes
                - self.alias_bytes + self.temp_peak_bytes)

    @property
    def device_peak_bytes(self) -> int:
        # interior temps are counted whole per device (shard_map bodies
        # are already per-shard; GSPMD-partitioned interiors are not
        # statically attributable — conservative, never optimistic)
        return (self.device_argument_bytes + self.device_output_bytes
                - self.device_alias_bytes + self.temp_peak_bytes)

    def as_record(self) -> Dict[str, Any]:
        return {
            "name": self.name, "label": self.label, "key": list(self.key),
            "eqns": self.eqns,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "alias_bytes": self.alias_bytes,
            "temp_peak_bytes": self.temp_peak_bytes,
            "peak_bytes": self.peak_bytes,
            "device_peak_bytes": self.device_peak_bytes,
            "digest": self.digest,
        }


def profile_executable(
    spec,
    closed=None,
    params_fraction: Optional[float] = None,
) -> ExecProfile:
    """Build the liveness profile of one `ExecutableSpec` from its
    (already traced, or traced here) closed jaxpr.  Pure host-side jaxpr
    arithmetic: no lowering, no backend, no devices."""
    if closed is None:
        closed = spec.fn.trace(*spec.args, **(spec.static_kwargs or {})).jaxpr
    jaxpr = closed.jaxpr
    leaves, argnums, roles = _flat_arg_meta(spec)
    if len(leaves) != len(jaxpr.invars):  # defensive: stay total
        leaves = list(jaxpr.invars)
        argnums = list(range(len(leaves)))
        roles = [None] * len(leaves)
    args_b = sum(_aval_nbytes(v) for v in jaxpr.invars)
    out_b = sum(_aval_nbytes(v) for v in jaxpr.outvars)
    alias_b, alias_per_invar, matched_out = _alias_matching(
        jaxpr, spec.donate or (), argnums
    )
    dev_args = dev_alias = 0
    for i, iv in enumerate(jaxpr.invars):
        nb = _aval_nbytes(iv)
        denom = _sharding_denom(leaves[i]) if i < len(leaves) else 1
        if denom > 1:
            dnb = nb // denom
        elif (i < len(roles) and roles[i] == "params"
              and params_fraction is not None):
            dnb = int(nb * params_fraction)
        else:
            dnb = nb
        dev_args += dnb
        if alias_per_invar[i]:
            dev_alias += dnb
    dev_out = 0
    out_in_ids = {id(v): i for i, v in enumerate(jaxpr.invars)}
    for j, ov in enumerate(jaxpr.outvars):
        nb = _aval_nbytes(ov)
        # an output aliased from a donated input shards like the input;
        # other outputs divide by their own declared sharding if the
        # aval carries one (it usually doesn't — counted whole)
        i = out_in_ids.get(id(ov))
        denom = _sharding_denom(leaves[i]) if i is not None and i < len(
            leaves
        ) else 1
        dev_out += nb // denom if denom > 1 else nb
    temp_peak = interior_peak_bytes(jaxpr)
    digest, ops = jaxpr_digest(closed)
    return ExecProfile(
        name=spec.name, label=spec.label, key=tuple(spec.key),
        argument_bytes=int(args_b), output_bytes=int(out_b),
        alias_bytes=int(alias_b), temp_peak_bytes=int(temp_peak),
        device_argument_bytes=int(dev_args),
        device_output_bytes=int(dev_out),
        device_alias_bytes=int(dev_alias),
        digest=digest, ops=ops,
        eqns=sum(len(j.eqns) for j, _ in _iter_jaxprs(closed)),
    )


# ---------------------------------------------------------------------------
# goldens (budgets + digests)
# ---------------------------------------------------------------------------


def load_goldens(path: Path) -> Dict[str, Any]:
    data = json.loads(Path(path).read_text())
    if not isinstance(data, dict) or "budgets" not in data:
        raise ValueError(f"{path}: not a flow goldens file (no 'budgets')")
    return data


def write_goldens(
    path: Path,
    origin: str,
    profiles: Sequence[ExecProfile],
    tolerance: float = DEFAULT_GOLDEN_TOLERANCE,
) -> Dict[str, Any]:
    """Merge this origin's budgets/digests into the goldens file (other
    origins' entries are preserved — the file accumulates the registry
    models' compile set one `--update-goldens` run at a time)."""
    path = Path(path)
    try:
        data = load_goldens(path)
    except (OSError, ValueError, json.JSONDecodeError):
        data = {"version": 1, "tolerance": tolerance, "budgets": {}}
    for p in profiles:
        data["budgets"][f"{origin}::{p.name}"] = {
            "peak_bytes": p.peak_bytes,
            "device_peak_bytes": p.device_peak_bytes,
            "digest": p.digest,
            "ops": dict(sorted(p.ops.items())),
        }
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(data, indent=2, sort_keys=True) + "\n")
    return data


def _check_goldens(
    profiles: Sequence[ExecProfile],
    goldens: Dict[str, Any],
    origin: str,
    tolerance: Optional[float] = None,
) -> List[Finding]:
    tol = tolerance if tolerance is not None else float(
        goldens.get("tolerance", DEFAULT_GOLDEN_TOLERANCE)
    )
    budgets = goldens.get("budgets", {})
    out: List[Finding] = []
    for p in profiles:
        key = f"{origin}::{p.name}"
        entry = budgets.get(key)
        if entry is None:
            continue  # no committed budget for this tuple — nothing to pin
        path = f"{origin}::{p.name}"
        golden_peak = int(entry.get("peak_bytes", 0))
        if golden_peak and p.peak_bytes > golden_peak * (1 + tol):
            grew = p.peak_bytes / golden_peak - 1
            out.append(Finding(
                rule="peak-memory-regression", path=path, line=0, col=0,
                message=(
                    f"{p.name} static peak {_fmt_bytes(p.peak_bytes)} is "
                    f"{grew:+.1%} over its golden budget "
                    f"{_fmt_bytes(golden_peak)} (tolerance {tol:.0%}): a "
                    "live-range or donation regression — fix it, or "
                    "re-baseline deliberately with --update-goldens"
                ),
                line_text=f"regression:{p.name}",
            ))
        golden_digest = entry.get("digest")
        if golden_digest and golden_digest != p.digest:
            diff = _op_diff(entry.get("ops", {}), p.ops)
            out.append(Finding(
                rule="jaxpr-drift", path=path, line=0, col=0,
                message=(
                    f"{p.name} canonical jaxpr digest {p.digest} != "
                    f"golden {golden_digest}; op-level diff: {diff} "
                    "(review the IR churn, then --update-goldens)"
                ),
                line_text=f"drift:{p.name}",
            ))
    return out


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def analyze_flow(
    specs: Sequence[Any],
    origin: str = "<specs>",
    min_bytes: int = DEFAULT_MIN_BYTES,
    params_fraction: Optional[float] = None,
) -> Tuple[List[Finding], List[ExecProfile]]:
    """Trace every `ExecutableSpec`, build its liveness profile, and run
    the per-executable rules (missed-donation, live-range-bloat).
    Returns (findings, profiles)."""
    findings: List[Finding] = []
    profiles: List[ExecProfile] = []
    for spec in specs:
        path = f"{origin}::{spec.name}"
        try:
            closed = spec.fn.trace(
                *spec.args, **(spec.static_kwargs or {})
            ).jaxpr
        except Exception as e:
            findings.append(Finding(
                rule="trace-failure", path=path, line=0, col=0,
                message=f"{spec.name} failed to trace abstractly: {e}",
                line_text="trace",
            ))
            continue
        profile = profile_executable(
            spec, closed, params_fraction=params_fraction
        )
        profiles.append(profile)
        leaves, argnums, _roles = _flat_arg_meta(spec)
        if len(leaves) != len(closed.jaxpr.invars):
            argnums = list(range(len(closed.jaxpr.invars)))
        _, _, matched_out = _alias_matching(
            closed.jaxpr, spec.donate or (), argnums
        )
        findings += _check_missed_donation(
            spec, closed.jaxpr, argnums, matched_out, path, min_bytes
        )
        findings += _check_live_range_bloat(spec, closed, path, min_bytes)
    return findings, profiles


@dataclasses.dataclass
class FlowReport:
    """One mdi-flow pass: findings + the per-executable liveness
    profiles."""

    origin: str
    findings: List[Finding]
    profiles: List[ExecProfile]
    breakdown: Dict[str, Any] = dataclasses.field(default_factory=dict)
    suppressed: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list
    )

    def severity(self, f: Finding) -> str:
        return FLOW_RULES.get(f.rule, (ERROR, ""))[0]

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if self.severity(f) == ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if self.severity(f) == WARNING]

    def suppress(self, reasons: Dict[str, str]) -> None:
        keep: List[Finding] = []
        for f in self.findings:
            reason = reasons.get(f.rule)
            if reason:
                self.suppressed.append({
                    "rule": f.rule, "path": f.path, "message": f.message,
                    "justification": reason,
                })
            else:
                keep.append(f)
        self.findings = keep

    def render_findings(self) -> List[str]:
        return [
            f"{f.path}: {self.severity(f)}: {f.rule}: {f.message}"
            for f in self.findings
        ]

    def render_text(self) -> str:
        lines = [f"liveness: {self.origin}"]
        for p in self.profiles:
            lines.append(
                f"  {p.name:<24} peak={p.peak_bytes / 2**20:8.1f} MiB  "
                f"(args={p.argument_bytes / 2**20:.1f} "
                f"out={p.output_bytes / 2**20:.1f} "
                f"alias=-{p.alias_bytes / 2**20:.1f} "
                f"temps={p.temp_peak_bytes / 2**20:.1f})  "
                f"dev={p.device_peak_bytes / 2**20:.1f} MiB  "
                f"digest={p.digest}"
            )
        dev = self.breakdown.get("per_device")
        if dev:
            lines.append(
                f"  per-device high-water: "
                f"{dev['high_water_bytes'] / 2**20:.1f} MiB "
                f"(params {dev['params_bytes'] / 2**20:.1f} + pool "
                f"{dev['pool_bytes'] / 2**20:.1f} + worst-exec "
                f"temps/operands, at {dev['worst_executable']})"
            )
        if self.findings:
            lines.extend(self.render_findings())
        else:
            lines.append("findings: none")
        for s in self.suppressed:
            lines.append(
                f"suppressed: {s['rule']} ({s['justification']}): "
                f"{s['message']}"
            )
        return "\n".join(lines)

    def as_json(self) -> Dict[str, Any]:
        return {
            "origin": self.origin,
            "executables": [p.as_record() for p in self.profiles],
            "breakdown": self.breakdown,
            "findings": [
                {**f.__dict__, "severity": self.severity(f)}
                for f in self.findings
            ],
            "suppressed": self.suppressed,
            "errors": len(self.errors),
            "warnings": len(self.warnings),
        }


def _check_hbm_budget(
    engine,
    profiles: Sequence[ExecProfile],
    origin: str,
    hbm_gb: float,
    breakdown: Dict[str, Any],
) -> List[Finding]:
    """Per-device static high-water vs the HBM budget: params + paged
    pool via the byte-exact ServingConfig formulas, plus the worst
    executable's remaining per-device live bytes (operands beyond
    params/pool, un-aliased outputs, interior temp peak)."""
    gen = engine.gen
    cfg = gen.cfg
    serving: ServingConfig = engine.cfg
    fraction = _params_device_fraction(gen)
    params_total = sum(
        int(getattr(leaf, "nbytes", 0) or _aval_nbytes(leaf))
        for leaf in _tree_leaves(gen.params)
    )
    params_dev = int(params_total * (fraction if fraction else 1.0))
    mesh = getattr(gen, "mesh", None)
    sizes = (
        {str(k): int(v) for k, v in dict(mesh.shape).items()}
        if mesh is not None else {}
    )
    tp = sizes.get("tp", 1)
    pp = sizes.get("pp", 1)
    try:
        pool_dev = serving.pool_bytes_per_device(
            cfg, tp, gen.max_seq_length,
            serving.resolved_kv_dtype(str(np.dtype(gen.cache_dtype))),
        )
    except (AttributeError, TypeError, ValueError):
        try:
            pool_dev = serving.pool_bytes(cfg, gen.max_seq_length) // max(
                1, tp
            )
        except ValueError:
            pool_dev = 0
    if pp > 1 and cfg.n_layer >= pp:
        from mdi_llm_tpu.parallel.partition import stage_layers

        pool_dev = pool_dev // cfg.n_layer * max(
            stage_layers(cfg.n_layer, pp)
        )
        params_dev = _pp_params_device_bytes(gen, params_dev, pp)
    worst = None
    worst_rest = 0
    for p in profiles:
        # the profile's device peak already contains params+pool (they
        # ride in as arguments); take everything BEYOND them so the
        # formula-exact params/pool numbers anchor the budget line
        rest = max(
            0, p.device_peak_bytes - int(params_total * (
                fraction if fraction else 1.0
            )) - (p.device_alias_bytes or 0)
        )
        if worst is None or rest > worst_rest:
            worst, worst_rest = p, rest
    host_credit = 0
    if serving.host_pool_mib > 0:
        # host KV tier: swapped-out victims and spilled prefix chains park
        # in host RAM and their HBM blocks return to the free list, so the
        # steady-state resident peak drops by the swappable share — capped
        # by host capacity and by the pool itself (the reserved trash
        # block 0 never leaves HBM)
        try:
            kv_name = serving.resolved_kv_dtype(
                str(np.dtype(gen.cache_dtype))
            )
            max_seq = int(min(
                gen.max_seq_length or cfg.block_size, cfg.block_size
            ))
            n_blocks = serving.num_pool_blocks(max_seq)
            per_block_dev = pool_dev // max(1, n_blocks)
            host_credit = min(
                serving.num_host_blocks(cfg, kv_name), max(0, n_blocks - 1)
            ) * per_block_dev
        except (AttributeError, TypeError, ValueError):
            host_credit = 0
    high_water = params_dev + pool_dev - host_credit + worst_rest
    breakdown["per_device"] = {
        "params_bytes": int(params_dev),
        "pool_bytes": int(pool_dev),
        "host_credit_bytes": int(host_credit),
        "high_water_bytes": int(high_water),
        "worst_executable": worst.name if worst else None,
    }
    budget = int(float(hbm_gb) * GiB)
    breakdown["budget_bytes"] = budget
    if high_water <= budget:
        return []
    return [Finding(
        rule="hbm-over-budget", path=f"{origin}::budget", line=0, col=0,
        message=(
            f"per-device static high-water {high_water / GiB:.2f} GiB "
            f"exceeds the {float(hbm_gb):g} GiB budget (params "
            f"{params_dev / GiB:.2f} + pool {pool_dev / GiB:.2f}"
            + (f" - host tier {host_credit / GiB:.2f}" if host_credit else "")
            + f" + {worst_rest / GiB:.2f} live at "
            f"{worst.name if worst else '?'}): shrink the pool "
            "(max_blocks / kv_dtype=int8), the batch, or the window, "
            "offload with --host-pool-mib — or raise --hbm-gb if the "
            "budget was wrong"
        ),
        line_text="hbm-over-budget",
    )]


def _pp_params_device_bytes(gen, params_dev: int, pp: int) -> int:
    """Per-stage params under pipelined serving: each device holds l_max
    zero-padded layer slots of the blocks plus the replicated
    embeddings/norm/head (mirrors mdi-audit's pipeline budget)."""
    try:
        from mdi_llm_tpu.analysis.plan import iter_leaves
        from mdi_llm_tpu.parallel.partition import stage_layers

        cfg = gen.cfg
        l_max = max(stage_layers(cfg.n_layer, pp))
        params = gen.params
        blocks = params.get("blocks") if isinstance(params, dict) else None
        if blocks is None:
            return params_dev
        blocks_b = sum(int(leaf.nbytes) for _, leaf in iter_leaves(blocks))
        head_b = sum(
            int(leaf.nbytes)
            for k, v in params.items() if k != "blocks"
            for _, leaf in iter_leaves(v)
        )
        return blocks_b // cfg.n_layer * l_max + head_b
    except Exception:
        return params_dev


def _tree_leaves(x):
    import jax

    return jax.tree_util.tree_leaves(x)


def flow_preflight(
    engine,
    origin: Optional[str] = None,
    min_bytes: int = DEFAULT_MIN_BYTES,
    hbm_gb: Optional[float] = None,
    goldens: Optional[Dict[str, Any]] = None,
    golden_tolerance: Optional[float] = None,
) -> FlowReport:
    """Run the liveness pass over one serving engine — abstract
    (`trace_serving`) or live (bench / mdi-serve: tracing is side-band,
    the jit cache and CompileGuard counters are untouched).  Purely
    host-side: `.trace()` only, never `.lower()`, never a device."""
    origin = origin or type(engine).__name__
    specs = engine.enumerate_executables()
    fraction = _params_device_fraction(engine.gen)
    findings, profiles = analyze_flow(
        specs, origin=origin, min_bytes=min_bytes,
        params_fraction=fraction,
    )
    breakdown: Dict[str, Any] = {}
    if hbm_gb is not None:
        findings += _check_hbm_budget(
            engine, profiles, origin, hbm_gb, breakdown
        )
    if goldens is not None:
        findings += _check_goldens(
            profiles, goldens, origin, golden_tolerance
        )
    return FlowReport(
        origin=origin, findings=findings, profiles=profiles,
        breakdown=breakdown,
    )


# ---------------------------------------------------------------------------
# launch gate (bench.py / mdi-serve)
# ---------------------------------------------------------------------------


def flow_refusal_text(tool: str) -> str:
    return (f"{tool}: mdi-flow preflight refused the launch "
            "(re-run with --no-preflight to launch anyway)")


def enforce_flow_preflight(
    report: FlowReport, tool: str, allow: bool = False, emit=None
) -> bool:
    """Mirror of mdi-ir's `enforce_ir_preflight` for the liveness pass:
    emit every finding, refuse on errors unless `allow`
    (--no-preflight)."""
    if emit is None:
        def emit(line):
            print(line, file=sys.stderr)
    for line in report.render_findings():
        emit(f"{tool}: flow-preflight: {line}")
    if not report.errors or allow:
        return True
    raise SystemExit(flow_refusal_text(tool))


def flow_detail(report: FlowReport) -> Dict[str, Any]:
    """The compact per-row record bench.py stores under
    `detail.liveness`."""
    return {
        "findings": len(report.errors),
        "warnings": len(report.warnings),
        "peak_bytes": {p.name: p.peak_bytes for p in report.profiles},
        "device_peak_bytes": {
            p.name: p.device_peak_bytes for p in report.profiles
        },
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="mdi-flow",
        description="Buffer-liveness static analysis: per-executable live "
        "ranges, donation-aware aliasing and a static peak-HBM high-water "
        "over the serving compile set — no checkpoint, no device, no "
        "compile (see docs/analysis.md, 'Buffer liveness (mdi-flow)')",
    )
    src = ap.add_argument_group("model source")
    src.add_argument("--model", default=None, help="registry model name")
    src.add_argument("--config", default=None, metavar="FILE",
                     help="model_config.yaml / config.json to trace")
    par = ap.add_argument_group("parallel plan")
    par.add_argument("--tp", type=int, default=1,
                     help="tensor-parallel mesh axis (abstract devices)")
    par.add_argument("--pp", type=int, default=1,
                     help="pipeline-parallel serving stages (>=2 routes "
                     "to PipelinedServingEngine, exactly like a real "
                     "launch)")
    run = ap.add_argument_group("run shape")
    run.add_argument("--seq-len", type=int, default=None,
                     help="engine window (default: model context)")
    run.add_argument("--dtype", default="bfloat16",
                     choices=("bfloat16", "float16", "float32"))
    run.add_argument("--quantize", default="none",
                     choices=("none", "int8", "w8a8"))
    srv = ap.add_argument_group("serving (ServingConfig)")
    srv.add_argument("--block-size", type=int, default=16)
    srv.add_argument("--max-batch", type=int, default=8)
    srv.add_argument("--prefill-chunk", type=int, default=128)
    srv.add_argument("--token-budget", type=int, default=None)
    srv.add_argument("--decode-chunk", type=int, default=8)
    srv.add_argument("--spec-k", type=int, default=0)
    srv.add_argument("--temperature", type=float, default=0.0,
                     help="0 budgets the exact-match verify; >0 the "
                          "rejection-sampled verify executable")
    srv.add_argument("--top-p", type=float, default=None)
    srv.add_argument("--draft-model", default=None, metavar="NAME",
                     help="budget the draft-model scan/mixed executables "
                          "and the carved-out draft pool")
    srv.add_argument("--draft-share", type=float, default=0.25)
    srv.add_argument("--kv-dtype", default="auto",
                     help="paged-pool storage dtype (e.g. int8)")
    seq = ap.add_argument_group("sequential generate() path")
    seq.add_argument("--sequential", action="store_true",
                     help="also profile the generate() compile set for "
                     "the workload below")
    seq.add_argument("--batch", type=int, default=1)
    seq.add_argument("--prompt-len", type=int, default=32)
    seq.add_argument("--new-tokens", type=int, default=32)
    seq.add_argument("--chunk-size", type=int, default=16)
    bud = ap.add_argument_group("budgets")
    bud.add_argument("--hbm-gb", type=float, default=None,
                     help="per-device HBM budget: the static high-water "
                     "must fit (hbm-over-budget)")
    bud.add_argument("--min-bytes", type=int, default=DEFAULT_MIN_BYTES,
                     help="missed-donation / live-range-bloat floor "
                     "(bytes)")
    bud.add_argument("--goldens", default=None, metavar="FILE",
                     help="committed golden budgets+digests to pin "
                     "against (peak-memory-regression / jaxpr-drift)")
    bud.add_argument("--update-goldens", action="store_true",
                     help="write this run's budgets/digests into "
                     "--goldens (merging other origins) and exit 0")
    bud.add_argument("--golden-tolerance", type=float, default=None,
                     help="peak growth fraction that trips the "
                     "regression rule (default: the goldens file's, "
                     f"else {DEFAULT_GOLDEN_TOLERANCE})")
    ap.add_argument("--suppress", action="append", default=[],
                    metavar="RULE=WHY",
                    help="suppress a rule WITH a justification "
                    "(mandatory); repeatable")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="grandfather findings via an mdi-lint-style "
                    "baseline")
    ap.add_argument("--update-baseline", default=None, metavar="FILE",
                    help="write the current findings as the baseline and "
                    "exit 0")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-checks", action="store_true",
                    help="print the flow rule registry and exit")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_checks:
        width = max(len(c) for c in FLOW_RULES)
        for code, (sev, summary) in FLOW_RULES.items():
            print(f"{code:<{width}}  [{sev}] {summary}")
        return 0
    reasons: Dict[str, str] = {}
    for s in args.suppress:
        rule, _, why = s.partition("=")
        rule, why = rule.strip(), why.strip()
        if rule not in FLOW_RULES:
            print(f"mdi-flow: unknown rule in --suppress: {rule!r}",
                  file=sys.stderr)
            return 2
        if not why:
            print("mdi-flow: --suppress requires a justification: "
                  f"{rule}=<why this is acceptable>", file=sys.stderr)
            return 2
        reasons[rule] = why
    goldens = None
    if args.goldens and not args.update_goldens:
        try:
            goldens = load_goldens(Path(args.goldens))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"mdi-flow: {e}", file=sys.stderr)
            return 2
    try:
        if args.config:
            cfg = Config.from_file(args.config)
        elif args.model:
            cfg = Config.from_name(args.model)
        else:
            raise ValueError("need --model or --config")
        serving = ServingConfig(
            block_size=args.block_size,
            max_batch=args.max_batch,
            prefill_chunk=args.prefill_chunk,
            token_budget=args.token_budget,
            decode_chunk=args.decode_chunk,
            spec_k=args.spec_k,
            temperature=args.temperature,
            top_p=args.top_p,
            draft_model=args.draft_model,
            draft_share=args.draft_share,
            kv_dtype=None if args.kv_dtype == "auto" else args.kv_dtype,
        )
        engine = trace_serving(
            cfg,
            serving,
            tp=args.tp,
            pp=args.pp,
            dtype=args.dtype,
            quantize=None if args.quantize == "none" else args.quantize,
            max_seq_length=args.seq_len,
        )
    except (ValueError, OSError, json.JSONDecodeError) as e:
        print(f"mdi-flow: {e}", file=sys.stderr)
        return 2
    name = args.model or Path(args.config).stem
    mesh_tag = "".join(
        t for t in (f"@tp{args.tp}" if args.tp > 1 else "",
                    f"@pp{args.pp}" if args.pp > 1 else "",
                    f"@spec{args.spec_k}" if args.spec_k else "",
                    "@draft" if args.draft_model else "")
    )
    origin = f"{name}{mesh_tag}"
    report = flow_preflight(
        engine,
        origin=origin,
        min_bytes=args.min_bytes,
        hbm_gb=args.hbm_gb,
        goldens=goldens,
        golden_tolerance=args.golden_tolerance,
    )
    seq_profiles: List[ExecProfile] = []
    if args.sequential:
        try:
            seq_specs = engine.gen.enumerate_executables(
                batch_size=args.batch,
                prompt_len=args.prompt_len,
                max_new_tokens=args.new_tokens,
                chunk_size=args.chunk_size,
            )
        except ValueError as e:
            print(f"mdi-flow: {e}", file=sys.stderr)
            return 2
        f2, seq_profiles = analyze_flow(
            seq_specs,
            origin=f"{origin}:generate",
            min_bytes=args.min_bytes,
            params_fraction=_params_device_fraction(engine.gen),
        )
        if goldens is not None:
            f2 += _check_goldens(
                seq_profiles, goldens, f"{origin}:generate",
                args.golden_tolerance,
            )
        report.findings += f2
    if args.update_goldens:
        gpath = Path(args.goldens) if args.goldens else DEFAULT_GOLDENS
        write_goldens(gpath, origin, report.profiles)
        if seq_profiles:
            write_goldens(gpath, f"{origin}:generate", seq_profiles)
        n = len(report.profiles) + len(seq_profiles)
        print(f"mdi-flow: wrote {n} budget(s) for {origin} to {gpath}")
        return 0
    report.profiles += seq_profiles
    report.suppress(reasons)
    if args.update_baseline:
        Baseline.from_findings(report.findings).save(
            Path(args.update_baseline)
        )
        print(f"mdi-flow: wrote {len(report.findings)} finding(s) to "
              f"{args.update_baseline}")
        return 0
    errors = report.errors
    if args.baseline:
        new, _old = Baseline.load(Path(args.baseline)).split(errors)
        errors = new
    if args.format == "json":
        out = report.as_json()
        out["new_errors"] = len(errors)
        print(json.dumps(out, indent=2))
    else:
        print(report.render_text())
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
