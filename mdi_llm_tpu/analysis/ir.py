"""`mdi-ir`: trace-level static analysis of the serving compile set.

The fourth analysis family, below mdi-lint (source AST), mdi-audit
(plan/shape arithmetic) and mdi-race (thread roles): abstractly trace —
`jitted.trace(...)` / `.lower()` over `ShapeDtypeStruct`s, never
`.compile()`, never a device — EVERY executable the serving engine can
dispatch for a (Config, mesh, ServingConfig) tuple, and run an IR rule
registry over each jaxpr.  The engine's headline guarantees (zero
post-warmup recompiles, donated-pool aliasing) are otherwise enforced only
dynamically (CompileGuard counters), so a shape that escapes the warmup
set or a silently-dropped donation (JAX warns on stderr, then keeps BOTH
pool copies) is invisible until a real run hits it.

Executables come from the enumeration seams this tool motivated:
`ServingEngine.enumerate_executables()` (the pipeline engine inherits it —
its ring variants trace under the same labels/keys) and
`Generator.enumerate_executables()` for the sequential `generate()` path,
both built on `obs/device.py`'s side-band AOT machinery
(`ExecutableSpec`, `abstractify`).  `trace_serving()` constructs the whole
engine abstractly (`Generator(..., abstract=True)` over
`analysis.plan.abstract_params` stubs), so the CLI needs no checkpoint, no
backend, and no device — pinned by the same trip-wire test style as
mdi-audit.

Rules (IR_RULES):

- **compile-set-closure** [error] — the enumerated warmup set must equal
  the `step()`-reachable dispatch set derived independently from the
  ServingConfig.  A reachable signature outside the enumeration is a
  zero-recompile hole (first hit recompiles mid-serve); an enumerated
  signature that is unreachable warms dead code.
- **dropped-donation** [error] — every `donate_argnums` buffer must
  surface in the lowered module's input-output aliasing
  (`tf.aliasing_output`, or `jax.buffer_donor` when aliasing is deferred
  to the SPMD partitioner under a mesh).  A donated-but-unaliased pool
  keeps two copies live: a 2x HBM spike per dispatch.
- **callback-in-executable** [error] — pure_callback / io_callback /
  debug_callback (incl. `jax.debug.print`) inside a serving dispatch is a
  host round-trip per step.
- **sharding-constraint-drift** [error] — kv-pool sharding constraints
  inside one executable must agree with the pool's declared sharding;
  a drifted constraint makes GSPMD resharding-copy the whole pool every
  step.
- **dtype-promotion-leak** [warning] — a bf16/f16 operand upcast to f32
  feeding a matmul on the compute path (weak-type promotion): 2x matmul
  bytes for no accuracy contract.
- **baked-constant-bloat** [warning] — a constant larger than
  `--max-const-bytes` materialized inside the jaxpr ships inside the
  executable (and re-uploads per compile); it belongs in an argument.
- **trace-failure** [error] — an enumerated executable refused to trace
  abstractly; whatever it does at runtime, the static contract is void.

CLI: ``mdi-ir --model pythia-14m --tp 2`` (or ``python -m
mdi_llm_tpu.analysis ir ...``); ``--format json``, ``--baseline`` /
``--update-baseline`` (mdi-lint `Baseline` round-trip), ``--suppress
RULE=justification`` (a justification is mandatory), ``--list-checks``.
Exit 0 clean, 1 on findings, 2 on usage/plan errors.  Wired as a
bench / mdi-serve preflight via `ir_preflight` + `enforce_ir_preflight`
(`detail.ir` per serve row).  See docs/analysis.md, "Trace-level
analysis (mdi-ir)".
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import warnings
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Sequence, Set, Tuple

import numpy as np

from mdi_llm_tpu.analysis.core import Baseline, Finding
from mdi_llm_tpu.config import Config, ServingConfig

__all__ = [
    "IR_RULES",
    "IrReport",
    "analyze_executables",
    "enforce_ir_preflight",
    "ir_detail",
    "ir_preflight",
    "main",
    "reachable_serving_set",
    "trace_serving",
]

ERROR, WARNING = "error", "warning"

# rule -> (severity, one-line summary); --list-checks prints this
IR_RULES: Dict[str, Tuple[str, str]] = {
    "compile-set-closure": (ERROR, (
        "enumerated warmup set != the step()-reachable dispatch set: a "
        "reachable shape outside the enumeration is a zero-recompile hole, "
        "an unreachable enumerated shape warms dead code"
    )),
    "dropped-donation": (ERROR, (
        "a donate_argnums buffer is missing from the lowered input-output "
        "aliasing (tf.aliasing_output / jax.buffer_donor): JAX keeps both "
        "copies live — a 2x pool HBM spike per dispatch"
    )),
    "callback-in-executable": (ERROR, (
        "pure_callback/io_callback/debug_callback embedded in a serving "
        "dispatch: a host round-trip per step"
    )),
    "sharding-constraint-drift": (ERROR, (
        "a kv-pool sharding constraint inside the executable disagrees "
        "with the pool's declared sharding: GSPMD resharding-copies the "
        "pool every step"
    )),
    "dtype-promotion-leak": (WARNING, (
        "a low-precision operand is upcast to f32 feeding a matmul on the "
        "compute path (weak-type promotion): 2x matmul bytes"
    )),
    "baked-constant-bloat": (WARNING, (
        "a large constant is materialized inside the jaxpr: it ships "
        "inside the executable instead of riding as an argument"
    )),
    "trace-failure": (ERROR, (
        "an enumerated executable refused to trace abstractly — the "
        "static compile-set contract cannot be checked"
    )),
}

DEFAULT_MAX_CONST_BYTES = 8 * 1024 * 1024  # rope tables for small/medium
# models sit well under this; a baked PARAM leaf blows straight through it

_LOW_PRECISION = ("bfloat16", "float16")
_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback")


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------


def _iter_jaxprs(closed) -> Iterator[Tuple[Any, Sequence[Any]]]:
    """Yield (jaxpr, consts) for a ClosedJaxpr and every jaxpr nested in
    its equations' params (pjit bodies, scan/while/cond branches,
    shard_map regions, custom_jvp calls, ...).  Duck-typed — any param
    value with `.eqns` is a Jaxpr, any with `.jaxpr` a ClosedJaxpr — so
    no jax-internal imports and no version pinning."""
    seen: Set[int] = set()

    def rec(jaxpr, consts):
        if id(jaxpr) in seen:
            return
        seen.add(id(jaxpr))
        yield jaxpr, consts
        for eqn in jaxpr.eqns:
            for val in eqn.params.values():
                vals = val if isinstance(val, (list, tuple)) else (val,)
                for v in vals:
                    inner = getattr(v, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        yield from rec(inner, getattr(v, "consts", ()))
                    elif hasattr(v, "eqns"):
                        yield from rec(v, ())

    top = getattr(closed, "jaxpr", closed)
    yield from rec(top, getattr(closed, "consts", ()))


def _count_eqns(closed) -> int:
    return sum(len(j.eqns) for j, _ in _iter_jaxprs(closed))


def _aval_nbytes(x) -> int:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


def sharding_denom(leaf) -> int:
    """Mesh-axis product a value's DECLARED sharding divides it by: axes
    whose size doesn't divide the dim are dropped by the runtime
    (`adapt_specs_to_tree`) and count whole.  1 for unsharded/opaque
    values.  Shared with mdi-flow's per-device byte attribution."""
    sh = getattr(leaf, "sharding", None)
    pspec = getattr(sh, "spec", None)
    mesh = getattr(sh, "mesh", None)
    if pspec is None or mesh is None:
        return 1
    try:
        sizes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    except (TypeError, ValueError):
        return 1
    shape = getattr(leaf, "shape", ())
    denom = 1
    for i, entry in enumerate(tuple(pspec)[: len(shape)]):
        axes = entry if isinstance(entry, (list, tuple)) else (entry,)
        for ax in axes:
            if ax is None:
                continue
            s = sizes.get(str(ax), 1)
            if s > 1 and shape[i] % s == 0:
                denom *= s
    return denom


def _dtype_name(x) -> str:
    try:
        return np.dtype(getattr(x, "dtype", x)).name
    except TypeError:
        return str(getattr(x, "dtype", x))


# ---------------------------------------------------------------------------
# per-executable rules
# ---------------------------------------------------------------------------


def _check_callbacks(spec, closed, path: str) -> List[Finding]:
    hits: Dict[str, int] = {}
    for jaxpr, _ in _iter_jaxprs(closed):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if any(c in name for c in _CALLBACK_PRIMS):
                hits[name] = hits.get(name, 0) + 1
    return [
        Finding(
            rule="callback-in-executable", path=path, line=0, col=0,
            message=(
                f"{spec.name} embeds {n}x `{prim}`: every dispatch makes a "
                "host round-trip (drop jax.debug.print / callbacks from the "
                "serving path, or move them behind an off-by-default flag)"
            ),
            line_text=f"callback:{prim}",
        )
        for prim, n in sorted(hits.items())
    ]


def _check_const_bloat(spec, closed, path: str, max_bytes: int) -> List[Finding]:
    """Threshold (`--const-bytes`) applies to the PER-DEVICE bytes: a
    constant sharded over tp/pp ships each device only its slice
    (`sharding_denom`), so sharded tables no longer trip the rule
    spuriously — unsharded consts count whole, exactly as before."""
    out: List[Finding] = []
    for jaxpr, consts in _iter_jaxprs(closed):
        for c in consts:
            denom = sharding_denom(c)
            nb = _aval_nbytes(c) // denom
            if nb >= max_bytes:
                shard = f" per device (/{denom})" if denom > 1 else ""
                out.append(Finding(
                    rule="baked-constant-bloat", path=path, line=0, col=0,
                    message=(
                        f"{spec.name} bakes a {nb / 2**20:.1f} MiB"
                        f"{shard} {_dtype_name(c)}{tuple(np.shape(c))} "
                        "constant into the jaxpr (threshold "
                        f"{max_bytes / 2**20:.0f} MiB): it ships inside "
                        "the executable — pass it as an argument instead"
                    ),
                    line_text=(
                        f"const:{_dtype_name(c)}:{tuple(np.shape(c))}"
                    ),
                ))
    return out


def _check_dtype_leaks(spec, closed, path: str) -> List[Finding]:
    """convert(low-precision -> f32) feeding a dot_general operand: the
    matmul runs at 2x the bytes the compute dtype promises.  Narrow by
    construction — only DIRECT convert->dot edges flag, so f32 softmax
    statistics, sampling logits upcasts etc. never false-positive."""
    hits: Dict[str, int] = {}
    for jaxpr, _ in _iter_jaxprs(closed):
        defn: Dict[Any, Any] = {}
        for eqn in jaxpr.eqns:
            for ov in eqn.outvars:
                defn[ov] = eqn
        for eqn in jaxpr.eqns:
            if eqn.primitive.name not in ("dot_general", "conv_general_dilated"):
                continue
            for iv in eqn.invars:
                src = defn.get(iv)
                if src is None or src.primitive.name != "convert_element_type":
                    continue
                src_in = src.invars[0]
                in_dt = _dtype_name(getattr(src_in, "aval", src_in))
                out_dt = _dtype_name(getattr(iv, "aval", iv))
                if out_dt == "float32" and in_dt in _LOW_PRECISION:
                    hits[in_dt] = hits.get(in_dt, 0) + 1
    return [
        Finding(
            rule="dtype-promotion-leak", path=path, line=0, col=0,
            message=(
                f"{spec.name} upcasts {n}x {dt}->f32 directly feeding a "
                "matmul: the contraction runs at 2x the compute-path bytes "
                "(keep operands in the compute dtype; accumulate via "
                "preferred_element_type if f32 accumulation is the intent)"
            ),
            line_text=f"leak:{dt}",
        )
        for dt, n in sorted(hits.items())
    ]


def _constraint_spec_str(sharding) -> Optional[str]:
    spec = getattr(sharding, "spec", None)
    return None if spec is None else str(spec)


def _check_sharding_drift(spec, closed, path: str) -> List[Finding]:
    """Compare every `sharding_constraint` whose operand rank matches a kv
    pool leaf against the pool's DECLARED sharding (the kv
    ShapeDtypeStructs in `spec.args` carry it).  Constraints on other
    ranks (activations etc.) are out of scope; unmeshed engines have no
    declared shardings and skip."""
    import jax

    expected: Dict[int, Set[str]] = {}  # rank -> declared spec strings
    for i in spec.donate:
        for leaf in jax.tree_util.tree_leaves(spec.args[i]):
            sh = getattr(leaf, "sharding", None)
            s = _constraint_spec_str(sh) if sh is not None else None
            if s is not None:
                expected.setdefault(len(leaf.shape), set()).add(s)
    if not expected:
        return []
    out: List[Finding] = []
    seen_mismatch: Set[Tuple[int, str]] = set()
    for jaxpr, _ in _iter_jaxprs(closed):
        for eqn in jaxpr.eqns:
            if "sharding_constraint" not in eqn.primitive.name:
                continue
            sh = eqn.params.get("sharding")
            s = _constraint_spec_str(sh)
            if s is None:
                continue  # opaque (GSPMD) constraint: nothing to compare
            rank = len(getattr(eqn.invars[0].aval, "shape", ()))
            declared = expected.get(rank)
            if declared is None or s in declared:
                continue
            key = (rank, s)
            if key in seen_mismatch:
                continue
            seen_mismatch.add(key)
            out.append(Finding(
                rule="sharding-constraint-drift", path=path, line=0, col=0,
                message=(
                    f"{spec.name} pins a rank-{rank} kv-pool value to "
                    f"{s}, but the pool is declared "
                    f"{sorted(declared)}: GSPMD inserts a resharding copy "
                    "of the pool on every dispatch (make _pin_kv and the "
                    "pool placement agree)"
                ),
                line_text=f"drift:rank{rank}:{s}",
            ))
    return out


def _check_donation(spec, traced, path: str) -> List[Finding]:
    """Lower (never compile) and count aliased/donor-marked inputs against
    the donated leaf count.  Single-device modules carry the final
    `tf.aliasing_output` attributes; under a mesh aliasing is decided by
    the SPMD partitioner, so the pre-compile module marks donors with
    `jax.buffer_donor` instead — both count.  JAX's own lower-time
    'donated buffers were not usable' warning is captured and quoted."""
    import jax

    expected = sum(
        len(jax.tree_util.tree_leaves(spec.args[i])) for i in spec.donate
    )
    if not expected:
        return []
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        try:
            text = traced.lower().as_text()
        except Exception as e:  # lowering is rule input, not a crash site
            return [Finding(
                rule="trace-failure", path=path, line=0, col=0,
                message=f"{spec.name} failed to lower abstractly: {e}",
                line_text="lower",
            )]
    marked = text.count("tf.aliasing_output") + text.count("jax.buffer_donor")
    if marked >= expected:
        return []
    dropped = [
        str(w.message) for w in caught
        if "donated buffers were not usable" in str(w.message)
    ]
    why = f" (JAX: {dropped[0]})" if dropped else ""
    return [Finding(
        rule="dropped-donation", path=path, line=0, col=0,
        message=(
            f"{spec.name} donates {expected} buffer(s) via donate_argnums="
            f"{tuple(spec.donate)} but only {marked} are aliased/marked in "
            "the lowered module: the un-aliased donations keep BOTH copies "
            "live — a 2x HBM spike per dispatch (every donated input needs "
            f"a shape/dtype-matched output){why}"
        ),
        line_text=f"donation:{expected - marked}",
    )]


# ---------------------------------------------------------------------------
# compile-set closure
# ---------------------------------------------------------------------------


def reachable_serving_set(
    serving: ServingConfig, max_batch: int, token_budget: int
) -> Set[Tuple[str, Tuple[int, ...]]]:
    """The dispatch signatures `ServingEngine.step()` can reach, derived
    INDEPENDENTLY from the ServingConfig semantics (engine.py step
    routing): mixed always; verify iff spec_k (spec decode falls through
    to plain decode when no slot drafts, so decode stays reachable);
    decode_chunk iff decode_chunk > 1, else decode.  Deliberately a
    second implementation — diffing it against the engine's own
    enumeration is the closure proof."""
    sigs: Set[Tuple[str, Tuple[int, ...]]] = {
        ("mixed", (int(max_batch), int(token_budget)))
    }
    if serving.spec_k:
        # spec_verify_sampled() routes between the pinned exact-match
        # verify (greedy) and the rejection-sampled verify (temperature>0)
        label = "verify_sample" if serving.spec_verify_sampled() else "verify"
        sigs.add((label, (int(max_batch), int(serving.spec_k) + 1)))
        if serving.draft_model:
            # draft model: mixed-step mirror + ragged catch-up/scan
            sigs.add(("draft_mixed", (int(max_batch), int(token_budget))))
            sigs.add(("draft_scan", (int(max_batch), int(serving.spec_k) + 2)))
    if serving.decode_chunk > 1:
        sigs.add(("decode_chunk", (int(max_batch), int(serving.decode_chunk))))
    else:
        sigs.add(("decode", (int(max_batch),)))
    if serving.host_pool_mib > 0:
        # host KV tier: swap-out gathers and restore scatters run in one
        # fixed transfer quantum so the tier adds exactly two executables
        W = max(1, int(serving.swap_chunk_blocks))
        sigs.add(("fetch", (W,)))
        sigs.add(("restore", (W,)))
    return sigs


def _check_compile_set(engine, specs, origin: str) -> List[Finding]:
    path = f"{origin}::compile-set"
    enumerated = {(s.label, tuple(s.key)) for s in specs}
    reachable = reachable_serving_set(
        engine.cfg, engine.scheduler.max_batch, engine.token_budget
    )
    out: List[Finding] = []
    for label, key in sorted(reachable - enumerated):
        out.append(Finding(
            rule="compile-set-closure", path=path, line=0, col=0,
            message=(
                f"step() can dispatch {label}{key} but the engine does not "
                "enumerate it: the first hit compiles MID-SERVE — a "
                "zero-recompile hole (fix enumerate_executables/"
                "reachable_signatures to cover every step() branch)"
            ),
            line_text=f"missing:{label}{key}",
        ))
    for label, key in sorted(enumerated - reachable):
        out.append(Finding(
            rule="compile-set-closure", path=path, line=0, col=0,
            message=(
                f"the engine enumerates {label}{key} but no step() branch "
                "can reach it under this ServingConfig: dead warmup "
                "(compile time + HBM for an executable that never runs)"
            ),
            line_text=f"unreachable:{label}{key}",
        ))
    return out


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def analyze_executables(
    specs: Sequence[Any],
    origin: str = "<specs>",
    compute_dtype: Optional[str] = None,
    max_const_bytes: int = DEFAULT_MAX_CONST_BYTES,
    check_donation: bool = True,
) -> Tuple[List[Finding], List[Dict[str, Any]]]:
    """Trace every `ExecutableSpec` and run the per-executable rules.
    Returns (findings, executable records).  `compute_dtype` gates the
    dtype-promotion-leak rule: it only means anything when the params
    are low-precision."""
    findings: List[Finding] = []
    records: List[Dict[str, Any]] = []
    leak_rule = compute_dtype is not None and (
        np.dtype(compute_dtype).name in _LOW_PRECISION
    )
    for spec in specs:
        path = f"{origin}::{spec.name}"
        try:
            traced = spec.fn.trace(*spec.args, **(spec.static_kwargs or {}))
            closed = traced.jaxpr
        except Exception as e:
            findings.append(Finding(
                rule="trace-failure", path=path, line=0, col=0,
                message=f"{spec.name} failed to trace abstractly: {e}",
                line_text="trace",
            ))
            records.append({"name": spec.name, "label": spec.label,
                            "key": list(spec.key), "error": str(e)})
            continue
        found_here: List[Finding] = []
        found_here += _check_callbacks(spec, closed, path)
        found_here += _check_const_bloat(spec, closed, path, max_const_bytes)
        if leak_rule:
            found_here += _check_dtype_leaks(spec, closed, path)
        found_here += _check_sharding_drift(spec, closed, path)
        if check_donation and spec.donate:
            found_here += _check_donation(spec, traced, path)
        findings.extend(found_here)
        records.append({
            "name": spec.name, "label": spec.label, "key": list(spec.key),
            "eqns": _count_eqns(closed),
            "donated": sum(
                len(_tree_leaves(spec.args[i])) for i in spec.donate
            ),
            "findings": len(found_here),
        })
    return findings, records


def _tree_leaves(x):
    import jax

    return jax.tree_util.tree_leaves(x)


@dataclasses.dataclass
class IrReport:
    """One mdi-ir pass: findings + the traced executable inventory."""

    origin: str
    findings: List[Finding]
    executables: List[Dict[str, Any]]
    suppressed: List[Dict[str, Any]] = dataclasses.field(default_factory=list)

    def severity(self, f: Finding) -> str:
        return IR_RULES.get(f.rule, (ERROR, ""))[0]

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if self.severity(f) == ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if self.severity(f) == WARNING]

    def suppress(self, reasons: Dict[str, str]) -> None:
        """Move findings whose rule has a justified suppression out of the
        active set (they still print, marked suppressed, and ride the JSON
        output with their justification)."""
        keep: List[Finding] = []
        for f in self.findings:
            reason = reasons.get(f.rule)
            if reason:
                self.suppressed.append({
                    "rule": f.rule, "path": f.path, "message": f.message,
                    "justification": reason,
                })
            else:
                keep.append(f)
        self.findings = keep

    def render_findings(self) -> List[str]:
        return [
            f"{f.path}: {self.severity(f)}: {f.rule}: {f.message}"
            for f in self.findings
        ]

    def render_text(self) -> str:
        lines = [f"traced: {self.origin}"]
        for r in self.executables:
            if "error" in r:
                lines.append(f"  {r['name']:<24} TRACE FAILED: {r['error']}")
            else:
                lines.append(
                    f"  {r['name']:<24} eqns={r['eqns']:<6} "
                    f"donated={r['donated']}"
                )
        if self.findings:
            lines.extend(self.render_findings())
        else:
            lines.append("findings: none")
        for s in self.suppressed:
            lines.append(
                f"suppressed: {s['rule']} ({s['justification']}): "
                f"{s['message']}"
            )
        return "\n".join(lines)

    def as_json(self) -> Dict[str, Any]:
        return {
            "origin": self.origin,
            "executables": self.executables,
            "findings": [
                {**f.__dict__, "severity": self.severity(f)}
                for f in self.findings
            ],
            "suppressed": self.suppressed,
            "errors": len(self.errors),
            "warnings": len(self.warnings),
        }


def ir_preflight(
    engine,
    origin: Optional[str] = None,
    max_const_bytes: int = DEFAULT_MAX_CONST_BYTES,
    check_donation: bool = True,
) -> IrReport:
    """Run the full rule set over one serving engine — abstract
    (`trace_serving`) or live (bench/mdi-serve: `abstractify` strips the
    real buffers; `.trace`/`.lower` are side-band, so the jit cache,
    donation behavior and CompileGuard counters of the real dispatches
    are untouched)."""
    from mdi_llm_tpu.models import transformer

    origin = origin or type(engine).__name__
    specs = engine.enumerate_executables()
    findings = _check_compile_set(engine, specs, origin)
    try:
        compute_dtype = np.dtype(
            transformer.param_dtype(engine.gen.params)
        ).name
    except (TypeError, ValueError):
        compute_dtype = None
    per_exec, records = analyze_executables(
        specs,
        origin=origin,
        compute_dtype=compute_dtype,
        max_const_bytes=max_const_bytes,
        check_donation=check_donation,
    )
    findings += per_exec
    return IrReport(origin=origin, findings=findings, executables=records)


def trace_serving(
    cfg: Config,
    serving: Optional[ServingConfig] = None,
    tp: int = 1,
    pp: int = 1,
    dtype: str = "bfloat16",
    quantize: Optional[str] = None,
    max_seq_length: Optional[int] = None,
    scan_unroll: int = 1,
):
    """Build the ENTIRE serving engine abstractly for a (Config, mesh,
    ServingConfig) tuple: zero-stride param stubs
    (`analysis.plan.abstract_params`), `Generator(abstract=True)` (no
    device_put, no PRNG seed compile), and a ShapeDtypeStruct kv pool —
    then `.serve()` routes to the flat or pipelined engine exactly like a
    real launch.  Returns the engine; run `ir_preflight` on it.  Requires
    only that jax can ENUMERATE tp*pp devices for the mesh (CI forces 8
    host-platform devices); nothing is compiled or placed."""
    from mdi_llm_tpu.analysis.plan import abstract_params
    from mdi_llm_tpu.generation import Generator

    serving = serving if serving is not None else ServingConfig()
    mesh = None
    axes: Dict[str, int] = {}
    if int(pp) > 1:
        axes["pp"] = int(pp)
    if int(tp) > 1:
        axes["tp"] = int(tp)
    if axes:
        from mdi_llm_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(axes)
    params = abstract_params(cfg, dtype=dtype, quantize=quantize)
    gen = Generator(
        cfg,
        params,
        max_seq_length=max_seq_length,
        mesh=mesh,
        scan_unroll=scan_unroll,
        abstract=True,
    )
    return gen.serve(serving=serving)


# ---------------------------------------------------------------------------
# launch gate (bench.py / mdi-serve)
# ---------------------------------------------------------------------------


def ir_refusal_text(tool: str) -> str:
    return (f"{tool}: mdi-ir preflight refused the launch "
            "(re-run with --no-preflight to launch anyway)")


def enforce_ir_preflight(
    report: IrReport, tool: str, allow: bool = False, emit=None
) -> bool:
    """Mirror of mdi-audit's `enforce_preflight` for the trace-level pass:
    emit every finding, refuse on errors unless `allow`
    (--no-preflight)."""
    if emit is None:
        def emit(line):
            print(line, file=sys.stderr)
    for line in report.render_findings():
        emit(f"{tool}: ir-preflight: {line}")
    if not report.errors or allow:
        return True
    raise SystemExit(ir_refusal_text(tool))


def ir_detail(report: IrReport) -> Dict[str, Any]:
    """The compact per-row record bench.py stores under `detail.ir`."""
    return {
        "findings": len(report.errors),
        "warnings": len(report.warnings),
        "executables": {
            r["name"]: r.get("eqns") for r in report.executables
        },
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="mdi-ir",
        description="Trace-level static analysis: abstractly trace every "
        "serving executable for a (model, mesh, ServingConfig) tuple — no "
        "checkpoint, no device, no compile — and verify compile-set "
        "closure, donation aliasing, and IR hygiene (see docs/analysis.md, "
        "'Trace-level analysis (mdi-ir)')",
    )
    src = ap.add_argument_group("model source")
    src.add_argument("--model", default=None, help="registry model name")
    src.add_argument("--config", default=None, metavar="FILE",
                     help="model_config.yaml / config.json to trace")
    par = ap.add_argument_group("parallel plan")
    par.add_argument("--tp", type=int, default=1,
                     help="tensor-parallel mesh axis (abstract devices)")
    par.add_argument("--pp", type=int, default=1,
                     help="pipeline-parallel serving stages (>=2 routes to "
                     "PipelinedServingEngine, exactly like a real launch)")
    run = ap.add_argument_group("run shape")
    run.add_argument("--seq-len", type=int, default=None,
                     help="engine window (default: model context)")
    run.add_argument("--dtype", default="bfloat16",
                     choices=("bfloat16", "float16", "float32"))
    run.add_argument("--quantize", default="none",
                     choices=("none", "int8", "w8a8"))
    srv = ap.add_argument_group("serving (ServingConfig)")
    srv.add_argument("--block-size", type=int, default=16)
    srv.add_argument("--max-batch", type=int, default=8)
    srv.add_argument("--prefill-chunk", type=int, default=128)
    srv.add_argument("--token-budget", type=int, default=None)
    srv.add_argument("--decode-chunk", type=int, default=8)
    srv.add_argument("--spec-k", type=int, default=0)
    srv.add_argument("--temperature", type=float, default=0.0)
    srv.add_argument("--top-k", type=int, default=None)
    srv.add_argument("--top-p", type=float, default=None)
    srv.add_argument("--draft-model", default=None, metavar="NAME",
                     help="registry name of a small draft model; traces "
                          "the draft_mixed/draft_scan executables and the "
                          "draft kv-pool carve-out")
    srv.add_argument("--draft-share", type=float, default=0.25,
                     help="fraction of a bounded block budget carved out "
                          "for the draft pool (default 0.25)")
    srv.add_argument("--kv-dtype", default="auto",
                     help="paged-pool storage dtype (e.g. int8)")
    seq = ap.add_argument_group("sequential generate() path")
    seq.add_argument("--sequential", action="store_true",
                     help="also trace the generate() compile set for the "
                     "workload below")
    seq.add_argument("--batch", type=int, default=1)
    seq.add_argument("--prompt-len", type=int, default=32)
    seq.add_argument("--new-tokens", type=int, default=32)
    seq.add_argument("--chunk-size", type=int, default=16)
    seq.add_argument("--speculative", type=int, default=None)
    ap.add_argument("--const-bytes", "--max-const-bytes",
                    dest="max_const_bytes", type=int,
                    default=DEFAULT_MAX_CONST_BYTES,
                    help="baked-constant-bloat threshold in bytes, "
                    "counted PER DEVICE under tp/pp (sharded constants "
                    "cost each device only their slice); "
                    "--max-const-bytes is the deprecated alias")
    ap.add_argument("--no-donation-check", action="store_true",
                    help="skip the .lower()-based dropped-donation rule "
                    "(the slowest rule on big models)")
    ap.add_argument("--suppress", action="append", default=[],
                    metavar="RULE=WHY",
                    help="suppress a rule WITH a justification (mandatory); "
                    "repeatable")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="grandfather findings via an mdi-lint-style "
                    "baseline")
    ap.add_argument("--update-baseline", default=None, metavar="FILE",
                    help="write the current findings as the baseline and "
                    "exit 0")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-checks", action="store_true",
                    help="print the IR rule registry and exit")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_checks:
        width = max(len(c) for c in IR_RULES)
        for code, (sev, summary) in IR_RULES.items():
            print(f"{code:<{width}}  [{sev}] {summary}")
        return 0
    reasons: Dict[str, str] = {}
    for s in args.suppress:
        rule, _, why = s.partition("=")
        rule, why = rule.strip(), why.strip()
        if rule not in IR_RULES:
            print(f"mdi-ir: unknown rule in --suppress: {rule!r}",
                  file=sys.stderr)
            return 2
        if not why:
            print("mdi-ir: --suppress requires a justification: "
                  f"{rule}=<why this is acceptable>", file=sys.stderr)
            return 2
        reasons[rule] = why
    try:
        if args.config:
            cfg = Config.from_file(args.config)
        elif args.model:
            cfg = Config.from_name(args.model)
        else:
            raise ValueError("need --model or --config")
        serving = ServingConfig(
            block_size=args.block_size,
            max_batch=args.max_batch,
            prefill_chunk=args.prefill_chunk,
            token_budget=args.token_budget,
            decode_chunk=args.decode_chunk,
            spec_k=args.spec_k,
            temperature=args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
            draft_model=args.draft_model,
            draft_share=args.draft_share,
            kv_dtype=None if args.kv_dtype == "auto" else args.kv_dtype,
        )
        engine = trace_serving(
            cfg,
            serving,
            tp=args.tp,
            pp=args.pp,
            dtype=args.dtype,
            quantize=None if args.quantize == "none" else args.quantize,
            max_seq_length=args.seq_len,
        )
    except (ValueError, OSError, json.JSONDecodeError) as e:
        print(f"mdi-ir: {e}", file=sys.stderr)
        return 2
    name = args.model or Path(args.config).stem
    mesh_tag = "".join(
        t for t in (f"@tp{args.tp}" if args.tp > 1 else "",
                    f"@pp{args.pp}" if args.pp > 1 else "")
    )
    report = ir_preflight(
        engine,
        origin=f"{name}{mesh_tag}",
        max_const_bytes=args.max_const_bytes,
        check_donation=not args.no_donation_check,
    )
    if args.sequential:
        try:
            seq_specs = engine.gen.enumerate_executables(
                batch_size=args.batch,
                prompt_len=args.prompt_len,
                max_new_tokens=args.new_tokens,
                chunk_size=args.chunk_size,
                speculative=args.speculative,
            )
        except ValueError as e:
            print(f"mdi-ir: {e}", file=sys.stderr)
            return 2
        f2, r2 = analyze_executables(
            seq_specs,
            origin=f"{name}{mesh_tag}:generate",
            compute_dtype=args.dtype,
            max_const_bytes=args.max_const_bytes,
            check_donation=not args.no_donation_check,
        )
        report.findings += f2
        report.executables += r2
    report.suppress(reasons)
    if args.update_baseline:
        Baseline.from_findings(report.findings).save(
            Path(args.update_baseline)
        )
        print(f"mdi-ir: wrote {len(report.findings)} finding(s) to "
              f"{args.update_baseline}")
        return 0
    errors = report.errors
    if args.baseline:
        new, _old = Baseline.load(Path(args.baseline)).split(errors)
        errors = new
    if args.format == "json":
        out = report.as_json()
        out["new_errors"] = len(errors)
        print(json.dumps(out, indent=2))
    else:
        print(report.render_text())
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
