"""mdi-race: thread-role static analysis for the open-system serving stack.

PR 11 made the engine genuinely concurrent — one dedicated engine
thread (`server/frontend.py`'s `_pump`), an asyncio HTTP event loop,
and `submit()`/`cancel()`/`drain()` callable from any thread, all
serialized through one `threading.Lock`.  The rules here prove that
discipline statically, the same way `rules.py` proves the compiled-XLA
discipline: every function gets a **thread role**, and cross-role state
must go through the lock.

Roles (inferred per module, seeded from the code shapes the serving
stack actually uses, overridable with a comment annotation):

- ``engine`` — runs on a spawned worker thread: `threading.Thread(
  target=f)` targets and everything they call (the `step_hook` cone).
- ``loop``   — runs on the asyncio event loop: every ``async def`` plus
  functions handed to ``loop.call_soon_threadsafe``.
- ``any``    — callable from any thread: the public methods of a class
  that spawns a thread (the `ServingFrontend` surface).

Annotation syntax — on the ``def`` line or the line above it::

    def sink(event):  # mdi-thread: engine
        ...

An annotated function's role is pinned: inference neither adds to nor
propagates into it.  Roles propagate through ``self.method()`` calls,
module-level calls, ``self.method`` callback references and property
reads, to a fixpoint.

Rules:

- ``unguarded-shared-state``   — a ``self.X`` written in one role and
  touched from another, with any cross-role access outside a
  ``with self._lock`` block (lexical with-scoping, like the host-sync
  rule).  One finding per (class, attribute), anchored at the first
  unguarded access.
- ``blocking-in-event-loop``   — ``time.sleep``, sync ``.acquire()`` /
  ``.wait()``, thread ``.join()`` or subprocess calls inside an
  ``async def`` (or a function pinned to the loop role).
- ``lock-order-inversion``     — two locks acquired in both nesting
  orders somewhere in the module (deadlock-capable).
- ``loop-call-from-wrong-thread`` — ``call_soon``/``create_task``/...
  from an engine/any role; ``call_soon_threadsafe`` is the one
  sanctioned crossing.

The runtime companion is the deterministic schedule explorer
(`server/explorer.py`): seeded adversarial interleavings against a live
CPU engine, asserting token-stream parity with the offline engine.
See docs/analysis.md "Concurrency analysis".
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from mdi_llm_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    _dotted,
    rule,
)

ROLE_ENGINE = "engine"
ROLE_LOOP = "loop"
ROLE_ANY = "any"
VALID_ROLES = (ROLE_ENGINE, ROLE_LOOP, ROLE_ANY)

_ANNOT_RE = re.compile(r"#\s*mdi-thread:\s*(?P<role>[a-z]+)\b")

# attribute types that ARE synchronization (holding them shared is the
# point): detected from `self.x = threading.Lock()`-style __init__ sites
_SYNC_CTORS = {
    "Lock", "RLock", "Event", "Condition", "Semaphore",
    "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue",
    "LifoQueue", "PriorityQueue",
}

# method calls on an attribute that mutate it in place
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "clear", "update", "add", "discard", "setdefault",
    "sort", "reverse",
}

# event-loop APIs that are only legal ON the loop thread; the
# threadsafe crossing is `call_soon_threadsafe`
_LOOP_ONLY_CALLS = {"call_soon", "call_later", "call_at", "create_task",
                    "ensure_future"}

_BLOCKING_SUBPROCESS = {"run", "call", "check_call", "check_output"}


# ---------------------------------------------------------------------------
# the per-module thread model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FuncInfo:
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    name: str
    cls: Optional[ast.ClassDef]
    roles: Set[str] = dataclasses.field(default_factory=set)
    pinned: bool = False  # annotated: inference must not add roles


@dataclasses.dataclass
class ClassInfo:
    node: ast.ClassDef
    methods: Dict[str, FuncInfo]
    spawns_thread: bool = False
    sync_attrs: Set[str] = dataclasses.field(default_factory=set)
    lock_attrs: Set[str] = dataclasses.field(default_factory=set)
    init_only_attrs: Set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class ThreadModel:
    funcs: Dict[ast.AST, FuncInfo]
    classes: Dict[ast.ClassDef, ClassInfo]
    bad_annotations: List[Tuple[ast.AST, str]]

    def roles_of(self, node: ast.AST) -> Set[str]:
        info = self.funcs.get(node)
        return info.roles if info is not None else set()


def _is_thread_ctor(call: ast.Call) -> bool:
    d = _dotted(call.func)
    return d == "Thread" or d.endswith(".Thread")


def _annotation_for(mod: ModuleInfo, node: ast.AST) -> Optional[str]:
    """The `# mdi-thread: <role>` annotation on the def line or the line
    directly above it (above the decorators is NOT searched)."""
    line = getattr(node, "lineno", 0)
    for text in (mod.line_text(line), mod.line_text(line - 1)):
        m = _ANNOT_RE.search(text)
        if m:
            return m.group("role")
    return None


def _enclosing_class(mod: ModuleInfo, node: ast.AST) -> Optional[ast.ClassDef]:
    for a in mod.ancestors(node):
        if isinstance(a, ast.ClassDef):
            return a
    return None


def _own_body_walk(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body WITHOUT descending into nested defs/lambdas:
    a nested function runs in its own thread context (executor callback,
    sink, ...), so its statements carry the nested function's role, not
    the enclosing one's."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _is_property(node: ast.AST) -> bool:
    for dec in getattr(node, "decorator_list", []):
        if _dotted(dec).split(".")[-1] in ("property", "cached_property"):
            return True
    return False


_CONSTRUCTION_METHODS = {"__init__", "__new__", "__del__", "__init_subclass__"}


def _is_self_attr(n: ast.AST) -> bool:
    return (
        isinstance(n, ast.Attribute)
        and isinstance(n.value, ast.Name)
        and n.value.id == "self"
    )


def _is_write(mod: ModuleInfo, n: ast.Attribute) -> bool:
    """Does this `self.X` access mutate X?  Plain/aug-assign stores and
    dels, `self.X[k] = v`, and in-place mutator calls all count."""
    if isinstance(n.ctx, (ast.Store, ast.Del)):
        return True
    parent = mod.parents.get(n)
    if isinstance(parent, ast.Attribute) and parent.attr in _MUTATORS:
        grand = mod.parents.get(parent)
        if isinstance(grand, ast.Call) and grand.func is parent:
            return True
    if (
        isinstance(parent, ast.Subscript)
        and parent.value is n
        and isinstance(parent.ctx, (ast.Store, ast.Del))
    ):
        return True
    return False


def thread_model(mod: ModuleInfo) -> ThreadModel:
    """Build (and cache on the ModuleInfo) the module's thread model:
    per-function role sets, per-class attribute typing, spawner flags."""
    cached = getattr(mod, "_mdi_thread_model", None)
    if cached is not None:
        return cached

    funcs: Dict[ast.AST, FuncInfo] = {}
    classes: Dict[ast.ClassDef, ClassInfo] = {}
    bad_annotations: List[Tuple[ast.AST, str]] = []

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            classes[node] = ClassInfo(node, methods={})
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs[node] = FuncInfo(
                node, node.name, None  # cls filled below
            )

    for node, info in funcs.items():
        info.cls = _enclosing_class(mod, node)
        if info.cls is not None and info.cls in classes:
            # direct class-body methods only define the class surface;
            # nested defs inside a method still resolve `self` to it
            if node in info.cls.body:
                classes[info.cls].methods[info.name] = info

    # -- annotations (pinned) + async seeds ---------------------------------
    for node, info in funcs.items():
        role = _annotation_for(mod, node)
        if role is not None:
            if role not in VALID_ROLES:
                bad_annotations.append((node, role))
            else:
                info.roles = {role}
                info.pinned = True
                continue
        if isinstance(node, ast.AsyncFunctionDef):
            info.roles.add(ROLE_LOOP)

    # -- resolve a callback reference to a FuncInfo -------------------------
    def resolve(ref: ast.AST, cls: Optional[ast.ClassDef]) -> Optional[FuncInfo]:
        if (
            isinstance(ref, ast.Attribute)
            and isinstance(ref.value, ast.Name)
            and ref.value.id == "self"
            and cls is not None
            and cls in classes
        ):
            return classes[cls].methods.get(ref.attr)
        if isinstance(ref, ast.Name):
            for fn, info in funcs.items():
                if info.cls is None and info.name == ref.id:
                    return info
        return None

    # -- seeds from Thread(target=...) and call_soon_threadsafe(...) --------
    handoff_nodes: Set[ast.AST] = set()  # refs already role-seeded
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        cls = _enclosing_class(mod, node)
        if _is_thread_ctor(node):
            if cls is not None and cls in classes:
                classes[cls].spawns_thread = True
            for kw in node.keywords:
                if kw.arg == "target":
                    handoff_nodes.add(kw.value)
                    target = resolve(kw.value, cls)
                    if target is not None and not target.pinned:
                        target.roles.add(ROLE_ENGINE)
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "call_soon_threadsafe"
            and node.args
        ):
            handoff_nodes.add(node.args[0])
            target = resolve(node.args[0], cls)
            if target is not None and not target.pinned:
                target.roles.add(ROLE_LOOP)

    # -- any-thread seeds: public surface of thread-spawning classes --------
    for cls, cinfo in classes.items():
        if not cinfo.spawns_thread:
            continue
        for name, info in cinfo.methods.items():
            if info is None or name.startswith("_"):
                continue
            if not info.pinned:
                info.roles.add(ROLE_ANY)

    # -- per-class attribute typing from __init__ ---------------------------
    for cls, cinfo in classes.items():
        init = cinfo.methods.get("__init__")
        init_writes: Set[str] = set()
        if init is not None:
            for n in _own_body_walk(init.node):
                if (
                    isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                    and isinstance(n.ctx, ast.Store)
                ):
                    init_writes.add(n.attr)
                    parent = mod.parents.get(n)
                    value = getattr(parent, "value", None)
                    if isinstance(parent, (ast.Assign, ast.AnnAssign)) and \
                            isinstance(value, ast.Call):
                        ctor = _dotted(value.func).split(".")[-1]
                        if ctor in _SYNC_CTORS:
                            cinfo.sync_attrs.add(n.attr)
                            if ctor in ("Lock", "RLock"):
                                cinfo.lock_attrs.add(n.attr)
        # attributes written ONLY in __init__ are construction-time
        # constants: publishing the object is the happens-before edge
        written_elsewhere: Set[str] = set()
        for name, info in cinfo.methods.items():
            if info is None or name == "__init__":
                continue
            for n in _own_body_walk(info.node):
                if _is_self_attr(n) and _is_write(mod, n):
                    written_elsewhere.add(n.attr)
        cinfo.init_only_attrs = init_writes - written_elsewhere

    # -- propagate roles through the call graph to a fixpoint ---------------
    def callees(info: FuncInfo) -> Iterator[FuncInfo]:
        cinfo = classes.get(info.cls) if info.cls is not None else None
        method_names = set(cinfo.methods) if cinfo is not None else set()
        for n in _own_body_walk(info.node):
            target: Optional[FuncInfo] = None
            if isinstance(n, ast.Call):
                target = resolve(n.func, info.cls)
            elif (
                isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Name)
                and n.value.id == "self"
                and isinstance(n.ctx, ast.Load)
                and n.attr in method_names
                and n not in handoff_nodes
            ):
                # callback reference (`step_hook=self._on_step`) or a
                # property read (`self.idle`): the caller's role reaches it
                target = cinfo.methods.get(n.attr)
            if target is not None:
                yield target

    changed = True
    while changed:
        changed = False
        for info in funcs.values():
            if not info.roles:
                continue
            for target in callees(info):
                if target.pinned or target.name in _CONSTRUCTION_METHODS:
                    continue
                before = len(target.roles)
                target.roles |= info.roles
                if len(target.roles) != before:
                    changed = True

    model = ThreadModel(funcs, classes, bad_annotations)
    mod._mdi_thread_model = model  # type: ignore[attr-defined]
    return model


# ---------------------------------------------------------------------------
# shared-state analysis
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Access:
    node: ast.AST
    method: FuncInfo
    write: bool
    guarded: bool


def _is_lockish(name: str, cinfo: Optional[ClassInfo]) -> bool:
    if "lock" in name.lower():
        return True
    return cinfo is not None and name in cinfo.lock_attrs


def _lock_name_of(expr: ast.AST, cinfo: Optional[ClassInfo]) -> Optional[str]:
    """The identity of a lock expression in a `with` item, or None when
    the expression does not look like a lock."""
    d = _dotted(expr)
    if not d:
        return None
    last = d.split(".")[-1]
    if _is_lockish(last, cinfo):
        return d
    return None


def _guarded(mod: ModuleInfo, node: ast.AST, fn: ast.AST,
             cinfo: Optional[ClassInfo]) -> bool:
    """True when `node` sits lexically inside a `with <lock>:` block of
    its own function (with-block scoping, same approach as host-sync)."""
    for a in mod.ancestors(node):
        if a is fn:
            return False
        if isinstance(a, (ast.With, ast.AsyncWith)):
            for item in a.items:
                if _lock_name_of(item.context_expr, cinfo) is not None:
                    return True
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
    return False


def _class_accesses(mod: ModuleInfo, model: ThreadModel,
                    cinfo: ClassInfo) -> Dict[str, List[_Access]]:
    """self.X reads/writes per attribute, across every role-carrying
    method of the class (nested defs with roles included — `self` still
    resolves to the class)."""
    out: Dict[str, List[_Access]] = {}
    method_names = set(cinfo.methods)
    for info in model.funcs.values():
        if info.cls is not cinfo.node:
            continue
        if not info.roles or info.name in _CONSTRUCTION_METHODS:
            continue
        for n in _own_body_walk(info.node):
            if not _is_self_attr(n):
                continue
            attr = n.attr
            if attr in method_names:  # method/property access, not state
                continue
            if attr in cinfo.sync_attrs or _is_lockish(attr, cinfo):
                continue  # synchronization primitives are meant to be shared
            if attr in cinfo.init_only_attrs:
                continue  # construction-time constant
            out.setdefault(attr, []).append(_Access(
                n, info, _is_write(mod, n),
                _guarded(mod, n, info.node, cinfo),
            ))
    return out


@rule(
    "unguarded-shared-state",
    "instance attribute shared across thread roles with accesses outside the lock",
)
def unguarded_shared_state(mod: ModuleInfo) -> Iterator[Finding]:
    model = thread_model(mod)
    for node, role in model.bad_annotations:
        yield mod.finding(
            "unguarded-shared-state",
            node,
            f"unknown thread role {role!r} in `# mdi-thread:` annotation "
            f"(valid: {', '.join(VALID_ROLES)})",
        )
    for cinfo in model.classes.values():
        for attr, accesses in sorted(_class_accesses(mod, model, cinfo).items()):
            write_roles: Set[str] = set()
            touch_roles: Set[str] = set()
            for a in accesses:
                touch_roles |= a.method.roles
                if a.write:
                    write_roles |= a.method.roles
            if not write_roles or len(touch_roles) < 2:
                continue  # single-role state, or never written post-init
            unguarded = sorted(
                (a for a in accesses if not a.guarded),
                key=lambda a: (a.node.lineno, a.node.col_offset),
            )
            if not unguarded:
                continue
            sites = ", ".join(
                f"`{a.method.name}`:{a.node.lineno}"
                f" ({'write' if a.write else 'read'})"
                for a in unguarded[:4]
            )
            more = len(unguarded) - 4
            if more > 0:
                sites += f" and {more} more"
            yield mod.finding(
                "unguarded-shared-state",
                unguarded[0].node,
                f"`self.{attr}` of `{cinfo.node.name}` is written on the "
                f"{'/'.join(sorted(write_roles))} role and touched from "
                f"{'/'.join(sorted(touch_roles))}, but not every cross-role "
                f"access is under `with self.<lock>`: {sites} — take the "
                "lock, or suppress with a justification if the racy read "
                "is the design (GIL-atomic snapshot)",
            )


# ---------------------------------------------------------------------------
# blocking-in-event-loop
# ---------------------------------------------------------------------------


def _blocking_reason(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    d = _dotted(call.func)
    awaited = isinstance(mod.parents.get(call), ast.Await)
    if d == "time.sleep":
        return "`time.sleep` parks the whole event loop"
    if d == "os.system":
        return "`os.system` blocks until the child exits"
    parts = d.split(".")
    if len(parts) >= 2 and parts[-2] == "subprocess" and \
            parts[-1] in _BLOCKING_SUBPROCESS:
        return f"`{d}` blocks until the child exits"
    if not isinstance(call.func, ast.Attribute) or awaited:
        return None
    attr = call.func.attr
    recv = _dotted(call.func.value)
    if attr == "acquire":
        return f"sync `{recv or '<expr>'}.acquire()` can block the loop " \
               "on a lock another thread holds"
    if attr == "wait" and not d.startswith("asyncio"):
        return f"un-awaited `{recv or '<expr>'}.wait()` blocks the loop " \
               "until another thread signals"
    if attr == "join" and "thread" in recv.lower():
        return f"`{recv}.join()` blocks the loop on a thread exit"
    return None


@rule(
    "blocking-in-event-loop",
    "time.sleep/.acquire()/.wait()/subprocess call inside an async def (stalls every connection)",
)
def blocking_in_event_loop(mod: ModuleInfo) -> Iterator[Finding]:
    model = thread_model(mod)
    for node, info in model.funcs.items():
        on_loop = isinstance(node, ast.AsyncFunctionDef) or \
            info.roles == {ROLE_LOOP}
        if not on_loop:
            continue
        for n in _own_body_walk(node):
            if not isinstance(n, ast.Call):
                continue
            why = _blocking_reason(mod, n)
            if why:
                yield mod.finding(
                    "blocking-in-event-loop",
                    n,
                    f"{why} inside loop-role `{info.name}`: every other "
                    "connection stalls behind it — await the async "
                    "equivalent, or push it off-loop with "
                    "`loop.run_in_executor` (server/http.py's pattern)",
                )


# ---------------------------------------------------------------------------
# lock-order-inversion
# ---------------------------------------------------------------------------


def _with_lock_names(node: ast.AST, cinfo: Optional[ClassInfo]) -> List[str]:
    if not isinstance(node, (ast.With, ast.AsyncWith)):
        return []
    out = []
    for item in node.items:
        name = _lock_name_of(item.context_expr, cinfo)
        if name is not None:
            out.append(name)
    return out


@rule(
    "lock-order-inversion",
    "two locks acquired in both nesting orders within the module (deadlock-capable)",
)
def lock_order_inversion(mod: ModuleInfo) -> Iterator[Finding]:
    model = thread_model(mod)
    edges: Dict[Tuple[str, str], List[ast.AST]] = {}
    for node, info in model.funcs.items():
        cinfo = model.classes.get(info.cls) if info.cls is not None else None
        for n in _own_body_walk(node):
            inner = _with_lock_names(n, cinfo)
            if not inner:
                continue
            held: List[str] = []
            for a in mod.ancestors(n):
                if a is node:
                    break
                held.extend(_with_lock_names(a, cinfo))
            # `with a, b:` acquires left-to-right: earlier items are
            # held while later ones are taken
            for i, b in enumerate(inner):
                for a_name in held + inner[:i]:
                    if a_name != b:
                        edges.setdefault((a_name, b), []).append(n)
    for (a, b), sites in sorted(edges.items()):
        rev = edges.get((b, a))
        if not rev:
            continue
        for site in sites:
            yield mod.finding(
                "lock-order-inversion",
                site,
                f"`{b}` is acquired while holding `{a}` here, but line "
                f"{rev[0].lineno} acquires `{a}` while holding `{b}` — two "
                "threads taking the two orders deadlock; pick one global "
                "acquisition order",
            )


# ---------------------------------------------------------------------------
# loop-call-from-wrong-thread
# ---------------------------------------------------------------------------


@rule(
    "loop-call-from-wrong-thread",
    "asyncio loop API (call_soon/create_task/...) touched from an engine/any role",
)
def loop_call_from_wrong_thread(mod: ModuleInfo) -> Iterator[Finding]:
    model = thread_model(mod)
    for node, info in model.funcs.items():
        if isinstance(node, ast.AsyncFunctionDef):
            continue
        if not info.roles or ROLE_LOOP in info.roles:
            continue
        for n in _own_body_walk(node):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)):
                continue
            if n.func.attr not in _LOOP_ONLY_CALLS:
                continue
            d = _dotted(n.func)
            yield mod.finding(
                "loop-call-from-wrong-thread",
                n,
                f"`{d}` in `{info.name}` (role: "
                f"{'/'.join(sorted(info.roles))}) touches the asyncio loop "
                "from off-loop: these APIs are not thread-safe — cross with "
                "`loop.call_soon_threadsafe(...)` (the HTTP sink's bridge)",
            )
