"""`mdi-lint`: JAX/TPU-aware static analysis for this repo's hot paths.

The serving story (recurrent pipeline parallelism, paged-KV continuous
batching) only holds while every decode path stays inside a single compiled
XLA program.  One stray Python branch on a tracer, an undonated KV buffer,
or a hidden host sync silently turns "as fast as the hardware allows" into
per-token recompiles and device<->host ping-pong.  The rules here encode
those invariants; the runtime companion (`utils.profiling.CompileGuard`)
proves the steady state on real traces.

Usage::

    mdi-lint mdi_llm_tpu/                  # or: python -m mdi_llm_tpu.analysis
    mdi-lint --list-rules
    mdi-lint mdi_llm_tpu/ --update-baseline

Findings are suppressed per line with ``# mdi-lint: disable=rule-name`` (or
``disable-next-line=`` on the preceding line); grandfathered findings live
in the committed ``.mdi-lint-baseline.json``.  See docs/analysis.md.
"""

from mdi_llm_tpu.analysis.core import (  # noqa: F401
    Baseline,
    Finding,
    Rule,
    RULES,
    lint_paths,
    lint_source,
)
import mdi_llm_tpu.analysis.rules  # noqa: E402,F401  (populates RULES)
import mdi_llm_tpu.analysis.threads  # noqa: E402,F401  (thread-role rules)

__all__ = [
    "Baseline", "Finding", "Rule", "RULES", "lint_paths", "lint_source",
    # mdi-audit (lazy: keeps bare mdi-lint free of the jax import)
    "AUDIT_RULES", "AuditReport", "MeshSpec", "PlanSpec", "audit_plan",
    "preflight",
    # mdi-ir (lazy for the same reason: tracing needs jax)
    "IR_RULES", "IrReport", "ir_preflight", "trace_serving",
    # mdi-flow (lazy: liveness shares mdi-ir's trace seam)
    "FLOW_RULES", "ExecProfile", "FlowReport", "analyze_flow",
    "flow_preflight", "jaxpr_digest", "profile_executable",
    # mdi-check (lazy: the aggregate gate pulls in every family)
    "FAMILIES", "run_check",
]

_AUDIT_NAMES = {"AUDIT_RULES", "AuditReport", "audit_plan", "preflight"}
_PLAN_NAMES = {"MeshSpec", "PlanSpec"}
_IR_NAMES = {"IR_RULES", "IrReport", "ir_preflight", "trace_serving"}
_FLOW_NAMES = {"FLOW_RULES", "ExecProfile", "FlowReport", "analyze_flow",
               "flow_preflight", "jaxpr_digest", "profile_executable"}
_CHECK_NAMES = {"FAMILIES", "run_check"}


def __getattr__(name):
    if name in _AUDIT_NAMES:
        from mdi_llm_tpu.analysis import audit

        return getattr(audit, name)
    if name in _PLAN_NAMES:
        from mdi_llm_tpu.analysis import plan

        return getattr(plan, name)
    if name in _IR_NAMES:
        from mdi_llm_tpu.analysis import ir

        return getattr(ir, name)
    if name in _FLOW_NAMES:
        from mdi_llm_tpu.analysis import liveness

        return getattr(liveness, name)
    if name in _CHECK_NAMES:
        from mdi_llm_tpu.analysis import check

        return getattr(check, name)
    raise AttributeError(name)
