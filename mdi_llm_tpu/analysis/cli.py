"""`mdi-lint` console entry point (also `python -m mdi_llm_tpu.analysis`).

Exit codes: 0 = clean (modulo baseline/suppressions), 1 = new findings,
2 = usage/parse errors.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Optional, Sequence

from mdi_llm_tpu.analysis.core import (
    BASELINE_NAME,
    Baseline,
    RULES,
    _selected_rules,
    lint_paths,
)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="mdi-lint",
        description="JAX/TPU-aware static analysis for mdi-llm-tpu "
        "(recompile hazards, host syncs, donation misuse; see docs/analysis.md)",
    )
    ap.add_argument("paths", nargs="*", default=["mdi_llm_tpu"],
                    help="files or directories to lint (default: mdi_llm_tpu)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help=f"baseline file (default: ./{BASELINE_NAME})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to grandfather all current findings")
    ap.add_argument("--select", default=None, metavar="RULES",
                    help="comma-separated rule names to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule registry and exit")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    return ap


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    rules = _selected_rules(None)  # import side effect: populate RULES

    if args.list_rules:
        width = max(len(r.name) for r in rules)
        for r in sorted(RULES.values(), key=lambda r: r.name):
            print(f"{r.name:<{width}}  {r.summary}")
        return 0

    select = [s.strip() for s in args.select.split(",")] if args.select else None
    if select:
        unknown = [s for s in select if s not in RULES]
        if unknown:
            print(f"mdi-lint: unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
            return 2

    baseline_path = Path(args.baseline) if args.baseline else Path(BASELINE_NAME)
    findings, errors = lint_paths(args.paths, select=select)
    for e in errors:
        print(f"mdi-lint: {e}", file=sys.stderr)

    if args.update_baseline:
        new_baseline = Baseline.from_findings(findings)
        if select:
            # refresh ONLY the selected rules' entries; other rules keep
            # their grandfathered findings (keys are "rule::path::text")
            old = Baseline.load(baseline_path)
            for key, count in old.counts.items():
                if key.split("::", 1)[0] not in select:
                    new_baseline.counts[key] = count
        new_baseline.save(baseline_path)
        print(
            f"mdi-lint: baseline written to {baseline_path} "
            f"({len(findings)} finding(s) grandfathered)"
        )
        return 0 if not errors else 2

    if args.no_baseline:
        new, old = list(findings), []
    else:
        new, old = Baseline.load(baseline_path).split(findings)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.__dict__ for f in new],
            "baselined": len(old),
            "errors": errors,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        summary = f"mdi-lint: {len(new)} finding(s)"
        if old:
            summary += f" ({len(old)} grandfathered by {baseline_path})"
        print(summary)
    if errors:
        return 2
    return 1 if new else 0


if __name__ == "__main__":
    raise SystemExit(main())
