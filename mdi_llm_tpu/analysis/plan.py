"""Abstract plan IR for `mdi-audit` (the PLAN-level companion to mdi-lint).

A *plan* is everything that determines where bytes live and how collectives
fire before the first compile: the model `Config`, a mesh declaration
(axis name → size), the parallel strategy (tp/ep axes, pipeline stages,
samples per ring slot), and optionally a `ServingConfig` for the paged-KV
pool.  This module models all of it **symbolically** — abstract shapes are
zero-stride numpy broadcast views (correct `.shape`/`.dtype`/`.nbytes`,
zero memory), permutations are plain `(src, dst)` tuples — so the auditor
(`audit.py`) can evaluate a plan without touching a device, initializing a
JAX backend, or compiling anything.  That constraint is load-bearing: the
whole point is to reject a bad plan before the expensive part starts, and
it is enforced by `tests/test_audit.py` with a backend trip-wire.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from mdi_llm_tpu.config import Config, ServingConfig, dtype_bytes

__all__ = [
    "MeshSpec",
    "PlanSpec",
    "abstract_params",
    "iter_leaves",
    "tree_bytes",
    "ring_permutation",
    "resolve_np_dtype",
]


# ---------------------------------------------------------------------------
# dtypes (no jax: ml_dtypes registers bfloat16/float8 with numpy)
# ---------------------------------------------------------------------------

_DTYPE_ALIASES = {
    "auto": "bfloat16",  # engine default when cache_dtype is unset
    "float8": "float8_e4m3fn",
    "bf16": "bfloat16",
    "f16": "float16",
    "f32": "float32",
}


def resolve_np_dtype(dtype) -> np.dtype:
    """Name/np-dtype/jax-scalar-type → numpy dtype, backend-free."""
    import ml_dtypes  # noqa: F401  (registers bfloat16/float8 with numpy)

    if isinstance(dtype, str):
        dtype = _DTYPE_ALIASES.get(dtype, dtype)
        if dtype in ("float8_e4m3fn", "float8_e5m2"):
            return np.dtype(getattr(ml_dtypes, dtype))
        if dtype == "bfloat16":
            return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(dtype)


def _stub(shape: Sequence[int], dtype) -> np.ndarray:
    """Abstract array: right shape/dtype/nbytes, zero actual memory."""
    return np.broadcast_to(np.zeros((), resolve_np_dtype(dtype)), tuple(shape))


# ---------------------------------------------------------------------------
# mesh + plan declarations
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declared device mesh: ordered {axis name: size}.  Purely symbolic —
    no devices are enumerated; `n_devices` is what the plan CLAIMS."""

    axes: Tuple[Tuple[str, int], ...] = ()

    @classmethod
    def from_dict(cls, d: Dict[str, int]) -> "MeshSpec":
        return cls(tuple((str(k), int(v)) for k, v in d.items()))

    @classmethod
    def parse(cls, text: str) -> "MeshSpec":
        """'pipe=4,tp=2' → MeshSpec.  Empty string → single device."""
        axes = []
        for part in filter(None, (p.strip() for p in text.split(","))):
            m = re.fullmatch(r"([A-Za-z_][A-Za-z0-9_]*)\s*=\s*(-?\d+)", part)
            if not m:
                raise ValueError(f"bad mesh axis {part!r} (want name=size)")
            axes.append((m.group(1), int(m.group(2))))
        return cls(tuple(axes))

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(k for k, _ in self.axes)

    @property
    def sizes(self) -> Dict[str, int]:
        return dict(self.axes)

    def size(self, name: str, default: int = 1) -> int:
        return self.sizes.get(name, default)

    @property
    def n_devices(self) -> int:
        return int(math.prod(v for _, v in self.axes)) if self.axes else 1

    def describe(self) -> str:
        return ",".join(f"{k}={v}" for k, v in self.axes) or "single-device"


@dataclasses.dataclass
class PlanSpec:
    """One auditable (Config, mesh, parallel plan, ServingConfig) tuple.

    `kv_seq_len` is the ACTUAL cache length a run will allocate (the
    engines size caches to the run, `generation._run_cache_len`); when
    None the budget uses `max_seq_length` — the conservative ceiling.
    `ring_perm` overrides the derived stage-ring permutation (the IR knob
    the schedule checker exercises; None → `ring_permutation(n_stages)`).
    `shard_head` mirrors which engine consumes the plan: the Generator
    mesh path shards embeddings/head on tp (vocab divisibility matters),
    the pipeline ring replicates them per stage.
    """

    cfg: Config
    mesh: MeshSpec = dataclasses.field(default_factory=MeshSpec)
    tp_axis: Optional[str] = None
    ep_axis: Optional[str] = None
    dp_axis: Optional[str] = None
    sp_axis: Optional[str] = None
    n_stages: int = 1
    pipeline: Optional[bool] = None  # None → inferred from n_stages > 1
    samples_per_slot: int = 1
    n_samples: int = 1
    batch: int = 1
    max_seq_length: Optional[int] = None
    kv_seq_len: Optional[int] = None
    act_seq_len: int = 1  # widest live token axis (decode=1, prefill=bucket)
    dtype: str = "bfloat16"
    cache_dtype: Optional[str] = None
    quantize: Optional[str] = None
    serving: Optional[ServingConfig] = None
    ring_perm: Optional[Tuple[Tuple[int, int], ...]] = None
    rank_programs: Optional[List[List[Tuple]]] = None  # per-rank op traces
    hbm_gb: Optional[float] = None
    host_gb: Optional[float] = None  # host-RAM budget for the KV block tier
    shard_head: bool = True
    donate_kv: bool = True
    origin: str = "<plan>"

    @property
    def is_pipeline(self) -> bool:
        """True when the plan runs the recurrent ring engine — a 1-stage
        ring (bench --pipeline 1) still uses slot-based KV, not the dense
        Generator cache."""
        return self.n_stages > 1 if self.pipeline is None else bool(self.pipeline)

    @property
    def seq_len(self) -> int:
        s = self.max_seq_length or self.cfg.block_size
        return int(min(s, self.cfg.block_size))

    @property
    def cache_len(self) -> int:
        return int(min(self.kv_seq_len or self.seq_len, self.seq_len))

    @property
    def kv_dtype(self) -> str:
        return self.cache_dtype or self.dtype

    def describe(self) -> str:
        bits = [self.cfg.name or "<config>", f"mesh {self.mesh.describe()}"]
        if self.n_stages > 1:
            bits.append(f"stages={self.n_stages} M={self.samples_per_slot}")
        bits.append(f"dtype={self.dtype}")
        if self.quantize and self.quantize != "none":
            bits.append(f"quant={self.quantize}")
        if self.serving is not None:
            bits.append(f"serve(bs={self.serving.block_size})")
        return " | ".join(bits)


# ---------------------------------------------------------------------------
# abstract parameter shapes
# ---------------------------------------------------------------------------


def abstract_params(cfg: Config, dtype="bfloat16", quantize: Optional[str] = None):
    """Pytree of zero-stride stubs mirroring `transformer.init_params`
    exactly (shapes, dtypes, and key layout), optionally transformed to the
    quantized storage layout of `ops.quant.quantize_params` so per-leaf
    `.nbytes` is the true HBM cost.  Costs no memory and no backend."""
    L, D, V = cfg.n_layer, cfg.n_embd, cfg.padded_vocab_size
    I = cfg.intermediate_size

    def lin(out_d, in_d, bias=cfg.bias):
        p = {"weight": _stub((L, out_d, in_d), dtype)}
        if bias:
            p["bias"] = _stub((L, out_d), dtype)
        return p

    def norm_p():
        p = {"weight": _stub((L, D), dtype)}
        if cfg.norm_class_name == "LayerNorm" and cfg.bias:
            p["bias"] = _stub((L, D), dtype)
        return p

    attn = {"qkv": lin(cfg.qkv_size, D), "proj": lin(D, cfg.attn_out_size)}
    if cfg.mlp_class_name == "GptNeoxMLP":
        mlp = {"fc": lin(I, D), "proj": lin(D, I)}
    elif cfg.mlp_class_name in ("LLaMAMLP", "GemmaMLP"):
        mlp = {
            "fc_1": lin(I, D, bias=False),
            "fc_2": lin(I, D, bias=False),
            "proj": lin(D, I, bias=False),
        }
    else:  # LLaMAMoE
        E = cfg.n_expert
        mlp = {
            "gate": {"weight": _stub((L, E, D), dtype)},
            "experts": {
                "fc_1": {"weight": _stub((L, E, I, D), dtype)},
                "fc_2": {"weight": _stub((L, E, I, D), dtype)},
                "proj": {"weight": _stub((L, E, D, I), dtype)},
            },
        }
    blocks = {"norm_1": norm_p(), "attn": attn, "mlp": mlp}
    if not cfg.shared_attention_norm:
        blocks["norm_2"] = norm_p()

    params: Dict[str, Any] = {
        "wte": {"weight": _stub((V, D), dtype)},
        "blocks": blocks,
        "ln_f": {
            "weight": _stub((D,), dtype),
            **(
                {"bias": _stub((D,), dtype)}
                if cfg.norm_class_name == "LayerNorm" and cfg.bias
                else {}
            ),
        },
    }
    if cfg.pos_embedding == "learned":
        params["wpe"] = {"weight": _stub((cfg.block_size, D), dtype)}
    if not cfg.tie_embeddings:
        params["lm_head"] = {"weight": _stub((V, D), dtype)}
        if cfg.lm_head_bias:
            params["lm_head"]["bias"] = _stub((V,), dtype)
    elif cfg.lm_head_bias:
        params["lm_head"] = {"bias": _stub((V,), dtype)}

    if quantize and quantize != "none":
        params = _quantize_stubs(params, quantize)
    return params


def _quantize_stubs(params, flag: str):
    """Apply the `ops.quant.quantize_params` storage transform to a stub
    tree: every >=2-D "weight" outside SKIP_KEYS becomes int8 storage
    (+ f32 scale); int4 packs two nibbles per byte with group scales."""
    from mdi_llm_tpu.ops.quant import FLAG_TO_MODE, SKIP_KEYS, w4_group_size

    mode = FLAG_TO_MODE.get(flag, flag)
    if mode not in ("w8", "w8a8", "w4"):
        raise ValueError(f"unknown quantize mode {flag!r}")
    wkey = {"w8": "weight_q", "w8a8": "weight_q8", "w4": "weight_q4"}[mode]

    def walk(node, name):
        if not isinstance(node, dict):
            return node
        if name in SKIP_KEYS:
            return node
        out = {}
        for k, v in node.items():
            if k == "weight" and np.ndim(v) >= 2:
                shape = np.shape(v)
                if mode == "w4":
                    in_d = shape[-1]
                    g = w4_group_size(in_d)
                    out[wkey] = _stub(shape[:-1] + (in_d // 2,), np.int8)
                    out["scale"] = _stub(shape[:-1] + (in_d // g,), np.float32)
                else:
                    out[wkey] = _stub(shape, np.int8)
                    out["scale"] = _stub(shape[:-1], np.float32)
            else:
                out[k] = walk(v, k)
        return out

    return walk(params, "")


def iter_leaves(tree, prefix: str = "") -> Iterator[Tuple[str, Any]]:
    """Yield ('blocks.attn.qkv.weight', leaf) pairs in key order."""
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from iter_leaves(tree[k], f"{prefix}.{k}" if prefix else k)
    else:
        yield prefix, tree


def tree_bytes(tree) -> int:
    """Logical bytes of a stub (or real) pytree — `.nbytes` is shape-based,
    so zero-stride stubs report the true allocation cost."""
    return sum(int(leaf.nbytes) for _, leaf in iter_leaves(tree))


# ---------------------------------------------------------------------------
# schedules
# ---------------------------------------------------------------------------


def ring_permutation(n: int) -> Tuple[Tuple[int, int], ...]:
    """The stage/sp ring every engine builds: i → (i+1) mod n.  This is the
    single source the symbolic schedule checker validates fixtures against
    (parallel/pipeline.py, ops/ring_attention.py build the same list)."""
    return tuple((i, (i + 1) % n) for i in range(n))
