"""mdi-lint core: findings, the rule registry, suppressions, the baseline.

A rule is a function ``check(module: ModuleInfo) -> Iterable[Finding]``
registered via the :func:`rule` decorator (implementations live in
``rules.py``).  ``ModuleInfo`` does the shared one-pass AST analysis every
rule needs: parent links, the set of jit-compiled function bodies with
their static/donated argument specs, and module-level state.

Suppressions are per line::

    toks = jax.device_get(emits)  # mdi-lint: disable=host-sync -- one batched fetch

    # mdi-lint: disable-next-line=tracer-branch -- shape check, not a value branch
    if x.ndim == 2: ...

Everything after ``--`` is a free-form justification.  ``disable=all``
silences every rule on that line.

The baseline (``.mdi-lint-baseline.json``) grandfathers existing findings:
keys are ``rule::path::<stripped source line>`` with an occurrence count,
so findings survive line-number drift but a NEW violation of the same rule
on a different line still fails.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

BASELINE_NAME = ".mdi-lint-baseline.json"

# ---------------------------------------------------------------------------
# findings + registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # posix, relative to the lint root when possible
    line: int
    col: int
    message: str
    line_text: str = ""  # stripped source line, used for baseline keys

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"

    @property
    def baseline_key(self) -> str:
        return f"{self.rule}::{self.path}::{self.line_text}"


@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    summary: str
    check: Callable[["ModuleInfo"], Iterable[Finding]]


RULES: Dict[str, Rule] = {}


def rule(name: str, summary: str):
    """Register a rule implementation under `name` (kebab-case)."""

    def deco(fn):
        if name in RULES:
            raise ValueError(f"duplicate rule {name!r}")
        RULES[name] = Rule(name, summary, fn)
        return fn

    return deco


# ---------------------------------------------------------------------------
# shared AST analysis
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class JitSpec:
    """Static/donated argument info parsed from a jit decoration site."""

    static_argnames: Set[str] = dataclasses.field(default_factory=set)
    static_argnums: Set[int] = dataclasses.field(default_factory=set)
    donate_argnums: Set[int] = dataclasses.field(default_factory=set)
    donate_argnames: Set[str] = dataclasses.field(default_factory=set)
    call: Optional[ast.Call] = None  # the jit/partial call node, if any


@dataclasses.dataclass
class JittedFn:
    node: ast.FunctionDef
    spec: JitSpec

    @property
    def param_names(self) -> List[str]:
        a = self.node.args
        return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]

    def static_params(self) -> Set[str]:
        names = set(self.spec.static_argnames)
        params = self.param_names
        for i in self.spec.static_argnums:
            if 0 <= i < len(params):
                names.add(params[i])
        return names

    def donated_params(self) -> Set[str]:
        names = set(self.spec.donate_argnames)
        params = self.param_names
        for i in self.spec.donate_argnums:
            if 0 <= i < len(params):
                names.add(params[i])
        return names


def _dotted(node: ast.AST) -> str:
    """'jax.jit' for Attribute/Name chains, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_ref(node: ast.AST) -> bool:
    d = _dotted(node)
    return d in ("jit", "pjit") or d.endswith(".jit") or d.endswith(".pjit")


def _int_elems(node: ast.AST) -> Set[int]:
    out: Set[int] = set()
    elems = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    for e in elems:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
            out.add(e.value)
    return out


def _str_elems(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    elems = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    for e in elems:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.add(e.value)
    return out


def _spec_from_kwargs(call: ast.Call) -> JitSpec:
    spec = JitSpec(call=call)
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            spec.static_argnames |= _str_elems(kw.value)
        elif kw.arg == "static_argnums":
            spec.static_argnums |= _int_elems(kw.value)
        elif kw.arg == "donate_argnums":
            spec.donate_argnums |= _int_elems(kw.value)
        elif kw.arg == "donate_argnames":
            spec.donate_argnames |= _str_elems(kw.value)
    return spec


def jit_spec_of_call(call: ast.Call) -> Optional[JitSpec]:
    """JitSpec if `call` is a jit decoration/wrapping site, else None.

    Recognizes ``jax.jit(...)``, ``jit(...)``, ``pjit(...)`` and
    ``[functools.]partial(jax.jit, ...)``.
    """
    if _is_jit_ref(call.func):
        return _spec_from_kwargs(call)
    d = _dotted(call.func)
    if (d == "partial" or d.endswith(".partial")) and call.args:
        if _is_jit_ref(call.args[0]):
            return _spec_from_kwargs(call)
    return None


def jit_spec_of_decorator(dec: ast.AST) -> Optional[JitSpec]:
    if _is_jit_ref(dec):
        return JitSpec()
    if isinstance(dec, ast.Call):
        return jit_spec_of_call(dec)
    return None


class ModuleInfo:
    """One parsed module plus the pre-computed facts rules share."""

    def __init__(self, path: str, source: str, tree: Optional[ast.Module] = None):
        self.path = path
        self.source = source
        self.tree = tree if tree is not None else ast.parse(source)
        self.lines = source.splitlines()
        # child -> parent links (rules walk up for enclosing context)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        # jit-compiled function bodies: decorated defs, plus defs wrapped at
        # an assignment site (g = jax.jit(f, ...)) resolved within the module
        self.jitted: List[JittedFn] = []
        wrapped: Dict[str, JitSpec] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    spec = jit_spec_of_decorator(dec)
                    if spec is not None:
                        self.jitted.append(JittedFn(node, spec))
                        break
            elif isinstance(node, ast.Call):
                spec = jit_spec_of_call(node)
                # jax.jit(f, ...) wrapping a named local function
                if (
                    spec is not None
                    and node.args
                    and not _is_jit_ref(node.args[0])
                    and isinstance(node.args[0], ast.Name)
                ):
                    wrapped[node.args[0].id] = spec
        if wrapped:
            jitted_nodes = {j.node for j in self.jitted}
            for node in ast.walk(self.tree):
                if (
                    isinstance(node, ast.FunctionDef)
                    and node.name in wrapped
                    and node not in jitted_nodes
                ):
                    self.jitted.append(JittedFn(node, wrapped[node.name]))
        self._jit_bodies: Optional[Set[ast.AST]] = None

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule_name: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=rule_name,
            path=self.path,
            line=line,
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            line_text=self.line_text(line),
        )

    # -- enclosing-context helpers ------------------------------------------

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        while node in self.parents:
            node = self.parents[node]
            yield node

    def jit_body_nodes(self) -> Set[ast.AST]:
        """Every AST node lexically inside a jit-compiled function body."""
        if self._jit_bodies is None:
            self._jit_bodies = set()
            for j in self.jitted:
                for n in ast.walk(j.node):
                    self._jit_bodies.add(n)
        return self._jit_bodies

    def in_jit(self, node: ast.AST) -> bool:
        return node in self.jit_body_nodes()

    def enclosing_loop(self, node: ast.AST) -> Optional[ast.AST]:
        for a in self.ancestors(node):
            if isinstance(a, (ast.For, ast.While, ast.AsyncFor)):
                return a
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return None  # don't escape into an enclosing function's loop
        return None


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*mdi-lint:\s*disable(?P<next>-next-line)?\s*=\s*"
    r"(?P<rules>all|[A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
)


def suppressions(source: str) -> Dict[int, Set[str]]:
    """Map 1-based line number -> rule names suppressed there ('all' wins)."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        names = {r.strip() for r in m.group("rules").split(",")}
        target = i + 1 if m.group("next") else i
        out.setdefault(target, set()).update(names)
    return out


def _is_suppressed(f: Finding, sup: Dict[int, Set[str]]) -> bool:
    names = sup.get(f.line, ())
    return "all" in names or f.rule in names


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


class Baseline:
    """Grandfathered findings, keyed by rule + path + source-line text."""

    def __init__(self, counts: Optional[Dict[str, int]] = None):
        self.counts: Dict[str, int] = dict(counts or {})

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not Path(path).exists():
            return cls()
        data = json.loads(Path(path).read_text())
        return cls(data.get("findings", {}))

    @classmethod
    def from_findings(cls, findings: Sequence[Finding]) -> "Baseline":
        b = cls()
        for f in findings:
            b.counts[f.baseline_key] = b.counts.get(f.baseline_key, 0) + 1
        return b

    def save(self, path: Path) -> None:
        data = {
            "note": (
                "mdi-lint grandfathered findings; regenerate with "
                "`mdi-lint <paths> --update-baseline`.  Fix findings rather "
                "than baselining them whenever possible."
            ),
            "version": 1,
            "findings": {k: self.counts[k] for k in sorted(self.counts)},
        }
        Path(path).write_text(json.dumps(data, indent=2) + "\n")

    def split(
        self, findings: Sequence[Finding]
    ) -> Tuple[List[Finding], List[Finding]]:
        """(new, grandfathered); at most `count` findings per key pass."""
        remaining = dict(self.counts)
        new: List[Finding] = []
        old: List[Finding] = []
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
            if remaining.get(f.baseline_key, 0) > 0:
                remaining[f.baseline_key] -= 1
                old.append(f)
            else:
                new.append(f)
        return new, old


# ---------------------------------------------------------------------------
# driving
# ---------------------------------------------------------------------------


def _selected_rules(select: Optional[Sequence[str]]) -> List[Rule]:
    import mdi_llm_tpu.analysis.rules  # noqa: F401  (registers RULES)
    import mdi_llm_tpu.analysis.threads  # noqa: F401  (thread-role rules)

    if not select:
        return list(RULES.values())
    missing = [s for s in select if s not in RULES]
    if missing:
        raise KeyError(f"unknown rule(s): {', '.join(missing)}")
    return [RULES[s] for s in select]


def lint_source(
    source: str,
    path: str = "<string>",
    select: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Lint one source string; returns unsuppressed findings."""
    mod = ModuleInfo(path, source)
    sup = suppressions(source)
    findings: List[Finding] = []
    for r in _selected_rules(select):
        findings.extend(r.check(mod))
    findings = [f for f in findings if not _is_suppressed(f, sup)]
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))


def iter_py_files(paths: Sequence[Path]) -> Iterator[Tuple[Path, Optional[str]]]:
    """Yield (py_file, None) for found files, (path, error) for bad inputs."""
    for p in paths:
        p = Path(p)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                # skip hidden dirs BELOW the lint root only (the root itself
                # may live under e.g. ~/.cache without hiding every file)
                if not any(part.startswith(".") for part in f.relative_to(p).parts):
                    yield f, None
        elif p.suffix == ".py" and p.exists():
            yield p, None
        else:
            yield p, (
                "no such file or directory" if not p.exists()
                else "not a .py file or directory"
            )


def lint_paths(
    paths: Sequence[Path],
    root: Optional[Path] = None,
    select: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], List[str]]:
    """Lint files/directories.  Returns (findings, errors).

    Paths in findings are relative to `root` (default: cwd) so baseline
    keys are stable regardless of how the tool was invoked.
    """
    root = Path(root) if root is not None else Path.cwd()
    findings: List[Finding] = []
    errors: List[str] = []
    for f, err in iter_py_files(paths):
        try:
            rel = f.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        if err is not None:
            errors.append(f"{rel}: {err}")
            continue
        try:
            source = f.read_text()
            findings.extend(lint_source(source, path=rel, select=select))
        except SyntaxError as e:
            errors.append(f"{rel}: syntax error: {e}")
        except OSError as e:
            errors.append(f"{rel}: {e}")
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)), errors
