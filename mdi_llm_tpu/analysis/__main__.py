"""`python -m mdi_llm_tpu.analysis` == `mdi-lint`;
`python -m mdi_llm_tpu.analysis audit ...` == `mdi-audit`;
`python -m mdi_llm_tpu.analysis ir ...` == `mdi-ir`
(an explicit leading `lint` is also accepted)."""

import sys

argv = sys.argv[1:]
if argv[:1] == ["audit"]:
    from mdi_llm_tpu.analysis.audit import main

    raise SystemExit(main(argv[1:]))
if argv[:1] == ["ir"]:
    from mdi_llm_tpu.analysis.ir import main

    raise SystemExit(main(argv[1:]))
if argv[:1] == ["lint"]:
    argv = argv[1:]

from mdi_llm_tpu.analysis.cli import main  # noqa: E402

raise SystemExit(main(argv))
