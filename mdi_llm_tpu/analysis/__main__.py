"""`python -m mdi_llm_tpu.analysis` == `mdi-lint`."""

from mdi_llm_tpu.analysis.cli import main

raise SystemExit(main())
