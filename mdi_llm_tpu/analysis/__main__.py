"""`python -m mdi_llm_tpu.analysis` == `mdi-lint`;
`python -m mdi_llm_tpu.analysis audit ...` == `mdi-audit`;
`python -m mdi_llm_tpu.analysis ir ...` == `mdi-ir`;
`python -m mdi_llm_tpu.analysis flow ...` == `mdi-flow`;
`python -m mdi_llm_tpu.analysis check ...` == `mdi-check`
(an explicit leading `lint` is also accepted)."""

import sys

argv = sys.argv[1:]
if argv[:1] == ["audit"]:
    from mdi_llm_tpu.analysis.audit import main

    raise SystemExit(main(argv[1:]))
if argv[:1] == ["ir"]:
    from mdi_llm_tpu.analysis.ir import main

    raise SystemExit(main(argv[1:]))
if argv[:1] == ["flow"]:
    from mdi_llm_tpu.analysis.liveness import main

    raise SystemExit(main(argv[1:]))
if argv[:1] == ["check"]:
    from mdi_llm_tpu.analysis.check import main

    raise SystemExit(main(argv[1:]))
if argv[:1] == ["lint"]:
    argv = argv[1:]

from mdi_llm_tpu.analysis.cli import main  # noqa: E402

raise SystemExit(main(argv))
