"""`mdi-check`: the aggregate analysis gate — lint + audit + ir + flow.

One command that runs every static analyzer the repo ships over one
(model, mesh, ServingConfig) tuple and the source tree, with unified
exit codes and a single `--format json` report:

- **lint** (mdi-lint, analysis/core.py + rules): AST rules over the
  package sources, grandfathered through the committed
  `.mdi-lint-baseline.json` exactly like bare `mdi-lint`.
- **audit** (mdi-audit, analysis/audit.py): plan/shape arithmetic for
  the serving launch the tuple implies — sharding consistency, byte
  budgets, schedule soundness.
- **ir** (mdi-ir, analysis/ir.py): abstract traces of every serving
  executable — compile-set closure, donation marks, IR hygiene.
- **flow** (mdi-flow, analysis/liveness.py): buffer liveness over the
  same traced engine — donation aliasing, live-range bloat, static
  peak-HBM (pinned against goldens/flow-goldens.json when present).

The engine is traced ONCE and shared by the ir and flow passes.  Purely
host-side: no checkpoint, no backend compile, no device placement — the
tier-1 self-check test drives this command so all four analyzers stay
clean in one place.

CLI: ``mdi-check --model pythia-14m`` (or ``python -m
mdi_llm_tpu.analysis check ...``); ``--tp/--pp``, serving knobs,
``--hbm-gb``, ``--goldens`` (default: goldens/flow-goldens.json when it
exists), ``--skip FAMILY`` (repeatable), ``--format json``,
``--list-checks``.  Exit 0 when every family is clean (modulo the lint
baseline), 1 on findings, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional

from mdi_llm_tpu.config import Config, ServingConfig

__all__ = ["FAMILIES", "main", "run_check"]

FAMILIES = ("lint", "audit", "ir", "flow")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="mdi-check",
        description="Aggregate analysis gate: run mdi-lint + mdi-audit + "
        "mdi-ir + mdi-flow over one (model, mesh, ServingConfig) tuple "
        "and the source tree, with unified exit codes and one JSON "
        "report (see docs/analysis.md, 'The aggregate gate (mdi-check)')",
    )
    src = ap.add_argument_group("model source")
    src.add_argument("--model", default=None, help="registry model name")
    src.add_argument("--config", default=None, metavar="FILE",
                     help="model_config.yaml / config.json to trace")
    par = ap.add_argument_group("parallel plan")
    par.add_argument("--tp", type=int, default=1)
    par.add_argument("--pp", type=int, default=1)
    run = ap.add_argument_group("run shape")
    run.add_argument("--seq-len", type=int, default=None)
    run.add_argument("--dtype", default="bfloat16",
                     choices=("bfloat16", "float16", "float32"))
    run.add_argument("--quantize", default="none",
                     choices=("none", "int8", "w8a8"))
    srv = ap.add_argument_group("serving (ServingConfig)")
    srv.add_argument("--block-size", type=int, default=16)
    srv.add_argument("--max-batch", type=int, default=8)
    srv.add_argument("--prefill-chunk", type=int, default=128)
    srv.add_argument("--token-budget", type=int, default=None)
    srv.add_argument("--decode-chunk", type=int, default=8)
    srv.add_argument("--spec-k", type=int, default=0)
    srv.add_argument("--kv-dtype", default="auto")
    srv.add_argument("--host-pool-mib", type=int, default=0,
                     help="host-RAM KV block tier size in MiB (0 = off); "
                     "audited by bad-host-tier and credited against the "
                     "flow hbm-over-budget static peak")
    srv.add_argument("--host-link-gbps", type=float, default=None,
                     help="host<->device bandwidth (GB/s) for the swap "
                     "cost model (default: per-device-kind table)")
    ap.add_argument("--paths", nargs="*", default=None, metavar="PATH",
                    help="files/dirs for the lint family (default: the "
                    "mdi_llm_tpu package next to this file)")
    ap.add_argument("--lint-baseline", default=None, metavar="FILE",
                    help="mdi-lint baseline (default: "
                    "./.mdi-lint-baseline.json when present)")
    ap.add_argument("--hbm-gb", type=float, default=None,
                    help="per-device HBM budget for the audit and flow "
                    "families")
    ap.add_argument("--host-gb", type=float, default=None,
                    help="host-RAM budget for the KV block tier "
                    "(audit family, bad-host-tier)")
    ap.add_argument("--goldens", default=None, metavar="FILE",
                    help="flow golden budgets (default: "
                    "goldens/flow-goldens.json when present)")
    ap.add_argument("--skip", action="append", default=[],
                    choices=FAMILIES, metavar="FAMILY",
                    help="skip one analyzer family; repeatable")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-checks", action="store_true",
                    help="print every family's rule registry and exit")
    return ap


def _list_checks() -> None:
    from mdi_llm_tpu.analysis.audit import AUDIT_RULES
    from mdi_llm_tpu.analysis.core import RULES
    from mdi_llm_tpu.analysis.ir import IR_RULES
    from mdi_llm_tpu.analysis.liveness import FLOW_RULES

    families = [
        ("lint", {name: ("error", r.summary) for name, r in RULES.items()}),
        ("audit", AUDIT_RULES),
        ("ir", IR_RULES),
        ("flow", FLOW_RULES),
    ]
    for family, rules in families:
        for code, (sev, summary) in rules.items():
            print(f"{family}:{code}  [{sev}] {summary}")


def run_check(args) -> Dict[str, Any]:
    """Run the requested families; returns the aggregate report dict
    (the `--format json` payload).  Raises ValueError on usage
    problems."""
    skip = set(args.skip or ())
    report: Dict[str, Any] = {"families": {}, "errors": 0, "warnings": 0}

    cfg = serving = engine = None
    need_engine = ("audit" not in skip or "ir" not in skip
                   or "flow" not in skip)
    if need_engine:
        if args.config:
            cfg = Config.from_file(args.config)
        elif args.model:
            cfg = Config.from_name(args.model)
        else:
            raise ValueError("need --model or --config (or skip the "
                             "audit/ir/flow families)")
        serving = ServingConfig(
            block_size=args.block_size,
            max_batch=args.max_batch,
            prefill_chunk=args.prefill_chunk,
            token_budget=args.token_budget,
            decode_chunk=args.decode_chunk,
            spec_k=args.spec_k,
            kv_dtype=None if args.kv_dtype == "auto" else args.kv_dtype,
            host_pool_mib=args.host_pool_mib,
            host_link_gbps=args.host_link_gbps,
        )
    name = args.model or (Path(args.config).stem if args.config else "?")
    mesh_tag = "".join(
        t for t in (f"@tp{args.tp}" if args.tp > 1 else "",
                    f"@pp{args.pp}" if args.pp > 1 else "")
    )
    origin = f"{name}{mesh_tag}"
    report["origin"] = origin

    if "lint" not in skip:
        from mdi_llm_tpu.analysis.cli import BASELINE_NAME
        from mdi_llm_tpu.analysis.core import Baseline, lint_paths

        if args.paths:
            paths = [Path(p) for p in args.paths]
            root = Path.cwd()
        else:
            pkg = Path(__file__).resolve().parent.parent
            paths, root = [pkg], pkg.parent
        findings, errors = lint_paths(paths, root=root)
        base_path = (Path(args.lint_baseline) if args.lint_baseline
                     else root / BASELINE_NAME)
        grandfathered = 0
        if base_path.exists():
            new, old = Baseline.load(base_path).split(findings)
            findings, grandfathered = new, len(old)
        report["families"]["lint"] = {
            "errors": len(findings) + len(errors),
            "warnings": 0,
            "grandfathered": grandfathered,
            "findings": [
                f"{f.path}:{f.line}: {f.rule}: {f.message}"
                for f in findings
            ] + errors,
        }

    if "audit" not in skip:
        from mdi_llm_tpu.analysis.audit import preflight

        audit_report = preflight(
            cfg,
            tp=args.tp,
            pp=args.pp,
            batch=args.max_batch,
            seq_len=args.seq_len,
            act_seq_len=serving.resolved_token_budget(),
            dtype=args.dtype,
            quantize=None if args.quantize == "none" else args.quantize,
            serving=serving,
            hbm_gb=args.hbm_gb,
            host_gb=args.host_gb,
            origin=f"check:{origin}",
        )
        report["families"]["audit"] = {
            "errors": len(audit_report.errors),
            "warnings": len(audit_report.warnings),
            "findings": audit_report.render_findings(),
            "breakdown": audit_report.breakdown,
        }

    if "ir" not in skip or "flow" not in skip:
        from mdi_llm_tpu.analysis.ir import trace_serving

        engine = trace_serving(
            cfg,
            serving,
            tp=args.tp,
            pp=args.pp,
            dtype=args.dtype,
            quantize=None if args.quantize == "none" else args.quantize,
            max_seq_length=args.seq_len,
        )

    if "ir" not in skip:
        from mdi_llm_tpu.analysis.ir import ir_preflight

        ir_report = ir_preflight(engine, origin=origin)
        report["families"]["ir"] = {
            "errors": len(ir_report.errors),
            "warnings": len(ir_report.warnings),
            "findings": ir_report.render_findings(),
            "executables": {
                r["name"]: r.get("eqns") for r in ir_report.executables
            },
        }

    if "flow" not in skip:
        from mdi_llm_tpu.analysis.liveness import (
            DEFAULT_GOLDENS,
            flow_preflight,
            load_goldens,
        )

        goldens = None
        goldens_path = (Path(args.goldens) if args.goldens
                        else Path(DEFAULT_GOLDENS))
        if args.goldens or goldens_path.exists():
            goldens = load_goldens(goldens_path)  # raises on a bad file
        flow_report = flow_preflight(
            engine, origin=origin, hbm_gb=args.hbm_gb, goldens=goldens
        )
        report["families"]["flow"] = {
            "errors": len(flow_report.errors),
            "warnings": len(flow_report.warnings),
            "findings": flow_report.render_findings(),
            "peak_bytes": {
                p.name: p.peak_bytes for p in flow_report.profiles
            },
        }

    report["errors"] = sum(
        f["errors"] for f in report["families"].values()
    )
    report["warnings"] = sum(
        f["warnings"] for f in report["families"].values()
    )
    return report


def render_text(report: Dict[str, Any]) -> str:
    lines = [f"mdi-check: {report.get('origin', '?')}"]
    for family, res in report["families"].items():
        status = "clean" if not res["errors"] else f"{res['errors']} error(s)"
        extra = ""
        if res.get("warnings"):
            extra += f", {res['warnings']} warning(s)"
        if res.get("grandfathered"):
            extra += f", {res['grandfathered']} grandfathered"
        lines.append(f"  {family:<6} {status}{extra}")
        for f in res.get("findings", []):
            lines.append(f"    {f}")
    lines.append(
        "check: " + ("PASS" if not report["errors"]
                     else f"FAIL ({report['errors']} error(s))")
    )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_checks:
        _list_checks()
        return 0
    try:
        report = run_check(args)
    except (ValueError, OSError, json.JSONDecodeError) as e:
        print(f"mdi-check: {e}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps(report, indent=2))
    else:
        print(render_text(report))
    return 1 if report["errors"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
