"""`mdi-audit`: static plan auditor — evaluate a (Config, mesh, parallel
plan, ServingConfig) tuple WITHOUT touching a device or compiling anything.

Three checker families over the abstract-shape IR (`analysis/plan.py`):

1. **Sharding consistency** — every `parallel/sharding.param_specs` leaf is
   checked against the declared mesh: axis names exist, each sharded dim is
   divisible by its axis size (heads % tp, experts % ep, vocab % tp where
   the head shards, n_layer % stages via `partition.stage_layers`), no dim
   uses one axis twice, and coverage is total — a params leaf with no spec
   is an error, not silent replication.
2. **Memory budgeting** — analytic per-device HBM footprint (params by
   dtype/quantized storage layout, dense KV cache or paged pool from
   `ServingConfig`, activation high-water mark, donation-aware) checked
   against an optional `--hbm-gb` budget, with a per-component breakdown
   and the max batch / max context that fits.
3. **Schedule soundness** — symbolic execution of the stage-ring/ring-
   attention permutation schedules: every ppermute send has a matching
   recv (bijection), the ring is a single cycle (activations return to
   stage 0), per-rank op traces are identical (SPMD deadlock-freedom), and
   the paper's recurrent-pipeline invariant `n_samples >= n_stages` is
   reported with the computed bubble fraction.

Findings reuse the mdi-lint `Finding`/`Baseline` machinery (analysis/core.py)
so both tools share one reporting pipeline.  Runnable as `mdi-audit` or
`python -m mdi_llm_tpu.analysis audit`; `bench.py`, `mdi-serve` and
`mdi-starter` call :func:`preflight` before building any engine and refuse
(or warn, with ``--no-preflight``) to launch a failing plan.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from mdi_llm_tpu.analysis.core import Baseline, Finding
from mdi_llm_tpu.analysis.plan import (
    MeshSpec,
    PlanSpec,
    abstract_params,
    iter_leaves,
    ring_permutation,
)
from mdi_llm_tpu.config import Config, ServingConfig, dtype_bytes

__all__ = [
    "AUDIT_RULES",
    "AuditReport",
    "audit_detail",
    "audit_plan",
    "enforce_preflight",
    "preflight",
    "main",
]

ERROR, WARNING = "error", "warning"

# code -> (severity, summary).  ERROR findings make preflight refuse to
# launch; WARNING findings are reported but never block.
AUDIT_RULES: Dict[str, Tuple[str, str]] = {
    "bad-mesh-axis": (
        ERROR, "a declared mesh axis has size < 1 (make_mesh rejects it; "
        "resolve -1 inference to a concrete size before auditing)"),
    "unknown-mesh-axis": (
        ERROR, "a PartitionSpec references an axis the mesh does not declare "
        "(the runtime silently replicates instead of sharding)"),
    "indivisible-dim": (
        ERROR, "a sharded dimension is not divisible by its mesh axis size"),
    "duplicate-axis": (
        ERROR, "one leaf shards two dimensions on the same mesh axis"),
    "missing-spec": (
        ERROR, "a params leaf has no PartitionSpec (silent full replication)"),
    "stale-spec": (
        WARNING, "param_specs names a leaf the params tree does not have"),
    "spec-rank-mismatch": (
        ERROR, "a PartitionSpec has more entries than the leaf has dims"),
    "bad-stage-split": (
        ERROR, "the layer->stage partition is invalid (empty stage or "
        "n_stages > n_layer)"),
    "hbm-over-budget": (
        ERROR, "the analytic per-device footprint exceeds the HBM budget"),
    "unmatched-permute": (
        ERROR, "a ppermute schedule has a send without a matching recv "
        "(not a permutation of the ranks)"),
    "broken-ring": (
        ERROR, "the ring permutation is a bijection but not one cycle — "
        "activations never return to stage 0"),
    "schedule-divergence": (
        ERROR, "ranks execute different collective sequences (deadlock)"),
    "pipeline-underfill": (
        WARNING, "n_samples < n_stages: the recurrent ring runs with "
        "bubbles (paper invariant, MDI-LLM README)"),
    "bad-serving-config": (
        ERROR, "the paged-KV ServingConfig cannot be instantiated"),
    "bad-token-budget": (
        ERROR, "the unified serving step's token budget cannot fit one "
        "decode token per max_batch slot plus any prefill chunk token "
        "(prefill could never progress)"),
    "bad-serving-mesh": (
        ERROR, "the serving plan's mesh cannot shard the paged-KV pool "
        "(n_query_groups % tp != 0, or a dp/other >1 axis the engine "
        "does not support)"),
    "bad-server-config": (
        ERROR, "the open-system server config cannot serve: the admission "
        "queue bound rejects everything, or it keeps every slot occupied "
        "over a pool too small to hold all slots' reservation headroom "
        "(sustained preemption thrash)"),
    "bad-host-tier": (
        ERROR, "the host KV block tier cannot work as configured: "
        "host_pool_mib exceeds the --host-gb budget, prefix spill is on "
        "without prefix_caching (no hash chains to key spilled blocks), "
        "or the swap cost model sees a zero-bandwidth host link (it "
        "would never choose to swap)"),
    "bad-kernel-tuning": (
        ERROR, "a ragged-kernel tuning-table entry cannot run on this "
        "config/device: kv_step does not divide block_size, q_pack does "
        "not divide n_query_groups, or the VMEM scratch estimate exceeds "
        "the device budget (obs/roofline.device_vmem_bytes)"),
}

GiB = float(1 << 30)


@dataclasses.dataclass
class AuditReport:
    plan: PlanSpec
    findings: List[Finding]
    breakdown: Dict[str, Any]

    def severity(self, f: Finding) -> str:
        return AUDIT_RULES.get(f.rule, (ERROR, ""))[0]

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if self.severity(f) == ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if self.severity(f) == WARNING]

    def render_findings(self) -> List[str]:
        return [
            f"{f.path}: {self.severity(f)}: {f.rule}: {f.message}"
            for f in self.findings
        ]

    def render_text(self) -> str:
        lines = [f"plan: {self.plan.describe()}"]
        dev = self.breakdown.get("per_device", {})
        if dev:
            lines.append("per-device HBM footprint:")
            for k in ("params_bytes", "kv_bytes", "act_bytes", "total_bytes"):
                label = k.replace("_bytes", "").replace("act", "activations")
                lines.append(f"  {label:<12} {dev[k] / GiB:9.3f} GiB")
            budget = self.breakdown.get("budget_bytes")
            if budget:
                lines.append(
                    f"  budget       {budget / GiB:9.3f} GiB "
                    f"({self.breakdown['budget_utilization']:.0%} used)"
                )
                fits = self.breakdown.get("fits", {})
                if fits:
                    lines.append(
                        "  fits: " + ", ".join(f"{k}={v}" for k, v in fits.items())
                    )
        if self.breakdown.get("stage_layers"):
            lines.append(f"stage layers: {self.breakdown['stage_layers']}")
        if "bubble_fraction" in self.breakdown:
            lines.append(
                f"ring lanes: {self.breakdown['ring_lanes']} "
                f"(bubble fraction {self.breakdown['bubble_fraction']:.2f})"
            )
        if self.findings:
            lines.extend(self.render_findings())
        else:
            lines.append("findings: none")
        return "\n".join(lines)

    def as_json(self) -> Dict[str, Any]:
        return {
            "plan": self.plan.describe(),
            "findings": [
                {**f.__dict__, "severity": self.severity(f)} for f in self.findings
            ],
            "errors": len(self.errors),
            "warnings": len(self.warnings),
            "breakdown": self.breakdown,
        }


def _finding(plan: PlanSpec, code: str, message: str) -> Finding:
    assert code in AUDIT_RULES, code
    return Finding(
        rule=code, path=plan.origin, line=0, col=0,
        message=message, line_text=plan.describe(),
    )


# ---------------------------------------------------------------------------
# checker 1: sharding consistency
# ---------------------------------------------------------------------------


def _axes_of(entry) -> Tuple[str, ...]:
    """PartitionSpec entry → axis names (None → (), 'tp' → ('tp',),
    ('dp','tp') → both)."""
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def _spec_axis_names(specs) -> List[str]:
    names: List[str] = []
    for _, spec in iter_leaves(specs):
        for entry in tuple(spec):
            for ax in _axes_of(entry):
                if ax not in names:
                    names.append(ax)
    return names


def _check_mesh(plan: PlanSpec, findings: List[Finding]) -> None:
    """Every declared axis size must be a concrete >= 1 — the IR can
    represent a nonsensical mesh, but the audit must flag it: every
    divisibility/memory check below is vacuous at size <= 1, so a 0 or -1
    axis would otherwise audit green and then die in `make_mesh`."""
    for name, size in plan.mesh.axes:
        if size < 1:
            findings.append(_finding(
                plan, "bad-mesh-axis",
                f"mesh axis {name!r} has size {size}; sizes must be >= 1 "
                "(the runtime's make_mesh rejects this mesh — pass the "
                "resolved size instead of -1 inference)",
            ))


def _check_sharding(plan: PlanSpec, findings: List[Finding]) -> None:
    from mdi_llm_tpu.parallel.sharding import adapt_specs_to_tree, param_specs

    cfg, mesh = plan.cfg, plan.mesh
    specs = param_specs(cfg, tp_axis=plan.tp_axis, ep_axis=plan.ep_axis)
    shapes = abstract_params(cfg, plan.dtype)  # standard (semantic) layout

    # -- axis existence: one finding per axis the mesh does not declare ----
    unknown = [a for a in _spec_axis_names(specs) if a not in mesh.names]
    for a in unknown:
        findings.append(_finding(
            plan, "unknown-mesh-axis",
            f"plan shards on axis {a!r} but the mesh ({mesh.describe()}) "
            "does not declare it — the runtime would silently replicate "
            "every leaf sharded on it (shard_params drops unknown axes)",
        ))
    unknown_set = set(unknown)

    # -- coverage + per-leaf divisibility/duplicates -----------------------
    missing: List[str] = []
    stale: List[str] = []
    indiv: Dict[str, List[str]] = {}
    dups: Dict[str, List[str]] = {}

    def leaf_paths(node, path):
        return [p for p, _ in iter_leaves(node, path)]

    def walk(spec_node, shape_node, path, check_div):
        if isinstance(shape_node, dict):
            if not isinstance(spec_node, dict):
                missing.extend(leaf_paths(shape_node, path))
                return
            for k, v in shape_node.items():
                sub = f"{path}.{k}" if path else k
                if k not in spec_node:
                    missing.extend(leaf_paths(v, sub))
                else:
                    walk(spec_node[k], v, sub, check_div)
            for k in spec_node:
                if k not in shape_node:
                    stale.append(f"{path}.{k}" if path else k)
            return
        # leaf
        entries = tuple(spec_node) if not isinstance(spec_node, dict) else None
        if entries is None:
            missing.append(path)
            return
        shape = np.shape(shape_node)
        if len(entries) > len(shape):
            findings.append(_finding(
                plan, "spec-rank-mismatch",
                f"{path}: spec {entries} has {len(entries)} entries but the "
                f"leaf has shape {shape}",
            ))
            return
        seen: Dict[str, int] = {}
        for i, entry in enumerate(entries):
            for ax in _axes_of(entry):
                if ax in seen:
                    dups.setdefault(ax, []).append(
                        f"{path} dims {seen[ax]} and {i}"
                    )
                else:
                    seen[ax] = i
                if ax in unknown_set or not check_div:
                    continue
                size = mesh.size(ax)
                if size > 1 and shape[i] % size:
                    indiv.setdefault(ax, []).append(
                        f"{path} dim{i}={shape[i]}"
                    )

    # head/embedding leaves replicate in the pipeline engine: their specs
    # only bind when the Generator mesh path consumes the plan
    def check_div_for(key):
        return plan.shard_head or key == "blocks"

    for k, v in shapes.items():
        if k in specs:
            walk(specs[k], v, k, check_div_for(k))
        else:
            missing.extend(leaf_paths(v, k))
    for k in specs:
        if k not in shapes:
            stale.append(k)

    # -- semantic dims (mirror parallel.sharding.validate_tp_divisibility):
    # head/group counts must divide even when the fused leaf dim happens to
    # (the interleaved qkv layout makes a divisible row count insufficient)
    t = plan.tp_axis
    if t and t in mesh.names and mesh.size(t) > 1:
        tp = mesh.size(t)
        moe = cfg.mlp_class_name == "LLaMAMoE"
        dims = [("n_head", cfg.n_head), ("n_query_groups", cfg.n_query_groups)]
        if not moe:
            dims.append(("intermediate_size", cfg.intermediate_size))
        if plan.shard_head:
            dims.append(("padded_vocab_size", cfg.padded_vocab_size))
        for name, dim in dims:
            if dim % tp:
                indiv.setdefault(t, []).insert(0, f"{name}={dim}")
    e = plan.ep_axis or plan.tp_axis
    if (cfg.mlp_class_name == "LLaMAMoE" and e and e in mesh.names
            and mesh.size(e) > 1 and cfg.n_expert % mesh.size(e)):
        indiv.setdefault(e, []).insert(0, f"n_expert={cfg.n_expert}")
    sp = plan.sp_axis
    if sp and sp in mesh.names and mesh.size(sp) > 1 and plan.seq_len % mesh.size(sp):
        indiv.setdefault(sp, []).insert(
            0, f"sequence length {plan.seq_len} (ring attention chunks)"
        )

    # aggregate: ONE finding per axis / per failure family, so one root
    # cause (e.g. heads % tp) reads as one actionable report
    for ax, items in indiv.items():
        shown = items[:6] + ([f"... {len(items) - 6} more"] if len(items) > 6 else [])
        findings.append(_finding(
            plan, "indivisible-dim",
            f"mesh axis {ax!r} (size {mesh.size(ax)}) does not divide: "
            + "; ".join(shown),
        ))
    for ax, items in dups.items():
        findings.append(_finding(
            plan, "duplicate-axis",
            f"mesh axis {ax!r} used on two dims of one leaf: "
            + "; ".join(items[:6]),
        ))
    for p in missing:
        findings.append(_finding(
            plan, "missing-spec",
            f"params leaf {p!r} has no PartitionSpec — it would be "
            "silently fully replicated on every device",
        ))
    for p in stale:
        findings.append(_finding(
            plan, "stale-spec",
            f"param_specs names {p!r} but the params tree has no such leaf",
        ))

    # -- quantized storage coverage: the adapted specs must still cover the
    # int8/int4 layout (weight_q*/scale leaves inherit the weight's spec)
    if plan.quantize and plan.quantize != "none" and not missing:
        storage = abstract_params(cfg, plan.dtype, plan.quantize)
        adapted = adapt_specs_to_tree(specs, storage, axis_sizes=mesh.sizes)
        for (p, _), (_, spec) in zip(iter_leaves(storage), iter_leaves(adapted)):
            if spec is None:
                findings.append(_finding(
                    plan, "missing-spec",
                    f"quantized storage leaf {p!r} has no adapted spec",
                ))


# ---------------------------------------------------------------------------
# checker 2: memory budgeting
# ---------------------------------------------------------------------------


def _sharded_nbytes(leaf, spec, sizes: Dict[str, int]) -> int:
    """Per-device bytes of a leaf under its PartitionSpec: divide by every
    axis size that actually divides its dim (indivisible shardings are
    dropped by the runtime — `adapt_specs_to_tree` — so count them whole)."""
    denom = 1
    shape = np.shape(leaf)
    for i, entry in enumerate(tuple(spec)[: len(shape)]):
        for ax in _axes_of(entry):
            s = sizes.get(ax, 1)
            if s > 1 and shape[i] % s == 0:
                denom *= s
    return int(leaf.nbytes) // denom


def _liveness_act_bytes(plan: PlanSpec) -> Optional[int]:
    """The liveness-derived activation high-water for an engine-enumerable
    serving plan: the worst executable's interior temp peak from
    mdi-flow's data-flow pass over the actual jaxprs
    (analysis/liveness.py), replacing the analytic activation term with a
    per-executable number.  None when the engine cannot be built
    abstractly (non-serving plans, non-engine meshes): callers keep the
    heuristic.  Still backend-free — `trace_serving` enumerates devices,
    compiles nothing."""
    if plan.serving is None:
        return None
    if any(n not in ("tp", "pp") for n in plan.mesh.names):
        return None  # dp/ep/pipe plans are not serving-engine-enumerable
    try:
        from mdi_llm_tpu.analysis.ir import trace_serving
        from mdi_llm_tpu.analysis.liveness import analyze_flow

        engine = trace_serving(
            plan.cfg,
            plan.serving,
            tp=plan.mesh.size("tp"),
            pp=plan.mesh.size("pp"),
            dtype=plan.dtype,
            quantize=plan.quantize,
            max_seq_length=plan.max_seq_length,
        )
        _, profiles = analyze_flow(
            engine.enumerate_executables(), origin=plan.origin
        )
    except Exception:
        return None  # a broken plan audits with the heuristic instead
    if not profiles:
        return None
    return max(p.temp_peak_bytes for p in profiles)


def _check_memory(
    plan: PlanSpec,
    findings: List[Finding],
    breakdown: Dict[str, Any],
    liveness: bool = False,
) -> None:
    from mdi_llm_tpu.parallel.partition import stage_layers
    from mdi_llm_tpu.parallel.sharding import adapt_specs_to_tree, param_specs

    cfg, mesh = plan.cfg, plan.mesh
    sizes = mesh.sizes
    par_item = dtype_bytes(plan.dtype)
    try:
        kv_item = dtype_bytes(plan.kv_dtype)
    except ValueError:
        if plan.serving is None:
            raise  # dense plans have no checker reporting dtype problems
        kv_item = 0  # already a bad-serving-config finding; budget KV as 0
    storage = abstract_params(cfg, plan.dtype, plan.quantize)
    try:
        specs = adapt_specs_to_tree(
            param_specs(cfg, tp_axis=plan.tp_axis, ep_axis=plan.ep_axis),
            storage,
            axis_sizes=sizes,
        )
    except (KeyError, TypeError):
        # incomplete spec tree — already reported as missing-spec by the
        # sharding checker; budget conservatively as fully replicated
        specs = None
    def leaf_spec_pairs(storage_sub, specs_sub):
        leaves = list(iter_leaves(storage_sub))
        if specs_sub is None:
            return [(leaf, ()) for _, leaf in leaves]  # replicated fallback
        return [
            (leaf, spec)
            for (_, leaf), (_, spec) in zip(leaves, iter_leaves(specs_sub))
        ]

    S = max(1, plan.n_stages)
    tp = mesh.size(plan.tp_axis) if plan.tp_axis else 1

    if plan.is_pipeline:
        try:
            counts = stage_layers(cfg.n_layer, S)
        except ValueError:
            return  # bad-stage-split already reported; no meaningful budget
        l_max = max(counts)
        # blocks: per-device = per-layer bytes * l_max (zero-padded stage
        # stack, parallel/partition.pad_stage_blocks), tp-sharded per spec
        blocks_dev = sum(
            _sharded_nbytes(leaf, spec, sizes) // cfg.n_layer * l_max
            for leaf, spec in leaf_spec_pairs(
                storage["blocks"], specs["blocks"] if specs else None
            )
        )
        # embeddings/final norm/head are replicated on every stage
        head_dev = sum(
            int(leaf.nbytes)
            for k, v in storage.items() if k != "blocks"
            for _, leaf in iter_leaves(v)
        )
        params_dev = blocks_dev + head_dev
        # per-stage rotating KV: (l_max, n_slots, M, G, seq, hs) x2, the
        # group dim tp-sharded when divisible (PipelineEngine._init_kv)
        G = cfg.n_query_groups
        g_denom = tp if (tp > 1 and G % tp == 0) else 1
        kv_dev = (
            2 * l_max * (S + 1) * plan.samples_per_slot * (G // g_denom)
            * plan.cache_len * cfg.head_size * kv_item
        )
        act_batch = plan.samples_per_slot
    else:
        params_dev = sum(
            _sharded_nbytes(leaf, spec, sizes)
            for leaf, spec in leaf_spec_pairs(storage, specs)
        )
        if plan.serving is not None:
            # an invalid pool geometry is already a bad-serving-config
            # finding; budget it as zero instead of dividing by block_size
            # (an unknown kv_dtype likewise — the serving checker reported
            # it).  Per DEVICE: the pool's KV-group axis shards over tp
            # (paged_kv_spec, int8 scale arrays included), so each chip
            # holds exactly 1/tp of the pool
            try:
                kv_dev = max(0, (
                    plan.serving.pool_bytes_per_device(
                        cfg, _serving_tp(plan), plan.seq_len, plan.kv_dtype
                    )
                    if plan.serving.block_size >= 1 else 0
                ))
            except ValueError:
                kv_dev = 0
            pp = _serving_pp(plan)
            if pp > 1 and cfg.n_layer >= pp:
                # pipelined serving: each device holds ONE stage's shard —
                # l_max zero-padded layer slots instead of all n_layer.
                # Pool bytes are layer-proportional and divisible by
                # n_layer, so the rescale is exact (== the kv_pool
                # breakdown's pool_bytes_per_device)
                l_max = max(stage_layers(cfg.n_layer, pp))
                kv_dev = kv_dev // cfg.n_layer * l_max
        else:
            kv_dev = cfg.estimate_kv_bytes(plan.batch, plan.cache_len, plan.kv_dtype)
        act_batch = plan.batch

    if not plan.donate_kv:
        kv_dev *= 2  # no donation: XLA ping-pongs two full cache buffers

    # activation high-water mark (rough, per live layer — not cumulative):
    # residual stream + qkv/attn-out + widest MLP intermediate, plus the
    # head's logits row.  Decode keeps T=1; prefill passes its bucket width.
    T = max(1, plan.act_seq_len)
    mlp_live = (
        2 * cfg.intermediate_size
        if cfg.mlp_class_name in ("LLaMAMLP", "GemmaMLP", "LLaMAMoE")
        else cfg.intermediate_size
    )
    act_dev = act_batch * T * (
        4 * cfg.n_embd + cfg.qkv_size + cfg.attn_out_size + mlp_live
    ) * par_item + act_batch * cfg.padded_vocab_size * par_item
    act_source = "heuristic"
    if liveness:
        # engine-enumerable (Config, mesh, ServingConfig) tuples get the
        # liveness-derived per-executable high-water instead of the
        # analytic term; everything else keeps the heuristic
        lv = _liveness_act_bytes(plan)
        if lv is not None:
            act_dev, act_source = int(lv), "liveness"

    total = params_dev + kv_dev + act_dev
    breakdown["per_device"] = {
        "params_bytes": int(params_dev),
        "kv_bytes": int(kv_dev),
        "act_bytes": int(act_dev),
        "act_source": act_source,
        "total_bytes": int(total),
    }
    breakdown["n_devices"] = mesh.n_devices

    if plan.hbm_gb is None:
        return
    budget = int(plan.hbm_gb * GiB)
    breakdown["budget_bytes"] = budget
    breakdown["budget_utilization"] = round(total / budget, 4) if budget else None
    avail = budget - params_dev - act_dev
    fits: Dict[str, Any] = {}
    if plan.serving is not None:
        # per-device block cost under the tp-sharded pool layout (the
        # itemized ServingConfig.block_bytes — payload AND int8 scale side
        # arrays, the same formula pool_bytes uses, so the fit and the
        # estimate can never disagree): the HBM budget is per chip, so
        # blocks-that-fit scales with the tp degree
        try:
            per_block = plan.serving.block_bytes(
                cfg, plan.kv_dtype, tp=_serving_tp(plan)
            )["total_bytes"]
            pp = _serving_pp(plan)
            if pp > 1 and cfg.n_layer >= pp:
                # per-device block cost is one STAGE's slice (l_max layer
                # slots) under pipelined serving — exact, see kv_dev above
                per_block = per_block // cfg.n_layer * max(
                    stage_layers(cfg.n_layer, pp)
                )
        except ValueError:
            per_block = 0  # unknown kv_dtype: bad-serving-config reported
        fits["max_pool_blocks"] = max(0, int(avail // per_block)) if per_block else 0
        if "kv_pool" in breakdown:
            breakdown["kv_pool"]["blocks_at_budget"] = fits["max_pool_blocks"]
    else:
        if plan.is_pipeline:
            per_lane = kv_dev // max(1, plan.samples_per_slot)
            fits["max_samples_per_slot"] = max(0, int(avail // per_lane)) if per_lane else 0
        else:
            per_seq = cfg.estimate_kv_bytes(1, plan.cache_len, plan.kv_dtype)
            per_tok = cfg.estimate_kv_bytes(plan.batch, 1, plan.kv_dtype)
            fits["max_batch"] = max(0, int(avail // per_seq)) if per_seq else 0
            fits["max_context"] = max(0, int(avail // per_tok)) if per_tok else 0
    breakdown["fits"] = fits

    if total > budget:
        dev = breakdown["per_device"]
        findings.append(_finding(
            plan, "hbm-over-budget",
            f"per-device footprint {total / GiB:.2f} GiB exceeds the "
            f"{plan.hbm_gb:g} GiB budget (params {dev['params_bytes'] / GiB:.2f}"
            f" + kv {dev['kv_bytes'] / GiB:.2f} + activations "
            f"{dev['act_bytes'] / GiB:.2f}); fits: "
            + (", ".join(f"{k}={v}" for k, v in fits.items()) or "nothing"),
        ))


# ---------------------------------------------------------------------------
# checker 3: schedule soundness
# ---------------------------------------------------------------------------


def _check_permutation(plan, perm, n, what, findings) -> bool:
    """Validate `perm` as a full bijection over `n` ranks; returns True when
    sound.  Aggregates all problems into ONE unmatched-permute finding."""
    problems: List[str] = []
    srcs = [s for s, _ in perm]
    dsts = [d for _, d in perm]
    for r in set(srcs) | set(dsts):
        if not (0 <= r < n):
            problems.append(f"rank {r} out of range [0, {n})")
    for r in sorted(set(srcs)):
        if srcs.count(r) > 1:
            problems.append(f"rank {r} sends twice in one ppermute")
    for r in sorted(set(dsts)):
        if dsts.count(r) > 1:
            problems.append(f"rank {r} receives two sends")
    for r in range(n):
        if r not in srcs:
            problems.append(f"rank {r} never sends (its neighbor's recv is unmatched)")
        if r not in dsts:
            problems.append(f"rank {r} never receives (it decodes garbage/zeros)")
    if problems:
        findings.append(_finding(
            plan, "unmatched-permute",
            f"{what} ppermute {list(perm)} over {n} ranks is not a "
            "permutation: " + "; ".join(dict.fromkeys(problems)),
        ))
        return False
    return True


def _check_schedule(
    plan: PlanSpec, findings: List[Finding], breakdown: Dict[str, Any]
) -> None:
    S, M = plan.n_stages, plan.samples_per_slot

    if S > 1 or plan.ring_perm is not None:
        n = max(S, 1)
        perm = tuple(plan.ring_perm) if plan.ring_perm is not None else ring_permutation(n)
        if _check_permutation(plan, perm, n, "stage-ring", findings):
            # symbolic execution: follow stage 0's activation around the
            # ring — it must visit every stage and return in exactly n hops
            nxt = dict(perm)
            rank, orbit = 0, [0]
            for _ in range(n):
                rank = nxt[rank]
                if rank == 0:
                    break
                orbit.append(rank)
            if len(orbit) < n:
                findings.append(_finding(
                    plan, "broken-ring",
                    f"stage-ring ppermute is a bijection but splits into "
                    f"disjoint cycles (stage 0's orbit is {orbit}, not all "
                    f"{n} stages): the head never sees stages outside its "
                    "cycle",
                ))
            else:
                breakdown["ring_rotation_steps"] = n

    if plan.rank_programs:
        progs = plan.rank_programs
        ref = progs[0]
        for r, prog in enumerate(progs[1:], start=1):
            if prog != ref:
                step = next(
                    (i for i, (a, b) in enumerate(zip(ref, prog)) if a != b),
                    min(len(ref), len(prog)),
                )
                findings.append(_finding(
                    plan, "schedule-divergence",
                    f"rank {r}'s collective sequence diverges from rank 0 "
                    f"at step {step}: every rank must issue the identical "
                    "op sequence per edge or the ring deadlocks",
                ))
                break

    if S > 1:
        lanes = S * M
        inflight = min(plan.n_samples, lanes)
        bubble = 1.0 - inflight / lanes if lanes else 1.0
        breakdown["ring_lanes"] = lanes
        breakdown["bubble_fraction"] = round(bubble, 4)
        if plan.n_samples < S:
            findings.append(_finding(
                plan, "pipeline-underfill",
                f"n_samples={plan.n_samples} < n_stages={S}: the recurrent "
                f"ring idles {bubble:.0%} of its {lanes} lanes (the paper's "
                "full-utilization invariant is n_samples >= n_stages; "
                f"{lanes} samples saturate this plan)",
            ))

    # pipelined serving (serving/pipeline.py): the scheduler's decode
    # lanes are the ring's fill, so the paper invariant reads
    # max_batch >= pp — below it, every ring sweep idles stages
    if plan.serving is not None:
        pp = _serving_pp(plan)
        if pp > 1:
            lanes = plan.serving.max_batch
            bubble = max(0.0, 1.0 - min(max(lanes, 0), pp) / pp)
            breakdown["serving_ring"] = {
                "stages": pp,
                "lanes": lanes,
                "bubble_fraction": round(bubble, 4),
            }
            if lanes < pp:
                findings.append(_finding(
                    plan, "pipeline-underfill",
                    f"max_batch={lanes} < pp={pp}: the serving ring idles "
                    f"{bubble:.0%} of its stages every sweep (decode lanes "
                    "are the pipeline's fill — set max_batch >= pp to "
                    "saturate it)",
                ))


def _check_stages(plan: PlanSpec, findings: List[Finding], breakdown) -> None:
    from mdi_llm_tpu.parallel.partition import stage_layers

    if plan.n_stages < 1:
        findings.append(_finding(
            plan, "bad-stage-split", f"n_stages={plan.n_stages} must be >= 1"
        ))
        return
    try:
        counts = stage_layers(plan.cfg.n_layer, plan.n_stages)
    except ValueError as e:
        findings.append(_finding(plan, "bad-stage-split", str(e)))
        return
    if plan.n_stages > 1:
        breakdown["stage_layers"] = counts


def _serving_tp(plan: PlanSpec) -> int:
    """The tp degree a serving plan shards its pool over: the declared tp
    axis when the KV-group axis divides (the `paged_kv_spec` layout),
    else 1 — mirroring the runtime's drop-indivisible-sharding rule so the
    byte estimates stay exact even on a plan the mesh checker flags."""
    tp = plan.mesh.size(plan.tp_axis) if plan.tp_axis else 1
    if tp > 1 and plan.cfg.n_query_groups % tp == 0:
        return tp
    return 1


def _serving_pp(plan: PlanSpec) -> int:
    """The serving plan's pipeline degree: the 'pp' mesh axis
    (serving/pipeline.py stacks a per-stage pool shard over it)."""
    return max(1, plan.mesh.size("pp"))


def _check_serving_mesh(plan: PlanSpec, findings: List[Finding]) -> None:
    """The serving engine's mesh contract (`serving.engine.
    validate_serving_mesh` + `paged_kv_spec`), checked statically: only tp
    (which must divide n_query_groups — the pool shards its KV-group axis;
    an indivisible G would silently replicate the pool, tp-fold the HBM
    the budget promised) and pp (which must not exceed n_layer — every
    ring stage needs >= 1 transformer block) may exceed 1, alone or
    composed."""
    sv = plan.serving
    if sv is None:
        return
    tp = plan.mesh.size(plan.tp_axis) if plan.tp_axis else 1
    if tp > 1 and plan.cfg.n_query_groups % tp:
        findings.append(_finding(
            plan, "bad-serving-mesh",
            f"tp={tp} does not divide n_query_groups="
            f"{plan.cfg.n_query_groups} of {plan.cfg.name}: the paged pool "
            "shards its KV-group axis (paged_kv_spec), so serving would "
            "silently replicate the whole pool on every chip",
        ))
    pp = _serving_pp(plan)
    if pp > 1:
        from mdi_llm_tpu.parallel.partition import stage_layers

        try:
            stage_layers(plan.cfg.n_layer, pp)
        except ValueError as e:
            findings.append(_finding(
                plan, "bad-serving-mesh",
                f"pp={pp} cannot stage {plan.cfg.name}: {e}",
            ))
    for name, size in plan.mesh.axes:
        if name == plan.tp_axis or name == "pp" or size <= 1:
            continue
        what = ("dp>1 serving is unsupported (requests are scheduler-"
                "routed, not batch-split; run one engine per replica)"
                if name == (plan.dp_axis or "dp")
                else "only tp (the pool's KV-group axis) and pp "
                "(per-stage pool shards) serve the paged pool, alone or "
                "composed")
        findings.append(_finding(
            plan, "bad-serving-mesh",
            f"serving mesh axis {name!r} (size {size}): {what} — "
            "Generator.serve() refuses this mesh",
        ))


def _check_kernel_tuning(plan: PlanSpec, findings, breakdown, bb) -> None:
    """Validate the unified ragged-kernel tuning entry the engine's
    dispatch would resolve (ops/tuning.py), HOST-side, before anything
    compiles: an entry whose kv_step does not divide block_size or whose
    VMEM scratch estimate exceeds the device budget errors here instead
    of failing (or worse, mis-running) at trace time.  Findings only fire
    when the kernel can actually be on the route — use_kernel=True, or a
    user tuning table supplying the entry; a CPU-fallback plan with the
    committed defaults never trips over a kernel it will not run.  The
    kv_pool breakdown always gains the route/provenance fields."""
    from mdi_llm_tpu.obs.roofline import device_vmem_bytes
    from mdi_llm_tpu.ops.tuning import (
        estimate_kernel_vmem,
        resolve_kernel_params,
        validate_kernel_params,
    )

    sv = plan.serving
    cfg = plan.cfg
    kv_kind = "int8" if bb["kv_dtype"] == "int8" else None
    variant = (
        "unified" if sv.use_kernel
        else ("fallback" if sv.use_kernel is False else "auto")
    )
    try:
        params, meta = resolve_kernel_params(
            n_head=cfg.n_head, n_groups=cfg.n_query_groups,
            head_size=cfg.head_size, block_size=sv.block_size,
            kv_dtype=kv_kind,
        )
    except Exception as e:  # unreadable/malformed MDI_TUNE_TABLE artifact
        findings.append(_finding(
            plan, "bad-kernel-tuning",
            f"the kernel tuning table cannot be read: {e} — fix or unset "
            "MDI_TUNE_TABLE",
        ))
        breakdown["kv_pool"].update({
            "kernel_variant": variant, "tuned": False,
            "kernel_table_source": None, "kernel_params": None,
        })
        return
    breakdown["kv_pool"].update({
        "kernel_variant": variant,
        "tuned": meta["tuned"],
        "kernel_table_source": meta["table_source"],
        "kernel_params": params.to_dict(),
    })
    if not (sv.use_kernel or meta["tuned"]):
        return
    src = meta["table_source"]
    for p in validate_kernel_params(
        params, sv.block_size, cfg.n_query_groups, cfg.head_size
    ):
        findings.append(_finding(
            plan, "bad-kernel-tuning", f"{src} ({meta['key']}): {p}",
        ))
    vmem = estimate_kernel_vmem(
        cfg.n_head, cfg.n_query_groups, cfg.head_size,
        n_tokens=sv.resolved_token_budget(), block_size=sv.block_size,
        params=params, kv_dtype=kv_kind,
    )
    budget = device_vmem_bytes(None)
    if vmem > budget:
        findings.append(_finding(
            plan, "bad-kernel-tuning",
            f"{src} ({meta['key']}): kernel VMEM estimate "
            f"{vmem / (1 << 20):.1f} MiB exceeds the device budget "
            f"{budget / (1 << 20):.1f} MiB at token_budget="
            f"{sv.resolved_token_budget()} — shrink scratch_width/"
            "kv_step in the tuning entry, or lower the token budget",
        ))


def _check_host_tier(
    plan: PlanSpec, sv: ServingConfig, findings: List[Finding], breakdown
) -> None:
    """The HBM->host block tier's static preconditions
    (serving/host_tier.py): a spill keyed on nothing, a cost model that
    can never choose to swap, or a slab allocation the host budget
    cannot hold are all launch-time mistakes, not runtime surprises."""
    if sv.host_pool_mib <= 0:
        return
    host_bytes = breakdown["kv_pool"]["host_pool_bytes"]
    if plan.host_gb is not None:
        budget = int(float(plan.host_gb) * GiB)
        if host_bytes > budget:
            findings.append(_finding(
                plan, "bad-host-tier",
                f"host_pool_mib={sv.host_pool_mib} allocates "
                f"{host_bytes / GiB:.2f} GiB of pinned block slabs, over "
                f"the {float(plan.host_gb):g} GiB --host-gb budget — "
                "shrink the tier or raise the budget",
            ))
    if sv.host_prefix_spill and not sv.prefix_caching:
        findings.append(_finding(
            plan, "bad-host-tier",
            "host_prefix_spill=True with prefix_caching=False: spilled "
            "blocks are keyed by the prefix hash chain, which only exists "
            "under prefix caching — enable prefix_caching or set "
            "host_prefix_spill=False (swap-only tier)",
        ))
    if sv.resolved_host_link_gbps() <= 0:
        findings.append(_finding(
            plan, "bad-host-tier",
            f"host_link_gbps={sv.host_link_gbps:g}: the swap cost model "
            "prices every transfer at infinite seconds, so preemption "
            "always recomputes and the tier never swaps — set a real "
            "bandwidth (or leave it None for the device-table default)",
        ))


def _check_serving(plan: PlanSpec, findings: List[Finding], breakdown) -> None:
    sv = plan.serving
    if sv is None:
        return
    _check_serving_mesh(plan, findings)
    problems = []
    if sv.block_size < 1:
        problems.append(f"block_size={sv.block_size} must be positive")
    if sv.max_batch < 1:
        problems.append(f"max_batch={sv.max_batch} must be positive")
    if sv.decode_chunk < 1:
        problems.append(f"decode_chunk={sv.decode_chunk} must be >= 1")
    if sv.spec_k < 0:
        problems.append(f"spec_k={sv.spec_k} must be >= 0")
    if sv.spec_k and sv.temperature != 0.0 and not sv.spec_verify_sampled():
        # temperature>0 + spec_k is legal since the rejection-sampled
        # verify; the wall now guards only the PINNED exact-match path
        problems.append(
            f"spec_k={sv.spec_k} with temperature={sv.temperature:g} and "
            "spec_sampled=False: the pinned exact-match verify emits "
            "greedy successors and is only exact at temperature=0 — drop "
            "spec_sampled=False (auto selects the rejection-sampled "
            "verify at temperature>0) or set temperature=0 "
            "(ServingEngine refuses this config)"
        )
    if sv.draft_model and not sv.spec_k:
        problems.append(
            f"draft_model={sv.draft_model!r} with spec_k=0: the draft "
            "model has nothing to draft for — set spec_k > 0 "
            "(ServingEngine refuses this config)"
        )
    n_blocks = sv.num_pool_blocks(plan.seq_len) if sv.block_size >= 1 else 0
    if sv.block_size >= 1 and n_blocks < 2:
        problems.append(
            f"pool of {n_blocks} block(s) cannot serve anything (block 0 is "
            "the reserved trash block; KVPool needs >= 2)"
        )
    headroom = sv.reserve_headroom_blocks() if (
        sv.block_size >= 1 and sv.decode_chunk >= 1 and sv.spec_k >= 0
    ) else 0
    if (
        sv.max_blocks is not None and sv.block_size >= 1 and n_blocks >= 2
        and n_blocks - 1 < headroom + 1
    ):
        # full-coverage pools (max_blocks=None) bound every slot at the
        # window, so only hand-sized pools can under-provision the K-step
        # reservation the chunked/speculative decode path holds per slot
        if sv.draft_model:
            # n_blocks is already draft-aware (num_pool_blocks subtracts
            # the carve-out), so name the knob that actually shrank it
            n_draft = sv.num_draft_blocks(plan.seq_len)
            problems.append(
                f"draft_share={sv.draft_share:g} carves {n_draft} of "
                f"max_blocks={sv.max_blocks} block(s) for the draft "
                f"pool, leaving the target {n_blocks - 1} usable "
                f"block(s) — below one slot's {headroom}-block "
                f"chunk-reservation headroom (decode_chunk="
                f"{sv.decode_chunk}, spec_k={sv.spec_k}, double_buffer="
                f"{sv.double_buffer}) plus its first write; shrink "
                "draft_share or grow max_blocks"
            )
        else:
            problems.append(
                f"max_blocks={sv.max_blocks}: {n_blocks - 1} usable "
                f"block(s) cannot hold one slot's {headroom}-block chunk "
                f"reservation headroom (decode_chunk={sv.decode_chunk}, "
                f"spec_k={sv.spec_k}, double_buffer={sv.double_buffer}) "
                "plus its first write"
            )
    for p in problems:
        findings.append(_finding(plan, "bad-serving-config", p))
    # open-system server sizing (server/frontend.py): only when the plan
    # declares an admission queue — replay configs (admission_queue=None)
    # never trip these, because without a front door the queue-vs-pool
    # interaction does not exist
    if sv.admission_queue is not None:
        q = sv.admission_queue
        if q < 1:
            findings.append(_finding(
                plan, "bad-server-config",
                f"admission_queue={q} rejects every arrival: the server "
                "would answer nothing but 429s (need >= 1; None defaults "
                f"to {4 * sv.max_batch} = 4 x max_batch)",
            ))
        elif (
            sv.max_blocks is not None and sv.block_size >= 1
            and n_blocks >= 2 and headroom
            and n_blocks - 1 < sv.max_batch * headroom
        ):
            # a bounded-queue front-end keeps every decode slot occupied
            # under sustained load (that is its job), so unlike the
            # one-slot replay bound above, the pool must hold EVERY
            # slot's chunk-reservation headroom at once — below that the
            # saturated steady state is preemption thrash: each chunk
            # reservation evicts a neighbor, recompute work crowds out
            # serving work, and goodput collapses exactly when traffic
            # peaks
            findings.append(_finding(
                plan, "bad-server-config",
                f"admission_queue={q} keeps all {sv.max_batch} slots "
                f"occupied under load, but max_blocks={sv.max_blocks} "
                f"leaves {n_blocks - 1} usable block(s) < max_batch x "
                f"{headroom}-block reservation headroom "
                f"({sv.max_batch * headroom}): the saturated steady state "
                "is preemption thrash — grow the pool or shrink "
                "max_batch/decode_chunk",
            ))
    # unified-step token budget: the mixed batch packs one decode token per
    # live slot FIRST, then prefill chunk tokens — a budget at or below
    # max_batch starves prefill forever (the engine refuses it too).  The
    # budget never changes the pool geometry, so the pool-byte estimates
    # below stay byte-exact vs the live engine whatever it is.
    if sv.max_batch >= 1 and sv.prefill_chunk >= 0:
        budget = sv.resolved_token_budget()
        if budget <= sv.max_batch:
            suggested = sv.max_batch + max(1, sv.prefill_chunk)
            findings.append(_finding(
                plan, "bad-token-budget",
                f"token_budget={budget} <= max_batch={sv.max_batch}: every "
                "unified step packs one decode token per live slot before "
                "any prefill token, so this budget leaves prefill zero "
                f"room; set token_budget >= {suggested} (max_batch + "
                "prefill_chunk) or leave it None for that default",
            ))
    if sv.block_size >= 1:
        tp = _serving_tp(plan)
        # itemized per-block cost (config.ServingConfig.block_bytes): the
        # ONE formula pool construction, this breakdown and the --hbm-gb
        # fit share.  Unknown kv_dtype names refuse here (dtype_bytes) —
        # the same wall the engine raises at construction
        try:
            bb = sv.block_bytes(plan.cfg, plan.kv_dtype)
        except ValueError as e:
            findings.append(_finding(
                plan, "bad-serving-config",
                f"kv_dtype {sv.resolved_kv_dtype(plan.kv_dtype)!r}: {e}",
            ))
            return
        breakdown["kv_pool"] = {
            "num_blocks": n_blocks,
            "block_size": sv.block_size,
            "kv_dtype": bb["kv_dtype"],
            "pool_bytes": sv.pool_bytes(plan.cfg, plan.seq_len, plan.kv_dtype),
            # the int8 side arrays (per-block-per-group f32 scales), 0 at
            # any fp dtype — pool_bytes already includes them
            "scale_bytes": n_blocks * bb["scale_bytes"],
            # per-device slice of the tp-sharded pool (== pool_bytes / tp,
            # exactly: the KV-group axis divides or bad-serving-mesh fires)
            "pool_bytes_per_device": sv.pool_bytes_per_device(
                plan.cfg, tp, plan.seq_len, plan.kv_dtype
            ),
            "tp": tp,
            # blocks the --hbm-gb budget admits after params+activations;
            # filled in by the memory checker when a budget is given
            "blocks_at_budget": None,
            "decode_chunk": sv.decode_chunk,
            "spec_k": sv.spec_k,
            "reserve_headroom_blocks": headroom,
            "token_budget": sv.resolved_token_budget(),
            # open-system bound (None for replay configs): the
            # bad-server-config checker sized it against the headroom
            "admission_queue": sv.admission_queue,
            # host KV tier (serving/host_tier.py): whole-block slab bytes,
            # byte-exact vs the live HostBlockStore (the MiB budget rounds
            # down to full tp=1 blocks); 0/0 when the tier is off
            "host_pool_bytes": sv.host_pool_bytes(plan.cfg, plan.kv_dtype),
            "host_blocks": sv.num_host_blocks(plan.cfg, plan.kv_dtype),
        }
        if sv.draft_model:
            # speculative draft model: its paged-pool carve-out, priced
            # with the DRAFT architecture's block_bytes — byte-exact
            # against the live engine's second KVPool
            # (ServingEngine._init_draft_kv)
            try:
                dcfg = sv.draft_config()
            except ValueError as e:
                findings.append(_finding(
                    plan, "bad-serving-config",
                    f"draft_model={sv.draft_model!r}: {e}",
                ))
                dcfg = None
            if dcfg is not None:
                breakdown["kv_pool"].update({
                    "draft_model": sv.draft_model,
                    "draft_num_blocks": sv.num_draft_blocks(plan.seq_len),
                    "draft_pool_bytes": sv.draft_pool_bytes(
                        dcfg, 1, plan.seq_len, plan.kv_dtype
                    ),
                    "draft_pool_bytes_per_device": sv.draft_pool_bytes(
                        dcfg, tp, plan.seq_len, plan.kv_dtype
                    ),
                })
                if dcfg.padded_vocab_size != plan.cfg.padded_vocab_size:
                    findings.append(_finding(
                        plan, "bad-serving-config",
                        f"draft_model={sv.draft_model!r} padded vocab "
                        f"{dcfg.padded_vocab_size} != target "
                        f"{plan.cfg.padded_vocab_size}: the rejection "
                        "verify compares token ids, so drafter and "
                        "verifier must share a vocabulary "
                        "(ServingEngine refuses this config)",
                    ))
        _check_host_tier(plan, sv, findings, breakdown)
        _check_kernel_tuning(plan, findings, breakdown, bb)
        pp = _serving_pp(plan)
        if pp > 1 and plan.cfg.n_layer >= pp:
            from mdi_llm_tpu.parallel.partition import stage_layers

            # per-stage pool shards (serving/pipeline.py): each stage
            # stores l_max = max(stage_layers) layer slots (zero-padded so
            # the ring stays single-trace) of every block.  block_bytes is
            # layer-proportional and divisible by n_layer, so the integer
            # rescale below is EXACT — the estimate matches the live
            # stacked (pp, l_max, ...) pool shard byte for byte
            counts = stage_layers(plan.cfg.n_layer, pp)
            l_max = max(counts)
            L = plan.cfg.n_layer

            def per_stage(b):
                return n_blocks * (
                    b["kv_bytes"] // L * l_max
                    + b["scale_bytes"] // L * l_max
                )

            bb_tp = sv.block_bytes(plan.cfg, plan.kv_dtype, tp=tp)
            stage_dev = per_stage(bb_tp)  # one stage, one tp shard
            breakdown["kv_pool"].update({
                "pp": pp,
                "stage_layers": counts,
                "l_max": l_max,
                # one stage's full shard (tp=1 bytes) and the per-device
                # slice of it; the stacked pool totals pp x the former
                "pool_bytes_per_stage": per_stage(bb),
                "pool_bytes_per_device": stage_dev,
                "pool_bytes": pp * per_stage(bb),
            })


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def audit_plan(plan: PlanSpec, liveness: bool = False) -> AuditReport:
    """Run every checker family; never touches a device or compiles.
    `liveness=True` swaps the analytic activation high-water for the
    mdi-flow liveness-derived per-executable number whenever the plan is
    serving-engine-enumerable (`_liveness_act_bytes`; heuristic
    fallback otherwise) — slower (it traces the whole compile set), so
    opt-in."""
    findings: List[Finding] = []
    breakdown: Dict[str, Any] = {}
    _check_mesh(plan, findings)
    _check_stages(plan, findings, breakdown)
    _check_sharding(plan, findings)
    _check_serving(plan, findings, breakdown)
    _check_schedule(plan, findings, breakdown)
    _check_memory(plan, findings, breakdown, liveness=liveness)
    order = {code: i for i, code in enumerate(AUDIT_RULES)}
    findings.sort(key=lambda f: (order.get(f.rule, 99), f.message))
    return AuditReport(plan=plan, findings=findings, breakdown=breakdown)


def preflight(
    cfg: Config,
    *,
    n_stages: int = 0,
    pipeline: Optional[bool] = None,
    tp: int = 1,
    pp: int = 1,
    samples_per_slot: int = 1,
    n_samples: Optional[int] = None,
    batch: int = 1,
    seq_len: Optional[int] = None,
    kv_seq_len: Optional[int] = None,
    act_seq_len: int = 1,
    dtype: str = "bfloat16",
    cache_dtype: Optional[str] = None,
    quantize: Optional[str] = None,
    serving: Optional[ServingConfig] = None,
    hbm_gb: Optional[float] = None,
    host_gb: Optional[float] = None,
    origin: str = "<preflight>",
    liveness: bool = False,
) -> AuditReport:
    """Build the PlanSpec an engine launch implies and audit it.  Shared by
    bench.py / mdi-serve / mdi-starter; pure host-side analysis — adds zero
    compiles (the CompileGuard counters are untouched by construction)."""
    S = max(1, int(n_stages or 1))
    axes: Dict[str, int] = {}
    if S > 1:
        axes["pipe"] = S
    if tp > 1:
        axes["tp"] = int(tp)
    if pp > 1:
        # serving-side pipeline axis (serving/pipeline.py): the paged pool
        # stacks per-stage shards over it — distinct from the dense
        # pipeline's n_stages/"pipe" plan axis
        axes["pp"] = int(pp)
    plan = PlanSpec(
        cfg=cfg,
        mesh=MeshSpec.from_dict(axes),
        tp_axis="tp" if tp > 1 else None,
        n_stages=S,
        pipeline=pipeline,
        samples_per_slot=max(1, int(samples_per_slot)),
        n_samples=int(n_samples if n_samples is not None else batch),
        batch=int(batch),
        max_seq_length=seq_len,
        kv_seq_len=kv_seq_len,
        act_seq_len=act_seq_len,
        dtype=dtype,
        cache_dtype=None if cache_dtype in (None, "auto") else cache_dtype,
        quantize=None if quantize in (None, "none") else quantize,
        serving=serving,
        hbm_gb=hbm_gb,
        host_gb=host_gb,
        # the pipeline ring replicates embeddings/head on every stage
        shard_head=not (pipeline if pipeline is not None else S > 1),
        origin=origin,
    )
    return audit_plan(plan, liveness=liveness)


def refusal_text(tool: str) -> str:
    return (f"{tool}: mdi-audit preflight refused the plan "
            "(re-run with --no-preflight to launch anyway)")


def enforce_preflight(
    report: AuditReport,
    tool: str,
    allow: bool = False,
    emit=None,
    exit_: bool = True,
) -> bool:
    """The shared launch gate for bench.py / mdi-serve / mdi-starter: emit
    every finding prefixed with `tool`, then refuse on ERROR findings
    unless `allow` (--no-preflight).  Returns True when the launch may
    proceed; with ``exit_=False`` a refusal returns False instead of
    raising SystemExit (mdi-starter ships an abort sentinel through its
    run-spec broadcast so secondaries exit instead of deadlocking)."""
    if emit is None:
        def emit(line):
            print(line, file=sys.stderr)
    for line in report.render_findings():
        emit(f"{tool}: preflight: {line}")
    if not report.errors or allow:
        return True
    if exit_:
        raise SystemExit(refusal_text(tool))
    return False


def audit_detail(report: AuditReport) -> Dict[str, Any]:
    """The compact per-row record bench.py stores under `detail.audit`."""
    dev = report.breakdown.get("per_device", {})
    return {
        "findings": len(report.errors),
        "warnings": len(report.warnings),
        "est_hbm_bytes": int(dev.get("params_bytes", 0) + dev.get("kv_bytes", 0)),
        "est_params_bytes": int(dev.get("params_bytes", 0)),
        "est_kv_bytes": int(dev.get("kv_bytes", 0)),
        "est_act_bytes": int(dev.get("act_bytes", 0)),
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="mdi-audit",
        description="Static plan auditor: sharding consistency, per-device "
        "HBM budgets, and pipeline/collective schedule checks — before the "
        "first compile (see docs/analysis.md, 'Plan audit')",
    )
    src = ap.add_argument_group("plan source")
    src.add_argument("--model", default=None, help="registry model name")
    src.add_argument("--config", default=None, metavar="FILE",
                     help="model_config.yaml / config.json to audit")
    src.add_argument("--plan", default=None, metavar="FILE",
                     help="mesh/nodes config JSON (examples/mesh_configs, "
                     "examples/node_configs schemas)")
    par = ap.add_argument_group("parallel plan")
    par.add_argument("--mesh", default=None, metavar="AXES",
                     help="explicit mesh, e.g. pipe=4,tp=2")
    par.add_argument("--stages", type=int, default=None,
                     help="pipeline stages (default: plan file or 1)")
    par.add_argument("--tp", type=int, default=None,
                     help="tensor-parallel devices per stage")
    par.add_argument("--samples-per-slot", type=int, default=None)
    par.add_argument("--n-samples", type=int, default=None,
                     help="concurrent samples (ring bubble check)")
    run = ap.add_argument_group("run shape")
    run.add_argument("--batch", type=int, default=1)
    run.add_argument("--seq-len", type=int, default=None)
    run.add_argument("--prompt-len", type=int, default=1,
                     help="widest live token axis for the activation term")
    run.add_argument("--dtype", default="bfloat16",
                     choices=("bfloat16", "float16", "float32"))
    run.add_argument("--quantize", default="none",
                     choices=("none", "int8", "w8a8", "int4"))
    run.add_argument("--kv-dtype", default="auto",
                     help="KV-cache / paged-pool storage dtype; with "
                     "--serve, 'int8' audits the quantized pool (int8 "
                     "payload + per-block-per-group f32 scales, "
                     "~2x blocks per --hbm-gb); unknown names are refused "
                     "(bad-serving-config)")
    srv = ap.add_argument_group("serving (paged KV pool)")
    srv.add_argument("--serve", action="store_true",
                     help="audit a ServingConfig pool instead of a dense cache")
    srv.add_argument("--block-size", type=int, default=16)
    srv.add_argument("--max-blocks", type=int, default=None)
    srv.add_argument("--max-batch", type=int, default=8)
    srv.add_argument("--prefill-chunk", type=int, default=128)
    srv.add_argument("--token-budget", type=int, default=None,
                     help="unified-step token budget (default: max_batch + "
                     "prefill_chunk)")
    srv.add_argument("--decode-chunk", type=int, default=8)
    srv.add_argument("--spec-k", type=int, default=0,
                     help="speculative draft length (exact-match verify at "
                     "temperature 0, rejection-sampled verify above)")
    srv.add_argument("--temperature", type=float, default=0.0)
    srv.add_argument("--draft-model", default=None, metavar="NAME",
                     help="registry name of a small draft model; audits "
                     "the draft kv-pool carve-out (draft_* breakdown "
                     "fields) and the target-pool headroom left after it")
    srv.add_argument("--draft-share", type=float, default=0.25,
                     help="fraction of a bounded --max-blocks budget "
                     "carved out for the draft pool (default 0.25)")
    srv.add_argument("--host-pool-mib", type=int, default=0,
                     help="host-RAM KV block tier size in MiB (0 = off): "
                     "preempted sequences swap their blocks to pinned host "
                     "slabs instead of recomputing, and cold prefix chains "
                     "spill there (bad-host-tier audits the config)")
    srv.add_argument("--host-link-gbps", type=float, default=None,
                     help="host<->device link bandwidth in GB/s for the "
                     "swap cost model (default: per-device-kind table)")
    ap.add_argument("--hbm-gb", type=float, default=None,
                    help="per-device HBM budget (e.g. 16 for v5e)")
    ap.add_argument("--host-gb", type=float, default=None,
                    help="host-RAM budget for the KV block tier "
                    "(bad-host-tier when --host-pool-mib exceeds it)")
    ap.add_argument("--liveness", action="store_true",
                    help="derive the activation high-water from mdi-flow's "
                    "buffer-liveness pass over the serving compile set "
                    "instead of the analytic heuristic (serving plans on "
                    "tp/pp meshes only; traces every executable, so "
                    "slower)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="grandfather findings via an mdi-lint-style baseline")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-checks", action="store_true",
                    help="print the audit rule registry and exit")
    return ap


def _plan_from_args(args) -> PlanSpec:
    stages, tp, spslot, n_samples, seq_len = args.stages, args.tp, None, None, args.seq_len
    plan_file: Dict[str, Any] = {}
    origin = "<cli>"
    if args.plan:
        plan_file = json.loads(Path(args.plan).read_text())
        origin = str(args.plan)
        if "nodes" in plan_file:  # reference settings_distr schema
            n_nodes = 1 + len(plan_file["nodes"].get("secondary") or [])
            stages = stages if stages is not None else plan_file.get(
                "pipeline_stages", n_nodes
            )
        else:
            stages = stages if stages is not None else plan_file.get("pipeline_stages")
        tp = tp if tp is not None else plan_file.get("tp_devices")
        spslot = plan_file.get("samples_per_slot")
        n_samples = plan_file.get("n_samples")
        seq_len = seq_len if seq_len is not None else plan_file.get("sequence_length")

    if args.config:
        cfg = Config.from_file(args.config)
    elif args.model:
        cfg = Config.from_name(args.model)
    elif plan_file.get("model"):
        cfg = Config.from_name(plan_file["model"])
    else:
        raise ValueError("need --model, --config, or a plan file with a "
                         "'model' key")

    stages = int(stages or 1)
    tp = int(tp or 1)
    spslot = int(args.samples_per_slot if args.samples_per_slot is not None
                 else (spslot or 1))
    n_samples = int(args.n_samples if args.n_samples is not None
                    else (n_samples or args.batch))

    if args.mesh is not None:
        mesh = MeshSpec.parse(args.mesh)
    else:
        axes: Dict[str, int] = {}
        if stages > 1:
            axes["pipe"] = stages
        if tp > 1:
            axes["tp"] = tp
        if "mesh" in plan_file:  # training mesh schema (train_dp4_tp2.json)
            axes = dict(plan_file["mesh"])
            tp = int(axes.get("tp", tp))
        mesh = MeshSpec.from_dict(axes)

    serving = None
    if args.serve:
        serving = ServingConfig(
            block_size=args.block_size,
            max_blocks=args.max_blocks,
            max_batch=args.max_batch,
            prefill_chunk=args.prefill_chunk,
            token_budget=args.token_budget,
            decode_chunk=args.decode_chunk,
            spec_k=args.spec_k,
            temperature=args.temperature,
            draft_model=args.draft_model,
            draft_share=args.draft_share,
            # the pool dtype rides --kv-dtype (e.g. int8 for the quantized
            # pool: payload + scale bytes both audited); unknown names
            # surface as bad-serving-config, exactly like the engine
            kv_dtype=None if args.kv_dtype == "auto" else args.kv_dtype,
            host_pool_mib=args.host_pool_mib,
            host_link_gbps=args.host_link_gbps,
        )
    return PlanSpec(
        cfg=cfg,
        mesh=mesh,
        tp_axis="tp" if ("tp" in mesh.names or tp > 1) else None,
        n_stages=stages,
        samples_per_slot=spslot,
        n_samples=n_samples,
        batch=args.batch,
        max_seq_length=seq_len,
        act_seq_len=args.prompt_len,
        dtype=args.dtype,
        cache_dtype=None if args.kv_dtype == "auto" else args.kv_dtype,
        quantize=None if args.quantize == "none" else args.quantize,
        serving=serving,
        hbm_gb=args.hbm_gb,
        host_gb=args.host_gb,
        shard_head=stages <= 1,
        origin=origin,
    )


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_checks:
        width = max(len(c) for c in AUDIT_RULES)
        for code, (sev, summary) in AUDIT_RULES.items():
            print(f"{code:<{width}}  [{sev}] {summary}")
        return 0
    try:
        plan = _plan_from_args(args)
    except (ValueError, OSError, json.JSONDecodeError) as e:
        print(f"mdi-audit: {e}", file=sys.stderr)
        return 2
    report = audit_plan(plan, liveness=args.liveness)

    errors = report.errors
    if args.baseline:
        new, _old = Baseline.load(Path(args.baseline)).split(errors)
        errors = new

    if args.format == "json":
        out = report.as_json()
        out["new_errors"] = len(errors)
        print(json.dumps(out, indent=2))
    else:
        print(report.render_text())
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
