"""Model library: functional transformer, parameter init, KV caches."""

from mdi_llm_tpu.models.transformer import (
    forward,
    embed,
    head,
    run_blocks,
    init_params,
    init_kv_cache,
    init_paged_kv_cache,
    count_params,
    cast_params,
    slice_blocks,
)

__all__ = [
    "forward",
    "embed",
    "head",
    "run_blocks",
    "init_params",
    "init_kv_cache",
    "init_paged_kv_cache",
    "count_params",
    "cast_params",
    "slice_blocks",
]
