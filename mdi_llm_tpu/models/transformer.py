"""Decoder-only transformer as pure functions over a parameter pytree.

TPU-native redesign of the reference model stack
(`/root/reference/src/sub/model.py:276-981` `GPT`/`Block`/`CausalSelfAttention`/
MLPs/`KVCache`, and `/root/reference/src/sub/submodels.py` `StarterNode`/
`SecondaryNode`).  Key re-design decisions:

- **Layer-stacked parameters**: every per-layer leaf carries a leading layer
  axis and the block stack runs under `lax.scan`, so XLA compiles ONE block
  and reuses it — compile time is O(1) in depth, and slicing the leading axis
  yields a pipeline stage's parameters (the TPU analog of the reference's
  `split_parameters`, utils.py:241-385).
- **Functional KV cache**: a `(L, B, G, S, hs)` array pair threaded through
  the scan and updated with `dynamic_update_slice` (≡ `KVCache.index_copy_`,
  model.py:918-933) — donated under jit so decode is in-place in HBM.
- **Position-based masking**: queries carry absolute positions; no (S, S)
  mask cache materialization (cf. `build_mask_cache`, model.py:940-947).
- **Three-phase API** (`embed` / `run_blocks` / `head`) replaces the
  reference's two-phase `StarterNode.forward(first_pass=...)`
  (submodels.py:170-220): stage 0 of a pipeline = embed + run_blocks, last
  hop output re-enters stage 0 through `head`.

All matmuls hit the MXU in the params' dtype (bf16 by default) with f32
softmax/norm accumulation.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from mdi_llm_tpu.config import Config
from mdi_llm_tpu.ops.attention import multihead_attention
from mdi_llm_tpu.ops.norms import layer_norm, rms_norm
from mdi_llm_tpu.ops.quant import quantized_einsum
from mdi_llm_tpu.ops.rope import apply_rope, build_rope_cache

Params = Dict[str, Any]
KVCache = Dict[str, jnp.ndarray]  # {"k": (L,B,G,S,hs), "v": (L,B,G,S,hs)}


# ---------------------------------------------------------------------------
# Linear helpers (torch layout: weight (out, in)) so converted HF/litGPT
# checkpoints drop in without transposition bookkeeping.
# ---------------------------------------------------------------------------


def linear(x: jnp.ndarray, p: Params) -> jnp.ndarray:
    y = quantized_einsum("...i,oi->...o", x, p)
    if "bias" in p:
        y = y + p["bias"]
    return y


def _norm(cfg: Config, x: jnp.ndarray, p: Params) -> jnp.ndarray:
    if cfg.norm_class_name == "RMSNorm":
        return rms_norm(
            x, p["weight"], cfg.norm_eps, add_unit_offset=cfg.rmsnorm_add_unit_offset
        )
    return layer_norm(x, p["weight"], p.get("bias"), cfg.norm_eps)


def _gelu(cfg: Config, x: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.gelu(x, approximate=cfg.gelu_approximate == "tanh")


# ---------------------------------------------------------------------------
# MLP variants (reference model.py:782-853)
# ---------------------------------------------------------------------------


def mlp_forward(
    cfg: Config, p: Params, x: jnp.ndarray, moe_impl=None, moe_aux: bool = False
):
    # returns the (B, T, D) output; with moe_aux (LLaMAMoE only), (out, aux)
    kind = cfg.mlp_class_name
    if moe_aux and kind != "LLaMAMoE":
        raise ValueError(f"moe_aux requires an MoE config (got {kind!r})")
    if kind == "GptNeoxMLP":
        return linear(_gelu(cfg, linear(x, p["fc"])), p["proj"])
    if kind == "LLaMAMLP":
        return linear(jax.nn.silu(linear(x, p["fc_1"])) * linear(x, p["fc_2"]), p["proj"])
    if kind == "GemmaMLP":
        return linear(_gelu(cfg, linear(x, p["fc_1"])) * linear(x, p["fc_2"]), p["proj"])
    if kind == "LLaMAMoE":
        if moe_aux:  # impl returns (out, load-balancing aux loss)
            return (moe_impl or moe_forward)(cfg, p, x, with_aux=True)
        return (moe_impl or moe_forward)(cfg, p, x)
    raise ValueError(f"unknown mlp_class_name {kind!r}")


def moe_forward(
    cfg: Config, p: Params, x: jnp.ndarray, with_aux: bool = False,
    stats_reduce=None,
):
    """Top-k routed mixture of experts (reference `LLaMAMoE`,
    model.py:823-853).

    Dense formulation: every expert runs on every token and the router's
    top-k weights (renormalized over the selected experts) zero out the rest.
    On TPU this keeps shapes static and the MXU busy; the token-dispatch
    expert-parallel variant (all_to_all over an `ep` mesh axis) is
    `parallel/expert.ep_moe_forward`, passed in here via `moe_impl`.

    `with_aux` also returns the Switch/GShard load-balancing auxiliary loss
    `E · Σ_e f_e · P_e` — `f_e` the fraction of top-k assignments routed to
    expert e, `P_e` the mean router probability on e; 1.0 at perfectly
    uniform routing, larger when imbalanced.  Gradient reaches the gate
    through `P_e` (the assignment counts are stop-gradiented, as in Switch
    Transformer).  The reference trains its MoE with no balancing term
    (model.py:823-853); this is the TPU-first addition that keeps
    sharded-expert training balanced.

    `stats_reduce` (used inside shard_map losses, e.g. sp training where
    each device routes only its sequence chunk) reduces the raw per-expert
    sums `(assign, prob_sum, n_tokens)` across devices — typically
    `lambda t: jax.lax.psum(t, axes)` — BEFORE the aux is formed, so the
    result is the exact global formula rather than a mean of per-chunk
    auxes (f·P is nonlinear in the stats).
    """
    E = cfg.n_expert
    router = quantized_einsum("...i,ei->...e", x, p["gate"]).astype(jnp.float32)
    probs = jax.nn.softmax(router, axis=-1)  # (..., E)
    topv, topi = jax.lax.top_k(probs, cfg.n_expert_per_token)
    topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
    # scatter the normalized top-k weights back to a dense (..., E) table
    onehot = jax.nn.one_hot(topi, E, dtype=probs.dtype)  # (..., k, E)
    dense_w = jnp.einsum("...k,...ke->...e", topv, onehot)  # (..., E)

    # expert params have a leading E axis: fc_1 (E, I, D) etc.
    h1 = quantized_einsum("...d,eid->...ei", x, p["experts"]["fc_1"])
    h2 = quantized_einsum("...d,eid->...ei", x, p["experts"]["fc_2"])
    h = jax.nn.silu(h1) * h2
    out = quantized_einsum("...ei,edi->...ed", h, p["experts"]["proj"])
    y = jnp.einsum("...ed,...e->...d", out, dense_w.astype(out.dtype)).astype(x.dtype)
    if not with_aux:
        return y
    k = cfg.n_expert_per_token
    assign = jnp.sum(
        jax.lax.stop_gradient(onehot).reshape(-1, E), axis=0
    ).astype(jnp.float32)  # (E,) top-k assignment counts (sum over k too)
    prob_sum = jnp.sum(probs.reshape(-1, E), axis=0)
    n_tokens = jnp.asarray(probs.size // E, jnp.float32)
    if stats_reduce is not None:
        assign, prob_sum, n_tokens = stats_reduce((assign, prob_sum, n_tokens))
    f = assign / (n_tokens * k)
    pm = prob_sum / n_tokens
    return y, E * jnp.sum(f * pm)


# ---------------------------------------------------------------------------
# Attention
# ---------------------------------------------------------------------------


def _split_qkv(cfg: Config, qkv: jnp.ndarray):
    """Un-interleave the fused litGPT QKV projection output.

    litGPT packs per KV-group [q * q_per_kv, k, v] (reference
    model.py:686-702); returns q (B,T,n_head,hs), k/v (B,T,G,hs).
    """
    B, T, _ = qkv.shape
    G = cfg.n_query_groups
    q_per_kv = cfg.n_head // G
    hs = cfg.head_size
    qkv = qkv.reshape(B, T, G, q_per_kv + 2, hs)
    q = qkv[:, :, :, :q_per_kv, :].reshape(B, T, cfg.n_head, hs)
    k = qkv[:, :, :, q_per_kv, :]
    v = qkv[:, :, :, q_per_kv + 1, :]
    return q, k, v


def attention_forward(
    cfg: Config,
    p: Params,
    x: jnp.ndarray,  # (B, T, D)
    pos: jnp.ndarray,  # (B, T) absolute positions
    cos: jnp.ndarray,  # (B, T, rope_n_elem) pre-gathered for these positions
    sin: jnp.ndarray,
    k_cache: Optional[jnp.ndarray],  # (B, G, S, hs) or None
    v_cache: Optional[jnp.ndarray],
    input_pos: Optional[jnp.ndarray],  # (B,) write offset into the cache
    sp_axis: Optional[str] = None,  # sequence-parallel mesh axis (ring attn)
    fresh_prefill: bool = False,  # input_pos==0 and cache empty: attend the
    # chunk itself (T×T) instead of the full cache buffer (T×S)
    use_flash: bool = False,  # pallas flash kernel on the chunk path
    sp_meta: Optional[Tuple] = None,  # sp inference: (k_pos (B, C) absolute
    # slot positions of the LOCAL cache shard, cache_off scalar local write
    # offset, write_on scalar — this device owns the decode token)
    paged_tables: Optional[jnp.ndarray] = None,  # (B, max_blocks) block
    # tables: k/v caches are the POOLED (num_blocks, block_size, G, hs)
    # layout and reads/writes resolve through the table (serving engine)
    paged_kernel: Optional[bool] = None,  # None → auto (TPU, decode step)
    paged_ragged: Optional[Tuple] = None,  # unified serving step: (q_slot
    # (T,), q_start (n_slots,), q_len (n_slots,)) — B == 1, tokens packed
    # slot-major, `paged_tables` is (n_slots, max_blocks) and every token
    # resolves reads/writes through its OWN slot's table row at `pos`
    paged_shard: Optional[Tuple] = None,  # (Mesh, tp_axis) for the tensor-
    # parallel serving engine: the Pallas kernel paths run per shard under
    # jax.shard_map (heads/KV groups split); the lax fallback and the
    # paged_update scatter are plain jnp and partition under GSPMD
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray], Optional[jnp.ndarray]]:
    B, T, D = x.shape
    qkv = linear(x, p["qkv"])
    q, k, v = _split_qkv(cfg, qkv)
    # (B, heads, T, hs)
    q = q.swapaxes(1, 2)
    k = k.swapaxes(1, 2)
    v = v.swapaxes(1, 2)

    n_elem = cfg.rope_n_elem
    if n_elem > 0:
        cos_b = cos[:, None, :, :]
        sin_b = sin[:, None, :, :]
        q = jnp.concatenate(
            [apply_rope(q[..., :n_elem], cos_b, sin_b), q[..., n_elem:]], axis=-1
        )
        k = jnp.concatenate(
            [apply_rope(k[..., :n_elem], cos_b, sin_b), k[..., n_elem:]], axis=-1
        )

    if paged_tables is not None:
        # serving path: pooled block cache, reads/writes through the table
        from mdi_llm_tpu.ops.paged_attention import (
            paged_attention,
            paged_prefill,
            paged_update,
        )

        if k_cache is None:
            raise ValueError("paged attention requires the pooled KV cache")
        if paged_ragged is not None:
            # unified mixed step: packed slot-major tokens, B == 1.  Each
            # token is one lane of the batched update with its OWN slot's
            # table row; packed-tail padding carries a position past the
            # table's coverage, so its write lands in the trash block
            q_slot, q_start, q_len = paged_ragged
            k_cache, v_cache = paged_update(
                k_cache, v_cache,
                k.swapaxes(1, 2)[0][:, None], v.swapaxes(1, 2)[0][:, None],
                paged_tables[q_slot], pos[0][:, None],
            )
            y = paged_prefill(
                q, k_cache, v_cache, paged_tables, q_slot, q_start, q_len,
                pos[0], use_kernel=paged_kernel, shard_axes=paged_shard,
            )
        else:
            k_cache, v_cache = paged_update(
                k_cache, v_cache, k.swapaxes(1, 2), v.swapaxes(1, 2),
                paged_tables, pos,
            )
            y = paged_attention(
                q, k_cache, v_cache, paged_tables, pos,
                use_kernel=paged_kernel, shard_axes=paged_shard,
            )
        y = y.swapaxes(1, 2).reshape(B, T, cfg.n_head * cfg.head_size)
        return linear(y.astype(x.dtype), p["proj"]), k_cache, v_cache

    if sp_axis is not None and k_cache is not None:
        # sequence-sharded KV cache (sp inference): the cache shard holds
        # LOCAL slots whose absolute positions live in sp_meta's k_pos
        from mdi_llm_tpu.ops.ring_attention import ring_attention, ring_decode

        if sp_meta is None:
            raise ValueError("sp inference with a KV cache requires sp_meta")
        kp, cache_off, write_on = sp_meta
        if T > 1:
            # sp prefill: every device writes its own chunk at local offset
            # 0 and attends the distributed sequence over the ring
            def upd0(cache, new):
                return jax.lax.dynamic_update_slice(
                    cache, new.astype(cache.dtype), (0, 0, 0)
                )

            k_cache = jax.vmap(upd0)(k_cache, k)
            v_cache = jax.vmap(upd0)(v_cache, v)
            # sp prefill shares the ring-flash contract (q_pos == k_pos ==
            # contiguous per-device chunk); padded bucket positions sit
            # after the prompt so causal masking keeps them invisible
            y = ring_attention(q, k, v, pos, pos, sp_axis, use_flash=use_flash)
        else:
            # sp decode: only the owning device appends the token's K/V at
            # cache_off.  The update itself is unconditional (in-place on the
            # donated buffer); non-owners write back the slot's current value
            # — a full-cache jnp.where select would double HBM traffic.
            def updo(cache, new):
                cur = jax.lax.dynamic_slice(
                    cache, (0, cache_off, 0), (cache.shape[0], 1, cache.shape[2])
                )
                sel = jnp.where(write_on, new.astype(cache.dtype), cur)
                return jax.lax.dynamic_update_slice(cache, sel, (0, cache_off, 0))

            k_cache = jax.vmap(updo)(k_cache, k)
            v_cache = jax.vmap(updo)(v_cache, v)
            y = ring_decode(q, k_cache, v_cache, kp, pos, sp_axis)
        y = y.swapaxes(1, 2).reshape(B, T, cfg.n_head * cfg.head_size).astype(x.dtype)
        return linear(y, p["proj"]), k_cache, v_cache

    if k_cache is not None:
        # scatter this chunk into the cache at each sample's offset (cache
        # may be narrower than the compute dtype, e.g. bf16 cache, f32 math)
        def upd(cache, new, off):
            return jax.lax.dynamic_update_slice(cache, new.astype(cache.dtype), (0, off, 0))

        k_cache = jax.vmap(upd)(k_cache, k, input_pos)
        v_cache = jax.vmap(upd)(v_cache, v, input_pos)

    if k_cache is not None and not fresh_prefill:
        k_att, v_att = k_cache, v_cache
        kv_valid = input_pos + T  # (B,)
        k_pos = None  # cache slot j holds absolute position j
    else:
        # no cache, or a fresh prefill at offset 0: attend the chunk itself
        # (T×T instead of T×cache_len — and flash-eligible)
        k_att, v_att = k, v
        kv_valid = None
        k_pos = pos  # uncached chunk: keys sit at the query positions

    if sp_axis is not None:
        from mdi_llm_tpu.ops.ring_attention import ring_attention

        # cache-less sp path (training / eval): q_pos == k_pos == the local
        # contiguous chunk, so the diagonal block may run the flash kernel
        y = ring_attention(q, k_att, v_att, pos, k_pos, sp_axis, use_flash=use_flash)
    elif use_flash and kv_valid is None and T > 1:
        from mdi_llm_tpu.ops.flash import flash_attention

        # flash path assumes q_pos == k_pos == arange(T) (fresh chunk at 0)
        y = flash_attention(q, k_att, v_att)
    else:
        # litGPT scales by 1/sqrt(head_size) (model.py:738-751)
        y = multihead_attention(q, k_att, v_att, pos, kv_valid, k_pos=k_pos)
    y = y.swapaxes(1, 2).reshape(B, T, cfg.n_head * cfg.head_size).astype(x.dtype)
    return linear(y, p["proj"]), k_cache, v_cache


# ---------------------------------------------------------------------------
# Block + scan over the stack
# ---------------------------------------------------------------------------


def block_forward(
    cfg: Config,
    p: Params,
    x: jnp.ndarray,
    pos: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    k_cache: Optional[jnp.ndarray],
    v_cache: Optional[jnp.ndarray],
    input_pos: Optional[jnp.ndarray],
    sp_axis: Optional[str] = None,
    fresh_prefill: bool = False,
    use_flash: bool = False,
    sp_meta: Optional[Tuple] = None,
    moe_impl=None,
    collect_moe_aux: bool = False,
    paged_tables: Optional[jnp.ndarray] = None,
    paged_kernel: Optional[bool] = None,
    paged_ragged: Optional[Tuple] = None,
    paged_shard: Optional[Tuple] = None,
):
    """One transformer block (reference `Block`, model.py:576-629), both the
    parallel-residual (GPT-NeoX/Falcon/Phi) and sequential (Llama) forms.

    With `collect_moe_aux` (MoE training) the return gains a 4th element:
    this layer's load-balancing auxiliary loss scalar."""
    n1 = _norm(cfg, x, p["norm_1"])
    att, k_cache, v_cache = attention_forward(
        cfg, p["attn"], n1, pos, cos, sin, k_cache, v_cache, input_pos, sp_axis,
        fresh_prefill, use_flash, sp_meta,
        paged_tables=paged_tables, paged_kernel=paged_kernel,
        paged_ragged=paged_ragged, paged_shard=paged_shard,
    )
    if cfg.parallel_residual:
        n2 = n1 if cfg.shared_attention_norm else _norm(cfg, x, p["norm_2"])
        mlp_out = mlp_forward(cfg, p["mlp"], n2, moe_impl, moe_aux=collect_moe_aux)
        if collect_moe_aux:
            mlp_out, aux = mlp_out
        x = x + att + mlp_out
    else:
        x = x + att
        mlp_out = mlp_forward(
            cfg, p["mlp"], _norm(cfg, x, p["norm_2"]), moe_impl,
            moe_aux=collect_moe_aux,
        )
        if collect_moe_aux:
            mlp_out, aux = mlp_out
        x = x + mlp_out
    if collect_moe_aux:
        return x, k_cache, v_cache, aux
    return x, k_cache, v_cache


def run_blocks(
    cfg: Config,
    blocks: Params,  # stacked: every leaf has leading axis L_stage
    x: jnp.ndarray,  # (B, T, D)
    pos: jnp.ndarray,  # (B, T)
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    kv: Optional[KVCache] = None,  # k/v: (L_stage, B, G, S, hs)
    input_pos: Optional[jnp.ndarray] = None,  # (B,)
    remat: bool = False,
    sp_axis: Optional[str] = None,
    fresh_prefill: bool = False,
    use_flash: bool = False,
    sp_meta: Optional[Tuple] = None,
    moe_impl=None,
    unroll: int = 1,
    collect_moe_aux: bool = False,
    paged_tables: Optional[jnp.ndarray] = None,
    paged_kernel: Optional[bool] = None,
    paged_ragged: Optional[Tuple] = None,
    paged_shard: Optional[Tuple] = None,
):
    # returns (x, kv), or (x, kv, aux_sum) under collect_moe_aux
    """Scan the block stack. One compiled block, L iterations.  `remat=True`
    rematerializes each block under autodiff (training memory ∝ 1 layer's
    activations instead of L — the TPU substitute for the reference's AMP
    memory savings, SURVEY.md §2.4).  `unroll` trades compile time for
    per-iteration loop overhead (decode steps are small enough that the
    XLA while-loop bookkeeping is a measurable slice of each layer).

    `collect_moe_aux` (MoE training, no KV cache) accumulates each layer's
    load-balancing aux loss through the scan carry; the return gains the
    layer-SUMMED aux scalar (caller normalizes by n_layer)."""

    if kv is None:
        if collect_moe_aux:

            def body(carry, layer_p):
                h, acc = carry
                y, _, _, aux = block_forward(
                    cfg, layer_p, h, pos, cos, sin, None, None, input_pos,
                    sp_axis, fresh_prefill, use_flash, moe_impl=moe_impl,
                    collect_moe_aux=True,
                )
                return (y, acc + aux), None

            if remat:
                body = jax.checkpoint(body)
            (x, aux_sum), _ = jax.lax.scan(
                body, (x, jnp.zeros((), jnp.float32)), blocks, unroll=unroll
            )
            return x, None, aux_sum

        def body(carry, layer_p):
            y, _, _ = block_forward(
                cfg, layer_p, carry, pos, cos, sin, None, None, input_pos, sp_axis,
                fresh_prefill, use_flash, moe_impl=moe_impl,
            )
            return y, None

        if remat:
            body = jax.checkpoint(body)
        x, _ = jax.lax.scan(body, x, blocks, unroll=unroll)
        return x, None

    if collect_moe_aux:
        raise ValueError("collect_moe_aux is a training path (kv must be None)")

    def body(carry, xs):
        layer_p, k_c, v_c = xs
        y, k_c, v_c = block_forward(
            cfg, layer_p, carry, pos, cos, sin, k_c, v_c, input_pos, sp_axis,
            fresh_prefill=fresh_prefill, use_flash=use_flash, sp_meta=sp_meta,
            moe_impl=moe_impl,
            paged_tables=paged_tables, paged_kernel=paged_kernel,
            paged_ragged=paged_ragged, paged_shard=paged_shard,
        )
        return y, (k_c, v_c)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (blocks, kv["k"], kv["v"]), unroll=unroll
    )
    return x, {"k": k_new, "v": v_new}


# ---------------------------------------------------------------------------
# Embedding / head phases
# ---------------------------------------------------------------------------


def embed(cfg: Config, params: Params, tokens: jnp.ndarray, pos: jnp.ndarray) -> jnp.ndarray:
    """Token (+ learned position, for the GPT-2 generation) embedding."""
    x = jnp.take(params["wte"]["weight"], tokens, axis=0)
    if cfg.scale_embeddings:  # Gemma (model.py:390-391)
        x = x * jnp.asarray(cfg.n_embd**0.5, dtype=x.dtype)
    if cfg.pos_embedding == "learned":
        # mode="clip": see forward()'s rope gather — padding positions past
        # the table must clip, not NaN-fill (0 * NaN poisons masked reads)
        x = x + jnp.take(params["wpe"]["weight"], pos, axis=0, mode="clip")
    return x


def head(cfg: Config, params: Params, x: jnp.ndarray) -> jnp.ndarray:
    """Final norm + LM head (the reference starter's `first_pass=False` path,
    submodels.py:203-218)."""
    x = _norm(cfg, x, params["ln_f"])
    w = params["wte"] if cfg.tie_embeddings else params["lm_head"]
    logits = quantized_einsum("...d,vd->...v", x, w)
    if cfg.lm_head_bias:
        logits = logits + params["lm_head"]["bias"]
    return logits


def forward(
    cfg: Config,
    params: Params,
    tokens: jnp.ndarray,  # (B, T) int32
    input_pos: jnp.ndarray,  # (B,) start offset of this chunk
    kv: Optional[KVCache] = None,
    rope: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    remat: bool = False,
    sp_axis: Optional[str] = None,
    fresh_prefill: bool = False,
    use_flash: bool = False,
    sp_meta: Optional[Tuple] = None,
    moe_impl=None,
    unroll: int = 1,
    collect_moe_aux: bool = False,
    paged_tables: Optional[jnp.ndarray] = None,
    paged_kernel: Optional[bool] = None,
    paged_ragged: Optional[Tuple] = None,
    paged_shard: Optional[Tuple] = None,
):
    # returns (logits, kv), or (logits, kv, aux_sum) under collect_moe_aux
    """Full-model forward: logits (B, T, padded_vocab), updated KV cache.

    `collect_moe_aux` (MoE training) adds a 3rd return: the layer-summed
    load-balancing auxiliary loss (see `moe_forward`).

    Works for prefill (T = prompt chunk) and decode (T = 1) alike; the same
    traced function is reused whenever shapes match (shape-bucketing lives in
    `generation.py`).  With `sp_axis` (inside a shard_map over that axis),
    `tokens` is the LOCAL sequence chunk and `input_pos` its absolute start —
    attention runs as ring attention over the distributed sequence.

    With `paged_tables` (serving engine), `kv` is the POOLED block cache
    from `init_paged_kv_cache` and every read/write resolves through the
    per-sequence block tables (ops/paged_attention.py).  With
    `paged_ragged` (the unified mixed serving step), `tokens` is a (1, T)
    slot-major PACKED ragged batch — pass `input_pos` as the (1, T)
    per-token absolute positions (a 2-D `input_pos` overrides the
    contiguous-chunk ramp) and `paged_tables` as the full
    (n_slots, max_blocks) table.  `paged_shard=(mesh, tp_axis)` (the
    tensor-parallel serving engine) routes the Pallas paged kernels
    through a per-shard `jax.shard_map`; everything else in the paged
    path partitions under GSPMD.

    `fresh_prefill` (caller contract: input_pos == 0, cache empty) attends
    over the chunk itself rather than the cache buffer, enabling the Pallas
    flash kernel via `use_flash`.  The kernel carries a custom VJP
    (FlashAttention-2 recompute backward, ops/flash.py), so `use_flash`
    also composes with `remat`/`jax.grad` for training.
    """
    B, T = tokens.shape
    if input_pos.ndim == 2:
        pos = input_pos  # explicit per-token positions (ragged mixed step)
    else:
        pos = input_pos[:, None] + jnp.arange(T, dtype=input_pos.dtype)[None, :]
    if rope is None:
        rope = get_rope_cache(cfg)
    # mode="clip" pins the documented out-of-bounds behavior: jnp.take's
    # default FILLS with NaN, and the ragged mixed step's padding tokens
    # deliberately carry a position past the table (their K/V goes to the
    # trash block) — a NaN there would leak through every masked-attention
    # read as 0 * NaN
    cos = jnp.take(rope[0], pos, axis=0, mode="clip")
    sin = jnp.take(rope[1], pos, axis=0, mode="clip")
    x = embed(cfg, params, tokens, pos)
    out = run_blocks(
        cfg, params["blocks"], x, pos, cos, sin, kv, input_pos, remat=remat,
        sp_axis=sp_axis, fresh_prefill=fresh_prefill, use_flash=use_flash,
        sp_meta=sp_meta, moe_impl=moe_impl, unroll=unroll,
        collect_moe_aux=collect_moe_aux,
        paged_tables=paged_tables, paged_kernel=paged_kernel,
        paged_ragged=paged_ragged, paged_shard=paged_shard,
    )
    if collect_moe_aux:
        x, kv, aux_sum = out
        return head(cfg, params, x), kv, aux_sum
    x, kv = out
    return head(cfg, params, x), kv


@functools.lru_cache(maxsize=16)
def _rope_cache_memo(block_size: int, n_elem: int, base: int, ratio: int):
    return build_rope_cache(block_size, n_elem, base, ratio)


def get_rope_cache(cfg: Config, seq_len: Optional[int] = None):
    """Memoized (cos, sin) tables for a config — eager decode loops would
    otherwise recompute block_size×n_elem trig tables every token.

    Positions beyond the table length would silently clip under jnp.take;
    generation code checks lengths host-side before stepping."""
    return _rope_cache_memo(
        seq_len or cfg.block_size, cfg.rope_n_elem, cfg.rope_base, cfg.rope_condense_ratio
    )


# ---------------------------------------------------------------------------
# Parameter initialization (scratch training)
# ---------------------------------------------------------------------------


def init_params(
    cfg: Config, key: jax.Array, dtype=jnp.float32, n_layer: Optional[int] = None
) -> Params:
    """GPT-NeoX-style init (reference train.py:35-55): normal(0, 0.02)
    everywhere, output projections scaled by 1/sqrt(2*n_layer)."""
    L = cfg.n_layer if n_layer is None else n_layer
    D, V = cfg.n_embd, cfg.padded_vocab_size
    I = cfg.intermediate_size
    std = 0.02
    proj_std = 0.02 / (2 * cfg.n_layer) ** 0.5
    keys = iter(jax.random.split(key, 64))

    def w(shape, s=std):
        return (jax.random.normal(next(keys), shape, jnp.float32) * s).astype(dtype)

    def lin(out_d, in_d, s=std, bias=cfg.bias):
        p = {"weight": w((L, out_d, in_d), s)}
        if bias:
            p["bias"] = jnp.zeros((L, out_d), dtype)
        return p

    def norm_p():
        p = {"weight": jnp.ones((L, D), dtype)}
        if cfg.norm_class_name == "LayerNorm" and cfg.bias:
            p["bias"] = jnp.zeros((L, D), dtype)
        return p

    attn = {
        "qkv": lin(cfg.qkv_size, D),
        "proj": lin(D, cfg.attn_out_size, proj_std),
    }
    if cfg.mlp_class_name == "GptNeoxMLP":
        mlp = {"fc": lin(I, D), "proj": lin(D, I, proj_std)}
    elif cfg.mlp_class_name in ("LLaMAMLP", "GemmaMLP"):
        mlp = {
            "fc_1": lin(I, D, bias=False),
            "fc_2": lin(I, D, bias=False),
            "proj": lin(D, I, proj_std, bias=False),
        }
    else:  # LLaMAMoE
        E = cfg.n_expert
        mlp = {
            "gate": {"weight": w((L, E, D))},
            "experts": {
                "fc_1": {"weight": w((L, E, I, D))},
                "fc_2": {"weight": w((L, E, I, D))},
                "proj": {"weight": w((L, E, D, I), proj_std)},
            },
        }
    blocks = {"norm_1": norm_p(), "attn": attn, "mlp": mlp}
    if not cfg.shared_attention_norm:
        blocks["norm_2"] = norm_p()

    params: Params = {
        "wte": {"weight": w((V, D))},
        "blocks": blocks,
        "ln_f": {
            "weight": jnp.ones((D,), dtype),
            **(
                {"bias": jnp.zeros((D,), dtype)}
                if cfg.norm_class_name == "LayerNorm" and cfg.bias
                else {}
            ),
        },
    }
    if cfg.pos_embedding == "learned":
        params["wpe"] = {"weight": w((cfg.block_size, D))}
    if not cfg.tie_embeddings:
        params["lm_head"] = {"weight": w((V, D))}
        if cfg.lm_head_bias:
            params["lm_head"]["bias"] = jnp.zeros((V,), dtype)
    elif cfg.lm_head_bias:
        params["lm_head"] = {"bias": jnp.zeros((V,), dtype)}
    return params


def init_kv_cache(
    cfg: Config,
    batch_size: int,
    max_seq_length: int,
    dtype=jnp.bfloat16,
    n_layer: Optional[int] = None,
) -> KVCache:
    """Preallocated zero cache (≡ reference `GPT.set_kv_cache`,
    model.py:423-447): k/v of shape (L, B, G, S, hs)."""
    L = cfg.n_layer if n_layer is None else n_layer
    shape = (L, batch_size, cfg.n_query_groups, max_seq_length, cfg.head_size)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def init_paged_kv_cache(
    cfg: Config,
    num_blocks: int,
    block_size: int,
    dtype=jnp.bfloat16,
    n_layer: Optional[int] = None,
) -> KVCache:
    """Pooled block cache for the serving engine: k/v of shape
    (L, num_blocks, block_size, G, hs).  Block 0 is reserved by the
    allocator (`serving.kv_pool.KVPool`) as the write-only trash block for
    padding lanes/positions.

    `dtype="int8"` builds the QUANTIZED pool: k/v each become
    `{"q": int8 (L, num_blocks, block_size, G, hs), "scale": f32
    (L, num_blocks, G)}` — symmetric per-block-per-KV-group scales,
    quantized on scatter and dequantized inside the attention kernels'
    block loop (`ops/paged_attention.py`).  The layer scan, donation and
    sharding all thread the scale leaves automatically (they ride the same
    (L, NB, ...) leading axes as the payload)."""
    L = cfg.n_layer if n_layer is None else n_layer
    shape = (L, num_blocks, block_size, cfg.n_query_groups, cfg.head_size)
    if dtype in ("int8", jnp.int8) or getattr(dtype, "name", None) == "int8":
        sshape = (L, num_blocks, cfg.n_query_groups)
        return {
            "k": {"q": jnp.zeros(shape, jnp.int8),
                  "scale": jnp.zeros(sshape, jnp.float32)},
            "v": {"q": jnp.zeros(shape, jnp.int8),
                  "scale": jnp.zeros(sshape, jnp.float32)},
        }
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def count_params(params: Params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))


def cast_params(params: Params, dtype) -> Params:
    """Cast float leaves; integer leaves (int8 quantized weights) pass
    through untouched."""
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        params,
    )


def param_dtype(params: Params):
    """Dtype of the first floating *weight* leaf.  Skips the f32 "scale"
    vectors of int8-quantized linears, which would otherwise win the
    sorted-key flattening order and silently flip KV caches / pipeline
    payloads to f32."""
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        last = path[-1]
        if getattr(last, "key", None) == "scale":
            continue
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf.dtype
    raise ValueError("no floating weight leaves in param tree")


def slice_blocks(blocks: Params, start: int, stop: int) -> Params:
    """Take layers [start, stop) from a stacked block pytree — the TPU-native
    `split_parameters` (reference utils.py:241-385): no renaming, just a
    leading-axis slice."""
    return jax.tree_util.tree_map(lambda x: x[start:stop], blocks)
