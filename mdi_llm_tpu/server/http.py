"""Asyncio HTTP/1.1 + SSE front door over the serving frontend.

Stdlib only: `asyncio.start_server` streams, a ~hundred-line HTTP/1.1
request parser, and Server-Sent Events for token streaming — the
reference repo's CherryPy-over-pickle node control plane (PAPER.md)
reproduced TPU-natively with none of either.  The HTTP layer holds NO
model state: every request flows through `ServingFrontend.submit` and
its token events arrive via a `loop.call_soon_threadsafe` bridge from
the engine thread, so the asyncio loop never blocks on device work and
the engine thread never touches a socket.

API (docs/serving.md has the full schema):

- ``POST /v1/completions`` — body ``{"prompt": [ids] | "text",
  "max_tokens": N, "stream": bool, "priority", "tenant",
  "ttft_slo_ms", "stop": [[ids], ...]}``.  ``stream: true`` answers
  ``text/event-stream``: one ``token`` event per generated token, a
  final ``done`` event with the request summary (or ``error``); client
  disconnect mid-stream cancels the request at the next step boundary.
  Non-streaming answers one JSON body on completion.
- backpressure: 429 + ``Retry-After`` when the admission queue is at
  its bound; 503 while draining; 400 for infeasible/invalid requests.
- ``GET /healthz`` — liveness + queue/lane depths (200 serving, 503
  draining).
- ``GET /v1/stats`` — the canonical `ServingStats.to_dict()` plus
  latency percentiles.
- ``GET /metrics`` — Prometheus text exposition of the observer's
  registry.

Graceful drain (`shutdown()`): stop accepting (new requests see 503),
wait for in-flight requests up to `drain_timeout_s`, stop the engine
thread, close the listener.
"""

from __future__ import annotations

import asyncio
import json
from typing import Dict, Optional, Tuple

from mdi_llm_tpu.server.frontend import (
    FrontendClosedError,
    QueueFullError,
    ServingFrontend,
)

__all__ = ["ServingHTTPServer"]

_MAX_HEADER_BYTES = 65536
_MAX_BODY_BYTES = 16 * 1024 * 1024

_STATUS_TEXT = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 413: "Payload Too Large",
    429: "Too Many Requests", 500: "Internal Server Error",
    503: "Service Unavailable",
}


class _HTTPError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


def _response_head(status: int, content_type: str,
                   extra: Optional[Dict[str, str]] = None,
                   content_length: Optional[int] = None) -> bytes:
    lines = [f"HTTP/1.1 {status} {_STATUS_TEXT.get(status, 'Unknown')}",
             f"Content-Type: {content_type}",
             "Connection: close"]
    if content_length is not None:
        lines.append(f"Content-Length: {content_length}")
    for k, v in (extra or {}).items():
        lines.append(f"{k}: {v}")
    return ("\r\n".join(lines) + "\r\n\r\n").encode()


def _sse(event: str, data: Dict) -> bytes:
    return f"event: {event}\ndata: {json.dumps(data)}\n\n".encode()


class ServingHTTPServer:
    """One HTTP listener over one `ServingFrontend`.

    `tokenizer` (optional) enables text prompts and decoded text in
    responses; token-id prompts always work.  `start()` binds and
    starts the engine thread if the frontend has not been started;
    `serve_forever()` blocks until `shutdown()` (e.g. from a signal
    handler).  Port 0 binds an ephemeral port (tests); `self.port`
    reports the bound one.
    """

    def __init__(self, frontend: ServingFrontend, host: str = "127.0.0.1",
                 port: int = 8000, tokenizer=None,
                 drain_timeout_s: float = 30.0):
        self.frontend = frontend
        self.host = host
        self.port = port
        self.tokenizer = tokenizer
        self.drain_timeout_s = drain_timeout_s
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._shutdown = asyncio.Event()

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        if self.frontend._thread is None:
            self.frontend.start()
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def serve_forever(self) -> None:
        await self._shutdown.wait()

    async def shutdown(self) -> None:
        """Graceful drain: refuse new work (503), wait for in-flight
        requests, stop the engine thread, close the listener."""
        # drain() flips the frontend closed; run the blocking wait off
        # the event loop so open SSE streams keep flushing through it
        await asyncio.get_running_loop().run_in_executor(
            None, lambda: self.frontend.drain(timeout=self.drain_timeout_s)
        )
        await asyncio.get_running_loop().run_in_executor(
            None, self.frontend.stop
        )
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self._shutdown.set()

    # -- request plumbing ----------------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            try:
                method, path, headers = await self._read_head(reader)
                body = await self._read_body(reader, headers)
            except _HTTPError as e:
                await self._send_json(writer, e.status,
                                      {"error": e.message})
                return
            except (asyncio.IncompleteReadError, ConnectionError):
                return
            try:
                await self._route(method, path, body, writer)
            except _HTTPError as e:
                await self._send_json(
                    writer, e.status, {"error": e.message},
                    extra={"Retry-After": "1"} if e.status == 429 else None,
                )
            except (ConnectionError, asyncio.CancelledError):
                raise
            except Exception as e:  # one bad request must not kill the server
                await self._send_json(
                    writer, 500, {"error": f"{type(e).__name__}: {e}"}
                )
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _read_head(self, reader) -> Tuple[str, str, Dict[str, str]]:
        head = await reader.readuntil(b"\r\n\r\n")
        if len(head) > _MAX_HEADER_BYTES:
            raise _HTTPError(413, "headers too large")
        lines = head.decode("latin-1").split("\r\n")
        parts = lines[0].split(" ")
        if len(parts) != 3:
            raise _HTTPError(400, f"malformed request line: {lines[0]!r}")
        method, path, _version = parts
        headers: Dict[str, str] = {}
        for ln in lines[1:]:
            if not ln:
                continue
            k, _, v = ln.partition(":")
            headers[k.strip().lower()] = v.strip()
        return method.upper(), path, headers

    async def _read_body(self, reader, headers: Dict[str, str]) -> bytes:
        n = int(headers.get("content-length", "0") or "0")
        if n > _MAX_BODY_BYTES:
            raise _HTTPError(413, f"body of {n} bytes exceeds the "
                             f"{_MAX_BODY_BYTES} limit")
        return await reader.readexactly(n) if n else b""

    async def _send_json(self, writer, status: int, payload: Dict,
                         extra: Optional[Dict[str, str]] = None) -> None:
        body = (json.dumps(payload) + "\n").encode()
        writer.write(_response_head(status, "application/json",
                                    extra=extra, content_length=len(body)))
        writer.write(body)
        await writer.drain()

    # -- routes --------------------------------------------------------------

    async def _route(self, method: str, path: str, body: bytes,
                     writer) -> None:
        path = path.split("?", 1)[0]
        if path == "/healthz" and method == "GET":
            await self._healthz(writer)
        elif path == "/v1/stats" and method == "GET":
            await self._stats(writer)
        elif path == "/metrics" and method == "GET":
            await self._metrics(writer)
        elif path == "/v1/completions":
            if method != "POST":
                raise _HTTPError(405, "POST only")
            await self._completions(body, writer)
        else:
            raise _HTTPError(404, f"no route for {method} {path}")

    async def _healthz(self, writer) -> None:
        eng = self.frontend.engine
        draining = self.frontend._draining
        await self._send_json(writer, 503 if draining else 200, {
            "status": "draining" if draining else "ok",
            "queue_depth": self.frontend.queue_depth(),
            "queue_bound": self.frontend.max_queue,
            "live_lanes": len(eng.scheduler.running()),
            "max_batch": eng.scheduler.max_batch,
            "requests_finished": eng.stats.requests_finished,
            "requests_rejected": eng.stats.requests_rejected,
        })

    async def _stats(self, writer) -> None:
        eng = self.frontend.engine
        out = eng.stats.to_dict()
        if eng.obs is not None:
            out["latency"] = eng.obs.latency_summaries()
        await self._send_json(writer, 200, out)

    async def _metrics(self, writer) -> None:
        obs = self.frontend.engine.obs
        if obs is None:
            raise _HTTPError(404, "no observer attached (metrics disabled)")
        body = obs.metrics.render_prometheus().encode()
        writer.write(_response_head(
            200, "text/plain; version=0.0.4", content_length=len(body)
        ))
        writer.write(body)
        await writer.drain()

    # -- completions ---------------------------------------------------------

    def _parse_completion(self, body: bytes) -> Dict:
        try:
            req = json.loads(body.decode() or "{}")
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise _HTTPError(400, f"invalid JSON body: {e}")
        if not isinstance(req, dict):
            raise _HTTPError(400, "body must be a JSON object")
        prompt = req.get("prompt")
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise _HTTPError(
                    400, "text prompts need a server-side tokenizer "
                    "(start mdi-server with --ckpt); send token ids"
                )
            prompt = [int(t) for t in self.tokenizer.encode(prompt)]
        elif isinstance(prompt, list) and all(
            isinstance(t, int) for t in prompt
        ):
            prompt = list(prompt)
        else:
            raise _HTTPError(
                400, "prompt must be a string or a list of token ids"
            )
        try:
            max_tokens = int(req.get("max_tokens", 64))
        except (TypeError, ValueError):
            raise _HTTPError(400, "max_tokens must be an integer")
        stop = req.get("stop", ())
        if stop and not (
            isinstance(stop, list)
            and all(isinstance(s, list)
                    and all(isinstance(t, int) for t in s) for s in stop)
        ):
            raise _HTTPError(400, "stop must be a list of token-id lists")
        ttft_ms = req.get("ttft_slo_ms")
        return {
            "prompt": prompt,
            "max_new_tokens": max_tokens,
            "stop_sequences": tuple(tuple(s) for s in stop) if stop else (),
            "priority": int(req.get("priority", 0)),
            "tenant": str(req.get("tenant", "")),
            "ttft_slo_s": float(ttft_ms) / 1e3 if ttft_ms is not None else None,
            "stream": bool(req.get("stream", False)),
        }

    def _submit(self, kwargs: Dict, sink=None):
        stream = kwargs.pop("stream")
        try:
            handle = self.frontend.submit(sink=sink, **kwargs)
        except QueueFullError as e:
            raise _HTTPError(429, str(e))
        except FrontendClosedError as e:
            raise _HTTPError(503, str(e))
        except ValueError as e:
            raise _HTTPError(400, str(e))
        return handle, stream

    def _decode(self, tokens) -> Optional[str]:
        if self.tokenizer is None or not tokens:
            return None
        try:
            import numpy as np

            return self.tokenizer.decode(np.asarray(list(tokens)))
        except Exception:
            return None

    def _summary(self, handle) -> Dict:
        gen = handle.generated()
        out = {
            "rid": handle.rid,
            "n_prompt": handle.n_prompt,
            "n_generated": len(gen),
            "tokens": [int(t) for t in gen],
        }
        text = self._decode(gen)
        if text is not None:
            out["text"] = text
        return out

    async def _completions(self, body: bytes, writer) -> None:
        kwargs = self._parse_completion(body)
        if not kwargs["stream"]:
            handle, _ = self._submit(kwargs)
            # completion latch is a threading.Event set on the engine
            # thread; wait off-loop so slow generations never stall
            # other connections
            await asyncio.get_running_loop().run_in_executor(
                None, handle.done.wait
            )
            if handle.error is not None:
                raise _HTTPError(500, handle.error)
            await self._send_json(writer, 200, self._summary(handle))
            return

        # SSE streaming: engine-thread events bridge into this
        # connection's asyncio queue via call_soon_threadsafe — the one
        # thread-crossing point, append-only and non-blocking on the
        # engine side
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def sink(event):  # mdi-thread: engine
            loop.call_soon_threadsafe(q.put_nowait, event)

        handle, _ = self._submit(kwargs, sink=sink)
        writer.write(_response_head(
            200, "text/event-stream", extra={"Cache-Control": "no-cache"}
        ))
        try:
            await writer.drain()
            while True:
                kind, payload = await q.get()
                if kind == "token":
                    ev: Dict = {"token": int(payload)}
                    piece = self._decode([payload])
                    if piece is not None:
                        ev["text"] = piece
                    writer.write(_sse("token", ev))
                elif kind == "done":
                    writer.write(_sse("done", self._summary(handle)))
                    await writer.drain()
                    return
                elif kind == "cancelled":
                    writer.write(_sse("done", dict(
                        self._summary(handle), cancelled=True
                    )))
                    await writer.drain()
                    return
                else:  # error
                    writer.write(_sse("error", {"error": str(payload)}))
                    await writer.drain()
                    return
                await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            # client went away mid-stream: release the lane — the engine
            # retires the request at its next step boundary
            self.frontend.cancel(handle.rid)
            raise
