"""Open-loop load generation and the offered-load SLO sweep.

Open-loop means arrivals follow their OWN clock: the generator submits
request i at its scheduled offset whether or not earlier requests have
finished, exactly like production traffic (a closed loop — next request
after the previous completes — hides queueing collapse, because the
arrival rate politely slows down with the server; the open loop is what
p99-under-load is defined against).

Pieces, all host-side and clock-injectable for deterministic tests:

- `poisson_arrivals` / `replay_arrivals` — build an `ArrivalSpec` list
  from a (rid, prompt, max_new) trace: exponential inter-arrival gaps at
  a target QPS, or replayed timestamps at a speed factor.
- `OpenLoopRunner` — submits the specs through a `ServingFrontend` at
  their offsets (sleeping on the injected clock), counts accepted vs
  rejected (backpressure is DATA in an open system, not an error), then
  waits for the accepted set to finish.
- `sweep_offered_load` — the knee finder: walk an ascending QPS grid,
  measure each point, and report the highest offered load whose p99
  TTFT/TPOT still meet the SLO.  `measure` is a callable so the same
  sweep drives the real engine (bench `--mode serve-open`) and a
  synthetic queueing model (the fake-clock tier-1 test).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "ArrivalSpec",
    "OpenLoopReport",
    "OpenLoopRunner",
    "poisson_arrivals",
    "replay_arrivals",
    "sweep_offered_load",
]


@dataclass
class ArrivalSpec:
    """One scheduled arrival: submit `prompt` at offset `at_s` (seconds
    from the run start) with the given budgets and policy attributes."""

    rid: str
    prompt: List[int]
    max_new_tokens: int
    at_s: float
    priority: int = 0
    tenant: str = ""
    ttft_slo_s: Optional[float] = None


def poisson_arrivals(trace: Sequence[Tuple], qps: float,
                     seed: int = 10137) -> List[ArrivalSpec]:
    """Poisson process at rate `qps` over a (rid, prompt, max_new) trace:
    inter-arrival gaps ~ Exp(qps), the memoryless arrival model open
    systems are judged under.  Deterministic per seed."""
    import numpy as np

    if qps <= 0:
        raise ValueError(f"qps must be positive, got {qps}")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / qps, size=len(trace))
    t, out = 0.0, []
    for (rid, prompt, new), gap in zip(trace, gaps):
        t += float(gap)
        out.append(ArrivalSpec(rid, list(prompt), int(new), at_s=t))
    return out


def replay_arrivals(trace: Sequence[Tuple], speed: float = 1.0) -> List[ArrivalSpec]:
    """Replayed-trace arrivals: items are (rid, prompt, max_new, at_s)
    with recorded offsets, compressed by `speed` (2.0 = twice as fast —
    the knob an offered-load sweep turns on a production trace)."""
    if speed <= 0:
        raise ValueError(f"speed must be positive, got {speed}")
    out = []
    for rid, prompt, new, at_s in trace:
        out.append(ArrivalSpec(rid, list(prompt), int(new),
                               at_s=float(at_s) / speed))
    return out


@dataclass
class OpenLoopReport:
    """What one open-loop run offered and what came back."""

    offered: int = 0
    accepted: int = 0
    rejected: int = 0
    completed: int = 0
    errored: int = 0
    wall_s: float = 0.0
    # offered arrivals / wall between first and last submission
    offered_qps: float = 0.0

    def to_dict(self) -> Dict:
        return {
            "offered": self.offered,
            "accepted": self.accepted,
            "rejected": self.rejected,
            "completed": self.completed,
            "errored": self.errored,
            "wall_s": round(self.wall_s, 3),
            "offered_qps": round(self.offered_qps, 3),
        }


class OpenLoopRunner:
    """Drive one arrival schedule through a `ServingFrontend`.

    `clock`/`sleep` are injectable (tests run on fake time; production
    uses the wall clock).  Rejections (QueueFullError) are counted, not
    raised — an open system SHEDS load at saturation, and the sweep
    reads the shed fraction as data.  `run()` blocks until every
    accepted request completes or `drain_timeout_s` expires."""

    def __init__(self, frontend, arrivals: Sequence[ArrivalSpec],
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 drain_timeout_s: float = 600.0):
        self.frontend = frontend
        self.arrivals = sorted(arrivals, key=lambda a: a.at_s)
        self.clock = clock
        self.sleep = sleep
        self.drain_timeout_s = drain_timeout_s

    def run(self) -> OpenLoopReport:
        from mdi_llm_tpu.server.frontend import (
            FrontendClosedError,
            QueueFullError,
        )

        rep = OpenLoopReport(offered=len(self.arrivals))
        handles = []
        t0 = self.clock()
        for spec in self.arrivals:
            delay = spec.at_s - (self.clock() - t0)
            if delay > 0:
                self.sleep(delay)  # open loop: arrivals keep their OWN
                # schedule; a slow server makes the queue grow (or shed),
                # never the arrival rate drop
            try:
                handles.append(self.frontend.submit(
                    spec.prompt, spec.max_new_tokens, rid=spec.rid,
                    priority=spec.priority, tenant=spec.tenant,
                    ttft_slo_s=spec.ttft_slo_s,
                ))
                rep.accepted += 1
            except QueueFullError:
                rep.rejected += 1
            except FrontendClosedError:
                rep.rejected += 1
        span = self.clock() - t0
        rep.offered_qps = rep.offered / span if span > 0 else 0.0
        deadline = self.clock() + self.drain_timeout_s
        for h in handles:
            remaining = deadline - self.clock()
            if remaining <= 0 or not h.done.wait(timeout=max(0.0, remaining)):
                rep.errored += 1
                continue
            if h.error is not None or h.cancelled:
                rep.errored += 1
            else:
                rep.completed += 1
        rep.wall_s = self.clock() - t0
        return rep


def sweep_offered_load(
    measure: Callable[[float], Dict],
    qps_grid: Sequence[float],
    slo: Dict[str, float],
    stop_after_misses: int = 1,
) -> Dict:
    """Walk `qps_grid` ascending, measure each offered load, and find the
    max QPS meeting the SLO — the headline number of an open system.

    `measure(qps)` returns at least `{"ttft_p99_s", "tpot_p99_s"}`
    (None/missing = no data at that point, treated as a miss only if an
    SLO names it); `slo` maps those keys to ceilings, e.g.
    ``{"ttft_p99_s": 2.0, "tpot_p99_s": 0.5}``.  A point also misses
    when it sheds load (`rejected > 0`): a 429'd arrival never got a
    first token, so counting the survivors' p99 alone would declare a
    saturated server healthy.

    The walk stops after `stop_after_misses` consecutive misses (the
    knee is behind us; measuring deeper collapse just burns wall clock —
    pass len(grid) to measure everything).  Returns ``{"max_qps_ok",
    "knee_qps", "rows"}``: `max_qps_ok` is the highest passing offered
    load (None if even the lowest missed), `knee_qps` the first failing
    one (None if none failed inside the grid).
    """
    rows: List[Dict] = []
    max_ok: Optional[float] = None
    knee: Optional[float] = None
    misses = 0
    for qps in sorted(qps_grid):
        row = dict(measure(qps))
        row["qps"] = qps
        failures = []
        for key, ceiling in slo.items():
            got = row.get(key)
            if got is None or got > ceiling:
                failures.append(
                    f"{key}={'n/a' if got is None else round(got, 4)}"
                    f" > {ceiling}"
                )
        if row.get("rejected"):
            failures.append(f"rejected={row['rejected']}")
        row["slo_ok"] = not failures
        row["slo_failures"] = failures
        rows.append(row)
        if failures:
            misses += 1
            if knee is None:
                knee = qps
            if misses >= stop_after_misses:
                break
        else:
            misses = 0
            knee = None
            max_ok = qps
    return {"max_qps_ok": max_ok, "knee_qps": knee, "slo": dict(slo),
            "rows": rows}
