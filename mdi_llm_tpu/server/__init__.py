"""Open-system serving front-end over the paged-KV engine.

Turns the replay-a-trace-and-exit `ServingEngine` into a live system
(docs/serving.md):

- `frontend.ServingFrontend` — the concurrency bridge: the engine loop
  runs in ONE dedicated thread (the only thread that ever touches the
  scheduler, pool or device arrays), arrivals flow through a bounded
  thread-safe submission channel drained at the engine's `step_hook`
  seam, tokens stream out per request through the `stream_cb` seam, and
  backpressure/drain/cancel are first-class.
- `http.ServingHTTPServer` — an asyncio HTTP/1.1 front door over the
  frontend: JSON POST completions with SSE token streaming, 429
  admission backpressure, health/metrics endpoints, graceful drain on
  shutdown.  Stdlib only (asyncio streams — no CherryPy, no pickle:
  the reference repo's node control plane reproduced TPU-natively).
- `loadgen` — open-loop arrival generation (Poisson, replayed-trace)
  and the offered-load sweep that finds the max QPS meeting a p99
  TTFT/TPOT SLO (`bench.py --mode serve-open`).
- `explorer` — mdi-race's deterministic schedule explorer: seeded
  adversarial interleavings through the frontend's yield points, with
  offline-replay token parity as the oracle (docs/analysis.md
  "Concurrency analysis").
"""

from mdi_llm_tpu.server.explorer import (
    ScheduleExplorer,
    doctor_burst,
    run_episode,
)
from mdi_llm_tpu.server.frontend import (
    FrontendClosedError,
    QueueFullError,
    RequestHandle,
    ServingFrontend,
)
from mdi_llm_tpu.server.loadgen import (
    ArrivalSpec,
    OpenLoopRunner,
    poisson_arrivals,
    replay_arrivals,
    sweep_offered_load,
)

__all__ = [
    "ArrivalSpec",
    "FrontendClosedError",
    "OpenLoopRunner",
    "QueueFullError",
    "RequestHandle",
    "ScheduleExplorer",
    "ServingFrontend",
    "doctor_burst",
    "poisson_arrivals",
    "replay_arrivals",
    "run_episode",
    "sweep_offered_load",
]
